"""Per-query span-tree tracing.

The third telemetry pillar (beside typed events and the metrics
registry): one :class:`Trace` per query execution, holding a tree of
:class:`Span` records — trace_id + span_id + parent links, wall-clock
anchor + ``perf_counter`` timestamps, and structured attributes — so a
single query's time can be attributed across optimize → rewrite → cache
lookup → program-bank lookup → per-stage execution → I/O → SPMD
dispatch. Events emitted during a traced execution are stamped with the
active (trace_id, span_id), correlating e.g. a ResultCacheMissEvent with
the IoReadEvents of the *same* query.

Propagation is a contextvar, not a thread-local: the serving frontend
snapshots ``contextvars.copy_context()`` per submission and the prefetch
producer runs under a copied context, so the active span follows the
QUERY across worker threads exactly like the r11 io attribution it rides
next to. Pool workers (reader pool) do NOT inherit the context — their
work is recorded on the consumer side (``add_span``), mirroring how
parallel/io.py credits the per-query io counters.

Tracing OFF is a hard no-op fast path: ``span(...)`` returns a shared
no-op context manager after one contextvar read, and ``Session.execute``
opens no trace at all unless ``hyperspace.tpu.telemetry.trace.enabled``
is set (conf via config.py only). Span NAMES come from the frozen
registry in span_names.py — the scripts/lint.py span-discipline gate
rejects free-form strings.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from . import span_names

# The (Trace, Span) pair of the in-flight traced execution, if any.
_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "hst_active_trace", default=None)


class Span:
    """One timed region. ``end_perf`` is None while open; attributes are
    a plain dict the owner may amend until the trace is exported."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "tid",
                 "start_perf", "end_perf", "attrs")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, attrs: dict):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.tid = threading.get_ident()
        self.start_perf = time.perf_counter()
        self.end_perf: Optional[float] = None
        self.attrs = attrs

    def finish(self) -> None:
        if self.end_perf is None:
            self.end_perf = time.perf_counter()

    @property
    def duration_s(self) -> float:
        end = self.end_perf if self.end_perf is not None \
            else time.perf_counter()
        return max(end - self.start_perf, 0.0)

    def __repr__(self) -> str:  # diagnostic only
        return (f"Span({self.name}, id={self.span_id}, "
                f"parent={self.parent_id}, {self.duration_s * 1000:.2f}ms)")


class Trace:
    """The span tree of one query (or one literal-sweep batch). Spans
    append under a lock — members of a sweep and prefetch producers can
    write from several threads — in completion-independent creation
    order; parent links carry the tree."""

    def __init__(self, max_spans: int = 4096, label: str = ""):
        self.trace_id = uuid.uuid4().hex[:16]
        self.label = label
        self.max_spans = max(int(max_spans), 1)
        self.created_wall_ms = int(time.time() * 1000)
        self._anchor_perf = time.perf_counter()
        self._lock = threading.Lock()
        self.spans: List[Span] = []
        self.dropped = 0
        self._ids = 0

    def new_span(self, name: str, parent_id: Optional[str],
                 attrs: Optional[dict] = None) -> Optional[Span]:
        """Open a span; None once the trace is at ``maxSpans`` (the
        would-be span's children then attach to its parent — the tree
        stays connected, the cap stays hard)."""
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return None
            self._ids += 1
            span = Span(self.trace_id, format(self._ids, "x"),
                        parent_id, name, dict(attrs) if attrs else {})
            self.spans.append(span)
            return span

    @property
    def root(self) -> Optional[Span]:
        for s in self.spans:
            if s.parent_id is None:
                return s
        return None

    def find(self, name: str) -> List[Span]:
        return [s for s in self.spans if s.name == name]

    def duration_s(self) -> float:
        root = self.root
        return root.duration_s if root is not None else 0.0

    # ------------------------------------------------------------------
    # Export: Chrome trace-event JSON (chrome://tracing, Perfetto).
    # ------------------------------------------------------------------

    def to_chrome_json(self) -> str:
        """Complete ("X") trace events, ts/dur in microseconds relative
        to the trace's start; span/parent ids ride in ``args`` so the
        tree survives the flat format."""
        pid = os.getpid()
        events = []
        for s in self.spans:
            args: Dict[str, object] = {"span_id": s.span_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            args.update(s.attrs)
            events.append({
                "name": s.name,
                "cat": "hyperspace",
                "ph": "X",
                "ts": round((s.start_perf - self._anchor_perf) * 1e6, 3),
                "dur": round(s.duration_s * 1e6, 3),
                "pid": pid,
                "tid": s.tid,
                "args": args,
            })
        return json.dumps({
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id,
                          "label": self.label,
                          "start_wall_ms": self.created_wall_ms,
                          "dropped_spans": self.dropped},
        }, default=str)


# ---------------------------------------------------------------------------
# Ambient-span API (the only span-opening surface outside this module).
# ---------------------------------------------------------------------------

class _NoopSpan:
    """Shared do-nothing context manager: the tracing-off fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False


NOOP = _NoopSpan()


class _SpanScope:
    __slots__ = ("_name", "_attrs", "_pair", "_token", "span")

    def __init__(self, name: str, attrs: dict):
        self._name = name
        self._attrs = attrs
        self.span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Optional[Span]:
        pair = _ACTIVE.get()
        if pair is None:
            return None
        tr, parent = pair
        span = tr.new_span(self._name,
                           parent.span_id if parent is not None else None,
                           self._attrs)
        if span is None:  # trace at maxSpans
            return None
        self.span = span
        self._token = _ACTIVE.set((tr, span))
        return span

    def __exit__(self, et, ev, tb):
        if self.span is not None:
            if et is not None:
                self.span.attrs["error"] = type(et).__name__
            self.span.finish()
            _ACTIVE.reset(self._token)
        return False


def span(name: str, **attrs):
    """Context manager timing one region under the active trace. Returns
    the shared no-op scope when no trace is active (one contextvar read —
    the instrumented hot paths pay effectively nothing while tracing is
    off); yields the open :class:`Span` (or None at the span cap)."""
    if _ACTIVE.get() is None:
        return NOOP
    return _SpanScope(name, attrs)


def add_span(name: str, start_perf: Optional[float] = None,
             **attrs) -> Optional[Span]:
    """Record an already-elapsed region as a completed child of the
    active span — the consumer-side recording shape for work that ran on
    non-context threads (the reader pool, the prefetch producer), rided
    by parallel/io.py exactly where it credits the per-query io
    counters."""
    pair = _ACTIVE.get()
    if pair is None:
        return None
    tr, parent = pair
    span = tr.new_span(name,
                       parent.span_id if parent is not None else None,
                       attrs)
    if span is None:
        return None
    if start_perf is not None:
        span.start_perf = float(start_perf)
    span.finish()
    return span


def active() -> Optional[Tuple[Trace, Span]]:
    return _ACTIVE.get()


def idle() -> bool:
    """True when no trace is active on this context — the guard the
    hottest call sites use to skip even attribute-dict construction."""
    return _ACTIVE.get() is None


def active_ids() -> Tuple[str, str]:
    """(trace_id, span_id) of the active span, ("", "") when idle — the
    stamp HyperspaceEvent picks up at construction/emission time."""
    pair = _ACTIVE.get()
    if pair is None:
        return "", ""
    tr, span = pair
    return tr.trace_id, span.span_id if span is not None else ""


@contextlib.contextmanager
def maintenance_trace(session, label: str = ""):
    """Root trace for a non-query operation (streaming append/commit/
    compact): when ``telemetry.trace.enabled`` is set and no trace is
    already active, opens a fresh Trace so the operation's spans
    (``ingest.*``) record, landing on ``session._last_trace`` like a
    query trace. Ambient-trace and tracing-off paths are no-ops — the
    operation's spans then nest under the caller's trace or vanish."""
    if _ACTIVE.get() is not None or session is None or \
            not session.hs_conf.telemetry_trace_enabled():
        yield None
        return
    tr = Trace(session.hs_conf.telemetry_trace_max_spans(), label=label)
    token = _ACTIVE.set((tr, None))
    try:
        yield tr
    finally:
        _ACTIVE.reset(token)
        session._last_trace = tr


@contextlib.contextmanager
def query_trace(session, ctx=None):
    """The root scope ``Session.execute`` opens around one query.

    Resolution order:
    - ``ctx.trace_parent`` set (a literal-sweep member handed a shared
      sweep trace by the frontend): open this query's QUERY span as a
      child in THAT trace;
    - a trace already active on this context (nested execution): open a
      child QUERY span in it;
    - ``telemetry.trace.enabled`` on the session: open a fresh Trace
      with a root QUERY span;
    - otherwise: hard no-op.

    The finished trace lands on ``session._last_trace`` (and on
    ``ctx.trace``) for Hyperspace.last_trace() / explain's "Trace:"
    section."""
    parent = getattr(ctx, "trace_parent", None) if ctx is not None else None
    ambient = _ACTIVE.get()
    if parent is None and ambient is None:
        if session is None or \
                not session.hs_conf.telemetry_trace_enabled():
            yield None
            return
    attrs = {}
    if ctx is not None:
        attrs["query_id"] = ctx.query_id
        if ctx.client:
            attrs["client"] = ctx.client
    if parent is not None:
        tr, parent_span = parent
        parent_id = parent_span.span_id if parent_span is not None else None
    elif ambient is not None:
        tr, parent_span = ambient
        parent_id = parent_span.span_id if parent_span is not None else None
    else:
        tr = Trace(session.hs_conf.telemetry_trace_max_spans(),
                   label=ctx.client if ctx is not None else "")
        parent_id = None
    root = tr.new_span(span_names.QUERY, parent_id, attrs)
    if ctx is not None:
        ctx.trace = tr
    token = _ACTIVE.set((tr, root)) if root is not None else None
    try:
        yield root
    finally:
        if root is not None:
            root.finish()
            _ACTIVE.reset(token)
        if session is not None:
            session._last_trace = tr


# ---------------------------------------------------------------------------
# Opt-in jax.profiler capture (one query per arm).
# ---------------------------------------------------------------------------

_PROFILER_LOCK = threading.Lock()
_PROFILER_DONE = False


@contextlib.contextmanager
def maybe_profile(session):
    """Bracket ONE query with ``jax.profiler.trace`` when
    ``hyperspace.tpu.telemetry.profiler.{enabled,dir}`` arm it. One-shot
    per process: the first execution after arming captures, later ones
    run untouched (a serving loop must not accumulate captures)."""
    global _PROFILER_DONE
    if session is None or \
            not session.hs_conf.telemetry_profiler_enabled():
        yield False
        return
    out_dir = session.hs_conf.telemetry_profiler_dir()
    if not out_dir:
        yield False
        return
    with _PROFILER_LOCK:
        if _PROFILER_DONE:
            yield False
            return
        _PROFILER_DONE = True
    import jax

    with jax.profiler.trace(out_dir):
        yield True


def reset_profiler() -> None:
    """Re-arm the one-shot profiler capture (tests)."""
    global _PROFILER_DONE
    _PROFILER_DONE = False


# ---------------------------------------------------------------------------
# Rendering (explain's "Trace:" section).
# ---------------------------------------------------------------------------

_RENDER_ATTRS = ("node", "hit", "tier", "mode", "files", "rows",
                 "size", "members")
_MAX_RENDER_LINES = 48


def render_timeline(trace: Trace) -> List[str]:
    """Indented span tree with per-span wall duration and self-time
    (duration minus direct children — where the time actually went)."""
    children: Dict[Optional[str], List[Span]] = {}
    for s in trace.spans:
        children.setdefault(s.parent_id, []).append(s)
    lines: List[str] = []
    total = 0

    def walk(span: Span, depth: int) -> None:
        nonlocal total
        total += 1
        if len(lines) >= _MAX_RENDER_LINES:
            return
        kids = children.get(span.span_id, [])
        dur = span.duration_s
        self_s = max(dur - sum(k.duration_s for k in kids), 0.0)
        detail = " ".join(
            f"{k}={span.attrs[k]}" for k in _RENDER_ATTRS
            if k in span.attrs)
        pad = "  " * depth
        lines.append(
            f"{pad}{span.name:<24} {dur * 1000:9.2f} ms "
            f"(self {self_s * 1000:.2f} ms)"
            + (f"  [{detail}]" if detail else ""))
        for k in kids:
            walk(k, depth + 1)

    for root in children.get(None, []):
        walk(root, 0)
    hidden = len(trace.spans) - min(len(trace.spans), _MAX_RENDER_LINES)
    if hidden > 0:
        lines.append(f"... {hidden} more span(s) not shown")
    if trace.dropped:
        lines.append(f"({trace.dropped} span(s) dropped at the "
                     f"maxSpans={trace.max_spans} cap)")
    return lines
