"""Per-query span-tree tracing.

The third telemetry pillar (beside typed events and the metrics
registry): one :class:`Trace` per query execution, holding a tree of
:class:`Span` records — trace_id + span_id + parent links, wall-clock
anchor + ``perf_counter`` timestamps, and structured attributes — so a
single query's time can be attributed across optimize → rewrite → cache
lookup → program-bank lookup → per-stage execution → I/O → SPMD
dispatch. Events emitted during a traced execution are stamped with the
active (trace_id, span_id), correlating e.g. a ResultCacheMissEvent with
the IoReadEvents of the *same* query.

Propagation is a contextvar, not a thread-local: the serving frontend
snapshots ``contextvars.copy_context()`` per submission and the prefetch
producer runs under a copied context, so the active span follows the
QUERY across worker threads exactly like the r11 io attribution it rides
next to. Pool workers (reader pool) do NOT inherit the context — their
work is recorded on the consumer side (``add_span``), mirroring how
parallel/io.py credits the per-query io counters.

Tracing OFF is a hard no-op fast path: ``span(...)`` returns a shared
no-op context manager after one contextvar read, and ``Session.execute``
opens no trace at all while ``hyperspace.tpu.telemetry.trace.enabled``
is false (conf via config.py only). Since the observability round the
flag defaults ON with head-sampled RETENTION: the per-query coin
(``telemetry.trace.sampleRate``) is flipped at ``Session.execute``; a
coin-negative query still records into a provisional trace — so the
tail-keep override (:func:`keep_active`, driven by deadline breaches,
retries, degradation ladders, flight-recorder anomalies, and the
live-latency threshold) can rescue exactly the unlucky queries — but
the trace is DISCARDED at completion unless kept (:func:`finish_root`).
Span NAMES come from the frozen registry in span_names.py — the
scripts/lint.py span-discipline gate rejects free-form strings.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from . import metric_names as MN
from . import span_names

# The (Trace, Span) pair of the in-flight traced execution, if any.
_ACTIVE: contextvars.ContextVar = contextvars.ContextVar(
    "hst_active_trace", default=None)


class Span:
    """One timed region. ``end_perf`` is None while open; attributes are
    a plain dict the owner may amend until the trace is exported."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "tid",
                 "start_perf", "end_perf", "attrs")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, attrs: dict):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.tid = threading.get_ident()
        self.start_perf = time.perf_counter()
        self.end_perf: Optional[float] = None
        self.attrs = attrs

    def finish(self) -> None:
        if self.end_perf is None:
            self.end_perf = time.perf_counter()

    @property
    def duration_s(self) -> float:
        end = self.end_perf if self.end_perf is not None \
            else time.perf_counter()
        return max(end - self.start_perf, 0.0)

    def __repr__(self) -> str:  # diagnostic only
        return (f"Span({self.name}, id={self.span_id}, "
                f"parent={self.parent_id}, {self.duration_s * 1000:.2f}ms)")


class Trace:
    """The span tree of one query (or one literal-sweep batch). Spans
    append under a lock — members of a sweep and prefetch producers can
    write from several threads — in completion-independent creation
    order; parent links carry the tree."""

    def __init__(self, max_spans: int = 4096, label: str = "",
                 sampled: bool = True):
        self.trace_id = uuid.uuid4().hex[:16]
        self.label = label
        self.max_spans = max(int(max_spans), 1)
        self.created_wall_ms = int(time.time() * 1000)
        self._anchor_perf = time.perf_counter()
        self._lock = threading.Lock()
        self.spans: List[Span] = []
        self.dropped = 0
        self._ids = 0
        # Retention state (the head-sampling layer): ``sampled`` is the
        # coin flipped at creation; ``keep_reasons`` collects tail-keep
        # marks (deadline breach, retry, degradation, anomaly, slow);
        # ``retained`` flips once finish_root decides to keep it.
        self.sampled = bool(sampled)
        self.keep_reasons: List[str] = []
        self.retained = False

    def new_span(self, name: str, parent_id: Optional[str],
                 attrs: Optional[dict] = None) -> Optional[Span]:
        """Open a span; None once the trace is at ``maxSpans`` (the
        would-be span's children then attach to its parent — the tree
        stays connected, the cap stays hard)."""
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return None
            self._ids += 1
            span = Span(self.trace_id, format(self._ids, "x"),
                        parent_id, name, dict(attrs) if attrs else {})
            self.spans.append(span)
            return span

    @property
    def root(self) -> Optional[Span]:
        for s in self.spans:
            if s.parent_id is None:
                return s
        return None

    def find(self, name: str) -> List[Span]:
        return [s for s in self.spans if s.name == name]

    def duration_s(self) -> float:
        root = self.root
        return root.duration_s if root is not None else 0.0

    # ------------------------------------------------------------------
    # Export: Chrome trace-event JSON (chrome://tracing, Perfetto).
    # ------------------------------------------------------------------

    def span_events(self, base_us: float = 0.0,
                    with_trace_id: bool = False) -> List[dict]:
        """Complete ("X") trace events for every span, ts/dur in
        microseconds offset by ``base_us``; span/parent ids ride in
        ``args`` so the tree survives the flat format (and, for
        multi-trace bundles like the flight-recorder dump, the
        trace_id)."""
        pid = os.getpid()
        events = []
        for s in list(self.spans):
            args: Dict[str, object] = {"span_id": s.span_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            if with_trace_id:
                args["trace_id"] = self.trace_id
            args.update(s.attrs)
            events.append({
                "name": s.name,
                "cat": "hyperspace",
                "ph": "X",
                "ts": round(base_us
                            + (s.start_perf - self._anchor_perf) * 1e6, 3),
                "dur": round(s.duration_s * 1e6, 3),
                "pid": pid,
                "tid": s.tid,
                "args": args,
            })
        return events

    def to_chrome_json(self) -> str:
        """One-trace Chrome trace-event JSON (chrome://tracing,
        Perfetto)."""
        events = self.span_events()
        return json.dumps({
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"trace_id": self.trace_id,
                          "label": self.label,
                          "start_wall_ms": self.created_wall_ms,
                          "dropped_spans": self.dropped},
        }, default=str)


# ---------------------------------------------------------------------------
# Ambient-span API (the only span-opening surface outside this module).
# ---------------------------------------------------------------------------

class _NoopSpan:
    """Shared do-nothing context manager: the tracing-off fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, et, ev, tb):
        return False


NOOP = _NoopSpan()


class _SpanScope:
    __slots__ = ("_name", "_attrs", "_pair", "_token", "span")

    def __init__(self, name: str, attrs: dict):
        self._name = name
        self._attrs = attrs
        self.span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Optional[Span]:
        pair = _ACTIVE.get()
        if pair is None:
            return None
        tr, parent = pair
        span = tr.new_span(self._name,
                           parent.span_id if parent is not None else None,
                           self._attrs)
        if span is None:  # trace at maxSpans
            return None
        self.span = span
        self._token = _ACTIVE.set((tr, span))
        return span

    def __exit__(self, et, ev, tb):
        if self.span is not None:
            if et is not None:
                self.span.attrs["error"] = type(et).__name__
            self.span.finish()
            _ACTIVE.reset(self._token)
        return False


def span(name: str, **attrs):
    """Context manager timing one region under the active trace. Returns
    the shared no-op scope when no trace is active (one contextvar read —
    the instrumented hot paths pay effectively nothing while tracing is
    off); yields the open :class:`Span` (or None at the span cap)."""
    if _ACTIVE.get() is None:
        return NOOP
    return _SpanScope(name, attrs)


def add_span(name: str, start_perf: Optional[float] = None,
             **attrs) -> Optional[Span]:
    """Record an already-elapsed region as a completed child of the
    active span — the consumer-side recording shape for work that ran on
    non-context threads (the reader pool, the prefetch producer), rided
    by parallel/io.py exactly where it credits the per-query io
    counters."""
    pair = _ACTIVE.get()
    if pair is None:
        return None
    tr, parent = pair
    span = tr.new_span(name,
                       parent.span_id if parent is not None else None,
                       attrs)
    if span is None:
        return None
    if start_perf is not None:
        span.start_perf = float(start_perf)
    span.finish()
    return span


def active() -> Optional[Tuple[Trace, Span]]:
    return _ACTIVE.get()


def idle() -> bool:
    """True when no trace is active on this context — the guard the
    hottest call sites use to skip even attribute-dict construction."""
    return _ACTIVE.get() is None


def keep_active(reason: str = "") -> None:
    """Mark the ACTIVE trace tail-keep: it survives a negative sample
    coin at completion. Called by the anomaly sites (deadline
    cancellation, retry, degradation ladders, flight-recorder anomalies)
    — a no-op outside a traced execution."""
    pair = _ACTIVE.get()
    if pair is None:
        return
    tr = pair[0]
    with tr._lock:
        if reason not in tr.keep_reasons:
            tr.keep_reasons.append(reason or "anomaly")


def sample_coin(session) -> bool:
    """One retention coin flip per root trace (``sampleRate`` conf)."""
    rate = session.hs_conf.telemetry_trace_sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return random.random() < rate


def _tail_slow_threshold_ms(session) -> Optional[float]:
    """The latency above which a coin-negative trace is kept anyway:
    the explicit ``tailSlowMs`` conf, else (0 = auto) 2x the live
    query-latency p99 (telemetry/slo.py caches it), else None."""
    ms = session.hs_conf.telemetry_trace_tail_slow_ms()
    if ms > 0:
        return ms
    from . import slo as _slo
    return _slo.adaptive_slow_threshold_ms()


def finish_root(session, tr: Trace) -> None:
    """Retention decision for one completed root-owned trace: keep it
    (``session._last_trace`` + the flight-recorder ring) when the head
    coin said yes, a tail-keep mark landed, or the query breached the
    live-latency threshold; discard it otherwise. Counted on the
    ``trace.sampled`` / ``trace.tail_kept`` / ``trace.discarded``
    process counters."""
    if tr.retained:
        return
    keep = tr.sampled
    kind = MN.TRACE_SAMPLED
    if not keep and tr.keep_reasons:
        keep, kind = True, MN.TRACE_TAIL_KEPT
    if not keep:
        thr = _tail_slow_threshold_ms(session)
        if thr is not None and tr.duration_s() * 1000.0 > thr:
            keep, kind = True, MN.TRACE_TAIL_KEPT
            tr.keep_reasons.append("slow")
    hs_conf = session.hs_conf
    if keep:
        tr.retained = True
        session._last_trace = tr
        if hs_conf.telemetry_flight_enabled():
            from . import flight_recorder as _fr
            _fr.get_recorder().note_trace(
                tr, cap=hs_conf.telemetry_flight_max_traces())
    if hs_conf.telemetry_metrics_enabled():
        from .metrics import get_registry
        reg = get_registry()
        if not keep:
            reg.counter_add(MN.TRACE_DISCARDED)
        elif kind == MN.TRACE_SAMPLED:
            reg.counter_add(MN.TRACE_SAMPLED)
        else:
            reg.counter_add(MN.TRACE_TAIL_KEPT)


def active_ids() -> Tuple[str, str]:
    """(trace_id, span_id) of the active span, ("", "") when idle — the
    stamp HyperspaceEvent picks up at construction/emission time."""
    pair = _ACTIVE.get()
    if pair is None:
        return "", ""
    tr, span = pair
    return tr.trace_id, span.span_id if span is not None else ""


@contextlib.contextmanager
def maintenance_trace(session, label: str = ""):
    """Root trace for a non-query operation (streaming append/commit/
    compact): when ``telemetry.trace.enabled`` is set and no trace is
    already active, opens a fresh Trace so the operation's spans
    (``ingest.*``) record, landing on ``session._last_trace`` like a
    query trace. Ambient-trace and tracing-off paths are no-ops — the
    operation's spans then nest under the caller's trace or vanish."""
    if _ACTIVE.get() is not None or session is None or \
            not session.hs_conf.telemetry_trace_enabled():
        yield None
        return
    tr = Trace(session.hs_conf.telemetry_trace_max_spans(), label=label,
               sampled=sample_coin(session))
    token = _ACTIVE.set((tr, None))
    try:
        yield tr
    finally:
        _ACTIVE.reset(token)
        finish_root(session, tr)


@contextlib.contextmanager
def query_trace(session, ctx=None):
    """The root scope ``Session.execute`` opens around one query.

    Resolution order:
    - ``ctx.trace_parent`` set (a literal-sweep member handed a shared
      sweep trace by the frontend): open this query's QUERY span as a
      child in THAT trace;
    - a trace already active on this context (nested execution): open a
      child QUERY span in it;
    - ``telemetry.trace.enabled`` on the session: open a fresh Trace
      with a root QUERY span;
    - otherwise: hard no-op.

    The finished trace lands on ``session._last_trace`` (and on
    ``ctx.trace``) for Hyperspace.last_trace() / explain's "Trace:"
    section."""
    parent = getattr(ctx, "trace_parent", None) if ctx is not None else None
    ambient = _ACTIVE.get()
    forced = bool(getattr(ctx, "trace_force", False)) \
        if ctx is not None else False
    if parent is None and ambient is None and not forced:
        if session is None or \
                not session.hs_conf.telemetry_trace_enabled():
            yield None
            return
    attrs = {}
    if ctx is not None:
        attrs["query_id"] = ctx.query_id
        if ctx.client:
            attrs["client"] = ctx.client
    fresh = False
    if parent is not None:
        tr, parent_span = parent
        parent_id = parent_span.span_id if parent_span is not None else None
    elif ambient is not None:
        tr, parent_span = ambient
        parent_id = parent_span.span_id if parent_span is not None else None
    else:
        # ``trace_force`` (explain_analyze) pins the coin: the caller
        # asked for THIS query's trace, sampling must not drop it.
        tr = Trace(session.hs_conf.telemetry_trace_max_spans(),
                   label=ctx.client if ctx is not None else "",
                   sampled=forced or sample_coin(session))
        parent_id = None
        fresh = True
    root = tr.new_span(span_names.QUERY, parent_id, attrs)
    if ctx is not None:
        ctx.trace = tr
    token = _ACTIVE.set((tr, root)) if root is not None else None
    try:
        yield root
    finally:
        if root is not None:
            root.finish()
            _ACTIVE.reset(token)
        if session is not None:
            if fresh:
                finish_root(session, tr)
            elif tr.sampled or tr.keep_reasons:
                # Shared sweep / nested traces: the owner (the serving
                # frontend / the outer query) runs the full retention;
                # members only surface an already-keep-worthy trace.
                session._last_trace = tr


# ---------------------------------------------------------------------------
# Opt-in jax.profiler capture (one query per arm).
# ---------------------------------------------------------------------------

_PROFILER_LOCK = threading.Lock()
_PROFILER_DONE = False


@contextlib.contextmanager
def maybe_profile(session):
    """Bracket ONE query with ``jax.profiler.trace`` when
    ``hyperspace.tpu.telemetry.profiler.{enabled,dir}`` arm it. One-shot
    per process: the first execution after arming captures, later ones
    run untouched (a serving loop must not accumulate captures)."""
    global _PROFILER_DONE
    if session is None or \
            not session.hs_conf.telemetry_profiler_enabled():
        yield False
        return
    out_dir = session.hs_conf.telemetry_profiler_dir()
    if not out_dir:
        yield False
        return
    with _PROFILER_LOCK:
        if _PROFILER_DONE:
            yield False
            return
        _PROFILER_DONE = True
    import jax

    with jax.profiler.trace(out_dir):
        yield True


def reset_profiler() -> None:
    """Re-arm the one-shot profiler capture (tests)."""
    global _PROFILER_DONE
    _PROFILER_DONE = False


# ---------------------------------------------------------------------------
# Rendering (explain's "Trace:" section).
# ---------------------------------------------------------------------------

_RENDER_ATTRS = ("node", "hit", "tier", "mode", "files", "rows",
                 "size", "members")
_MAX_RENDER_LINES = 48


def render_timeline(trace: Trace) -> List[str]:
    """Indented span tree with per-span wall duration and self-time
    (duration minus direct children — where the time actually went)."""
    children: Dict[Optional[str], List[Span]] = {}
    for s in trace.spans:
        children.setdefault(s.parent_id, []).append(s)
    lines: List[str] = []
    total = 0

    def walk(span: Span, depth: int) -> None:
        nonlocal total
        total += 1
        if len(lines) >= _MAX_RENDER_LINES:
            return
        kids = children.get(span.span_id, [])
        dur = span.duration_s
        self_s = max(dur - sum(k.duration_s for k in kids), 0.0)
        detail = " ".join(
            f"{k}={span.attrs[k]}" for k in _RENDER_ATTRS
            if k in span.attrs)
        pad = "  " * depth
        lines.append(
            f"{pad}{span.name:<24} {dur * 1000:9.2f} ms "
            f"(self {self_s * 1000:.2f} ms)"
            + (f"  [{detail}]" if detail else ""))
        for k in kids:
            walk(k, depth + 1)

    for root in children.get(None, []):
        walk(root, 0)
    hidden = len(trace.spans) - min(len(trace.spans), _MAX_RENDER_LINES)
    if hidden > 0:
        lines.append(f"... {hidden} more span(s) not shown")
    if trace.dropped:
        lines.append(f"({trace.dropped} span(s) dropped at the "
                     f"maxSpans={trace.max_spans} cap)")
    return lines
