"""Typed telemetry event model.

Parity reference: telemetry/HyperspaceEvent.scala:28-156 — one event class
per action (start/success/failure carried in ``message``/``emitted_on``), plus
an index-usage event emitted by the rewrite rules.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class HyperspaceEvent:
    """Base event. ``app_id`` identifies the session; ``message`` carries
    RUNNING/SUCCESS/FAILURE details.

    ``trace_id``/``span_id`` correlate the event with the query that
    emitted it: auto-stamped from the ACTIVE trace span
    (telemetry/trace.py) at construction time — which IS emission time,
    events are built at their emit sites — and empty outside a traced
    execution, so tracing-off event streams are byte-identical to
    pre-trace ones."""

    app_id: str = ""
    message: str = ""
    emitted_on_ms: int = field(default_factory=lambda: int(time.time() * 1000))
    trace_id: str = ""
    span_id: str = ""

    def __post_init__(self):
        if not self.trace_id:
            from .trace import active_ids
            self.trace_id, self.span_id = active_ids()
        # Flight-recorder feed (telemetry/flight_recorder.py): every
        # event construction — which IS emission — rings the recorder
        # and runs its anomaly/tail-keep classifier. Bounded, lock +
        # append; failures must never reach the emit site.
        try:
            from .flight_recorder import note_event
            note_event(self)
        except Exception:
            pass

    @property
    def event_name(self) -> str:
        return type(self).__name__


@dataclass
class HyperspaceIndexCRUDEvent(HyperspaceEvent):
    index_name: str = ""
    log_entry_json: Optional[str] = None


@dataclass
class CreateActionEvent(HyperspaceIndexCRUDEvent):
    index_config: Optional[object] = None


@dataclass
class DeleteActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class RestoreActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class VacuumActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class CancelActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class RefreshActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class RefreshIncrementalActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class RefreshQuickActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class OptimizeActionEvent(HyperspaceIndexCRUDEvent):
    pass


@dataclass
class DistributedFallbackEvent(HyperspaceEvent):
    """Emitted whenever a distributed path (mesh build, SPMD query) silently
    would have degraded to single-device execution — making the degradation
    observable instead (VERDICT r2 weak #3). ``where`` is the path
    ("index_build" | "spmd_query"); ``reason`` the structural cause."""

    where: str = ""
    reason: str = ""


@dataclass
class ShardedExecutionEvent(HyperspaceEvent):
    """Emitted per successful SPMD dispatch (execution/spmd.py): the mesh
    identity the program partitioned over, the PartitionSpecs chosen for
    its inputs/outputs, whether the leaf sharded file-aligned, and the
    compiled program's HLO collective counts (all-to-all = the bucket
    exchange, all-reduce = psum partial merges; all-gather /
    collective-permute / reduce-scatter would be resharding the program
    never asked for). ``cap_attempts`` counts capacity-escalation
    compiles (1 = first program fit)."""

    mode: str = ""            # global-agg | grouped-agg | stream | sort
    mesh_axes: Optional[List[str]] = None
    mesh_shape: Optional[List[int]] = None
    mesh_platform: str = ""
    shard_rows: int = 0
    file_aligned_scan: bool = False
    in_specs: str = ""
    out_specs: str = ""
    collectives: Optional[dict] = None
    cap_attempts: int = 1


@dataclass
class SpmdExchangeEvent(HyperspaceEvent):
    """Emitted per join stage (and per distributed-sort range exchange)
    of an SPMD dispatch: which strategy ran — ``broadcast`` (replicated
    side, zero row movement), ``exchange`` (hash-routed bucket exchange,
    one all_to_all per side), or ``sort-route`` (sample-sort range
    partitioning) — and the static capacities the program was compiled
    with. ``all_to_all`` is the number of logical all-to-all collectives
    the stage asked for (compiled totals ride ShardedExecutionEvent)."""

    stage: int = -1
    join_type: str = ""
    strategy: str = ""        # broadcast | exchange | sort-route
    capacity: int = 0
    output_slots: int = 0
    all_to_all: int = 0


@dataclass
class HyperspaceIndexUsageEvent(HyperspaceEvent):
    """Emitted when a rewrite rule applies indexes to a plan
    (parity: rules/FilterIndexRule.scala:69-78)."""

    index_names: List[str] = field(default_factory=list)
    plan_string: str = ""


@dataclass
class ResultCacheEvent(HyperspaceEvent):
    """Base of the serving-layer result-cache events (no reference
    analogue; see serving/result_cache.py). ``key_digest`` is the stable
    short form of the cache key; ``tier`` is "device" | "host"."""

    key_digest: str = ""
    tier: str = ""
    nbytes: int = 0


@dataclass
class ResultCacheHitEvent(ResultCacheEvent):
    pass


@dataclass
class ResultCacheMissEvent(ResultCacheEvent):
    """``reason`` distinguishes robustness misses ("spill-corrupt" — a
    truncated/corrupt spill file was evicted and served as a miss) from
    plain cold misses ("", byte-compatible with the pre-robustness
    event stream)."""

    reason: str = ""


@dataclass
class ResultCacheAdmitEvent(ResultCacheEvent):
    pass


@dataclass
class ResultCacheEvictionEvent(ResultCacheEvent):
    """``demoted`` — a device-tier victim that moved to the host tier
    (still servable) rather than leaving the cache entirely."""

    demoted: bool = False


@dataclass
class KernelCompileEvent(HyperspaceEvent):
    """XLA compilation tally for one plan execution (no reference
    analogue; see execution/shapes.py). ``count`` is the number of
    backend compiles the execution triggered, ``seconds`` their summed
    compile time, ``total`` the process-lifetime compile count. With
    shape bucketing healthy, steady-state executions emit no event at
    all (count 0 is not reported); a stream of these on a warm serving
    path is the recompilation-storm signature."""

    count: int = 0
    seconds: float = 0.0
    total: int = 0


@dataclass
class AdvisorWhatIfEvent(HyperspaceEvent):
    """Emitted per user-facing what-if analysis (advisor/whatif.py).
    ``index_names`` are the hypothetical configs analyzed,
    ``applied_names`` the subset the re-optimized plan would use. Bulk
    what-if passes inside `recommend` are silent (one event per
    recommendation run, not per candidate x record)."""

    index_names: List[str] = field(default_factory=list)
    applied_names: List[str] = field(default_factory=list)


@dataclass
class AdvisorRecommendationEvent(HyperspaceEvent):
    """Emitted per `Hyperspace.recommend` run (advisor/recommend.py):
    the ranked index names plus how much evidence backed them."""

    recommended: List[str] = field(default_factory=list)
    candidates_evaluated: int = 0
    records_considered: int = 0


@dataclass
class IoReadEvent(HyperspaceEvent):
    """Emitted per pooled multi-file read fan-out (parallel/io.py
    imap_ordered): how many file tasks ran, their summed size estimate,
    the summed in-worker read+decode time, and the pool width used.
    Sequential reads (pool off / threads=1 / single file) are silent."""

    files: int = 0
    nbytes: int = 0
    seconds: float = 0.0
    threads: int = 0


@dataclass
class IoWaitEvent(HyperspaceEvent):
    """Emitted per completed prefetch stream (parallel/io.py
    prefetch_iter): ``wait_seconds`` is consumer time blocked on the
    queue (I/O-bound share), ``read_seconds`` the producer's read+decode
    time — their gap is the decode/compute overlap the pipeline bought.
    ``where`` labels the stream (dataset_chunks, sketch_build, ...)."""

    where: str = ""
    wait_seconds: float = 0.0
    read_seconds: float = 0.0
    items: int = 0


@dataclass
class JoinReorderEvent(HyperspaceEvent):
    """Emitted when the cost-based join reorderer
    (optimizer/join_order.py) re-linearizes an inner-equi-join chain:
    ``tables`` in the original (text) order, ``order`` as chosen, and
    the per-step estimated intermediate cardinalities. Diagnostic
    passes (explain) are silent."""

    tables: List[str] = field(default_factory=list)
    order: List[str] = field(default_factory=list)
    estimated_rows: List[float] = field(default_factory=list)


@dataclass
class CardinalityEstimateEvent(HyperspaceEvent):
    """One cardinality estimate the reorderer committed to (per join
    step of a reordered chain). ``subject`` is the join condition repr —
    the same key the executor records actual inner-join output rows
    under, so estimate and observation can be paired for q-error."""

    subject: str = ""
    estimated_rows: float = 0.0


@dataclass
class ServingAdmitEvent(HyperspaceEvent):
    """Emitted per query the serving frontend admits
    (serving/frontend.py). ``estimated_bytes`` is the admission-control
    recompute-input estimate; ``queue_depth`` the queue length after the
    enqueue."""

    client: str = ""
    estimated_bytes: int = 0
    queue_depth: int = 0


@dataclass
class ServingRejectEvent(HyperspaceEvent):
    """Emitted per submission admission control refuses (queue at
    ``serving.queueDepth`` or in-flight bytes past
    ``serving.admission.maxBytes``); the caller sees a
    ServingRejectedError carrying the same ``reason``."""

    client: str = ""
    estimated_bytes: int = 0
    reason: str = ""


@dataclass
class ServingBatchEvent(HyperspaceEvent):
    """Emitted per executed literal-sweep batch (serving/batcher.py):
    ``size`` member queries collapsed onto ``sweep_invocations`` batched
    predicate invocations over ``shared_scans`` shared source reads;
    ``positions`` is how many Filter positions the template swept."""

    size: int = 0
    positions: int = 0
    sweep_invocations: int = 0
    shared_scans: int = 0


@dataclass
class ProgramBankEvent(HyperspaceEvent):
    """Base of the compiled-program-bank events
    (serving/program_bank.py). ``stage_digest`` identifies the stage
    fingerprint; ``shape_vec`` the shape-class vector; ``hits``/
    ``misses`` are the bank's running totals at emission time."""

    stage_digest: str = ""
    shape_vec: List[int] = field(default_factory=list)
    hits: int = 0
    misses: int = 0


@dataclass
class ProgramBankMissEvent(ProgramBankEvent):
    """A new (stage, shape-class vector) program registered — a backend
    compile is expected right after."""


@dataclass
class ProgramBankHitEvent(ProgramBankEvent):
    """A program's FIRST reuse (later reuses only bump the counters —
    per-lookup events would swamp the log on a warm serving path)."""


@dataclass
class RetryEvent(HyperspaceEvent):
    """Emitted per retried sequence (robustness/retry.py — pooled
    reader tasks, op-log store writes): how many attempts ran, whether
    the sequence recovered, and the ORIGINAL transient error (the one
    surfaced on exhaustion). Sequences that succeed first try are
    silent — a healthy system emits no retry telemetry."""

    where: str = ""
    attempts: int = 0
    succeeded: bool = False
    error: str = ""


@dataclass
class QueryCancelledEvent(HyperspaceEvent):
    """Emitted ONCE per query cancelled at a cooperative deadline check
    (serving/context.check_deadline): which boundary the cancellation
    struck and how long the query had been running. The caller sees the
    typed QueryDeadlineError; the serving worker slot is freed."""

    query_id: int = 0
    where: str = ""
    elapsed_ms: float = 0.0


@dataclass
class SloBreachEvent(HyperspaceEvent):
    """Emitted per healthy->breached transition of one named SLO
    objective (telemetry/slo.py): which objective, the configured
    threshold, the observed value, and the sliding window it was
    evaluated over. Recoveries re-arm silently; Hyperspace.health()
    carries the live verdict."""

    objective: str = ""
    threshold: float = 0.0
    observed: float = 0.0
    window_s: float = 0.0
    count: int = 0


@dataclass
class StreamingAppendEvent(HyperspaceEvent):
    """Emitted per staged batch (streaming/ingest.py append): how many
    rows landed in staging, the batch's parquet size, and how many
    covering/skipping index deltas were prebuilt on-device at load time
    (the aggressive-elephants contract: index work rides the upload)."""

    table: str = ""
    rows: int = 0
    nbytes: int = 0
    covering_deltas: int = 0
    sketch_deltas: int = 0
    seconds: float = 0.0


@dataclass
class StreamingCommitEvent(HyperspaceEvent):
    """Emitted per commit() publishing staged batches through the
    op-log protocol: batches/files/rows landed, which indexes received
    prebuilt deltas, and the commit's wall-clock (metadata + renames —
    the index build already happened at append time)."""

    table: str = ""
    batches: int = 0
    files: int = 0
    rows: int = 0
    indexes_updated: List[str] = field(default_factory=list)
    seconds: float = 0.0


@dataclass
class StreamingIndexDeltaEvent(HyperspaceIndexCRUDEvent):
    """One prebuilt index delta landed by a streaming commit (the
    load-time analogue of RefreshIncrementalActionEvent — its presence
    with ZERO RefreshActionEvents is the 'fresh with no refresh pass'
    telemetry assertion)."""


@dataclass
class StreamingCompactionEvent(HyperspaceEvent):
    """Emitted per op-log compacted by compact() (streaming/
    compaction.py): how many superseded entries folded into the
    checkpoint, the new compaction generation (pinned into the
    checkpoint entry bytes so result-cache keys can never alias across
    a compaction), and data versions vacuumed."""

    subject: str = ""
    entries_folded: int = 0
    generation: int = 0
    versions_vacuumed: int = 0


@dataclass
class StreamingWaveEvent(HyperspaceEvent):
    """Emitted per group-commit publication wave (streaming/ingest.py
    CommitCoordinator): how many staged batches the wave coalesced into
    one op-log entry per table, how many concurrent ``commit()``
    callers rode the wave instead of publishing themselves, and how
    many bounded sub-waves drained a deeper queue."""

    table: str = ""
    batches: int = 0
    rows: int = 0
    joined: int = 0
    sub_waves: int = 0
    seconds: float = 0.0


@dataclass
class StreamingSourceEvent(HyperspaceEvent):
    """Emitted per productive continuous-source poll (streaming/
    sources.py): the tailer appended ``batches`` new input batches
    (``rows`` rows) and drove ``commits`` group commits itself;
    ``waits`` counts blocking-backpressure stalls this poll."""

    source: str = ""
    table: str = ""
    batches: int = 0
    rows: int = 0
    commits: int = 0
    waits: int = 0


@dataclass
class StandingQueryEvent(HyperspaceEvent):
    """Emitted per standing-query fire wave (streaming/
    subscriptions.py): a commit re-fired ``fired`` subscribed plans
    through the serving worker pool (``rejected`` were shed by
    admission control and delivered as errors). ``groups`` counts the
    same-template groups routed through the literal batcher as shared
    scans (0 = every fire ran as its own submission)."""

    table: str = ""
    fired: int = 0
    rejected: int = 0
    groups: int = 0


@dataclass
class IndexCacheProbeEvent(HyperspaceEvent):
    """Base of the HBM index-table-cache probe events: the executor emits
    one per IndexScan cache lookup (execution/index_cache.py counts were
    previously invisible outside the process)."""

    index_name: str = ""


@dataclass
class IndexCacheHitEvent(IndexCacheProbeEvent):
    pass


@dataclass
class IndexCacheMissEvent(IndexCacheProbeEvent):
    pass


@dataclass
class BufferPoolEvent(HyperspaceEvent):
    """Base of the tiered columnar buffer-pool events
    (execution/buffer_pool.py): ``namespace`` is the key family
    ("scan" | "stream" | "index" | "blocks"), ``tier`` where the probe
    landed ("device" | "host"), ``nbytes`` the entry's residency cost."""

    namespace: str = ""
    tier: str = ""
    nbytes: int = 0


@dataclass
class BufferPoolHitEvent(BufferPoolEvent):
    """A decoded, padded buffer served from the pool — a parquet decode
    and (on the device tier) a host→device transfer that did NOT
    happen."""


@dataclass
class BufferPoolMissEvent(BufferPoolEvent):
    """``reason`` is "" (cold/evicted key — the caller re-reads) or
    "fault" (the ``buffer.load`` point struck and the degrade contract
    dropped the entry: a silent miss, never a wrong answer)."""

    reason: str = ""


@dataclass
class BufferPoolEvictEvent(BufferPoolEvent):
    """One entry moved down the device→host→drop ladder: ``demoted``
    means it survived to the host tier; otherwise it was dropped."""

    demoted: bool = False


@dataclass
class ReplanEvent(HyperspaceEvent):
    """Emitted per mid-query re-plan (adaptive/feedback.py): a staged
    join boundary observed ``actual_rows`` against the reorderer's
    ``est_rows`` for the composite join key, past the configured
    ``adaptive.replan.errorThreshold`` — the query re-optimized with
    the fresh correction and re-executed (one re-plan per query)."""

    key: str = ""
    est_rows: float = 0.0
    actual_rows: int = 0
    threshold: float = 0.0


@dataclass
class AdaptiveActionEvent(HyperspaceEvent):
    """One autonomous control-plane decision (adaptive/): ``action`` is
    the namespaced verb — ``builder.build`` / ``builder.retire`` /
    ``builder.maintain`` from the background builder,
    ``admission.engage`` / ``admission.recover`` from SLO-driven
    admission — ``subject`` the index/table/mode acted on."""

    action: str = ""
    subject: str = ""
    detail: str = ""


@dataclass
class ArtifactEvent(HyperspaceEvent):
    """Base of the compiled-program artifact store events
    (artifacts/store.py). ``key_digest`` is the blob filename digest
    (the full key's stable short form); ``kind`` is "bank" | "spmd";
    ``nbytes`` the serialized payload size where the store knows it."""

    key_digest: str = ""
    kind: str = ""
    nbytes: int = 0


@dataclass
class ArtifactHitEvent(ArtifactEvent):
    """A lake blob deserialized into a live executable — a backend
    compile that did NOT happen."""


@dataclass
class ArtifactMissEvent(ArtifactEvent):
    """``reason`` is "absent" (cold/stale key, the silent-fallback
    contract) or "corrupt" (checksum/header/deserialize failure: the
    blob was evicted and served as a miss — the r14 spill-corrupt
    ladder applied to programs)."""

    reason: str = ""


@dataclass
class ArtifactPersistEvent(ArtifactEvent):
    """One executable serialized and published put-if-absent (this
    process won the publication race)."""


@dataclass
class ArtifactEvictEvent(ArtifactEvent):
    """A blob deleted to fit ``artifacts.maxBytes`` (coldest first by
    persisted usage order)."""


@dataclass
class ClusterEvent(HyperspaceEvent):
    """Base of the serving-cluster events (cluster/worker.py).
    ``worker_id`` is the emitting worker's identity — the same label
    the OpenMetrics exposition stamps on its samples."""

    worker_id: str = ""


@dataclass
class ClusterJoinEvent(ClusterEvent):
    """This worker registered its membership record and started
    heartbeating (``host``/``port`` are its transport address)."""

    host: str = ""
    port: int = 0


@dataclass
class ClusterLeaveEvent(ClusterEvent):
    """This worker removed its membership record (clean shutdown; a
    crashed worker leaves by staleness expiry instead)."""


@dataclass
class ClusterForwardEvent(ClusterEvent):
    """One routed submission shipped to its shard ``owner``. ``ok``
    False means the owner was unreachable or refused (fingerprint
    mismatch) and the query degraded to local execution; ``hit`` True
    means the owner served it from its result-cache shard without
    executing."""

    owner: str = ""
    key_digest: str = ""
    ok: bool = False
    hit: bool = False
    millis: float = 0.0


@dataclass
class ClusterBroadcastEvent(ClusterEvent):
    """One commit notice fanned out to the live peers so standing
    queries fire on every worker (``delivered`` of ``peers`` acked)."""

    table: str = ""
    peers: int = 0
    delivered: int = 0
    # Wave width: how many staged batches the notice covers (group
    # commit sends ONE notice per publication wave, not per batch).
    batches: int = 0
