"""Telemetry config keys + defaults (``hyperspace.tpu.telemetry.*``).

No reference analogue: the reference delegates observability to Spark's
listener bus; this family governs the unified tracing/metrics layer
(telemetry/trace.py, telemetry/metrics.py). Keys are read via config.py
accessors only (the lint gate rejects ad-hoc env reads).
"""

from __future__ import annotations


class TelemetryConstants:
    # Per-query span-tree tracing (telemetry/trace.py). Default ON since
    # the observability round: recording costs ~the r13 traced bar
    # (bench `observability` pins it <= ~2-3%) and the sampleRate knob
    # below bounds retention; `false` restores the hard no-op fast path
    # (byte-identical results, ~0 overhead).
    TRACE_ENABLED = "hyperspace.tpu.telemetry.trace.enabled"
    TRACE_ENABLED_DEFAULT = "true"

    # Head-sampled trace RETENTION (telemetry/trace.py): the coin is
    # flipped once per query at Session.execute; a coin-negative query
    # still records into a provisional trace (so the tail-keep override
    # can rescue exactly the unlucky ones — deadline breaches, retries,
    # degradations, anomalies, live-latency outliers) but the trace is
    # DISCARDED at completion unless kept. 1.0 (default) retains every
    # trace; serving deployments drop to ~0.1 (the bench
    # `trace_sampled_overhead_pct` arm proves <= ~2% there); 0 retains
    # only tail-kept traces.
    TRACE_SAMPLE_RATE = "hyperspace.tpu.telemetry.trace.sampleRate"
    TRACE_SAMPLE_RATE_DEFAULT = "1.0"

    # Tail-keep latency override: a coin-negative query whose wall-clock
    # exceeds this many milliseconds is retained anyway. 0 (default) =
    # adaptive — 2x the live `query.latency_ms` p99 once the window
    # holds >= 64 samples (telemetry/slo.py caches the threshold).
    TRACE_TAIL_SLOW_MS = "hyperspace.tpu.telemetry.trace.tailSlowMs"
    TRACE_TAIL_SLOW_MS_DEFAULT = "0"

    # Anomaly flight recorder (telemetry/flight_recorder.py): bounded
    # process-wide rings of retained traces + recent events + metrics
    # snapshots; `enabled=false` stops the trace ring only (the event /
    # anomaly rings are always-on and bounded).
    FLIGHT_ENABLED = "hyperspace.tpu.telemetry.flightRecorder.enabled"
    FLIGHT_ENABLED_DEFAULT = "true"
    FLIGHT_MAX_TRACES = "hyperspace.tpu.telemetry.flightRecorder.maxTraces"
    FLIGHT_MAX_TRACES_DEFAULT = "32"

    # SLO monitors (telemetry/slo.py): named objectives evaluated over a
    # sliding window of completed queries — p99 latency (ms), error
    # rate, degrade rate (each 0 = objective disarmed). Breaches emit
    # SloBreachEvent and flip Hyperspace.health(); deliberately NOT
    # wired to admission control yet (ROADMAP item 2c's sensor half).
    SLO_ENABLED = "hyperspace.tpu.telemetry.slo.enabled"
    SLO_ENABLED_DEFAULT = "true"
    SLO_P99_MS = "hyperspace.tpu.telemetry.slo.p99Ms"
    SLO_P99_MS_DEFAULT = "0"
    SLO_ERROR_RATE = "hyperspace.tpu.telemetry.slo.errorRate"
    SLO_ERROR_RATE_DEFAULT = "0"
    SLO_DEGRADE_RATE = "hyperspace.tpu.telemetry.slo.degradeRate"
    SLO_DEGRADE_RATE_DEFAULT = "0"
    SLO_WINDOW_S = "hyperspace.tpu.telemetry.slo.windowS"
    SLO_WINDOW_S_DEFAULT = "60"
    SLO_MIN_COUNT = "hyperspace.tpu.telemetry.slo.minCount"
    SLO_MIN_COUNT_DEFAULT = "5"

    # OpenMetrics HTTP exposition (telemetry/exposition.py): a localhost
    # scrape endpoint serving Hyperspace.metrics_text(). 0 (default) =
    # off; a port (or 0 passed explicitly to serve_metrics for an
    # ephemeral bind) starts the listener on 127.0.0.1 only.
    EXPORT_HTTP_PORT = "hyperspace.tpu.telemetry.export.httpPort"
    EXPORT_HTTP_PORT_DEFAULT = "0"

    # Span cap per trace: past it new spans are dropped (counted on
    # Trace.dropped) instead of growing without bound — a pathological
    # plan or a huge literal sweep must not balloon host memory.
    TRACE_MAX_SPANS = "hyperspace.tpu.telemetry.trace.maxSpans"
    TRACE_MAX_SPANS_DEFAULT = "4096"

    # Process-metrics registry feeds (telemetry/metrics.py). Governs the
    # push-side instruments (the serving latency histogram); the named
    # collectors (io / program bank / serving / ...) are snapshot pulls
    # and stay readable regardless.
    METRICS_ENABLED = "hyperspace.tpu.telemetry.metrics.enabled"
    METRICS_ENABLED_DEFAULT = "true"

    # Sliding window (seconds) of the serving frontend's live latency
    # histogram — p50/p95/p99 + QPS are computed over samples this
    # recent (Hyperspace.metrics() -> histograms["serving.latency_ms"]).
    SERVING_LATENCY_WINDOW = "hyperspace.tpu.telemetry.serving.latencyWindow"
    SERVING_LATENCY_WINDOW_DEFAULT = "60"

    # Opt-in jax.profiler capture bracketing ONE query (the first
    # executed after arming): device timelines land under `dir` for
    # TensorBoard/xprof. One-shot per process (re-arm via
    # telemetry.trace.reset_profiler, tests only) so a serving loop
    # cannot accumulate unbounded capture directories.
    PROFILER_ENABLED = "hyperspace.tpu.telemetry.profiler.enabled"
    PROFILER_ENABLED_DEFAULT = "false"
    PROFILER_DIR = "hyperspace.tpu.telemetry.profiler.dir"
    PROFILER_DIR_DEFAULT = ""
