"""Telemetry config keys + defaults (``hyperspace.tpu.telemetry.*``).

No reference analogue: the reference delegates observability to Spark's
listener bus; this family governs the unified tracing/metrics layer
(telemetry/trace.py, telemetry/metrics.py). Keys are read via config.py
accessors only (the lint gate rejects ad-hoc env reads).
"""

from __future__ import annotations


class TelemetryConstants:
    # Per-query span-tree tracing (telemetry/trace.py). Default off:
    # tracing-off is a hard no-op fast path (bench `observability` phase
    # pins the traced overhead <= ~3% and ~0 when off).
    TRACE_ENABLED = "hyperspace.tpu.telemetry.trace.enabled"
    TRACE_ENABLED_DEFAULT = "false"

    # Span cap per trace: past it new spans are dropped (counted on
    # Trace.dropped) instead of growing without bound — a pathological
    # plan or a huge literal sweep must not balloon host memory.
    TRACE_MAX_SPANS = "hyperspace.tpu.telemetry.trace.maxSpans"
    TRACE_MAX_SPANS_DEFAULT = "4096"

    # Process-metrics registry feeds (telemetry/metrics.py). Governs the
    # push-side instruments (the serving latency histogram); the named
    # collectors (io / program bank / serving / ...) are snapshot pulls
    # and stay readable regardless.
    METRICS_ENABLED = "hyperspace.tpu.telemetry.metrics.enabled"
    METRICS_ENABLED_DEFAULT = "true"

    # Sliding window (seconds) of the serving frontend's live latency
    # histogram — p50/p95/p99 + QPS are computed over samples this
    # recent (Hyperspace.metrics() -> histograms["serving.latency_ms"]).
    SERVING_LATENCY_WINDOW = "hyperspace.tpu.telemetry.serving.latencyWindow"
    SERVING_LATENCY_WINDOW_DEFAULT = "60"

    # Opt-in jax.profiler capture bracketing ONE query (the first
    # executed after arming): device timelines land under `dir` for
    # TensorBoard/xprof. One-shot per process (re-arm via
    # telemetry.trace.reset_profiler, tests only) so a serving loop
    # cannot accumulate unbounded capture directories.
    PROFILER_ENABLED = "hyperspace.tpu.telemetry.profiler.enabled"
    PROFILER_ENABLED_DEFAULT = "false"
    PROFILER_DIR = "hyperspace.tpu.telemetry.profiler.dir"
    PROFILER_DIR_DEFAULT = ""
