"""Process-wide metrics registry: one surface over every subsystem.

Before this module the engine's counters lived in five disjoint ad-hoc
dicts (io pool stats, spmd dispatch tallies, serving frontend counters,
result-cache counters, program-bank counters), each with its own
accessor and spelling. The registry unifies them:

- **counters / gauges** — push-side scalars any module may bump
  (``counter_add`` / ``gauge_set``), snapshot together;
- **histograms** — sliding-window value records with p50/p95/p99 + rate
  (the serving frontend feeds ``serving.latency_ms`` per completed
  query, giving LIVE tail latency instead of bench-only percentiles);
- **collectors** — named pull callbacks the existing stats surfaces
  register (``io`` → parallel/io.pool_stats, ``program_bank`` → the
  bank's counters, ``serving`` → the default frontend's stats); a
  snapshot invokes them all, and the legacy API methods
  (``Hyperspace.io_stats()`` etc.) now delegate here.

Naming convention (the r13 unification): cache-shaped collectors spell
their counters ``hits`` / ``misses`` / ``evictions`` — the canonical
names, with no legacy aliases (the last one, the program bank's
``stage_evictions``, was retired in the observability round). Push-side
instrument names come from the frozen telemetry/metric_names.py registry
(lint-enforced, like span and fault names).

``hyperspace.tpu.telemetry.metrics.enabled`` gates the push-side feeds
(histogram records); collectors are pull-only snapshots and stay
readable regardless. No jax imports — config.py-adjacent modules load
this at import time.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

_DEFAULT_WINDOW_S = 60.0
_MAX_SAMPLES = 32768


def percentile(ordered: List[float], frac: float) -> float:
    """Upper-index percentile over an ASCENDING-sorted list (the one
    convention every surface shares: the live histograms, the SLO
    monitors, bench's _pct)."""
    return ordered[min(int(len(ordered) * frac), len(ordered) - 1)]


class SlidingHistogram:
    """Timestamped samples over a sliding window; percentiles and rate
    are computed at snapshot time over the samples still inside it.

    The sample buffer is bounded (``max_samples``, ~546 QPS sustained
    at the default 60 s window before it saturates). When load exceeds
    that, the OLDEST in-window samples drop — the snapshot then flags
    ``truncated`` and computes the rate over the time span the retained
    samples actually cover (so QPS stays honest under exactly the load
    the histogram exists to measure); percentiles are over the retained
    (most recent) samples."""

    def __init__(self, window_s: float = _DEFAULT_WINDOW_S,
                 max_samples: int = _MAX_SAMPLES):
        self.window_s = max(float(window_s), 0.001)
        self.max_samples = max(int(max_samples), 16)
        self._lock = threading.Lock()
        self._samples: "deque[tuple]" = deque()
        self.total_count = 0
        self._cap_dropped = 0  # in-window samples lost to max_samples

    def record(self, value: float, now: Optional[float] = None) -> None:
        t = now if now is not None else time.monotonic()
        with self._lock:
            self._samples.append((t, float(value)))
            self.total_count += 1
            while len(self._samples) > self.max_samples:
                old_t, _ = self._samples.popleft()
                if old_t >= t - self.window_s:
                    self._cap_dropped += 1

    _pct = staticmethod(percentile)

    def snapshot(self, now: Optional[float] = None) -> dict:
        t = now if now is not None else time.monotonic()
        with self._lock:
            while self._samples and self._samples[0][0] < t - self.window_s:
                self._samples.popleft()
            # Truncation is CURRENT only while the buffer is still full:
            # once the window slides past the drop region the retained
            # samples cover the whole window again.
            truncated = self._cap_dropped > 0 \
                and len(self._samples) >= self.max_samples
            if not truncated:
                self._cap_dropped = 0
            span = (t - self._samples[0][0]) if self._samples else 0.0
            values = sorted(v for _, v in self._samples)
        effective = max(span, 1e-6) if truncated else self.window_s
        out = {
            "count": len(values),
            "total_count": self.total_count,
            "window_s": self.window_s,
            "qps": round(len(values) / effective, 4),
        }
        if truncated:
            out["truncated"] = True
        if values:
            out.update({
                "p50": self._pct(values, 0.50),
                "p95": self._pct(values, 0.95),
                "p99": self._pct(values, 0.99),
                "mean": sum(values) / len(values),
                "max": values[-1],
            })
        return out


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, SlidingHistogram] = {}
        self._collectors: Dict[str, Callable[[], dict]] = {}

    # -- push-side instruments ----------------------------------------

    def counter_add(self, name: str, delta: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    def gauge_set(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def histogram(self, name: str,
                  window_s: Optional[float] = None) -> SlidingHistogram:
        """The named histogram, created on first use. ``window_s=None``
        (the recording-side default) never re-windows an existing
        instrument — only an OWNER passing an explicit window does (the
        process-default serving frontend governs ``serving.latency_ms``;
        a non-default frontend recording into the shared instrument must
        not flip its window per record). The window applies at snapshot
        time, so samples survive a re-window."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = SlidingHistogram(window_s if window_s is not None
                                     else _DEFAULT_WINDOW_S)
                self._hists[name] = h
            elif window_s is not None \
                    and abs(h.window_s - float(window_s)) > 1e-9:
                h.window_s = max(float(window_s), 0.001)
            return h

    # -- pull-side collectors ------------------------------------------

    def register_collector(self, name: str,
                           fn: Callable[[], dict]) -> None:
        """Register (or replace) the named stats source; its dict is
        embedded verbatim under ``collectors[name]`` in snapshots."""
        with self._lock:
            self._collectors[name] = fn

    def collect(self, name: str) -> Optional[dict]:
        with self._lock:
            fn = self._collectors.get(name)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            # A broken stats source must not take the whole surface down.
            return {"error": "collector failed"}

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
            names = list(self._collectors)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {n: h.snapshot() for n, h in hists.items()},
            "collectors": {n: self.collect(n) for n in names},
        }


_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """THE process registry (every subsystem and every session share
    it, like the program bank)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY
