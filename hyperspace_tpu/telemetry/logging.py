"""Pluggable event logger.

Parity reference: telemetry/HyperspaceEventLogging.scala:30-66 — the sink
class is named by conf (hyperspace.eventLoggerClass), defaulting to a no-op;
instances are cached per class name.
"""

from __future__ import annotations

import importlib
from typing import Dict, Optional

from ..exceptions import HyperspaceException
from .events import HyperspaceEvent


class EventLogger:
    def log_event(self, event: HyperspaceEvent) -> None:
        raise NotImplementedError


class NoOpEventLogger(EventLogger):
    def log_event(self, event: HyperspaceEvent) -> None:
        pass


_logger_cache: Dict[str, EventLogger] = {}


def get_logger(class_name: Optional[str]) -> EventLogger:
    if not class_name:
        return NoOpEventLogger()
    if class_name not in _logger_cache:
        module_name, _, cls_name = class_name.rpartition(".")
        try:
            cls = getattr(importlib.import_module(module_name), cls_name)
        except (ImportError, AttributeError) as e:
            raise HyperspaceException(
                f"Cannot load event logger class {class_name}") from e
        _logger_cache[class_name] = cls()
    return _logger_cache[class_name]


class HyperspaceEventLogging:
    """Mixin: emit events through the conf-selected logger."""

    def log_event(self, session, event: HyperspaceEvent) -> None:
        get_logger(session.hs_conf.event_logger_class()).log_event(event)


def emit_distributed_fallback(session, where: str, reason: str) -> None:
    """Record that a distributed path degraded to single-device execution
    (VERDICT r2 weak #3/#5: degradation must be observable). One shared
    emission point for every fallback site."""
    from .events import DistributedFallbackEvent
    get_logger(session.hs_conf.event_logger_class()).log_event(
        DistributedFallbackEvent(
            message=f"{where} fell back to single-device execution",
            where=where, reason=reason))
