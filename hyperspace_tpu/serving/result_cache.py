"""Two-tier, byte-budgeted query result cache.

The serving-layer memo over `Session.execute`: executed results are kept
keyed by :class:`fingerprint.ResultCacheKey` (canonical plan fingerprint +
source signature + index log versions + config hash) so a repeated query
is served without re-planning or re-executing, and any change that could
alter the answer changes the key — stale entries become unreachable, they
are never "expired".

Tiers (the HBM-residency design of execution/index_cache.py, extended):

  device  — the executed Table as-is (device-resident columns); LRU
            victims DEMOTE to the host tier instead of being dropped.
  host    — `Table.to_host()` copies (numpy-backed, HBM-free); LRU
            victims here are evicted for good.

Admission is decided by the caller (execute_with_cache) from observed
execution time + the optimized plan's input-byte estimate: results that
are cheap to recompute are not worth residency.

Thread safety: one lock around both tiers — the serving pattern is many
query threads sharing a session.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Optional, Tuple

from .fingerprint import (ResultCacheKey, compute_key,
                          estimate_recompute_bytes, normalize)

TIER_DEVICE = "device"
TIER_HOST = "host"


def _to_device(table):
    """Upload a host-resident result into HBM with ONE batched device_put
    (shape-class execution trims padded final results at the host
    boundary, so most results arrive numpy-backed). The device tier must
    hold REAL device buffers — otherwise its byte budget would charge
    host RAM against HBM and 'demotion' would be a no-op copy."""
    import jax
    import numpy as np

    from ..execution.columnar import Column
    from ..execution.columnar import Table as _Table
    if not any(isinstance(c.data, np.ndarray)
               for c in table.columns.values()):
        return table
    arrays = {}
    for n, c in table.columns.items():
        arrays[(n, "d")] = c.data
        if c.validity is not None:
            arrays[(n, "v")] = c.validity
    dev = jax.device_put(arrays)
    return _Table({n: Column(c.dtype, dev[(n, "d")],
                             dev[(n, "v")] if c.validity is not None
                             else None, c.dictionary)
                   for n, c in table.columns.items()},
                  bucket_order=table.bucket_order)


def table_nbytes(table) -> int:
    """One byte-accounting for every residency cache in the system
    (execution/index_cache.py owns it; imported lazily because the
    execution package pulls in jax, and `import hyperspace_tpu` — which
    loads this module through config.py — must stay light)."""
    from ..execution.index_cache import table_nbytes as impl
    return impl(table)


class ResultCache:
    def __init__(self, device_bytes: int, host_bytes: int, on_evict=None):
        self.device_bytes = device_bytes
        self.host_bytes = host_bytes
        # on_evict(tier, nbytes, demoted): observability hook; MAY be
        # called while the lock is held, so it must not reenter the
        # cache.
        self._on_evict = on_evict
        self._lock = threading.Lock()
        self._device: "OrderedDict[ResultCacheKey, Tuple[object, int]]" = \
            OrderedDict()
        self._host: "OrderedDict[ResultCacheKey, Tuple[object, int]]" = \
            OrderedDict()
        self._device_nbytes = 0
        self._host_nbytes = 0
        self.hits = 0
        self.device_hits = 0
        self.host_hits = 0
        self.misses = 0
        self.admissions = 0
        self.rejections = 0
        self.demotions = 0
        self.evictions = 0

    # ------------------------------------------------------------------
    # Lookup.
    # ------------------------------------------------------------------

    def get(self, key: ResultCacheKey):
        """(table, tier) on hit — device tier first — else None."""
        with self._lock:
            entry = self._device.get(key)
            if entry is not None:
                self._device.move_to_end(key)
                self.hits += 1
                self.device_hits += 1
                return entry[0], TIER_DEVICE
            entry = self._host.get(key)
            if entry is not None:
                self._host.move_to_end(key)
                self.hits += 1
                self.host_hits += 1
                return entry[0], TIER_HOST
            self.misses += 1
            return None

    def peek(self, key: ResultCacheKey) -> Optional[str]:
        """Tier holding ``key`` (no counter/LRU effect) — explain's probe."""
        with self._lock:
            if key in self._device:
                return TIER_DEVICE
            if key in self._host:
                return TIER_HOST
            return None

    # ------------------------------------------------------------------
    # Admission / eviction.
    # ------------------------------------------------------------------

    def put(self, key: ResultCacheKey, table) -> Optional[str]:
        """Store an admitted result; returns the tier it landed in, or
        None when it exceeds every budget (too large to hold).

        Device→host transfers (``to_host``) happen OUTSIDE the lock —
        one demotion cascade must not stall every concurrent get()
        probe behind a multi-hundred-MB device fetch."""
        nbytes = table_nbytes(table)
        if nbytes <= self.device_bytes:
            table = _to_device(table)  # outside the lock
            with self._lock:
                self._drop(key)
                self._device[key] = (table, nbytes)
                self._device_nbytes += nbytes
                self.admissions += 1
                victims = self._pop_device_victims()
            self._demote(victims)
            return TIER_DEVICE
        if nbytes <= self.host_bytes:
            host_copy = table.to_host()  # outside the lock
            with self._lock:
                self._drop(key)
                self._host[key] = (host_copy, nbytes)
                self._host_nbytes += nbytes
                self.admissions += 1
                self._evict_host_overflow()
            return TIER_HOST
        return None

    def note_rejected(self) -> None:
        with self._lock:
            self.rejections += 1

    def _drop(self, key: ResultCacheKey) -> None:
        old = self._device.pop(key, None)
        if old is not None:
            self._device_nbytes -= old[1]
        old = self._host.pop(key, None)
        if old is not None:
            self._host_nbytes -= old[1]

    def _pop_device_victims(self) -> list:
        """Under the lock: pop LRU device entries past the budget.
        Victims that fit the host budget are returned for out-of-lock
        demotion (a concurrent get() during the handoff misses them —
        a benign recompute, never a stale serve); the rest are evicted
        for good right here."""
        victims = []
        while self._device_nbytes > self.device_bytes \
                and len(self._device) > 1:
            vk, (vt, vn) = self._device.popitem(last=False)
            self._device_nbytes -= vn
            if vn <= self.host_bytes:
                self.demotions += 1
                victims.append((vk, vt, vn))
            else:
                self.evictions += 1
                if self._on_evict is not None:
                    self._on_evict(TIER_DEVICE, vn, False)
        return victims

    def _demote(self, victims: list) -> None:
        for vk, vt, vn in victims:
            host_copy = vt.to_host()  # outside the lock
            with self._lock:
                if vk in self._device or vk in self._host:
                    continue  # re-admitted during the handoff; keep that
                self._host[vk] = (host_copy, vn)
                self._host_nbytes += vn
                self._evict_host_overflow()
            if self._on_evict is not None:
                self._on_evict(TIER_DEVICE, vn, True)

    def _evict_host_overflow(self) -> None:
        # Caller holds the lock. Host victims are gone for good.
        while self._host_nbytes > self.host_bytes and len(self._host) > 1:
            _, (_, vn) = self._host.popitem(last=False)
            self._host_nbytes -= vn
            self.evictions += 1
            if self._on_evict is not None:
                self._on_evict(TIER_HOST, vn, False)

    def clear(self) -> None:
        with self._lock:
            self._device.clear()
            self._host.clear()
            self._device_nbytes = 0
            self._host_nbytes = 0

    # ------------------------------------------------------------------
    # Observability.
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "device_hits": self.device_hits,
                "host_hits": self.host_hits,
                "misses": self.misses,
                "admissions": self.admissions,
                "rejections": self.rejections,
                "demotions": self.demotions,
                "evictions": self.evictions,
                "device_entries": len(self._device),
                "host_entries": len(self._host),
                "device_nbytes": self._device_nbytes,
                "host_nbytes": self._host_nbytes,
            }


def build_result_cache(session) -> Optional[ResultCache]:
    """Session hook (wired through CacheWithTransform on the serving conf
    string, so budget changes rebuild — and thereby clear — the cache)."""
    conf = session.hs_conf
    if not conf.result_cache_enabled():
        return None

    def on_evict(tier: str, nbytes: int, demoted: bool) -> None:
        from ..telemetry.events import ResultCacheEvictionEvent
        from ..telemetry.logging import get_logger
        get_logger(conf.event_logger_class()).log_event(
            ResultCacheEvictionEvent(
                message=f"result cache evicted {nbytes} bytes from "
                        f"{tier} tier" + (" (demoted)" if demoted else ""),
                tier=tier, nbytes=nbytes, demoted=demoted))

    return ResultCache(conf.result_cache_device_bytes(),
                       conf.result_cache_host_bytes(), on_evict)


def execute_with_cache(session, cache: ResultCache, plan):
    """Session.execute body when the result cache is on: probe, serve on
    hit (skipping plan rewrite AND execution), otherwise execute and run
    the admission policy. Events mirror the action-event convention."""
    from ..telemetry import span_names as SN
    from ..telemetry import trace as _trace
    from ..telemetry.events import (ResultCacheAdmitEvent,
                                    ResultCacheHitEvent,
                                    ResultCacheMissEvent)
    from ..telemetry.logging import get_logger

    # The cache-lookup span covers key computation + probe (NOT the
    # recompute on a miss): a hit trace and a cold trace differ exactly
    # here — hit attr flips, and the cold trace grows the optimize/exec
    # spans below.
    with _trace.span(SN.CACHE_LOOKUP) as sp:
        norm = normalize(plan)
        key = compute_key(session, plan, normalized=norm)
        hit = cache.get(key) if key is not None else None
        if sp is not None:
            sp.attrs["cacheable"] = key is not None
            sp.attrs["hit"] = hit is not None
            if hit is not None:
                sp.attrs["tier"] = hit[1]
    if key is None:
        # Uncacheable shape: execute as if the cache did not exist.
        return session._run_optimized(
            session.optimize(norm, _pre_normalized=True))
    logger = get_logger(session.hs_conf.event_logger_class())
    if hit is not None:
        table, tier = hit
        logger.log_event(ResultCacheHitEvent(
            message=f"result served from cache ({tier} tier)",
            key_digest=key.digest(), tier=tier,
            nbytes=table_nbytes(table)))
        return table
    logger.log_event(ResultCacheMissEvent(
        message="result cache miss", key_digest=key.digest()))
    optimized = session.optimize(norm, _pre_normalized=True)
    t0 = time.perf_counter()
    table = session._run_optimized(optimized)
    elapsed = time.perf_counter() - t0
    conf = session.hs_conf
    admit = elapsed >= conf.result_cache_min_compute_seconds() and \
        estimate_recompute_bytes(optimized) >= \
        conf.result_cache_min_input_bytes()
    tier = cache.put(key, table) if admit else None
    if tier is not None:
        logger.log_event(ResultCacheAdmitEvent(
            message=f"result admitted to cache ({tier} tier)",
            key_digest=key.digest(), tier=tier,
            nbytes=table_nbytes(table)))
    else:
        cache.note_rejected()
    return table
