"""Three-tier, byte-budgeted query result cache.

The serving-layer memo over `Session.execute`: executed results are kept
keyed by :class:`fingerprint.ResultCacheKey` (canonical plan fingerprint +
source signature + index log versions + config hash) so a repeated query
is served without re-planning or re-executing, and any change that could
alter the answer changes the key — stale entries become unreachable, they
are never "expired".

Tiers (the HBM-residency design of execution/index_cache.py, extended):

  device  — the executed Table as-is (device-resident columns); LRU
            victims DEMOTE to the host tier instead of being dropped.
  host    — `Table.to_host()` copies (numpy-backed, HBM-free); LRU
            victims demote to the disk-spill tier when one is
            configured, else are evicted for good.
  spill   — optional (``serving.result_cache.spillDir``): length-framed
            pickled host tables on disk up to ``spillBytes``; victims
            here are gone. Read-back is CRASH-SAFE by contract: a
            truncated or corrupt spill file is a MISS (entry evicted,
            file deleted, ResultCacheMissEvent reason="spill-corrupt")
            — never a propagated exception mid-query, never a wrong
            answer (robustness layer; fault point
            ``result_cache.spill_read`` proves it under injection).

Admission is decided by the caller (execute_with_cache) from observed
execution time + the optimized plan's input-byte estimate: results that
are cheap to recompute are not worth residency. A device_put failure on
device-tier admission degrades the entry to the host tier (fault point
``result_cache.device_put``) — residency is an optimization and must
never fail the query that produced the result.

Thread safety: one lock around all tiers — the serving pattern is many
query threads sharing a session. Spill file reads/writes and
device→host transfers happen OUTSIDE the lock.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from collections import OrderedDict
from typing import Optional, Tuple

from ..robustness import fault_names as _fltn
from ..robustness import faults as _faults
from .fingerprint import (ResultCacheKey, compute_key,
                          estimate_recompute_bytes, normalize)

TIER_DEVICE = "device"
TIER_HOST = "host"
TIER_SPILL = "spill"

# Sentinel: a spill file a concurrent drop/clear unlinked mid-probe —
# a plain miss, never corruption (see _spill_read).
_GONE = object()


def _to_device(table):
    """Upload a host-resident result into HBM with ONE batched device_put
    (shape-class execution trims padded final results at the host
    boundary, so most results arrive numpy-backed). The device tier must
    hold REAL device buffers — otherwise its byte budget would charge
    host RAM against HBM and 'demotion' would be a no-op copy."""
    import jax
    import numpy as np

    from ..execution.columnar import Column
    from ..execution.columnar import Table as _Table
    _faults.fault_point(_fltn.RESULT_CACHE_DEVICE_PUT)
    if not any(isinstance(c.data, np.ndarray)
               for c in table.columns.values()):
        return table
    arrays = {}
    for n, c in table.columns.items():
        arrays[(n, "d")] = c.data
        if c.validity is not None:
            arrays[(n, "v")] = c.validity
    dev = jax.device_put(arrays)
    return _Table({n: Column(c.dtype, dev[(n, "d")],
                             dev[(n, "v")] if c.validity is not None
                             else None, c.dictionary)
                   for n, c in table.columns.items()},
                  bucket_order=table.bucket_order)


def table_nbytes(table) -> int:
    """One byte-accounting for every residency cache in the system
    (execution/index_cache.py owns it; imported lazily because the
    execution package pulls in jax, and `import hyperspace_tpu` — which
    loads this module through config.py — must stay light)."""
    from ..execution.index_cache import table_nbytes as impl
    return impl(table)


class ResultCache:
    def __init__(self, device_bytes: int, host_bytes: int, on_evict=None,
                 spill_dir: Optional[str] = None, spill_bytes: int = 0,
                 on_spill_corrupt=None):
        self.device_bytes = device_bytes
        self.host_bytes = host_bytes
        self.spill_dir = spill_dir or None
        if self.spill_dir is not None:
            try:
                os.makedirs(self.spill_dir, exist_ok=True)
            except OSError:
                self.spill_dir = None  # unusable dir: run two-tier
        self.spill_bytes = spill_bytes if self.spill_dir else 0
        # on_evict(tier, nbytes, demoted): observability hook; MAY be
        # called while the lock is held, so it must not reenter the
        # cache. on_spill_corrupt(nbytes): a corrupt/truncated spill
        # entry was evicted and served as a miss.
        self._on_evict = on_evict
        self._on_spill_corrupt = on_spill_corrupt
        self._lock = threading.Lock()
        self._device: "OrderedDict[ResultCacheKey, Tuple[object, int]]" = \
            OrderedDict()
        self._host: "OrderedDict[ResultCacheKey, Tuple[object, int]]" = \
            OrderedDict()
        # key -> (file path, nbytes); the table lives on disk only.
        self._spill: "OrderedDict[ResultCacheKey, Tuple[str, int]]" = \
            OrderedDict()
        self._device_nbytes = 0
        self._host_nbytes = 0
        self._spill_nbytes = 0
        self._spill_seq = 0
        self.hits = 0
        self.device_hits = 0
        self.host_hits = 0
        self.spill_hits = 0
        self.misses = 0
        self.admissions = 0
        self.rejections = 0
        self.demotions = 0
        self.evictions = 0
        self.spill_corruptions = 0

    # ------------------------------------------------------------------
    # Lookup.
    # ------------------------------------------------------------------

    def get(self, key: ResultCacheKey):
        """(table, tier) on hit — device tier first — else None."""
        with self._lock:
            entry = self._device.get(key)
            if entry is not None:
                self._device.move_to_end(key)
                self.hits += 1
                self.device_hits += 1
                return entry[0], TIER_DEVICE
            entry = self._host.get(key)
            if entry is not None:
                self._host.move_to_end(key)
                self.hits += 1
                self.host_hits += 1
                return entry[0], TIER_HOST
            spilled = self._spill.get(key)
            if spilled is None:
                self.misses += 1
                return None
            self._spill.move_to_end(key)
            path, nbytes = spilled
        # Disk read-back OUTSIDE the lock (a multi-MB read must not
        # stall concurrent probes). Corruption/truncation — torn by a
        # crash mid-spill, bit-rotted — is a MISS: evict the entry,
        # drop the file, recompute downstream. A file a CONCURRENT
        # drop/clear unlinked mid-probe is a plain miss, NOT corruption
        # (the counter must stay a real disk-health signal).
        table = self._spill_read(path)
        if table is None or table is _GONE:
            with self._lock:
                old = self._spill.pop(key, None)
                if old is not None:
                    self._spill_nbytes -= old[1]
                self.misses += 1
                # Only the thread that actually evicted the entry
                # counts the corruption — concurrent probes of one
                # corrupt file must not inflate the disk-health signal.
                corrupt = table is None and old is not None
                if corrupt:
                    self.spill_corruptions += 1
            if corrupt:
                self._unlink(path)
                _faults.note(spill_corruptions=1)
                if self._on_spill_corrupt is not None:
                    self._on_spill_corrupt(nbytes)
            return None
        # Promote back to the host tier: a hot spilled entry must not
        # pay disk + deserialize on every repeat hit once host pressure
        # subsides (the device→host demotion path, in reverse). Host
        # victims the promotion displaces spill as usual.
        host_victims = []
        with self._lock:
            self.hits += 1
            self.spill_hits += 1
            still = self._spill.pop(key, None)
            if still is not None:
                self._spill_nbytes -= still[1]
                if key not in self._device and key not in self._host:
                    self._host[key] = (table, still[1])
                    self._host_nbytes += still[1]
                    host_victims = self._pop_host_victims()
        if still is not None:
            self._unlink(path)
        self._spill_store(host_victims)
        return table, TIER_SPILL

    def peek(self, key: ResultCacheKey) -> Optional[str]:
        """Tier holding ``key`` (no counter/LRU effect) — explain's probe."""
        with self._lock:
            if key in self._device:
                return TIER_DEVICE
            if key in self._host:
                return TIER_HOST
            if key in self._spill:
                return TIER_SPILL
            return None

    # ------------------------------------------------------------------
    # Admission / eviction.
    # ------------------------------------------------------------------

    def put(self, key: ResultCacheKey, table) -> Optional[str]:
        """Store an admitted result; returns the tier it landed in, or
        None when it exceeds every budget (too large to hold).

        Device→host transfers (``to_host``) and spill file writes happen
        OUTSIDE the lock — one demotion cascade must not stall every
        concurrent get() probe behind a multi-hundred-MB device fetch.
        A device_put failure (fault point ``result_cache.device_put``)
        degrades the entry to the host tier: residency must never fail
        the query that computed the result."""
        nbytes = table_nbytes(table)
        if nbytes <= self.device_bytes:
            try:
                dev_table = _to_device(table)  # outside the lock
            except Exception:
                if not _faults.degrade_enabled():
                    raise  # fail-loud debugging mode
                _faults.note(degraded_device_put=1)
                dev_table = None  # degrade to the host tier below
            if dev_table is not None:
                with self._lock:
                    self._drop(key)
                    self._device[key] = (dev_table, nbytes)
                    self._device_nbytes += nbytes
                    self.admissions += 1
                    victims = self._pop_device_victims()
                self._demote(victims)
                return TIER_DEVICE
        if nbytes <= self.host_bytes:
            host_copy = table.to_host()  # outside the lock
            with self._lock:
                self._drop(key)
                self._host[key] = (host_copy, nbytes)
                self._host_nbytes += nbytes
                self.admissions += 1
                host_victims = self._pop_host_victims()
            self._spill_store(host_victims)
            return TIER_HOST
        return None

    def note_rejected(self) -> None:
        with self._lock:
            self.rejections += 1

    def _drop(self, key: ResultCacheKey) -> None:
        old = self._device.pop(key, None)
        if old is not None:
            self._device_nbytes -= old[1]
        old = self._host.pop(key, None)
        if old is not None:
            self._host_nbytes -= old[1]
        old = self._spill.pop(key, None)
        if old is not None:
            self._spill_nbytes -= old[1]
            self._unlink(old[0])

    def _pop_device_victims(self) -> list:
        """Under the lock: pop LRU device entries past the budget.
        Victims that fit the host budget are returned for out-of-lock
        demotion (a concurrent get() during the handoff misses them —
        a benign recompute, never a stale serve); the rest are evicted
        for good right here."""
        victims = []
        while self._device_nbytes > self.device_bytes \
                and len(self._device) > 1:
            vk, (vt, vn) = self._device.popitem(last=False)
            self._device_nbytes -= vn
            if vn <= self.host_bytes:
                self.demotions += 1
                victims.append((vk, vt, vn))
            else:
                self.evictions += 1
                if self._on_evict is not None:
                    self._on_evict(TIER_DEVICE, vn, False)
        return victims

    def _demote(self, victims: list) -> None:
        spill_victims = []
        for vk, vt, vn in victims:
            host_copy = vt.to_host()  # outside the lock
            with self._lock:
                if vk in self._device or vk in self._host:
                    continue  # re-admitted during the handoff; keep that
                self._host[vk] = (host_copy, vn)
                self._host_nbytes += vn
                spill_victims.extend(self._pop_host_victims())
            if self._on_evict is not None:
                self._on_evict(TIER_DEVICE, vn, True)
        self._spill_store(spill_victims)

    def _pop_host_victims(self) -> list:
        """Under the lock: pop LRU host entries past the budget. With a
        spill tier configured, victims that fit its budget return for
        out-of-lock spilling; otherwise they are evicted for good."""
        victims = []
        while self._host_nbytes > self.host_bytes and len(self._host) > 1:
            vk, (vt, vn) = self._host.popitem(last=False)
            self._host_nbytes -= vn
            if self.spill_dir is not None and vn <= self.spill_bytes:
                # Counted as a demotion only once the spill WRITE lands
                # (_spill_store) — a failed write is an eviction, and
                # counting both would skew the stats.
                victims.append((vk, vt, vn))
            else:
                self.evictions += 1
                if self._on_evict is not None:
                    self._on_evict(TIER_HOST, vn, False)
        return victims

    # ------------------------------------------------------------------
    # Disk-spill tier.
    # ------------------------------------------------------------------

    def _spill_path(self) -> str:
        with self._lock:
            self._spill_seq += 1
            seq = self._spill_seq
        return os.path.join(self.spill_dir, f"rc-{os.getpid()}-{seq}.bin")

    def _spill_store(self, victims: list) -> None:
        """Write host-tier victims to disk (outside the lock). A write
        failure (disk full, unwritable dir) evicts the victim for good —
        spilling is an optimization and must never fail the query."""
        for vk, vt, vn in victims:
            path = self._spill_path()
            try:
                payload = pickle.dumps(vt, protocol=pickle.HIGHEST_PROTOCOL)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    # Length framing: read-back can tell a torn tail
                    # (crash mid-spill) from a complete payload.
                    f.write(len(payload).to_bytes(8, "big"))
                    f.write(payload)
                os.replace(tmp, path)
            except Exception:
                with self._lock:
                    self.evictions += 1
                if self._on_evict is not None:
                    self._on_evict(TIER_HOST, vn, False)
                continue
            overflow = []
            with self._lock:
                if vk in self._device or vk in self._host \
                        or vk in self._spill:
                    stale = True  # re-admitted during the handoff
                else:
                    stale = False
                    self._spill[vk] = (path, vn)
                    self._spill_nbytes += vn
                    # The write already landed (it precedes this lock):
                    # the demotion counts here, in the same acquisition.
                    self.demotions += 1
                    while self._spill_nbytes > self.spill_bytes \
                            and len(self._spill) > 1:
                        _, (op, on) = self._spill.popitem(last=False)
                        self._spill_nbytes -= on
                        self.evictions += 1
                        overflow.append((op, on))
            if stale:
                self._unlink(path)
                continue
            for op, on in overflow:
                self._unlink(op)
                if self._on_evict is not None:
                    self._on_evict(TIER_SPILL, on, False)
            if self._on_evict is not None:
                self._on_evict(TIER_HOST, vn, True)

    def _spill_read(self, path: str):
        """Deserialize one spilled entry; None on ANY corruption-shaped
        failure — the crash-safe read-back contract (fault point
        ``result_cache.spill_read`` injects failures here). ``_GONE``
        when the file vanished (a concurrent drop/clear won the race):
        a miss, but never counted as corruption."""
        try:
            _faults.fault_point(_fltn.RESULT_CACHE_SPILL_READ)
        except Exception:
            return None
        try:
            with open(path, "rb") as f:
                header = f.read(8)
                if len(header) != 8:
                    return None
                expected = int.from_bytes(header, "big")
                payload = f.read()
        except FileNotFoundError:
            return _GONE
        except Exception:
            return None
        try:
            if len(payload) != expected:
                return None  # torn tail: crash mid-spill
            return pickle.loads(payload)
        except Exception:
            return None

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def clear(self) -> None:
        with self._lock:
            self._device.clear()
            self._host.clear()
            spilled = list(self._spill.values())
            self._spill.clear()
            self._device_nbytes = 0
            self._host_nbytes = 0
            self._spill_nbytes = 0
        for path, _ in spilled:
            self._unlink(path)

    # ------------------------------------------------------------------
    # Observability.
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "device_hits": self.device_hits,
                "host_hits": self.host_hits,
                "spill_hits": self.spill_hits,
                "misses": self.misses,
                "admissions": self.admissions,
                "rejections": self.rejections,
                "demotions": self.demotions,
                "evictions": self.evictions,
                "spill_corruptions": self.spill_corruptions,
                "device_entries": len(self._device),
                "host_entries": len(self._host),
                "spill_entries": len(self._spill),
                "device_nbytes": self._device_nbytes,
                "host_nbytes": self._host_nbytes,
                "spill_nbytes": self._spill_nbytes,
            }


def build_result_cache(session) -> Optional[ResultCache]:
    """Session hook (wired through CacheWithTransform on the serving conf
    string, so budget changes rebuild — and thereby clear — the cache)."""
    conf = session.hs_conf
    if not conf.result_cache_enabled():
        return None

    def on_evict(tier: str, nbytes: int, demoted: bool) -> None:
        from ..telemetry.events import ResultCacheEvictionEvent
        from ..telemetry.logging import get_logger
        get_logger(conf.event_logger_class()).log_event(
            ResultCacheEvictionEvent(
                message=f"result cache evicted {nbytes} bytes from "
                        f"{tier} tier" + (" (demoted)" if demoted else ""),
                tier=tier, nbytes=nbytes, demoted=demoted))

    def on_spill_corrupt(nbytes: int) -> None:
        from ..telemetry.events import ResultCacheMissEvent
        from ..telemetry.logging import get_logger
        get_logger(conf.event_logger_class()).log_event(
            ResultCacheMissEvent(
                message=("corrupt/truncated spill entry evicted; "
                         "serving as a miss"),
                tier=TIER_SPILL, nbytes=nbytes, reason="spill-corrupt"))

    # The constructor owns spill-dir creation and the unusable-dir
    # fallback (run two-tier); pass the raw conf value through.
    return ResultCache(conf.result_cache_device_bytes(),
                       conf.result_cache_host_bytes(), on_evict,
                       spill_dir=conf.result_cache_spill_dir() or None,
                       spill_bytes=conf.result_cache_spill_bytes(),
                       on_spill_corrupt=on_spill_corrupt)


def execute_with_cache(session, cache: ResultCache, plan):
    """Session.execute body when the result cache is on: probe, serve on
    hit (skipping plan rewrite AND execution), otherwise execute and run
    the admission policy. Events mirror the action-event convention."""
    from ..telemetry import span_names as SN
    from ..telemetry import trace as _trace
    from ..telemetry.events import (ResultCacheAdmitEvent,
                                    ResultCacheHitEvent,
                                    ResultCacheMissEvent)
    from ..telemetry.logging import get_logger

    # The cache-lookup span covers key computation + probe (NOT the
    # recompute on a miss): a hit trace and a cold trace differ exactly
    # here — hit attr flips, and the cold trace grows the optimize/exec
    # spans below.
    with _trace.span(SN.CACHE_LOOKUP) as sp:
        norm = normalize(plan)
        key = compute_key(session, plan, normalized=norm)
        hit = cache.get(key) if key is not None else None
        if sp is not None:
            sp.attrs["cacheable"] = key is not None
            sp.attrs["hit"] = hit is not None
            if hit is not None:
                sp.attrs["tier"] = hit[1]
    if key is None:
        # Uncacheable shape: execute as if the cache did not exist.
        return session._run_optimized(
            session.optimize(norm, _pre_normalized=True))
    logger = get_logger(session.hs_conf.event_logger_class())
    if hit is not None:
        table, tier = hit
        logger.log_event(ResultCacheHitEvent(
            message=f"result served from cache ({tier} tier)",
            key_digest=key.digest(), tier=tier,
            nbytes=table_nbytes(table)))
        return table
    logger.log_event(ResultCacheMissEvent(
        message="result cache miss", key_digest=key.digest()))
    optimized = session.optimize(norm, _pre_normalized=True)
    t0 = time.perf_counter()
    table = session._run_optimized(optimized)
    elapsed = time.perf_counter() - t0
    conf = session.hs_conf
    admit = elapsed >= conf.result_cache_min_compute_seconds() and \
        estimate_recompute_bytes(optimized) >= \
        conf.result_cache_min_input_bytes()
    tier = cache.put(key, table) if admit else None
    if tier is not None:
        logger.log_event(ResultCacheAdmitEvent(
            message=f"result admitted to cache ({tier} tier)",
            key_digest=key.digest(), tier=tier,
            nbytes=table_nbytes(table)))
    else:
        cache.note_rejected()
    return table
