"""Process-wide shared compiled-program bank.

r07's shape-class layer already funnels every fused stage (predicate
masks, arithmetic projections) through ONE wrapper per program STRUCTURE
with literals as runtime arguments; jax then compiles one executable per
(structure, shape-class vector). Those wrappers used to live in an
anonymous module-level dict inside ops/kernels.py — shared across
sessions by accident of process layout, unbounded in visibility, and
invisible to observability.

This module lifts them into an explicit registry — THE program bank of
the serving tier: keyed on (stage fingerprint, shape-class vector),
size-bounded (LRU over stage entries; evicting one stage drops its jit
wrapper and every executable under it), and instrumented. Because the
bank is process-wide, tenant A's warm-up pays tenant B's compiles: two
sessions executing the same warm workload share every program, which is
what makes the serving frontend's multi-session fan-in cheap.

Accounting model: a *stage* is one jitted wrapper (one structure key);
a *program* is one (stage, shape-class vector) pair — the unit XLA
actually compiles. ``lookup`` records a **miss** the first time a
(stage, shape vector) pair is seen (a backend compile is expected right
after) and a **hit** on every later sighting. ``ProgramBankMissEvent``
is emitted per new program, ``ProgramBankHitEvent`` once per program on
its FIRST reuse (bounded event volume; the counters carry the totals).

The jit wrappers themselves are constructed by the CALLER (ops/kernels
passes a factory) — scripts/lint.py pins ``jax.jit`` to the
instrumented kernel modules, and this module stays importable without
jax (config.py pulls in the serving package).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Optional, Tuple


class ProgramBank:
    def __init__(self, max_stages: int = 1024):
        self.max_stages = max_stages
        self._lock = threading.Lock()
        # stage key -> (callable, {shape vector: reuse count})
        self._stages: "OrderedDict[tuple, Tuple[Callable, dict]]" = \
            OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.program_count = 0

    def lookup(self, stage_key: tuple, shape_vec: tuple,
               factory: Callable[[], Callable]) -> Callable:
        """The jitted wrapper for ``stage_key``, created via ``factory``
        on first sighting. ``shape_vec`` (the shape-class vector of the
        arguments about to be passed) drives hit/miss accounting only —
        jax's own cache keys executables under the wrapper."""
        from ..telemetry import span_names as SN
        from ..telemetry import trace as _trace
        first_reuse = False
        with _trace.span(SN.BANK_LOOKUP) as sp, self._lock:
            entry = self._stages.get(stage_key)
            if entry is None:
                while len(self._stages) >= self.max_stages:
                    _, (_, shapes_seen) = self._stages.popitem(last=False)
                    self.evictions += 1
                    self.program_count -= len(shapes_seen)
                with _trace.span(SN.BANK_COMPILE):
                    fn, degraded = self._build(factory)
                self.misses += 1
                if degraded:
                    # Bank-compile degradation ladder (robustness
                    # layer): the wrapper that failed once is handed
                    # back UNREGISTERED — this execution runs the
                    # uncached eager path, and the next lookup tries
                    # the bank again from scratch.
                    if sp is not None:
                        sp.attrs["hit"] = False
                        sp.attrs["degraded"] = True
                    return fn
                # Artifact seam (r20): when the active session enables
                # the persistent store, the freshly built jit wrapper
                # registers wrapped for AOT export/import; off = the
                # wrapper registers untouched (byte-identical, asserted
                # in tests/test_artifacts.py). SPMD stages pass through
                # — MeshProgram owns its own compile seam.
                fn = self._maybe_aot(stage_key, fn)
                # shape vector -> times this program was looked up again
                # after registration (0 = registered, never reused yet).
                self._stages[stage_key] = (fn, {shape_vec: 0})
                self.program_count += 1
                hit = False
            else:
                self._stages.move_to_end(stage_key)
                fn, shapes_seen = entry
                if shape_vec in shapes_seen:
                    self.hits += 1
                    shapes_seen[shape_vec] += 1
                    first_reuse = shapes_seen[shape_vec] == 1
                    hit = True
                else:
                    shapes_seen[shape_vec] = 0
                    self.misses += 1
                    self.program_count += 1
                    hit = False
            if sp is not None:
                sp.attrs["hit"] = hit
        self._emit(stage_key, shape_vec, hit=hit, first_reuse=first_reuse)
        return fn

    @staticmethod
    def _maybe_aot(stage_key: tuple, fn: Callable) -> Callable:
        """The artifact store's registration hook, failure-proofed: the
        bank must keep serving (unwrapped) even if the artifacts
        package cannot (mis-configured store root, import trouble)."""
        try:
            from ..artifacts.manager import maybe_wrap_stage
            return maybe_wrap_stage(stage_key, fn)
        except Exception:
            return fn

    @staticmethod
    def _build(factory: Callable[[], Callable]):
        """Run the caller's wrapper factory behind the ``bank.compile``
        fault point. A failure (injected or real) degrades to ONE
        immediate rebuild whose result is returned UNCACHED — the eager
        path — unless degradation is off (or the rebuild fails too, a
        persistent error that must surface). Returns (fn, degraded)."""
        from ..robustness import fault_names as _fltn
        from ..robustness import faults as _faults
        try:
            _faults.fault_point(_fltn.BANK_COMPILE)
            return factory(), False
        except Exception:
            if not _faults.degrade_enabled():
                raise
            fn = factory()  # persistent failures raise here, loudly
            _faults.note(degraded_bank_compile=1)
            return fn, True

    # ------------------------------------------------------------------
    # Observability.
    # ------------------------------------------------------------------

    def _emit(self, stage_key: tuple, shape_vec: tuple, hit: bool,
              first_reuse: bool) -> None:
        """One MissEvent per new program; HitEvents would be per-lookup
        spam, so only a program's FIRST reuse emits one. Needs an active
        query context to find a logger; bankless paths stay silent."""
        if hit and not first_reuse:
            return
        from .context import active_context
        ctx = active_context()
        if ctx is None or ctx.session is None:
            return
        try:
            from ..telemetry.events import (ProgramBankHitEvent,
                                            ProgramBankMissEvent)
            from ..telemetry.logging import get_logger
            from ..util import hashing
            digest = hashing.md5_hex(repr(stage_key))[:12]
            cls = ProgramBankHitEvent if hit else ProgramBankMissEvent
            get_logger(ctx.session.hs_conf.event_logger_class()).log_event(
                cls(message=("program bank " + ("reuse" if hit else "new")
                             + f" stage {digest} shapes {shape_vec}"),
                    stage_digest=digest, shape_vec=list(shape_vec),
                    hits=self.hits, misses=self.misses))
        except Exception:
            pass  # observability must never fail an execution

    def stats(self) -> dict:
        """Counters follow the registry-wide ``hits``/``misses``/
        ``evictions`` spelling (telemetry/metrics.py naming convention;
        the pre-r13 ``stage_evictions`` alias was retired in the
        observability round — ``evictions`` is the one name).
        ``stages_by_kind`` breaks the resident stages down by their
        key's kind tag ("fused-predicate", "fused-predicate-sweep",
        "fused-region", "spmd", ...) so the fusion bench/metrics can see
        how much of the bank is whole-plan regions vs per-stage
        programs."""
        with self._lock:
            kinds: dict = {}
            for k in self._stages:
                tag = k[0] if isinstance(k, tuple) and k \
                    and isinstance(k[0], str) else "other"
                kinds[tag] = kinds.get(tag, 0) + 1
            return {
                "stages": len(self._stages),
                "programs": self.program_count,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "stages_by_kind": kinds,
            }

    def clear(self) -> None:
        """Drop every wrapper (tests; a clear() re-traces every hot
        stage — never on a serving path)."""
        with self._lock:
            self._stages.clear()
            self.program_count = 0


_BANK: Optional[ProgramBank] = None
_BANK_LOCK = threading.Lock()


def get_bank() -> ProgramBank:
    global _BANK
    if _BANK is None:
        with _BANK_LOCK:
            if _BANK is None:
                _BANK = ProgramBank()
    return _BANK


def _bank_stats() -> dict:
    return get_bank().stats()


# The bank's counters are a named collector in the process metrics
# registry (telemetry/metrics.py): Hyperspace.metrics() and
# serving_stats() read the SAME dict through it.
from ..telemetry import metrics as _metrics  # noqa: E402

_metrics.get_registry().register_collector("program_bank", _bank_stats)
