"""Cross-query literal batching: N literal-variant queries, one invocation.

The serving observation (ROADMAP item 1, the Flare idiom): a high-QPS
workload is dominated by *literal sweeps* — many users issuing the same
query shape with different constants (dates, keys, thresholds). r07
already compiles such variants to ONE program with literals as runtime
arguments; what still costs N× is everything around the program: N scans
of the same source and N separate mask evaluations. This module
collapses both:

1. **Template matching** (:func:`plan_template`): a literal-abstracted
   serialization of the canonical (normalized) plan. Two plans batch
   together iff their templates are byte-identical — same operators,
   same columns, same expression structure — and only Filter-condition
   literals differ. Anything the serializer does not fully understand
   keeps its concrete repr, so differing unsupported shapes simply never
   match (conservative by construction).

2. **SweepContext**: installed around the members' executions by the
   serving frontend. It memoizes
   - *shared scans* — the first member's source read is reused by every
     other member (row-group pushdown is disabled under a sweep: the
     full predicate re-applies on device, so reading the superset is
     byte-identical, and one shared table beats N pruned reads);
   - *stacked masks* — the first member to reach a swept Filter
     evaluates ALL members' predicates in ONE vmapped fused-predicate
     invocation (literal matrix padded to a power-of-two batch class so
     batch sizes share programs); later members index their row out of
     the memo. This is the "N queries → 1 padded batched invocation".

Per-member results stay byte-identical to serial execution: each member
keeps its own survivor count, its own downstream pipeline, and its own
result-cache key. Unsupported positions (non-fusable predicates,
IndexScan children, chunked-scan sources) silently fall back to normal
per-member execution inside the same batch.

No jax at module import time (config.py loads the serving package); the
vmapped program itself is built in ops/kernels.py (the lint-sanctioned
jit site) through the program bank.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Callable, List, Optional, Tuple

from ..plan import expr as E
from ..plan.nodes import Filter, LogicalPlan

_SWEEP: contextvars.ContextVar = contextvars.ContextVar(
    "hst_literal_sweep", default=None)


class Unbatchable(Exception):
    """Plan shape the template serializer cannot soundly abstract."""


_COMPARISONS = (E.EqualTo, E.LessThan, E.LessThanOrEqual, E.GreaterThan,
                E.GreaterThanOrEqual)


def condition_template(e: E.Expr, lits: Optional[list] = None
                       ) -> Tuple[str, list]:
    """(literal-abstracted template, literal values) for a filter
    condition. Only the shapes the fused-predicate path can sweep are
    abstracted (Col-vs-Lit comparisons, In over literals, under
    And/Or/Not); everything else serializes concretely — differing
    concrete parts make templates differ, which simply prevents
    batching. The literal's python type rides in the template (it is
    part of the compiled program's structure)."""
    if lits is None:
        lits = []
    if isinstance(e, (E.And, E.Or)):
        lt, _ = condition_template(e.left, lits)
        rt, _ = condition_template(e.right, lits)
        op = "And" if isinstance(e, E.And) else "Or"
        return f"{op}({lt},{rt})", lits
    if isinstance(e, E.Not):
        ct, _ = condition_template(e.child, lits)
        return f"Not({ct})", lits
    if isinstance(e, E.In) and isinstance(e.value, E.Col) \
            and all(isinstance(o, E.Lit) for o in e.options):
        tags = []
        for o in e.options:
            tags.append(type(o.value).__name__)
            lits.append(o.value)
        return (f"In({e.value.column};{len(e.options)};"
                f"{','.join(tags)})"), lits
    if isinstance(e, _COMPARISONS):
        left, right = e.left, e.right
        flipped = False
        if isinstance(left, E.Lit) and not isinstance(right, E.Lit):
            left, right = right, left
            flipped = True
        if isinstance(left, E.Col) and isinstance(right, E.Lit):
            from ..execution.evaluator import _op_name
            lits.append(right.value)
            return (f"{_op_name(e, flipped)}({left.column};"
                    f"{type(right.value).__name__})"), lits
    return repr(e), lits


def plan_template(plan: LogicalPlan) -> Tuple[str, List[E.Expr]]:
    """(template string, swept Filter conditions in DFS order) for a
    normalized plan. Raises :class:`Unbatchable` for plans containing
    nodes the result-cache serializer does not understand (same
    soundness bar: unknown operators cannot be proven literal-only
    variants)."""
    from .fingerprint import _node_detail
    parts: List[str] = []
    conditions: List[E.Expr] = []

    def walk(p: LogicalPlan) -> None:
        if isinstance(p, Filter):
            lits: list = []
            t, _ = condition_template(p.condition, lits)
            parts.append(f"(Filter[{t}]")
            if lits:
                conditions.append(p.condition)
        else:
            detail = _node_detail(p)
            if detail is None:
                raise Unbatchable(p.node_name)
            parts.append("(" + detail)
        for c in p.children:
            walk(c)
        parts.append(")")

    walk(plan)
    return "".join(parts), conditions


def template_key(session, plan: LogicalPlan) -> Optional[Tuple[str, str]]:
    """Batch-compatibility key for a normalized plan: the literal-
    abstracted template plus the session's config hash (two sessions
    whose conf could steer planning differently must not share a
    sweep). None when the plan cannot be batched at all."""
    from ..util import hashing
    from .fingerprint import config_hash
    try:
        template, conditions = plan_template(plan)
    except Unbatchable:
        return None
    if not conditions:
        return None  # nothing literal-variant to sweep
    return hashing.md5_hex(template), config_hash(session)


def _padded_batch(n: int) -> int:
    """Power-of-two batch class: batches of 5..8 members share one
    compiled sweep program at batch dimension 8."""
    b = 1
    while b < n:
        b *= 2
    return b


class SweepContext:
    """Shared execution state for one batch of literal-variant plans.

    Built by the frontend from the members' NORMALIZED plans (their
    per-position Filter conditions); activated per member via
    :func:`use_sweep` around the member's normal ``Session.execute``.
    The executor and evaluator consult it through
    :func:`active_sweep`."""

    def __init__(self, member_conditions: List[List[E.Expr]]):
        # member_conditions[m] = swept conditions of member m, DFS order.
        self.size = len(member_conditions)
        self.padded_size = _padded_batch(self.size)
        positions = len(member_conditions[0]) if member_conditions else 0
        # _conditions[p][m] = member m's condition at position p.
        self._conditions: List[List[E.Expr]] = [
            [member_conditions[m][p] for m in range(self.size)]
            for p in range(positions)]
        # Template -> position; a template claimed by two positions is
        # ambiguous and disabled (both fall back to per-member eval).
        self._by_template = {}
        disabled = set()
        for p in range(positions):
            t, _ = condition_template(self._conditions[p][0])
            if t in self._by_template:
                disabled.add(t)
            else:
                self._by_template[t] = p
        for t in disabled:
            self._by_template.pop(t, None)
        self.member = -1  # set by use_sweep
        self._lock = threading.Lock()
        self._tables: dict = {}      # scan share key -> Table
        self._shared_ids: set = set()
        self._masks: dict = {}       # (position, id(table)) -> (masks, counts)
        # Stats surfaced through ServingBatchEvent / serving_stats.
        self.shared_scans = 0
        self.shared_scan_hits = 0
        self.sweep_invocations = 0
        self.sweep_hits = 0
        self.fallbacks = 0

    # ------------------------------------------------------------------
    # Shared scans (executor hook).
    # ------------------------------------------------------------------

    def shared_scan(self, key, compute: Callable):
        """The scanned Table for ``key``, read once per batch. The read
        runs under the member's own session scope (io attribution goes
        to the member that happened to read; later members hit)."""
        with self._lock:
            table = self._tables.get(key)
            if table is not None:
                self.shared_scan_hits += 1
                return table
        table = compute()  # outside the lock: reads can be slow
        with self._lock:
            existing = self._tables.get(key)
            if existing is not None:
                self.shared_scan_hits += 1
                return existing
            self._tables[key] = table
            self._shared_ids.add(id(table))
            self.shared_scans += 1
        return table

    # ------------------------------------------------------------------
    # Stacked masks (evaluator hook).
    # ------------------------------------------------------------------

    def try_masked_count(self, table, condition, key, builder, cols):
        """(member's mask row, member's survivor count) from the batched
        invocation, or None when this condition/table combination cannot
        be swept (caller falls back to the normal fused path)."""
        if id(table) not in self._shared_ids or self.member < 0:
            return None
        t, _ = condition_template(condition)
        pos = self._by_template.get(t)
        if pos is None:
            return None
        registered = self._conditions[pos][self.member]
        if repr(registered) != repr(condition):
            # A rewrite changed the member's predicate after template
            # registration: the stacked literals would be stale.
            with self._lock:
                self.fallbacks += 1
            return None
        memo_key = (pos, id(table))
        with self._lock:
            memo = self._masks.get(memo_key)
        if memo is None:
            memo = self._compute_stacked(memo_key, table, key, builder,
                                         cols)
            if memo is None:
                return None
        else:
            with self._lock:
                self.sweep_hits += 1
        masks, counts = memo
        import jax.lax
        import jax.numpy as jnp
        mask = jax.lax.dynamic_index_in_dim(
            masks, jnp.int32(self.member), axis=0, keepdims=False)
        return mask, int(counts[self.member])

    def _compute_stacked(self, memo_key, table, key, builder, cols):
        import numpy as np

        from ..execution.evaluator import predicate_slots
        from ..ops import kernels
        pos = memo_key[0]
        ref_spec = None
        rows = []
        for cond_m in self._conditions[pos]:
            slots = predicate_slots(table, cond_m)
            if slots is None or \
                    (ref_spec is not None and slots[0] != ref_spec):
                with self._lock:
                    self.fallbacks += 1
                return None
            if ref_spec is None:
                ref_spec = slots[0]
            rows.append(slots[1])
        # Pad member rows to the batch class by repeating row 0 (the
        # padded rows' masks are computed and discarded).
        while len(rows) < self.padded_size:
            rows.append(rows[0])
        slots_n = len(rows[0])
        from ..execution.evaluator import predicate_slot_dtypes
        names = sorted(set(self._conditions[pos][0].references))
        slot_np = predicate_slot_dtypes(
            ref_spec, [table.column(nm).dtype for nm in names], slots_n)
        lit_matrix = tuple(
            np.asarray([rows[m][j] for m in range(self.padded_size)],
                       dtype=slot_np[j])
            for j in range(slots_n))
        masks, counts = kernels.run_fused_predicate_sweep(
            key, builder, cols, lit_matrix, table.num_rows,
            batch=self.padded_size)
        memo = (masks, np.asarray(counts))
        with self._lock:
            self._masks[memo_key] = memo
            self.sweep_invocations += 1
        # Process-lifetime tally in the metrics registry (the frontend's
        # batch counters reset with the frontend; this one survives it).
        from ..telemetry import metrics as _metrics
        _metrics.get_registry().counter_add("serving.sweep_invocations")
        return memo

    def stats(self) -> dict:
        with self._lock:
            return {
                "members": self.size,
                "positions": len(self._conditions),
                "shared_scans": self.shared_scans,
                "shared_scan_hits": self.shared_scan_hits,
                "sweep_invocations": self.sweep_invocations,
                "sweep_hits": self.sweep_hits,
                "fallbacks": self.fallbacks,
            }


@contextlib.contextmanager
def use_sweep(sweep: Optional[SweepContext], member: int):
    """Activate ``sweep`` for one member's execution. Members run
    sequentially on one worker, so the member index is a plain
    attribute; the contextvar keeps concurrent OTHER batches (other
    workers) isolated."""
    if sweep is None:
        yield
        return
    token = _SWEEP.set(sweep)
    sweep.member = member
    try:
        yield
    finally:
        sweep.member = -1
        _SWEEP.reset(token)


def active_sweep() -> Optional[SweepContext]:
    return _SWEEP.get()
