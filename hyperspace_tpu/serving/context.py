"""Explicit per-query execution context.

Before the serving tier, everything one query needed at runtime was
implicit per-``Session`` state: the result-cache handle was re-probed
from the session, advisor capture re-read the conf, the parallel-io
layer attributed reads to a session-wide pile, and the executor wrote
join cardinalities straight onto session attributes. That works for one
thread per session; a process-wide frontend multiplexing many sessions
over shared worker threads needs the per-query state to be an explicit
object it can build, hand to a worker, and inspect afterwards.

:class:`QueryContext` is that object. ``Session.execute`` creates one
per call (or accepts one from the serving frontend), activates it on a
contextvar for the duration of the execution, and every layer below —
the executor, the result cache, the parallel-io pool, the program bank
— reads the ACTIVE context instead of reaching for session attributes:

- ``result_cache``: resolved ONCE at context creation — the frontend's
  cross-session shared cache when the query came through the serving
  tier, else the session's own. Mid-query conf flips cannot swap the
  cache out from under an execution.
- ``capture``: the advisor-capture decision, pinned at creation for the
  same reason.
- ``io``: per-query read counters (tasks, bytes, seconds, waits) that
  ``parallel/io.py`` credits to the active context — so a multi-tenant
  frontend can attribute I/O to the query that caused it, not just to
  the process-wide pile.
- ``record_join_actual``: the executor's observed-join-cardinality
  write, routed through the context to the owning session's bounded
  store (locked — worker threads share sessions).

The contextvar (not a thread-local) matters: the prefetch producer and
the serving workers enter copied contexts (``contextvars.copy_context``),
so attribution follows the QUERY across threads, exactly like the io
session scope it generalizes.

No jax imports here — sessions (and config.py) must stay importable
without touching the execution stack.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
from typing import Optional

# Process-wide monotonically increasing query ids (itertools.count is
# atomic under the GIL; the lock guards readers that want a stable
# snapshot semantics anyway).
_QUERY_IDS = itertools.count(1)

_CONTEXT: contextvars.ContextVar = contextvars.ContextVar(
    "hst_query_context", default=None)

_IO_COUNTER_KEYS = ("read_tasks", "read_bytes", "read_seconds",
                    "wait_seconds", "prefetch_items",
                    "pool_hits", "pool_misses", "pool_bytes_saved")


class QueryContext:
    """Everything one query execution needs, made explicit."""

    def __init__(self, session, result_cache=None, capture: Optional[bool]
                 = None, client: str = "", query_id: Optional[int] = None,
                 deadline_s: Optional[float] = None):
        self.session = session
        self.query_id = query_id if query_id is not None \
            else next(_QUERY_IDS)
        self.client = client
        self.created_s = time.perf_counter()
        # Resolved handles (pinned for the query's lifetime).
        self.result_cache = result_cache
        self.capture = bool(capture) if capture is not None else False
        # Cooperative deadline (robustness layer): an ABSOLUTE
        # perf_counter stamp, or None. Checked at the executor's
        # per-node stage boundary, the io wait loops, and SPMD dispatch
        # via :func:`check_deadline`; expiry raises the typed
        # QueryDeadlineError and emits ONE QueryCancelledEvent.
        self.deadline_s = deadline_s
        self._cancel_emitted = False
        # Unified tracing (telemetry/trace.py): ``trace`` is the Trace
        # this query's spans landed in (set by query_trace once tracing
        # is on); ``trace_parent`` is an optional (Trace, Span) pair a
        # literal-sweep batch hands in so member queries nest under ONE
        # shared sweep span instead of opening their own roots.
        self.trace = None
        self.trace_parent = None
        # ``trace_force`` (explain_analyze): open and RETAIN this
        # query's trace regardless of telemetry.trace.{enabled,
        # sampleRate}. ``degraded``: a robustness degradation ladder
        # fired during this query (faults.note sets it; the SLO
        # monitor's degrade-rate objective reads it).
        self.trace_force = False
        self.degraded = False
        # A SWEEP-member attempt whose failure the frontend's member
        # ladder will rescue with a standalone rerun: its error must
        # not land in the SLO window (the rerun records the query's
        # REAL outcome — counting both would show errors for queries
        # every client saw succeed).
        self.slo_suppress_error = False
        # Per-query io counters; the lock is for cross-thread writers
        # (prefetch producers run in a copied context on another thread).
        self._io_lock = threading.Lock()
        self._io = {k: 0 if not k.endswith("seconds") else 0.0
                    for k in _IO_COUNTER_KEYS}

    # ------------------------------------------------------------------
    # Construction.
    # ------------------------------------------------------------------

    @classmethod
    def for_session(cls, session, shared_cache=None, client: str = "",
                    deadline_s: Optional[float] = None,
                    query_id: Optional[int] = None) -> "QueryContext":
        """The per-query context ``Session.execute`` builds when none was
        handed in. ``shared_cache`` (the serving frontend's cross-session
        result cache) takes precedence over the session's own; an
        explicit ``deadline_s`` (the frontend's submit-time deadline)
        over the session's ``robustness.deadlineMs`` conf; an explicit
        ``query_id`` (allocated at SUBMIT time by the frontend, so
        queue-expired cancellations correlate) over a fresh one."""
        cache = shared_cache if shared_cache is not None \
            else session.result_cache
        if deadline_s is None:
            ms = session.hs_conf.robustness_deadline_ms()
            if ms > 0:
                deadline_s = time.perf_counter() + ms / 1000.0
        return cls(session, result_cache=cache,
                   capture=session.hs_conf.advisor_capture_enabled(),
                   client=client, deadline_s=deadline_s,
                   query_id=query_id)

    @contextlib.contextmanager
    def activate(self):
        token = _CONTEXT.set(self)
        try:
            yield self
        finally:
            _CONTEXT.reset(token)

    # ------------------------------------------------------------------
    # Per-query io attribution (parallel/io.py credits the active ctx).
    # ------------------------------------------------------------------

    def note_io(self, **deltas) -> None:
        with self._io_lock:
            for k, v in deltas.items():
                if k in self._io:
                    self._io[k] += v

    def io_stats(self) -> dict:
        with self._io_lock:
            return dict(self._io)

    # ------------------------------------------------------------------
    # Executor write-backs (session stores, locked — workers share
    # sessions).
    # ------------------------------------------------------------------

    def record_join_actual(self, condition_repr: str, rows: int) -> None:
        record_join_actual(self.session, condition_repr, rows)


_JOIN_ACTUALS_MAX = 256


def _leaf_identity(leaf) -> str:
    """A leaf identity STABLE across the optimizer's own rewrites: the
    join reorderer records estimate keys BEFORE index substitution and
    partition pruning, the executors record actuals AFTER, and the two
    must pair. A Scan's ``partition_base_path`` survives ``with_files``
    (pruning replaces root_paths with the kept file list but copies the
    partition base); an IndexScan's log-entry source rootPaths are the
    original Scan's directories, abspath'd at create time. Both reduce
    a rewritten leaf to the source directory the pre-rewrite Scan
    carried."""
    rel = getattr(leaf, "relation", None)
    if rel is not None:  # Scan
        base = getattr(rel, "partition_base_path", None)
        if base:
            return str(base)
        paths = getattr(rel, "root_paths", None) or []
        return str(paths[0]) if paths else leaf.node_name
    entry = getattr(leaf, "index_entry", None)
    if entry is not None:  # IndexScan
        try:
            paths = entry.relations[0].rootPaths
            if paths:
                return str(paths[0])
        except Exception:
            pass
        return f"index:{getattr(entry, 'name', '?')}"
    return leaf.node_name


def join_side_signature(plan) -> str:
    """Order-insensitive signature of one join input: the sorted,
    rewrite-stable identities of its scan leaves."""
    try:
        leaves = plan.collect_leaves()
    except Exception:
        return getattr(plan, "node_name", "?")
    return "+".join(sorted(_leaf_identity(leaf) for leaf in leaves))


def join_actual_key(condition, left, right) -> str:
    """THE estimate/actual pairing key for one executed inner join:
    condition repr qualified by both input-side signatures, so two
    table pairs sharing a condition TEXT (``a.k = b.k`` joined from
    different sources) never collide in the bounded actuals store or in
    the adaptive correction store. Written identically by the join
    reorderer (estimates) and the staged/fused/SPMD executors
    (actuals)."""
    return (f"{condition!r} @ {join_side_signature(left)} >< "
            f"{join_side_signature(right)}")


def record_join_actual(session, condition_repr: str, rows: int) -> None:
    """Locked LRU write-back of an executed inner join's observed output
    rows onto the owning session (the ONE copy of the bound/eviction
    policy — shared by the serving QueryContext and the executor's
    contextless fallback). Keys are the composite
    :func:`join_actual_key` strings. When the adaptive feedback loop is
    on, the observation also feeds the process-wide correction store."""
    actuals = getattr(session, "_join_actuals", None)
    lock = getattr(session, "_join_actuals_lock", None)
    if actuals is None or lock is None:
        return
    with lock:
        actuals[condition_repr] = int(rows)
        actuals.move_to_end(condition_repr)
        while len(actuals) > _JOIN_ACTUALS_MAX:
            actuals.popitem(last=False)
    try:
        if session.hs_conf.adaptive_feedback_enabled():
            from ..adaptive import feedback as _feedback
            _feedback.get_store().observe(session, condition_repr,
                                          int(rows))
    except Exception:
        pass  # feedback accounting must never fail a query


def next_query_id() -> int:
    """Allocate one process-wide query id eagerly (the serving frontend
    stamps it at SUBMIT time, so events emitted before execution — the
    queue-expired cancellation — still correlate)."""
    return next(_QUERY_IDS)


def active_context() -> Optional[QueryContext]:
    """The QueryContext of the in-flight execution, if any."""
    return _CONTEXT.get()


# ---------------------------------------------------------------------------
# Cooperative per-query deadline (robustness layer).
# ---------------------------------------------------------------------------

def deadline_remaining_s() -> Optional[float]:
    """Seconds until the active query's deadline (may be negative), or
    None when no context / no deadline — the io wait loops use this to
    bound their condition waits."""
    ctx = _CONTEXT.get()
    if ctx is None or ctx.deadline_s is None:
        return None
    return ctx.deadline_s - time.perf_counter()


def check_deadline(where: str = "") -> None:
    """The cooperative cancellation point: a hard no-op (one contextvar
    read, one attribute check) unless the active query carries a
    deadline AND it has expired — then the typed QueryDeadlineError
    aborts the execution at this boundary. Instrumented at the
    executor's per-node stage entry, the pooled-read gather, the
    prefetch consumer wait, retry backoffs, and SPMD dispatch."""
    ctx = _CONTEXT.get()
    if ctx is None or ctx.deadline_s is None:
        return
    if time.perf_counter() < ctx.deadline_s:
        return
    _trip_deadline(ctx, where)


def _trip_deadline(ctx: QueryContext, where: str) -> None:
    elapsed_ms = (time.perf_counter() - ctx.created_s) * 1000.0
    with ctx._io_lock:
        first = not ctx._cancel_emitted
        ctx._cancel_emitted = True
    if first:
        try:  # trace attribution: flag the span the cancellation hit
            from ..telemetry import trace as _trace
            pair = _trace.active()
            if pair is not None and pair[1] is not None:
                pair[1].attrs["deadline_exceeded"] = True
                pair[1].attrs["cancelled_at"] = where
        except Exception:
            pass
    deadline_cancel(ctx.session, ctx.query_id, where, elapsed_ms,
                    emit=first)


def deadline_cancel(session, query_id: int, where: str,
                    elapsed_ms: float, emit: bool = True) -> None:
    """THE cancellation protocol, shared by the mid-query trip above
    and the serving frontend's queue fast-fail: bump the process
    counter, emit ONE QueryCancelledEvent (``emit=False`` on re-trips
    of an already-cancelled query), raise the typed error."""
    from ..exceptions import QueryDeadlineError
    if emit:
        from ..robustness import faults as _faults
        _faults.note(deadline_cancellations=1)
        try:
            if session is not None:
                from ..telemetry.events import QueryCancelledEvent
                from ..telemetry.logging import get_logger
                get_logger(
                    session.hs_conf.event_logger_class()
                ).log_event(QueryCancelledEvent(
                    message=(f"query {query_id} cancelled at "
                             f"{where or 'boundary'}: deadline expired "
                             f"after {elapsed_ms:.1f} ms"),
                    query_id=query_id, where=where,
                    elapsed_ms=round(elapsed_ms, 3)))
        except Exception:
            pass  # observability must never mask the cancellation
    raise QueryDeadlineError(
        f"query {query_id} exceeded its deadline "
        f"({elapsed_ms:.1f} ms elapsed; cancelled at "
        f"{where or 'stage boundary'})")
