"""Explicit per-query execution context.

Before the serving tier, everything one query needed at runtime was
implicit per-``Session`` state: the result-cache handle was re-probed
from the session, advisor capture re-read the conf, the parallel-io
layer attributed reads to a session-wide pile, and the executor wrote
join cardinalities straight onto session attributes. That works for one
thread per session; a process-wide frontend multiplexing many sessions
over shared worker threads needs the per-query state to be an explicit
object it can build, hand to a worker, and inspect afterwards.

:class:`QueryContext` is that object. ``Session.execute`` creates one
per call (or accepts one from the serving frontend), activates it on a
contextvar for the duration of the execution, and every layer below —
the executor, the result cache, the parallel-io pool, the program bank
— reads the ACTIVE context instead of reaching for session attributes:

- ``result_cache``: resolved ONCE at context creation — the frontend's
  cross-session shared cache when the query came through the serving
  tier, else the session's own. Mid-query conf flips cannot swap the
  cache out from under an execution.
- ``capture``: the advisor-capture decision, pinned at creation for the
  same reason.
- ``io``: per-query read counters (tasks, bytes, seconds, waits) that
  ``parallel/io.py`` credits to the active context — so a multi-tenant
  frontend can attribute I/O to the query that caused it, not just to
  the process-wide pile.
- ``record_join_actual``: the executor's observed-join-cardinality
  write, routed through the context to the owning session's bounded
  store (locked — worker threads share sessions).

The contextvar (not a thread-local) matters: the prefetch producer and
the serving workers enter copied contexts (``contextvars.copy_context``),
so attribution follows the QUERY across threads, exactly like the io
session scope it generalizes.

No jax imports here — sessions (and config.py) must stay importable
without touching the execution stack.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
from typing import Optional

# Process-wide monotonically increasing query ids (itertools.count is
# atomic under the GIL; the lock guards readers that want a stable
# snapshot semantics anyway).
_QUERY_IDS = itertools.count(1)

_CONTEXT: contextvars.ContextVar = contextvars.ContextVar(
    "hst_query_context", default=None)

_IO_COUNTER_KEYS = ("read_tasks", "read_bytes", "read_seconds",
                    "wait_seconds", "prefetch_items")


class QueryContext:
    """Everything one query execution needs, made explicit."""

    def __init__(self, session, result_cache=None, capture: Optional[bool]
                 = None, client: str = "", query_id: Optional[int] = None):
        self.session = session
        self.query_id = query_id if query_id is not None \
            else next(_QUERY_IDS)
        self.client = client
        self.created_s = time.perf_counter()
        # Resolved handles (pinned for the query's lifetime).
        self.result_cache = result_cache
        self.capture = bool(capture) if capture is not None else False
        # Unified tracing (telemetry/trace.py): ``trace`` is the Trace
        # this query's spans landed in (set by query_trace once tracing
        # is on); ``trace_parent`` is an optional (Trace, Span) pair a
        # literal-sweep batch hands in so member queries nest under ONE
        # shared sweep span instead of opening their own roots.
        self.trace = None
        self.trace_parent = None
        # Per-query io counters; the lock is for cross-thread writers
        # (prefetch producers run in a copied context on another thread).
        self._io_lock = threading.Lock()
        self._io = {k: 0 if not k.endswith("seconds") else 0.0
                    for k in _IO_COUNTER_KEYS}

    # ------------------------------------------------------------------
    # Construction.
    # ------------------------------------------------------------------

    @classmethod
    def for_session(cls, session, shared_cache=None,
                    client: str = "") -> "QueryContext":
        """The per-query context ``Session.execute`` builds when none was
        handed in. ``shared_cache`` (the serving frontend's cross-session
        result cache) takes precedence over the session's own."""
        cache = shared_cache if shared_cache is not None \
            else session.result_cache
        return cls(session, result_cache=cache,
                   capture=session.hs_conf.advisor_capture_enabled(),
                   client=client)

    @contextlib.contextmanager
    def activate(self):
        token = _CONTEXT.set(self)
        try:
            yield self
        finally:
            _CONTEXT.reset(token)

    # ------------------------------------------------------------------
    # Per-query io attribution (parallel/io.py credits the active ctx).
    # ------------------------------------------------------------------

    def note_io(self, **deltas) -> None:
        with self._io_lock:
            for k, v in deltas.items():
                if k in self._io:
                    self._io[k] += v

    def io_stats(self) -> dict:
        with self._io_lock:
            return dict(self._io)

    # ------------------------------------------------------------------
    # Executor write-backs (session stores, locked — workers share
    # sessions).
    # ------------------------------------------------------------------

    def record_join_actual(self, condition_repr: str, rows: int) -> None:
        record_join_actual(self.session, condition_repr, rows)


_JOIN_ACTUALS_MAX = 256


def record_join_actual(session, condition_repr: str, rows: int) -> None:
    """Locked LRU write-back of an executed inner join's observed output
    rows onto the owning session (the ONE copy of the bound/eviction
    policy — shared by the serving QueryContext and the executor's
    contextless fallback)."""
    actuals = getattr(session, "_join_actuals", None)
    lock = getattr(session, "_join_actuals_lock", None)
    if actuals is None or lock is None:
        return
    with lock:
        actuals[condition_repr] = int(rows)
        actuals.move_to_end(condition_repr)
        while len(actuals) > _JOIN_ACTUALS_MAX:
            actuals.popitem(last=False)


def active_context() -> Optional[QueryContext]:
    """The QueryContext of the in-flight execution, if any."""
    return _CONTEXT.get()
