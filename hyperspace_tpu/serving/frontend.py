"""Process-wide concurrent serving frontend.

The "millions of users" entry point (ROADMAP item 1): one
:class:`ServingFrontend` accepts queries from MANY independent sessions
and executes them on a bounded worker pool, sharing everything that is
safe to share across tenants:

- **compiled programs** — process-wide through the program bank
  (serving/program_bank.py): tenant A's warm-up pays tenant B's
  compiles;
- **results** — a frontend-owned cross-session
  :class:`~..serving.result_cache.ResultCache`; the r06 keys already pin
  the plan fingerprint, source signatures, index log versions, and the
  session's config hash, so an entry computed for one session can be
  served to another session iff recomputing there would be byte-identical
  — no new invalidation machinery needed;
- **literal sweeps** — queued queries whose canonical plans differ only
  in Filter literals (serving/batcher.py) execute as ONE batched
  invocation over a shared scan.

Admission control keeps the tier honest under overload: a bounded
submission queue (``serving.queueDepth``) plus an in-flight input-byte
budget (``serving.admission.maxBytes``); rejected submissions raise
:class:`~..exceptions.ServingRejectedError` immediately (load shedding,
the hook the AQP degradation tier of ROADMAP item 5b will land behind).

Threading: workers come from the dedicated serving pool in
parallel/io.py (the lint-sanctioned thread module) — NOT the reader
pool, so a serving query can still fan its reads out underneath. Each
submission snapshots ``contextvars.copy_context()`` and each execution
runs inside it, so the io/session contextvars and the QueryContext
propagate into worker threads exactly as they do on the caller's thread.

Config: ``hyperspace.tpu.serving.*`` via config.py accessors, read live
from the frontend's governing conf at each decision point.
"""

from __future__ import annotations

import contextvars
import threading
import time
import weakref
from collections import deque
from typing import List, Optional

from ..exceptions import HyperspaceException, ServingRejectedError
from . import batcher
from .context import QueryContext


class PendingQuery:
    """Handle returned by :meth:`ServingFrontend.submit`."""

    def __init__(self, query_id: int, client: str, estimated_bytes: int):
        self.query_id = query_id
        self.client = client
        self.estimated_bytes = estimated_bytes
        self.submitted_s = time.perf_counter()
        self.started_s: Optional[float] = None
        self.completed_s: Optional[float] = None
        self.batched = False
        self.batch_size = 0
        self.context: Optional[QueryContext] = None
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._done_cb = None
        self._cb_lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    def on_done(self, cb) -> None:
        """Invoke ``cb(self)`` exactly once when the query completes —
        on the worker thread that finishes it, or immediately if it
        already did. The standing-query delivery hook
        (streaming/subscriptions.py); callbacks must be quick and must
        not raise. The lock makes the register/finish handoff
        exactly-once under the 8-thread pool."""
        with self._cb_lock:
            if not self._event.is_set():
                self._done_cb = cb
                return
        try:
            cb(self)
        except Exception:
            pass

    def result(self, timeout: Optional[float] = None):
        """The executed Table; blocks until completion. Raises the
        query's own error if it failed, TimeoutError on timeout."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} not done after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def latency_s(self) -> Optional[float]:
        if self.completed_s is None:
            return None
        return self.completed_s - self.submitted_s

    def _finish(self, result=None, error: Optional[BaseException] = None
                ) -> None:
        self.completed_s = time.perf_counter()
        self._result = result
        self._error = error
        with self._cb_lock:
            self._event.set()
            cb = self._done_cb
            self._done_cb = None
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass  # a delivery hook must never fail the query


class _Entry:
    __slots__ = ("plan", "norm", "session", "ctx", "pending", "batch_key",
                 "deadline_s", "approx")

    def __init__(self, plan, norm, session, ctx, pending, batch_key,
                 deadline_s=None, approx=False):
        self.plan = plan
        self.norm = norm
        self.session = session
        self.ctx = ctx                # contextvars.Context snapshot
        self.pending = pending
        self.batch_key = batch_key    # None = never batchable
        self.deadline_s = deadline_s  # absolute perf_counter, or None
        self.approx = approx          # SLO degrade: try approximate tier


class ServingFrontend:
    """One instance serves the whole process; sessions are clients."""

    def __init__(self, session):
        # The governing session: its conf carries the serving.* family
        # and its event logger receives the frontend's telemetry.
        self._session = session
        self._hs_conf = session.hs_conf
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: "deque[_Entry]" = deque()
        self._active_workers = 0
        self._inflight_bytes = 0
        # Cross-session result cache: rebuilt — and thereby cleared —
        # when the governing serving.result_cache.* budgets change
        # (CacheWithTransform carries its own lock, so a rebuild never
        # contends with the submit/_drain admission path).
        from ..config import CacheWithTransform
        from .result_cache import build_result_cache
        self._shared_cache_holder = CacheWithTransform(
            self._hs_conf.result_cache_conf_string,
            lambda raw: build_result_cache(self._session))
        self._stats = {
            "submitted": 0, "admitted": 0, "rejected": 0,
            "completed": 0, "failed": 0,
            "batches": 0, "batched_queries": 0,
            "sweep_invocations": 0, "shared_scans": 0,
            "shared_scan_hits": 0,
        }
        # Standing queries (streaming/subscriptions.py): plans that
        # re-fire through this frontend on every streaming commit.
        from ..streaming.subscriptions import SubscriptionRegistry
        self._subscriptions = SubscriptionRegistry()
        # Construction is the opt-in (README/bench construct directly):
        # the first live frontend becomes the process default so
        # serving_stats()/explain's "Serving:" section observe it
        # without going through get_frontend(). The default frontend
        # also registers as the "serving" collector in the process
        # metrics registry (telemetry/metrics.py).
        global _DEFAULT
        with _DEFAULT_LOCK:
            _ALL_FRONTENDS.add(self)
            if _DEFAULT is None:
                _DEFAULT = self
                from ..telemetry import metrics as _metrics
                _metrics.get_registry().register_collector(
                    "serving", self.stats)

    # ------------------------------------------------------------------
    # Shared cross-session result cache.
    # ------------------------------------------------------------------

    def result_cache(self):
        """The frontend's cross-session result cache (built from the
        governing conf's serving.result_cache.* budgets; None while that
        flag is off). Budget changes rebuild — and thereby clear — it,
        the same CacheWithTransform contract as Session.result_cache."""
        return self._shared_cache_holder.load()

    # ------------------------------------------------------------------
    # Submission + admission control.
    # ------------------------------------------------------------------

    def submit(self, query, session=None, client: str = "",
               deadline_ms: Optional[float] = None) -> PendingQuery:
        """Enqueue one query (a DataFrame, or a LogicalPlan plus an
        explicit ``session``). Returns immediately with a
        :class:`PendingQuery`; raises :class:`ServingRejectedError` when
        admission control refuses it.

        ``deadline_ms`` (robustness layer) bounds the query end to end
        FROM SUBMIT TIME — queue wait counts. Expiry cancels the query
        at the next cooperative boundary (or before it ever starts),
        frees the worker slot, and surfaces the typed
        :class:`~..exceptions.QueryDeadlineError` on ``result()``;
        unset falls back to the session's
        ``hyperspace.tpu.robustness.deadlineMs`` conf."""
        plan = getattr(query, "plan", query)
        session = session if session is not None \
            else getattr(query, "session", None)
        if session is None:
            raise HyperspaceException(
                "submit() needs a DataFrame or an explicit session=")
        from .fingerprint import estimate_recompute_bytes, normalize
        norm = normalize(plan)
        est = estimate_recompute_bytes(norm)
        # Cluster router (cluster/worker.py): when another worker owns
        # this plan's result-cache shard, ship the submission there and
        # return its finished PendingQuery; any failure falls through
        # to the local path below, byte-identical (the r14 ladder).
        # Disabled clusters pay exactly this one conf read.
        if self._hs_conf.cluster_routing_enabled():
            from ..cluster import worker as _cluster
            forwarded = _cluster.try_forward(
                session, plan, norm, client=client,
                deadline_ms=deadline_ms, est=est)
            if forwarded is not None:
                with self._lock:
                    self._stats["submitted"] += 1
                    self._stats["admitted"] += 1
                self._observe_latency(forwarded)
                return forwarded
        batch_key = batcher.template_key(session, norm) \
            if self._hs_conf.serving_batching_enabled() else None
        # SLO-driven admission (adaptive/admission.py): while an armed
        # objective is breached, new submissions shed (typed rejection,
        # same contract as queue-depth sheds) or degrade (the worker
        # tries the sampled approximate tier; ineligible plans run
        # exact). Recovery is automatic on the first healthy verdict.
        approx = False
        if session.hs_conf.adaptive_admission_enabled():
            from ..adaptive.admission import get_controller
            verdict = get_controller().decide(session)
            if verdict == "shed":
                with self._lock:
                    self._stats["submitted"] += 1
                    self._stats["rejected"] += 1
                reason = "slo breach: shedding load"
                self._emit_reject(session, client, est, reason)
                raise ServingRejectedError(
                    f"serving admission rejected query: {reason}")
            if verdict == "degrade":
                # Approximate members must never join a literal sweep
                # (the sweep shares exact scans across members).
                approx = True
                batch_key = None
        from .context import next_query_id
        pending = PendingQuery(query_id=next_query_id(), client=client,
                               estimated_bytes=est)
        deadline_s = time.perf_counter() + deadline_ms / 1000.0 \
            if deadline_ms is not None and deadline_ms > 0 else None
        depth = self._hs_conf.serving_queue_depth()
        max_bytes = self._hs_conf.serving_admission_max_bytes()
        with self._lock:
            self._stats["submitted"] += 1
            queued = len(self._queue)
            inflight = self._inflight_bytes
            if queued >= depth or \
                    (inflight > 0 and inflight + est > max_bytes):
                self._stats["rejected"] += 1
                reason = (f"queue full ({queued}/{depth})"
                          if queued >= depth else
                          f"byte budget ({inflight + est} > {max_bytes})")
                self._emit_reject(session, client, est, reason)
                raise ServingRejectedError(
                    f"serving admission rejected query: {reason}")
            self._stats["admitted"] += 1
            entry = _Entry(plan, norm, session,
                           contextvars.copy_context(), pending, batch_key,
                           deadline_s=deadline_s, approx=approx)
            self._queue.append(entry)
            self._inflight_bytes += est
            spawn = self._active_workers < \
                self._hs_conf.serving_max_concurrency()
            if spawn:
                self._active_workers += 1
            self._cv.notify_all()  # wake EVERY window-waiting worker:
            # notify() could pick one holding an incompatible batch,
            # leaving a compatible (even full) batch waiting out its
            # whole window.
        self._emit_admit(session, client, est, queued + 1)
        if spawn:
            from ..parallel import io as pio
            try:
                pio.submit_serving(
                    self._drain, self._hs_conf.serving_max_concurrency())
            except BaseException:
                # Roll the whole admission back: a stranded entry would
                # consume queue depth and byte budget forever (and could
                # execute later despite the caller being told the
                # submission failed). If another worker already took it,
                # leave it — it will complete normally.
                with self._lock:
                    self._active_workers -= 1
                    try:
                        self._queue.remove(entry)
                    except ValueError:
                        pass
                    else:
                        self._inflight_bytes = max(
                            0, self._inflight_bytes - est)
                        self._stats["admitted"] -= 1
                raise
        return pending

    def batching_enabled(self) -> bool:
        """Whether this frontend's governing conf batches literal
        variants (the standing-query fan-out asks before grouping)."""
        return self._hs_conf.serving_batching_enabled()

    def submit_wave(self, requests: List[tuple]) -> List:
        """Admit a PREFORMED literal-sweep group — the standing-query
        fan-out path (streaming/subscriptions.py): N same-template
        fires enter as ONE wave that executes as one shared-scan sweep,
        bypassing the queue's window/collect machinery (the group is
        already assembled; re-queueing N entries would let concurrent
        workers split it and the ``batching.maxBatch`` collector cap
        fragment it). Each request is ``(plan, session, client,
        deadline_ms)``; the returned list is aligned with ``requests``
        and carries a :class:`PendingQuery` per admitted member or the
        exception submit() would have raised (SLO shed, byte budget, a
        FULL QUEUE — wave members never occupy queue slots, but a
        backed-up queue sheds fires exactly as it does single ones).
        One member's rejection never aborts the wave."""
        from .context import next_query_id
        from .fingerprint import estimate_recompute_bytes, normalize
        out: List = []
        entries: List[_Entry] = []
        depth = self._hs_conf.serving_queue_depth()
        max_bytes = self._hs_conf.serving_admission_max_bytes()
        for plan, session, client, deadline_ms in requests:
            try:
                norm = normalize(plan)
                est = estimate_recompute_bytes(norm)
                approx = False
                if session.hs_conf.adaptive_admission_enabled():
                    from ..adaptive.admission import get_controller
                    verdict = get_controller().decide(session)
                    if verdict == "shed":
                        with self._lock:
                            self._stats["submitted"] += 1
                            self._stats["rejected"] += 1
                        reason = "slo breach: shedding load"
                        self._emit_reject(session, client, est, reason)
                        raise ServingRejectedError(
                            f"serving admission rejected query: {reason}")
                    if verdict == "degrade":
                        # Approximate members never join the sweep —
                        # _drain_wave runs them standalone.
                        approx = True
                pending = PendingQuery(query_id=next_query_id(),
                                       client=client,
                                       estimated_bytes=est)
                deadline_s = time.perf_counter() + deadline_ms / 1000.0 \
                    if deadline_ms is not None and deadline_ms > 0 \
                    else None
                with self._lock:
                    self._stats["submitted"] += 1
                    queued = len(self._queue)
                    inflight = self._inflight_bytes
                    if queued >= depth or \
                            (inflight > 0 and inflight + est > max_bytes):
                        self._stats["rejected"] += 1
                        reason = (f"queue full ({queued}/{depth})"
                                  if queued >= depth else
                                  f"byte budget ({inflight + est} > "
                                  f"{max_bytes})")
                    else:
                        reason = None
                        self._stats["admitted"] += 1
                        self._inflight_bytes += est
                if reason is not None:
                    self._emit_reject(session, client, est, reason)
                    raise ServingRejectedError(
                        f"serving admission rejected query: {reason}")
                entries.append(_Entry(
                    plan, norm, session, contextvars.copy_context(),
                    pending, None, deadline_s=deadline_s, approx=approx))
                out.append(pending)
                self._emit_admit(session, client, est, queued + 1)
            except Exception as e:
                out.append(e)
        if entries:
            with self._lock:
                self._active_workers += 1
            from ..parallel import io as pio
            try:
                pio.submit_serving(
                    lambda: self._drain_wave(entries),
                    self._hs_conf.serving_max_concurrency())
            except BaseException as e:
                # No worker will ever run these members: fail their
                # futures (deliveries observe the error) and release
                # their admission so budgets stay honest.
                with self._lock:
                    self._active_workers -= 1
                for entry in entries:
                    entry.pending._finish(error=e)
                    self._note(failed=1)
                    self._release(entry)
        return out

    def _drain_wave(self, entries: List[_Entry]) -> None:
        """Execute one preformed wave: the sweep-eligible members as a
        single literal-sweep batch (one shared scan per source, one
        vmapped invocation per swept position — however many members),
        SLO-degraded members standalone. Same death guarantees as
        _drain: any escape releases unstarted members to per-member
        execution and the worker slot is always returned."""
        try:
            singles = [e for e in entries if e.approx]
            sweepers = [e for e in entries if not e.approx]
            for e in singles:
                self._run_single(e)
            if len(sweepers) == 1:
                self._run_single(sweepers[0])
            elif sweepers:
                self._run_batch(sweepers)
        except BaseException as e:
            self._release_batch(entries, e)
        finally:
            with self._lock:
                self._active_workers -= 1

    # ------------------------------------------------------------------
    # Standing queries (streaming tier).
    # ------------------------------------------------------------------

    def subscribe(self, query, session=None, client: str = "",
                  deadline_ms: Optional[float] = None):
        """Register a standing query: the plan re-fires through this
        frontend's worker pool on every streaming commit (a standing
        query is a cached plan + the result-cache invalidation hook —
        between commits a re-fire is a cache hit by construction).
        Returns a :class:`~..streaming.subscriptions.Subscription`;
        ``deadline_ms`` bounds each fire like a submit() deadline."""
        session = session if session is not None \
            else getattr(query, "session", None)
        if session is None:
            raise HyperspaceException(
                "subscribe() needs a DataFrame or an explicit session=")
        return self._subscriptions.subscribe(
            self, query, session, client, deadline_ms,
            self._hs_conf.streaming_subscriptions_max(),
            self._hs_conf.streaming_subscription_history())

    def unsubscribe(self, subscription) -> bool:
        return self._subscriptions.unsubscribe(subscription)

    def notify_commit(self, session, table: str = "") -> int:
        """Re-fire every live standing query (called by the streaming
        tier after a commit publishes). Returns fires admitted."""
        return self._subscriptions.fire(self, session, table)

    # ------------------------------------------------------------------
    # Worker loop.
    # ------------------------------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._lock:
                if not self._queue:
                    self._active_workers -= 1
                    return
                entry = self._queue.popleft()
            batch = [entry]
            # Everything past the pop is guarded: a worker dying with
            # popped entries in hand would strand the clients' futures,
            # leak _inflight_bytes, and wedge _active_workers forever
            # (e.g. a malformed batching.window conf string). A death in
            # the window/collection phase — BEFORE any member started —
            # releases the held members to per-member execution (each
            # with its own error handling) instead of failing innocents
            # with the worker's own error; a death with members already
            # started lands the error on the unfinished futures. Either
            # way the worker lives on.
            try:
                from ..robustness import fault_names as _fn
                from ..robustness import faults as _faults
                # Runs under the HEAD entry's submit-time context
                # snapshot: the worker thread itself carries no armed
                # fault scope, the submitter's does (one registry across
                # a whole submission wave — worker death is a property
                # of the workload, not of one query's execution).
                entry.ctx.run(_faults.fault_point, _fn.SERVING_WORKER)
                window = self._hs_conf.serving_batching_window()
                limit = self._hs_conf.serving_batching_max_batch()
                with self._lock:
                    self._collect_batch(entry, batch, limit)
                if entry.batch_key is not None and window > 0 and \
                        len(batch) < limit:
                    # Hold the door open one full window for
                    # co-batchable arrivals (a literal sweep is worth a
                    # bounded wait); submits notify the cv, so the loop
                    # re-collects as they land and exits early once the
                    # batch is full.
                    deadline = time.monotonic() + window
                    with self._lock:
                        while len(batch) < limit:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                break
                            self._cv.wait(remaining)
                            self._collect_batch(entry, batch, limit)
                if len(batch) == 1:
                    self._run_single(entry)
                else:
                    self._run_batch(batch)
            except BaseException as e:
                self._release_batch(batch, e)

    def _release_batch(self, batch: List[_Entry], error) -> None:
        """Worker-death recovery: members the dying worker never started
        re-execute per-member (their own errors land on their own
        futures); started-but-unfinished members get the worker's error.
        Last-resort guard: anything this release path itself fails to
        place lands the original error, so no future is ever stranded
        until the drain timeout."""
        from ..robustness import faults as _faults

        def _fail(b: _Entry) -> None:
            if not b.pending.done():
                b.pending._finish(error=error)
                self._note(failed=1)
                self._release(b)

        for b in batch:
            if b.pending.done():
                continue
            if b.pending.started_s is None and \
                    b.session.hs_conf.robustness_degrade_enabled():
                try:
                    _faults.note(worker_releases=1)
                    # own try/except per member; degraded=True marks
                    # the rerun's QueryContext for the SLO degrade-rate
                    # objective (note() runs on the batch thread where
                    # no query context is active, so it cannot).
                    self._run_single(b, degraded=True)
                except BaseException:
                    _fail(b)
                continue
            _fail(b)

    def _collect_batch(self, head: _Entry, batch: List[_Entry],
                       limit: int) -> None:
        """Under the lock: move queued entries batch-compatible with
        ``head`` into ``batch`` (submission order preserved)."""
        if head.batch_key is None:
            return
        if len(batch) >= limit:
            return
        keep = deque()
        while self._queue and len(batch) < limit:
            e = self._queue.popleft()
            if e.batch_key == head.batch_key:
                batch.append(e)
            else:
                keep.append(e)
        keep.extend(self._queue)
        self._queue.clear()
        self._queue.extend(keep)

    def _run_single(self, entry: _Entry, degraded: bool = False) -> None:
        entry.pending.started_s = time.perf_counter()
        try:
            self._check_entry_deadline(entry, "serving.queue")
            result = entry.ctx.run(self._execute_entry, entry, None, 0,
                                   None, degraded)
            entry.pending._finish(result=result)
            self._note(completed=1)
        except BaseException as e:  # the submitter gets the error
            entry.pending._finish(error=e)
            self._note(failed=1)
        finally:
            self._release(entry)
            self._observe_latency(entry.pending)

    def _check_entry_deadline(self, entry: _Entry, where: str) -> None:
        """Fast-fail an entry whose submit-time deadline already expired
        BEFORE paying any execution: the slot frees immediately and the
        submitter gets the same typed error a mid-query cancellation
        raises."""
        if entry.deadline_s is None or \
                time.perf_counter() < entry.deadline_s:
            return
        from .context import deadline_cancel
        waited_s = time.perf_counter() - entry.pending.submitted_s
        # Queue sheds never reach Session.execute's SLO feed, yet they
        # are exactly the client-visible failures an error storm is
        # made of — record them here so the errorRate objective can
        # breach under queue overload (mid-query trips are fed by
        # execute's own finally, not this path).
        from ..telemetry import slo as _slo
        _slo.observe_query(entry.session, waited_s * 1000.0, error=True)
        deadline_cancel(entry.session, entry.pending.query_id, where,
                        waited_s * 1000.0)

    def _sweep_trace(self, batch: List[_Entry]):
        """The shared sweep trace (telemetry/trace.py): ONE
        ``serving.sweep`` span whose children are the member queries'
        roots — opened only when the governing conf traces, handed to
        members via QueryContext.trace_parent (their submit-time context
        snapshots predate the batch, so a contextvar cannot carry it)."""
        if not self._hs_conf.telemetry_trace_enabled():
            return None
        from ..telemetry import span_names as SN
        from ..telemetry import trace as _trace
        # The whole sweep shares ONE retention coin (governing conf):
        # members record into the shared trace either way; tail-keep
        # marks from any member rescue it for all of them.
        tr = _trace.Trace(self._hs_conf.telemetry_trace_max_spans(),
                          label="sweep",
                          sampled=_trace.sample_coin(self._session))
        span = tr.new_span(SN.SERVING_SWEEP, None,
                           {"size": len(batch)})
        return (tr, span)

    def _run_batch(self, batch: List[_Entry]) -> None:
        """Execute literal-variant members under one SweepContext: one
        shared scan per source, one vmapped mask invocation per swept
        Filter position; members otherwise run their normal path (own
        result-cache key, own capture record, own downstream)."""
        try:
            conditions = [batcher.plan_template(e.norm)[1] for e in batch]
        except batcher.Unbatchable:
            for e in batch:
                self._run_single(e)
            return
        sweep = batcher.SweepContext(conditions)
        trace_parent = self._sweep_trace(batch)
        for i, e in enumerate(batch):
            e.pending.started_s = time.perf_counter()
            e.pending.batched = True
            e.pending.batch_size = len(batch)
            try:
                self._check_entry_deadline(e, "serving.queue")
                try:
                    result = e.ctx.run(self._execute_entry, e, sweep, i,
                                       trace_parent)
                except BaseException as err:
                    # Sweep-member degradation ladder (robustness
                    # layer): one member's failure inside the shared
                    # sweep must not poison its siblings OR itself —
                    # re-execute the member standalone (no sweep). The
                    # standalone rerun is the plain single-query path,
                    # so a persistent error surfaces from it unchanged;
                    # cancellations and disabled degradation skip the
                    # rerun.
                    from ..exceptions import QueryDeadlineError
                    if isinstance(err, QueryDeadlineError) or not \
                            e.session.hs_conf.robustness_degrade_enabled():
                        raise
                    from ..robustness import faults as _faults
                    # note() runs on the batch thread (no active query
                    # context), so the rerun's QueryContext is marked
                    # degraded explicitly — the SLO degrade-rate signal
                    # for a sweep that rode the member ladder.
                    _faults.note(member_fallbacks=1)
                    result = e.ctx.run(self._execute_entry, e, None, 0,
                                       trace_parent, True)
                e.pending._finish(result=result)
                self._note(completed=1)
            except BaseException as err:
                e.pending._finish(error=err)
                self._note(failed=1)
            finally:
                self._release(e)
                self._observe_latency(e.pending)
        s = sweep.stats()
        if trace_parent is not None:
            sweep_tr, sweep_span = trace_parent
            if sweep_span is not None:
                sweep_span.attrs["positions"] = s["positions"]
                sweep_span.attrs["members"] = len(batch)
                sweep_span.finish()
            # The frontend owns the shared sweep trace's retention
            # (members only surface it): coin / tail-keep / counters.
            from ..telemetry import trace as _trace
            _trace.finish_root(self._session, sweep_tr)
        self._note(batches=1, batched_queries=len(batch),
                   sweep_invocations=s["sweep_invocations"],
                   shared_scans=s["shared_scans"],
                   shared_scan_hits=s["shared_scan_hits"])
        self._emit_batch(batch, s)

    def _execute_entry(self, entry: _Entry,
                       sweep: Optional[batcher.SweepContext],
                       member: int, trace_parent=None,
                       degraded: bool = False):
        qc = QueryContext.for_session(
            entry.session, shared_cache=self.result_cache(),
            client=entry.pending.client, deadline_s=entry.deadline_s,
            query_id=entry.pending.query_id)
        qc.trace_parent = trace_parent
        qc.degraded = degraded
        # Sweep attempts with the member ladder armed get rescued by a
        # standalone rerun on failure — the rerun's sample is the
        # query's real SLO outcome (deadline cancellations skip the
        # rerun and are never suppressed; see Session.execute).
        qc.slo_suppress_error = sweep is not None and \
            entry.session.hs_conf.robustness_degrade_enabled()
        entry.pending.context = qc
        if entry.approx and sweep is None:
            # SLO degrade tier: run the sampled rewrite when the plan is
            # eligible; the result carries its stated error bound and
            # counts as degraded for the SLO degrade-rate objective.
            from ..adaptive.admission import approximate_plan
            hit = approximate_plan(entry.session, entry.plan)
            if hit is not None:
                approx_plan, bound = hit
                qc.degraded = True
                result = entry.session.execute(approx_plan, context=qc)
                try:
                    result.approx_error_bound = dict(bound)
                except Exception:
                    pass
                return result
        with batcher.use_sweep(sweep, member):
            return entry.session.execute(entry.plan, context=qc)

    def _observe_latency(self, pending: PendingQuery) -> None:
        """Feed the live serving latency histogram
        (telemetry/metrics.py ``serving.latency_ms``) — the source of
        Hyperspace.metrics()'s rolling p50/p95/p99 + QPS."""
        if pending.latency_s is None:
            return
        try:
            if not self._hs_conf.telemetry_metrics_enabled():
                return
            from ..telemetry import metrics as _metrics
            # Only the process-DEFAULT frontend's conf governs the
            # shared instrument's window; other frontends just record
            # (two frontends with different latencyWindow confs must
            # not thrash the window per completed query).
            window = self._hs_conf.telemetry_serving_latency_window() \
                if _DEFAULT is self else None
            _metrics.get_registry().histogram(
                "serving.latency_ms", window
            ).record(pending.latency_s * 1000.0)
        except Exception:
            pass  # observability must never fail a query

    def _release(self, entry: _Entry) -> None:
        with self._lock:
            self._inflight_bytes = max(
                0, self._inflight_bytes - entry.pending.estimated_bytes)

    # ------------------------------------------------------------------
    # Observability.
    # ------------------------------------------------------------------

    def _note(self, **deltas) -> None:
        with self._lock:
            for k, v in deltas.items():
                self._stats[k] += v

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["queued"] = len(self._queue)
            out["active_workers"] = self._active_workers
            out["inflight_bytes"] = self._inflight_bytes
        cache = self.result_cache()
        out["shared_result_cache"] = cache.stats() \
            if cache is not None else None
        from .program_bank import get_bank
        out["program_bank"] = get_bank().stats()
        out["subscriptions"] = self._subscriptions.stats()
        return out

    def drain(self, timeout: float = 60.0) -> None:
        """Block until the queue is empty and workers are idle."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and self._active_workers == 0:
                    return
            time.sleep(0.005)
        raise TimeoutError("serving frontend did not drain")

    def _logger(self, session):
        from ..telemetry.logging import get_logger
        return get_logger(session.hs_conf.event_logger_class())

    def _emit_admit(self, session, client, est, depth) -> None:
        try:
            from ..telemetry.events import ServingAdmitEvent
            self._logger(session).log_event(ServingAdmitEvent(
                message=f"query admitted (queue depth {depth})",
                client=client, estimated_bytes=est, queue_depth=depth))
        except Exception:
            pass

    def _emit_reject(self, session, client, est, reason) -> None:
        try:
            from ..telemetry.events import ServingRejectEvent
            self._logger(session).log_event(ServingRejectEvent(
                message=f"query rejected: {reason}",
                client=client, estimated_bytes=est, reason=reason))
        except Exception:
            pass

    def _emit_batch(self, batch: List[_Entry], s: dict) -> None:
        try:
            from ..telemetry.events import ServingBatchEvent
            self._logger(batch[0].session).log_event(ServingBatchEvent(
                message=(f"literal sweep: {len(batch)} queries, "
                         f"{s['sweep_invocations']} batched "
                         f"invocation(s), {s['shared_scans']} shared "
                         "scan(s)"),
                size=len(batch), positions=s["positions"],
                sweep_invocations=s["sweep_invocations"],
                shared_scans=s["shared_scans"]))
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Process-default frontend (Hyperspace.serving_frontend / bench).
# ---------------------------------------------------------------------------

_DEFAULT: Optional[ServingFrontend] = None
# Reentrant: get_frontend constructs under this lock and __init__
# re-acquires it to self-register.
_DEFAULT_LOCK = threading.RLock()
# EVERY live frontend (weak: a dropped frontend must not be kept alive
# by the registry) — the streaming commit hook notifies all of them, so
# a subscription on a non-default frontend still fires.
_ALL_FRONTENDS: "weakref.WeakSet[ServingFrontend]" = weakref.WeakSet()


def all_frontends() -> List[ServingFrontend]:
    """Every live frontend in the process (the commit hook fan-out)."""
    with _DEFAULT_LOCK:
        return list(_ALL_FRONTENDS)


def get_frontend(session) -> ServingFrontend:
    """The process-default frontend, created on first use with
    ``session`` as its governing session (conf + telemetry). Requires
    ``hyperspace.tpu.serving.enabled=true`` on that session — the
    explicit constructor carries no such gate."""
    if not session.hs_conf.serving_enabled():
        raise HyperspaceException(
            "hyperspace.tpu.serving.enabled is false; set it (or "
            "construct ServingFrontend directly) to use the serving tier")
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = ServingFrontend(session)
        return _DEFAULT
