"""Serving-layer config keys + defaults (`serving.result_cache.*`).

No reference analogue: the reference is a batch library; this subsystem is
the first piece of a serving layer (ROADMAP north star: high-QPS repeated
queries). Keys follow the conf-string convention of
``index/constants.py``; env fallbacks follow the ``HST_*`` convention of
``execution/index_cache.py`` but are resolved ONLY in ``config.py`` (the
lint gate `scripts/lint.py` enforces that no serving module reads
``os.environ`` directly).
"""

from __future__ import annotations


class ServingConstants:
    # Master switch. Default off: enabling changes no answers (tested by
    # the disable-and-compare oracle) but trades memory for latency, a
    # serving-deployment decision.
    RESULT_CACHE_ENABLED = "serving.result_cache.enabled"
    RESULT_CACHE_ENABLED_DEFAULT = "false"

    # Byte budget of the device-resident (HBM) tier.
    RESULT_CACHE_DEVICE_BYTES = "serving.result_cache.deviceBytes"
    RESULT_CACHE_DEVICE_BYTES_DEFAULT = str(256 * 1024 * 1024)

    # Byte budget of the host spill tier (device-tier LRU victims demote
    # here instead of being dropped; host victims are gone).
    RESULT_CACHE_HOST_BYTES = "serving.result_cache.hostBytes"
    RESULT_CACHE_HOST_BYTES_DEFAULT = str(1024 * 1024 * 1024)

    # Admission policy: a result is admitted only if BOTH its observed
    # execution time and its estimated recompute input volume (from the
    # optimized plan's file/index statistics) clear these floors — cheap
    # results are cheaper to recompute than to hold resident.
    RESULT_CACHE_MIN_COMPUTE_SECONDS = "serving.result_cache.minComputeSeconds"
    RESULT_CACHE_MIN_COMPUTE_SECONDS_DEFAULT = "0.005"
    RESULT_CACHE_MIN_INPUT_BYTES = "serving.result_cache.minInputBytes"
    RESULT_CACHE_MIN_INPUT_BYTES_DEFAULT = "0"

    # Optional disk-spill tier (r11-robustness): host-tier LRU victims
    # spill to files under ``spillDir`` (empty = disabled) up to
    # ``spillBytes``; spill victims are gone for good. A truncated or
    # corrupt spill file reads back as a MISS (entry evicted,
    # ResultCacheMissEvent reason="spill-corrupt") — never an error or a
    # wrong answer mid-query.
    RESULT_CACHE_SPILL_DIR = "serving.result_cache.spillDir"
    RESULT_CACHE_SPILL_DIR_DEFAULT = ""
    RESULT_CACHE_SPILL_BYTES = "serving.result_cache.spillBytes"
    RESULT_CACHE_SPILL_BYTES_DEFAULT = str(4 * 1024 * 1024 * 1024)

    # SQL text -> logical plan memo (active only while the result cache is
    # enabled): a high-QPS serving loop re-issues identical SQL, and the
    # parse+analyze pass is pure given the temp-view registry version.
    # 0 disables.
    RESULT_CACHE_PLAN_CACHE_SIZE = "serving.result_cache.planCacheSize"
    RESULT_CACHE_PLAN_CACHE_SIZE_DEFAULT = "64"

    # ------------------------------------------------------------------
    # Concurrent serving frontend (serving/frontend.py). The family is
    # prefixed hyperspace.tpu.serving.* (the io/optimizer convention);
    # the result-cache keys above predate it and keep their spelling.
    # ------------------------------------------------------------------

    # Master switch for the process-default frontend accessor
    # (Hyperspace.serving_frontend / Session-level auto-routing). A
    # directly-constructed ServingFrontend works regardless — the
    # construction IS the opt-in.
    SERVING_ENABLED = "hyperspace.tpu.serving.enabled"
    SERVING_ENABLED_DEFAULT = "false"

    # Worker-slot cap: how many queries execute concurrently. Workers
    # come from the dedicated serving pool in parallel/io.py (NOT the
    # reader pool — a query must be able to fan reads out underneath).
    SERVING_MAX_CONCURRENCY = "hyperspace.tpu.serving.maxConcurrency"
    SERVING_MAX_CONCURRENCY_DEFAULT = "4"

    # Bounded submission queue: submissions beyond this many WAITING
    # queries are rejected (ServingRejectEvent + ServingRejectedError)
    # instead of queueing unboundedly.
    SERVING_QUEUE_DEPTH = "hyperspace.tpu.serving.queueDepth"
    SERVING_QUEUE_DEPTH_DEFAULT = "64"

    # Admission byte budget: summed recompute-input estimates
    # (serving/fingerprint.estimate_recompute_bytes) of queued+running
    # queries; a submission pushing past it is rejected — unless nothing
    # is in flight, so one over-budget query still runs alone.
    SERVING_ADMISSION_MAX_BYTES = "hyperspace.tpu.serving.admission.maxBytes"
    SERVING_ADMISSION_MAX_BYTES_DEFAULT = str(4 * 1024 * 1024 * 1024)

    # Cross-query literal batching (serving/batcher.py): queries whose
    # canonical plans differ only in Filter literals execute as one
    # sweep. ``window`` (seconds) is how long a worker holding one
    # batchable query waits for co-batchable arrivals; ``maxBatch`` caps
    # members per sweep.
    SERVING_BATCHING_ENABLED = "hyperspace.tpu.serving.batching.enabled"
    SERVING_BATCHING_ENABLED_DEFAULT = "true"
    SERVING_BATCHING_WINDOW = "hyperspace.tpu.serving.batching.window"
    SERVING_BATCHING_WINDOW_DEFAULT = "0.01"
    SERVING_BATCHING_MAX_BATCH = "hyperspace.tpu.serving.batching.maxBatch"
    SERVING_BATCHING_MAX_BATCH_DEFAULT = "8"

    # Env-var fallbacks (HST_INDEX_CACHE* convention), applied when the
    # conf key is unset. "on"/"off" spellings are accepted for the
    # boolean. Resolution happens in config.py exclusively.
    ENV_FALLBACKS = {
        RESULT_CACHE_ENABLED: "HST_RESULT_CACHE",
        RESULT_CACHE_DEVICE_BYTES: "HST_RESULT_CACHE_DEVICE_BYTES",
        RESULT_CACHE_HOST_BYTES: "HST_RESULT_CACHE_HOST_BYTES",
        RESULT_CACHE_MIN_COMPUTE_SECONDS: "HST_RESULT_CACHE_MIN_COMPUTE_S",
        RESULT_CACHE_MIN_INPUT_BYTES: "HST_RESULT_CACHE_MIN_INPUT_BYTES",
    }
