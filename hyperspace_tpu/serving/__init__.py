"""Serving layer: high-QPS query-path infrastructure.

Subsystems: the plan-signature-keyed result cache with log-version
invalidation (result_cache.py, fingerprint.py) plus the SQL plan memo
wired into Session.sql; and the concurrent serving tier — explicit
per-query contexts (context.py), the process-wide compiled-program bank
(program_bank.py), cross-query literal batching (batcher.py), and the
multi-session frontend with admission control (frontend.py). Knobs:
``serving.result_cache.*`` and ``hyperspace.tpu.serving.*``
(constants.py, read through config.py accessors only).

ServingFrontend/QueryContext are imported lazily by callers (frontend
pulls in the execution stack; ``import hyperspace_tpu`` must stay
light).
"""

from .constants import ServingConstants  # noqa: F401
from .context import QueryContext  # noqa: F401
from .fingerprint import ResultCacheKey, compute_key  # noqa: F401
from .result_cache import ResultCache, build_result_cache  # noqa: F401
