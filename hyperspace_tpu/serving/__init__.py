"""Serving layer: high-QPS query-path infrastructure.

First subsystem: the plan-signature-keyed result cache with log-version
invalidation (result_cache.py, fingerprint.py), plus the SQL plan memo
wired into Session.sql. Knobs: ``serving.result_cache.*`` (constants.py,
read through config.py accessors only).
"""

from .constants import ServingConstants  # noqa: F401
from .fingerprint import ResultCacheKey, compute_key  # noqa: F401
from .result_cache import ResultCache, build_result_cache  # noqa: F401
