"""Result-cache key derivation.

A cached result may be served iff recomputing the query NOW would produce
the identical table. The key therefore pins every input the executor's
answer depends on:

  1. canonical plan fingerprint — the plan AFTER the deterministic
     normalization passes (predicate pushdown + column pruning), serialized
     with full operator detail (expressions, join types, sort orders, file
     listings), so `select().where()` and `where().select()` spellings of
     one query share an entry;
  2. source-relation signature — (size, mtime, path) of every source file
     the plan's relations have pinned (the FileBasedSignatureProvider
     fingerprint, index/signatures.py); in-place file changes flip it;
  3. index log versions — (index name, latest op-log id, entry-bytes
     md5) for every index under the system path, collected only while
     hyperspace is enabled (disabled plans cannot touch an index):
     refreshIndex/optimizeIndex/createIndex all change the latest log
     entry (a full refresh restarts the log at the SAME ids, which the
     byte hash catches), so stale keys become unreachable by
     construction, never by heuristic TTLs;
  4. config hash — the session conf + the hyperspace-enabled flag (a conf
     change can alter the chosen physical plan and with it row order).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from ..plan.nodes import (Aggregate, BucketUnion, Filter, IndexScan, Join,
                          Limit, LogicalPlan, Project, Scan, Sort, Union,
                          Window)
from ..util import hashing


@dataclass(frozen=True)
class ResultCacheKey:
    plan_fingerprint: str
    source_signature: str
    index_versions: Tuple[Tuple[str, int, str], ...]
    config_hash: str

    def digest(self) -> str:
        """Stable short form for telemetry/explain output."""
        return hashing.md5_hex(
            (self.plan_fingerprint, self.source_signature,
             self.index_versions, self.config_hash))[:12]


def _node_detail(plan: LogicalPlan) -> Optional[str]:
    """Full-detail one-node serialization (tree_string is NOT enough: e.g.
    Project's simple_string shows output names only, hiding the exprs).
    Returns None for nodes this module does not understand — the whole
    plan is then uncacheable rather than wrongly keyed."""
    if isinstance(plan, Scan):
        rel = plan.relation
        return (f"Scan[{rel.file_format};{','.join(rel.root_paths)};"
                f"{sorted(rel.options.items())}]")
    if isinstance(plan, IndexScan):
        e = plan.index_entry
        return (f"IndexScan[{e.name};{e.log_version};"
                f"{sorted(plan.deleted_file_ids)};"
                f"{sorted(plan.appended_files)};{plan.use_bucket_spec}]")
    if isinstance(plan, Filter):
        return f"Filter[{plan.condition!r}]"
    if isinstance(plan, Project):
        return "Project[" + ";".join(repr(e) for e in plan.exprs) + "]"
    if isinstance(plan, Join):
        return f"Join[{plan.join_type};{plan.condition!r}]"
    if isinstance(plan, Aggregate):
        return (f"Aggregate[{plan.group_cols};"
                + ";".join(repr(a) for a in plan.aggs) + "]")
    if isinstance(plan, Window):
        return ("Window[" + ";".join(f"{n}={w!r}" for n, w in plan.wexprs)
                + "]")
    if isinstance(plan, Sort):
        return f"Sort[{plan.orders}]"
    if isinstance(plan, Limit):
        return f"Limit[{plan.n}]"
    if isinstance(plan, (Union, BucketUnion)):
        return plan.node_name
    return None


def _serialize(plan: LogicalPlan, out) -> bool:
    detail = _node_detail(plan)
    if detail is None:
        return False
    out.append(f"({detail}")
    for c in plan.children:
        if not _serialize(c, out):
            return False
    out.append(")")
    return True


def normalize(plan: LogicalPlan) -> LogicalPlan:
    """The deterministic, environment-free prefix of Session.optimize:
    predicates sink below projections and columns prune, so syntactic
    variants of one query canonicalize to one fingerprint. (The
    hyperspace rewrite and partition pruning are NOT applied here — they
    depend on the environment, which the other key components pin.)"""
    from ..rules.column_pruning import prune_columns
    from ..rules.pushdown import push_filters
    return prune_columns(push_filters(plan))


def plan_fingerprint(plan: LogicalPlan,
                     normalized: Optional[LogicalPlan] = None
                     ) -> Optional[str]:
    """Fingerprint of ``plan``; pass ``normalized`` (= normalize(plan))
    when the caller already computed it — the miss path feeds the same
    normalized tree into the rest of the optimizer, so the passes run
    once, not twice."""
    parts: list = []
    if not _serialize(normalized if normalized is not None
                      else normalize(plan), parts):
        return None
    return hashing.md5_hex("".join(parts))


def source_signature(plan: LogicalPlan) -> Optional[str]:
    """Combined (size, mtime, path) fingerprint of every file-based leaf
    (the FileBasedSignatureProvider semantics). Sizes/mtimes are stat'ed
    live, so an in-place rewrite of a pinned file invalidates; the file
    LIST is the relation's pinned snapshot — exactly what execution will
    read (keying on a re-listing would let a just-appended file's rows be
    cached under a fresh relation's key without being in the result)."""
    parts = []
    for leaf in plan.collect_leaves():
        relation = getattr(leaf, "relation", None)
        if relation is None:
            return None
        for path, size, mtime in relation.all_file_infos():
            parts.append(f"{size}{mtime}{path}")
    return hashing.md5_hex("".join(parts))


def index_versions(session) -> Tuple[Tuple[str, int, str], ...]:
    """(name, latest log id, entry-bytes md5) per index, sorted — read
    fresh from the op logs (NOT through the TTL metadata cache: a
    cross-process refresh must flip the key immediately; nothing here
    parses JSON)."""
    if not session.is_hyperspace_enabled():
        return ()
    return session.index_collection_manager.latest_log_ids()


def config_hash(session) -> str:
    """Conf + enabled-flag hash. The serving, telemetry, robustness, and
    fusion knobs themselves are excluded: they steer THIS cache
    (admission floors, budgets), pure observability (tracing/metrics/
    profiler — results are byte-identical by contract, asserted in
    tests/test_tracing.py), fault handling (deadlines/retry/degradation
    ladders produce byte-identical answers or typed errors, never a
    different answer — asserted in tests/test_robustness.py), or pure
    execution strategy (whole-plan fusion answers byte-identical to
    staged execution — asserted in tests/test_fusion.py; the artifact
    store serves the same compiled programs from the lake instead of
    recompiling, byte-identical by the AOT contract — asserted in
    tests/test_artifacts.py) — hashing them would orphan every warm
    entry on an admission-threshold tweak, a tracing toggle, a fault
    (dis)arming, or a fusion/artifacts toggle, breaking config.py's
    live-tuning contract. Cluster knobs are excluded for the same
    reason PLUS a sharper one: fleet workers differ exactly in their
    cluster.* values (worker id, port), and the router refuses a
    forward whenever sender and owner disagree on the key — hashing
    them would make every cross-worker digest mismatch by
    construction (asserted in tests/test_cluster.py). Buffer-pool knobs
    (execution.bufferPool.*) are excluded because the pool is pure
    residency strategy: pool-on and pool-off answers are byte-identical
    by the file-signature invalidation contract (asserted in
    tests/test_buffer_pool.py), so toggling or resizing it must not
    orphan warm result-cache entries."""
    items = [(k, v) for k, v in sorted(session.conf.as_dict().items())
             if not k.startswith("serving.")
             and not k.startswith("hyperspace.tpu.serving.")
             and not k.startswith("hyperspace.tpu.telemetry.")
             and not k.startswith("hyperspace.tpu.robustness.")
             and not k.startswith("hyperspace.tpu.execution.fusion.")
             and not k.startswith("hyperspace.tpu.execution.bufferPool.")
             and not k.startswith("hyperspace.tpu.artifacts.")
             and not k.startswith("hyperspace.tpu.cluster.")]
    return hashing.md5_hex((items, session.is_hyperspace_enabled()))


def compute_key(session, plan: LogicalPlan,
                normalized: Optional[LogicalPlan] = None
                ) -> Optional[ResultCacheKey]:
    """The full key, or None when the plan is not soundly cacheable."""
    fp = plan_fingerprint(plan, normalized)
    if fp is None:
        return None
    sig = source_signature(normalized if normalized is not None else plan)
    if sig is None:
        return None
    return ResultCacheKey(fp, sig, index_versions(session),
                          config_hash(session))


def estimate_recompute_bytes(optimized: LogicalPlan) -> int:
    """Admission-policy cost proxy: total input bytes the optimized plan
    would read if recomputed — source file sizes for relation leaves,
    IndexStatistics sizes (index files + hybrid appends) for index
    leaves."""
    total = 0
    for leaf in optimized.collect_leaves():
        relation = getattr(leaf, "relation", None)
        if relation is not None:
            total += sum(size for _, size, _ in relation.all_file_infos())
        elif isinstance(leaf, IndexScan):
            from ..index.statistics import IndexStatistics
            stats = IndexStatistics.from_entry(leaf.index_entry)
            total += stats.index_size_bytes
            from ..util import file_utils
            for f in leaf.appended_files:
                try:
                    total += file_utils.file_info_triple(f)[1]
                except OSError:
                    pass
    return total
