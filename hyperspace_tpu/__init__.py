"""hyperspace_tpu — a TPU-native data-lake indexing framework.

A ground-up re-design of the capabilities of microsoft/hyperspace (an indexing
subsystem for Apache Spark) for TPU hardware: covering indexes are built with
JAX/XLA (hash-partition + sort-within-bucket on device, bucket exchange over
ICI via mesh-partitioned jit collectives), queries are transparently rewritten
to probe
HBM-resident bucketed columnar indexes, and data-skipping sketches are computed
as on-device reductions — while the operation log and the Parquet index layout
live on the TPU-VM host filesystem, mirroring the reference's on-disk
contracts (_hyperspace_log, v__=N version dirs).
"""

from .config import Conf, HyperspaceConf  # noqa: F401
from .exceptions import HyperspaceException, NoChangesException  # noqa: F401
from .index.constants import IndexConstants, States  # noqa: F401
from .schema import Field, Schema  # noqa: F401

__version__ = "0.1.0"


def __getattr__(name):
    # Lazy imports to keep `import hyperspace_tpu` light and cycle-free.
    try:
        if name in ("Hyperspace", "IndexConfig", "DataSkippingIndexConfig",
                    "MinMaxSketch", "BloomFilterSketch"):
            from . import api
            return getattr(api, name)
        if name == "Session":
            from .session import Session
            return Session
        if name in ("col", "lit"):
            from .plan import expr as _expr
            return getattr(_expr, name)
    except ImportError as e:
        raise AttributeError(
            f"module {__name__!r} attribute {name!r} is unavailable: {e}") from e
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
