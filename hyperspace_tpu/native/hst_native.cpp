// Native host-side scan-planning kernels.
//
// Scan planning probes per-file sketches (MinMax ranges, bloom bitsets) for
// every candidate file of a query — a host-side hot loop at lake scale
// (thousands of files x predicates), independent of the TPU compute path.
// The reference delegates this class of work to the JVM; here it is C++
// loaded via ctypes (hyperspace_tpu/native/__init__.py), with semantics
// mirroring the Python/numpy implementations bit-for-bit:
//
// - bloom bitsets are np.packbits layout (MSB-first within each byte);
// - probe positions are precomputed by the caller with the same wrapping
//   uint32 double-hashing as ops/sketches.py (device/host mirrored);
// - comparison ops are encoded 0..4 = between/lt/le/gt/ge.

#include <cstdint>

extern "C" {

// Probe n_filters equal-size packed bitsets for one literal whose k bit
// positions are precomputed. valid[i]==0 means "no sketch for this file"
// (missing bitset) -> keep (out=1).
void hst_bloom_probe_many(const uint8_t* bits, int64_t stride_bytes,
                          int64_t n_filters, const uint8_t* valid,
                          const int32_t* positions, int32_t n_pos,
                          uint8_t* out) {
  for (int64_t f = 0; f < n_filters; ++f) {
    if (!valid[f]) {
      out[f] = 1;
      continue;
    }
    const uint8_t* b = bits + f * stride_bytes;
    uint8_t keep = 1;
    for (int32_t i = 0; i < n_pos; ++i) {
      const int32_t p = positions[i];
      if (!((b[p >> 3] >> (7 - (p & 7))) & 1)) {
        keep = 0;
        break;
      }
    }
    out[f] = keep;
  }
}

// op: 0 = equality probe (lo <= v <= hi), 1 = '<' (lo < v),
//     2 = '<=' (lo <= v), 3 = '>' (hi > v), 4 = '>=' (hi >= v).
// has[i]==0 -> all-null file stats: keep (out=1), matching the Python path.
#define MINMAX_PRUNE_IMPL(T)                                          \
  for (int64_t i = 0; i < n; ++i) {                                   \
    if (!has[i]) {                                                    \
      out[i] = 1;                                                     \
      continue;                                                       \
    }                                                                 \
    const T l = lo[i];                                                \
    const T h = hi[i];                                                \
    uint8_t keep = 1;                                                 \
    switch (op) {                                                     \
      case 0: keep = (l <= value) && (value <= h); break;             \
      case 1: keep = l < value; break;                                \
      case 2: keep = l <= value; break;                               \
      case 3: keep = h > value; break;                                \
      case 4: keep = h >= value; break;                               \
      default: keep = 1; break;                                       \
    }                                                                 \
    out[i] = keep;                                                    \
  }

void hst_minmax_prune_f64(const double* lo, const double* hi,
                          const uint8_t* has, int64_t n, double value,
                          int32_t op, uint8_t* out) {
  MINMAX_PRUNE_IMPL(double)
}

void hst_minmax_prune_i64(const int64_t* lo, const int64_t* hi,
                          const uint8_t* has, int64_t n, int64_t value,
                          int32_t op, uint8_t* out) {
  MINMAX_PRUNE_IMPL(int64_t)
}

// ---------------------------------------------------------------------------
// Avro block decoder (the data-loader hot loop for the avro source).
//
// Decodes one object-container-file block — `count` rows of a flat record
// whose per-field plan is (prim, null_branch) — into columnar buffers, the
// exact loop util/avro.py runs per row in Python. Semantics mirror the
// Python decoder bit-for-bit; util/avro.py cross-checks the two in tests.
//
// prim codes: 0=boolean 1=int 2=long 3=float 4=double 5=string 6=bytes
// 7=null. null_branch is the union index of "null" (-1 = non-nullable).
//
// Outputs per field (caller-allocated; irrelevant pointers null):
//   ivals[f] : int64[count]  for prims 0-2
//   dvals[f] : double[count] for prims 3-4
//   offs[f]  : int32[count+1], sdata[f] : uint8[<= buf_len] for prims 5-6
//   valids[f]: uint8[count]
// Returns bytes consumed, or -1 truncated, -2 bad union branch,
// -3 varint too long, -4 unknown prim.
// ---------------------------------------------------------------------------

static inline int read_varint(const uint8_t* buf, int64_t len, int64_t* pos,
                              int64_t* out) {
  uint64_t acc = 0;
  int shift = 0;
  while (true) {
    if (*pos >= len) return -1;
    const uint8_t b = buf[(*pos)++];
    // Guard BEFORE shifting: a shift >= 64 is UB (would silently wrap on
    // x86 and feed a corrupted length into the memcpy bounds check).
    if (shift >= 64) return -3;
    acc |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  *out = static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
  return 0;
}

int64_t hst_avro_decode_block(const uint8_t* buf, int64_t buf_len,
                              int64_t count, int32_t n_fields,
                              const int32_t* plans, int64_t** ivals,
                              double** dvals, int32_t** offs,
                              uint8_t** sdata, int64_t* sdata_len,
                              uint8_t** valids) {
  int64_t pos = 0;
  for (int32_t f = 0; f < n_fields; ++f) {
    if (offs[f]) offs[f][0] = 0;
    if (sdata_len) sdata_len[f] = 0;
  }
  for (int64_t r = 0; r < count; ++r) {
    for (int32_t f = 0; f < n_fields; ++f) {
      const int32_t prim = plans[2 * f];
      const int32_t null_branch = plans[2 * f + 1];
      uint8_t is_valid = 1;
      if (null_branch >= 0) {
        int64_t branch;
        const int rc = read_varint(buf, buf_len, &pos, &branch);
        if (rc) return rc;
        if (branch < 0 || branch > 1) return -2;
        if (branch == null_branch) is_valid = 0;
      }
      valids[f][r] = is_valid;
      if (offs[f]) offs[f][r + 1] = offs[f][r];  // default: empty slot
      if (!is_valid) {
        if (ivals[f]) ivals[f][r] = 0;
        if (dvals[f]) dvals[f][r] = 0.0;
        continue;
      }
      switch (prim) {
        case 0: {  // boolean
          if (pos >= buf_len) return -1;
          ivals[f][r] = buf[pos++] != 0;
          break;
        }
        case 1:
        case 2: {  // int / long (shared zigzag varint encoding)
          int64_t v;
          const int rc = read_varint(buf, buf_len, &pos, &v);
          if (rc) return rc;
          ivals[f][r] = v;
          break;
        }
        case 3: {  // float (4-byte LE)
          if (pos + 4 > buf_len) return -1;
          float v;
          __builtin_memcpy(&v, buf + pos, 4);
          pos += 4;
          dvals[f][r] = static_cast<double>(v);
          break;
        }
        case 4: {  // double (8-byte LE)
          if (pos + 8 > buf_len) return -1;
          double v;
          __builtin_memcpy(&v, buf + pos, 8);
          pos += 8;
          dvals[f][r] = v;
          break;
        }
        case 5:
        case 6: {  // string / bytes: length + raw bytes
          int64_t n;
          const int rc = read_varint(buf, buf_len, &pos, &n);
          if (rc) return rc;
          // `pos + n` would overflow signed int64 for huge corrupt
          // lengths (UB) — compare against the remaining bytes instead.
          if (n < 0 || n > buf_len - pos) return -1;
          const int64_t at = sdata_len[f];
          __builtin_memcpy(sdata[f] + at, buf + pos, n);
          sdata_len[f] = at + n;
          offs[f][r + 1] = static_cast<int32_t>(at + n);
          pos += n;
          break;
        }
        case 7:  // null type: zero bytes
          break;
        default:
          return -4;
      }
    }
  }
  return pos;
}

}  // extern "C"
