// Native host-side scan-planning kernels.
//
// Scan planning probes per-file sketches (MinMax ranges, bloom bitsets) for
// every candidate file of a query — a host-side hot loop at lake scale
// (thousands of files x predicates), independent of the TPU compute path.
// The reference delegates this class of work to the JVM; here it is C++
// loaded via ctypes (hyperspace_tpu/native/__init__.py), with semantics
// mirroring the Python/numpy implementations bit-for-bit:
//
// - bloom bitsets are np.packbits layout (MSB-first within each byte);
// - probe positions are precomputed by the caller with the same wrapping
//   uint32 double-hashing as ops/sketches.py (device/host mirrored);
// - comparison ops are encoded 0..4 = between/lt/le/gt/ge.

#include <cstdint>

extern "C" {

// Probe n_filters equal-size packed bitsets for one literal whose k bit
// positions are precomputed. valid[i]==0 means "no sketch for this file"
// (missing bitset) -> keep (out=1).
void hst_bloom_probe_many(const uint8_t* bits, int64_t stride_bytes,
                          int64_t n_filters, const uint8_t* valid,
                          const int32_t* positions, int32_t n_pos,
                          uint8_t* out) {
  for (int64_t f = 0; f < n_filters; ++f) {
    if (!valid[f]) {
      out[f] = 1;
      continue;
    }
    const uint8_t* b = bits + f * stride_bytes;
    uint8_t keep = 1;
    for (int32_t i = 0; i < n_pos; ++i) {
      const int32_t p = positions[i];
      if (!((b[p >> 3] >> (7 - (p & 7))) & 1)) {
        keep = 0;
        break;
      }
    }
    out[f] = keep;
  }
}

// op: 0 = equality probe (lo <= v <= hi), 1 = '<' (lo < v),
//     2 = '<=' (lo <= v), 3 = '>' (hi > v), 4 = '>=' (hi >= v).
// has[i]==0 -> all-null file stats: keep (out=1), matching the Python path.
#define MINMAX_PRUNE_IMPL(T)                                          \
  for (int64_t i = 0; i < n; ++i) {                                   \
    if (!has[i]) {                                                    \
      out[i] = 1;                                                     \
      continue;                                                       \
    }                                                                 \
    const T l = lo[i];                                                \
    const T h = hi[i];                                                \
    uint8_t keep = 1;                                                 \
    switch (op) {                                                     \
      case 0: keep = (l <= value) && (value <= h); break;             \
      case 1: keep = l < value; break;                                \
      case 2: keep = l <= value; break;                               \
      case 3: keep = h > value; break;                                \
      case 4: keep = h >= value; break;                               \
      default: keep = 1; break;                                       \
    }                                                                 \
    out[i] = keep;                                                    \
  }

void hst_minmax_prune_f64(const double* lo, const double* hi,
                          const uint8_t* has, int64_t n, double value,
                          int32_t op, uint8_t* out) {
  MINMAX_PRUNE_IMPL(double)
}

void hst_minmax_prune_i64(const int64_t* lo, const int64_t* hi,
                          const uint8_t* has, int64_t n, int64_t value,
                          int32_t op, uint8_t* out) {
  MINMAX_PRUNE_IMPL(int64_t)
}

}  // extern "C"
