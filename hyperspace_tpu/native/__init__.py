"""Native host-ops runtime: builds and binds hst_native.cpp via ctypes.

The shared library is compiled once per source hash into
``~/.cache/hyperspace_tpu/native/`` (g++ -O3) and loaded with ctypes;
every entry point has a vectorized numpy implementation with identical
semantics, so callers use this module unconditionally.

Dispatch policy (round 5, measured — see BASELINE.md §"Native C++ probe
path"): the sketch-PROBE entry points (``bloom_probe_*``,
``minmax_prune*``) default to the NUMPY implementation — it measured
2-3x faster at every lake scale up to 50k files, because the arrays are
tiny and ctypes call + bitmap marshalling dominates — and use C++ only
when ``HST_NATIVE_PROBE=on`` (probe_native_enabled). The Avro codec
(``avro_decode_block``) always prefers native when built: byte-level
varint decode has no numpy equivalent. ``HST_NATIVE=off`` still
disables the build entirely.

Entry points (all host-side scan-planning hot loops):

- ``bloom_probe_many``: one literal against many per-file bloom bitsets.
- ``minmax_prune``: one comparison literal against per-file (min, max) rows.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "hst_native.cpp")

_lib = None
_lib_tried = False


def _cache_dir() -> str:
    return os.environ.get(
        "HST_NATIVE_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "hyperspace_tpu",
                     "native"))


def _build() -> Optional[ctypes.CDLL]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.md5(src).hexdigest()[:16]
    out_dir = _cache_dir()
    so_path = os.path.join(out_dir, f"hst_native_{tag}.so")
    if not os.path.isfile(so_path):
        os.makedirs(out_dir, exist_ok=True)
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
               "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except Exception:
            return None
        os.replace(tmp, so_path)  # atomic: concurrent builders converge.
    lib = ctypes.CDLL(so_path)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.hst_bloom_probe_many.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, u8p, i32p, ctypes.c_int32, u8p]
    lib.hst_bloom_probe_many.restype = None
    lib.hst_minmax_prune_f64.argtypes = [
        f64p, f64p, u8p, ctypes.c_int64, ctypes.c_double, ctypes.c_int32, u8p]
    lib.hst_minmax_prune_f64.restype = None
    lib.hst_minmax_prune_i64.argtypes = [
        i64p, i64p, u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, u8p]
    lib.hst_minmax_prune_i64.restype = None
    lib.hst_avro_decode_block.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, i32p,
        ctypes.POINTER(i64p), ctypes.POINTER(f64p), ctypes.POINTER(i32p),
        ctypes.POINTER(u8p), i64p, ctypes.POINTER(u8p)]
    lib.hst_avro_decode_block.restype = ctypes.c_int64
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        if os.environ.get("HST_NATIVE", "on") != "off":
            try:
                _lib = _build()
            except Exception:
                _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


def probe_min_files() -> int:
    """File-count floor below which the C++ probe NEVER dispatches, even
    when enabled: at every measured lake scale up to 50k files numpy wins
    (see probe_native_enabled), so the native path must not be allowed to
    lose there. Deployments that profile a native win on bigger lakes
    lower/raise HST_NATIVE_PROBE_MIN_FILES alongside HST_NATIVE_PROBE=on;
    HST_NATIVE_PROBE=force bypasses the gate (benchmark A/B use)."""
    try:
        return int(os.environ.get("HST_NATIVE_PROBE_MIN_FILES", "100000"))
    except ValueError:
        return 100000


def probe_native_enabled(n_files: Optional[int] = None) -> bool:
    """The C++ sketch-PROBE loops are OPT-IN (HST_NATIVE_PROBE=on) and,
    since round 7, additionally gated on the probed file count
    (``n_files`` >= probe_min_files()) so the native path auto-disables
    itself on workload shapes where numpy is faster.

    Measured round 5 at 1,600-50,000 synthetic files x 1-16 predicates:
    the numpy fallback is 2-3x FASTER than the ctypes-dispatched C++
    probe at every lake scale this corpus can generate — the arrays are
    small enough (<=400 KB at 50k files) that numpy's vectorized
    compares are already memory-bound-optimal and the per-call ctypes
    marshalling dominates the native path. numpy is therefore the
    default; the C++ loops remain for deployments that profile a win on
    their own shapes. The Avro codec is NOT gated — its byte-level
    varint decode has no vectorized numpy equivalent and native genuinely
    wins there."""
    mode = os.environ.get("HST_NATIVE_PROBE", "off").lower()
    if mode == "force":
        return True
    if mode != "on":
        return False
    return n_files is None or n_files >= probe_min_files()


_OPS = {"EqualTo": 0, "LessThan": 1, "LessThanOrEqual": 2,
        "GreaterThan": 3, "GreaterThanOrEqual": 4}


def _as_u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


# ---------------------------------------------------------------------------
# Bloom probe: one literal vs many per-file bitsets.
# ---------------------------------------------------------------------------

def bloom_positions(value, dtype: str, num_bits: int,
                    num_hashes: int) -> np.ndarray:
    """The literal's k probe positions, mirroring ops/sketches.py double
    hashing (wrapping uint32 arithmetic)."""
    from ..ops import kernels
    from ..ops.sketches import _h2_host

    h1 = kernels.hash32_value_host(value, dtype)
    h2 = _h2_host(h1)
    return np.array([((h1 + i * h2) & 0xFFFFFFFF) % num_bits
                     for i in range(num_hashes)], dtype=np.int32)


def prepare_bloom(bits_rows: List[Optional[bytes]],
                  num_bits: int) -> Tuple[np.ndarray, np.ndarray]:
    """Assemble the per-file bitset matrix ONCE. At lake scale (thousands
    of files) this Python loop dominates the probe cost, so callers cache
    the result next to the sketch table and probe with
    bloom_probe_prepared — microseconds per literal instead of
    milliseconds."""
    n = len(bits_rows)
    stride = num_bits // 8
    buf = np.zeros((n, stride), dtype=np.uint8)
    valid = np.zeros(n, dtype=np.uint8)
    for i, b in enumerate(bits_rows):
        if b is not None:
            row = np.frombuffer(b, dtype=np.uint8)
            buf[i, :row.shape[0]] = row[:stride]
            valid[i] = 1
    return buf, valid


def bloom_probe_prepared(buf: np.ndarray, valid: np.ndarray, value,
                         dtype: str, num_bits: int,
                         num_hashes: int) -> np.ndarray:
    """keep-mask over files from a prepare_bloom matrix: False where the
    bitset proves the literal absent; missing bitsets keep the file."""
    n, stride = buf.shape
    positions = bloom_positions(value, dtype, num_bits, num_hashes)
    lib = get_lib() if probe_native_enabled(n) else None
    out = np.zeros(n, dtype=np.uint8)
    if lib is not None:
        lib.hst_bloom_probe_many(
            _as_u8p(buf), stride, n, _as_u8p(valid),
            positions.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(positions), _as_u8p(out))
        return out.astype(bool)
    # numpy fallback: gather each position's byte, test the MSB-first bit.
    keep = np.ones(n, dtype=bool)
    for p in positions:
        byte = buf[:, p >> 3]
        keep &= ((byte >> (7 - (p & 7))) & 1).astype(bool)
    return keep | ~valid.astype(bool)


def bloom_probe_many(bits_rows: List[Optional[bytes]], value, dtype: str,
                     num_bits: int, num_hashes: int) -> np.ndarray:
    """One-shot convenience: prepare + probe (callers with repeated
    probes should cache prepare_bloom's result instead)."""
    buf, valid = prepare_bloom(bits_rows, num_bits)
    return bloom_probe_prepared(buf, valid, value, dtype, num_bits,
                                num_hashes)


# ---------------------------------------------------------------------------
# MinMax prune: one comparison vs many per-file (min, max) rows.
# ---------------------------------------------------------------------------

_I64_MAX = 2**63 - 1
_I64_MIN = -(2**63)


def _int_domain_literal(op: str, value):
    """Rewrite ``col <op> value`` for an integer-domain column into an exact
    int64 form. Returns one of:

    - (op, int_value): the (possibly transformed) comparison;
    - ("ALL", None): the predicate keeps every file;
    - ("NONE", None): no stats-backed file can match (all-null files are
      still kept by the caller — only IS NULL matches them).

    Handles fractional float literals (col < 5.5 ⇔ col <= 5) and literals
    outside int64 range (which would otherwise wrap through c_int64)."""
    import math

    if isinstance(value, float):
        if math.isnan(value):
            return "NONE", None
        if math.isinf(value):
            up = value > 0
            keep_all = (op in ("LessThan", "LessThanOrEqual")) == up
            return ("ALL", None) if keep_all else ("NONE", None)
        if not float(value).is_integer():
            if op == "EqualTo":
                return "NONE", None  # no integer equals a fractional.
            if op in ("LessThan", "LessThanOrEqual"):
                bound = math.floor(value)           # col <= floor(v)
                if bound > _I64_MAX:
                    return "ALL", None
                if bound < _I64_MIN:
                    return "NONE", None
                return "LessThanOrEqual", bound
            bound = math.floor(value) + 1           # col >= floor(v)+1
            if bound < _I64_MIN:
                return "ALL", None
            if bound > _I64_MAX:
                return "NONE", None
            return "GreaterThanOrEqual", bound
    v = int(value)
    if v > _I64_MAX:
        return ("ALL", None) if op in ("LessThan", "LessThanOrEqual") \
            else ("NONE", None)
    if v < _I64_MIN:
        return ("ALL", None) if op in ("GreaterThan", "GreaterThanOrEqual") \
            else ("NONE", None)
    return op, v


def prepare_minmax(lo_rows: List, hi_rows: List,
                   dtype: str) -> Optional[Tuple]:
    """Convert the per-file (min, max) pylists into probe-ready numpy
    arrays ONCE — at lake scale the Python conversion loop dominates the
    probe, so callers cache this next to the sketch table. Returns
    (lo, hi, has) or None for natively-unsupported dtypes (strings)."""
    import datetime

    from ..schema import BOOL, DATE, FLOAT32, FLOAT64, INT32, INT64

    n = len(lo_rows)
    has = np.array([l is not None and h is not None
                    for l, h in zip(lo_rows, hi_rows)], dtype=np.uint8)

    def fill(rows, np_dtype, conv):
        a = np.zeros(n, dtype=np_dtype)
        for i, r in enumerate(rows):
            if has[i]:
                a[i] = conv(r)
        return a

    if dtype in (INT32, INT64, BOOL, DATE):
        if dtype == DATE:
            epoch = datetime.date(1970, 1, 1)
            conv = lambda v: (v - epoch).days
        else:
            conv = int
        return fill(lo_rows, np.int64, conv), \
            fill(hi_rows, np.int64, conv), has
    if dtype in (FLOAT32, FLOAT64):
        return fill(lo_rows, np.float64, float), \
            fill(hi_rows, np.float64, float), has
    return None


def minmax_prune_prepared(prep: Tuple, op: str, value,
                          dtype: str) -> np.ndarray:
    """keep-mask from a prepare_minmax triple for ``col <op> value``."""
    import datetime
    import math

    from ..schema import DATE, FLOAT32, FLOAT64

    lo, hi, has = prep
    n = lo.shape[0]
    lib = get_lib() if probe_native_enabled(n) else None
    out = np.zeros(n, dtype=np.uint8)
    if dtype in (FLOAT32, FLOAT64):
        try:
            v = float(value)
        except OverflowError:
            v = math.inf if value > 0 else -math.inf
        if math.isnan(v):
            return ~has.astype(bool)
        op_code = _OPS[op]
        if lib is not None:
            lib.hst_minmax_prune_f64(
                lo.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                hi.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                _as_u8p(has), n, v, op_code, _as_u8p(out))
            return out.astype(bool)
        return _np_prune(lo, hi, has, v, op_code)
    if dtype == DATE:
        v = (value - datetime.date(1970, 1, 1)).days
    else:
        op, v = _int_domain_literal(op, value)
        if op == "ALL":
            return np.ones(n, dtype=bool)
        if op == "NONE":
            return ~has.astype(bool)  # only all-null files survive.
    op_code = _OPS[op]
    if lib is not None:
        lib.hst_minmax_prune_i64(
            lo.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            hi.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _as_u8p(has), n, v, op_code, _as_u8p(out))
        return out.astype(bool)
    return _np_prune(lo, hi, has, v, op_code)


def minmax_prune(lo_rows: List, hi_rows: List, op: str, value, dtype: str
                 ) -> Optional[np.ndarray]:
    """keep-mask over files for ``col <op> value`` given per-file min/max.
    Returns None when the dtype isn't supported natively (caller falls back
    to the generic Python path — e.g. strings). One-shot convenience over
    prepare_minmax + minmax_prune_prepared."""
    prep = prepare_minmax(lo_rows, hi_rows, dtype)
    if prep is None:
        return None
    return minmax_prune_prepared(prep, op, value, dtype)


# ---------------------------------------------------------------------------
# Avro block decode: one C++ pass over a block instead of a Python row loop.
# ---------------------------------------------------------------------------

# prim name → wire code (must match hst_native.cpp's switch).
AVRO_PRIMS = {"boolean": 0, "int": 1, "long": 2, "float": 3, "double": 4,
              "string": 5, "bytes": 6, "null": 7}

_AVRO_ERRORS = {-1: "truncated data", -2: "bad union branch",
                -3: "varint too long", -4: "unknown primitive"}


def avro_decode_block(block: bytes, count: int, plans: List) -> Optional[List]:
    """Decode one OCF block natively. ``plans`` is [(prim, null_branch)]
    per field (null_branch None for non-nullable). Returns per-field
    (kind, values, valid) where kind is "i" (int64 array), "d" (float64
    array), or "s" (offsets int32 array, data bytes) — or None when the
    native library is unavailable (caller runs the Python decoder).
    Raises ValueError on corrupt blocks (same conditions as the Python
    decoder's HyperspaceException paths)."""
    lib = get_lib()
    if lib is None or count == 0:
        return None
    n_fields = len(plans)
    buf = np.frombuffer(block, dtype=np.uint8)
    plan_arr = np.zeros(2 * n_fields, dtype=np.int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ivals = (i64p * n_fields)()
    dvals = (f64p * n_fields)()
    offs = (i32p * n_fields)()
    sdata = (u8p * n_fields)()
    valids = (u8p * n_fields)()
    holders = []  # (field, kind, arrays...) keeping numpy alive + for output
    sdata_len = np.zeros(n_fields, dtype=np.int64)
    for f, (prim, null_branch) in enumerate(plans):
        code = AVRO_PRIMS[prim]
        plan_arr[2 * f] = code
        plan_arr[2 * f + 1] = -1 if null_branch is None else null_branch
        valid = np.ones(count, dtype=np.uint8)
        valids[f] = valid.ctypes.data_as(u8p)
        if code in (0, 1, 2):
            a = np.zeros(count, dtype=np.int64)
            ivals[f] = a.ctypes.data_as(i64p)
            holders.append(("i", a, valid))
        elif code in (3, 4):
            a = np.zeros(count, dtype=np.float64)
            dvals[f] = a.ctypes.data_as(f64p)
            holders.append(("d", a, valid))
        elif code in (5, 6):
            o = np.zeros(count + 1, dtype=np.int32)
            d = np.zeros(max(len(block), 1), dtype=np.uint8)
            offs[f] = o.ctypes.data_as(i32p)
            sdata[f] = d.ctypes.data_as(u8p)
            holders.append(("s", o, d, valid))
        else:  # null type
            a = np.zeros(count, dtype=np.int64)
            ivals[f] = a.ctypes.data_as(i64p)
            valid[:] = 0
            holders.append(("i", a, valid))
    rc = lib.hst_avro_decode_block(
        buf.ctypes.data_as(u8p), len(block), count, n_fields,
        plan_arr.ctypes.data_as(i32p), ivals, dvals, offs, sdata,
        sdata_len.ctypes.data_as(i64p), valids)
    if rc < 0:
        raise ValueError(str(_AVRO_ERRORS.get(int(rc), rc)))
    out = []
    for f, h in enumerate(holders):
        if h[0] == "s":
            _, o, d, valid = h
            out.append(("s", o, bytes(d[:int(sdata_len[f])]), valid))
        else:
            kind, a, valid = h
            out.append((kind, a, valid))
    return out


def _np_prune(lo, hi, has, v, op_code) -> np.ndarray:
    if op_code == 0:
        keep = (lo <= v) & (v <= hi)
    elif op_code == 1:
        keep = lo < v
    elif op_code == 2:
        keep = lo <= v
    elif op_code == 3:
        keep = hi > v
    else:
        keep = hi >= v
    return keep | ~has.astype(bool)
