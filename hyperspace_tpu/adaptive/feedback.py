"""Feedback-corrected cardinality estimation + mid-query re-planning.

The sensors already exist: the join reorderer (optimizer/join_order.py)
leaves per-step ``est_rows`` records keyed by the composite
``join_actual_key`` on ``session._last_join_order``, and the staged,
fused, and SPMD executors write every executed inner join's actual
output rows to the same keys (serving/context.record_join_actual). This
module closes the loop:

- :class:`CorrectionStore` — a process-wide store (one per process,
  like telemetry/slo.get_monitor) accumulating what execution taught
  us. Two tiers: an EXACT tier keyed by the full composite join key
  (condition repr + both side signatures) holding an EMA of observed
  output rows, and a COARSE tier keyed by the unordered pair of side
  signatures holding a clamped EMA of the actual/estimate ratio. The
  exact tier answers "this very join ran before — reuse its observed
  cardinality"; the coarse tier generalizes a learned mis-estimate to
  other enumeration candidates over the same table pair.
- ``observe()`` — called from record_join_actual while
  ``adaptive.feedback.enabled`` is on; pairs the actual with the
  recorded estimate (when the reorderer left one) and updates both
  tiers under the store lock.
- ``maybe_replan()`` — the staged executor calls this at its join
  stage boundary (executor._record_join_actual): when the observed
  actual diverges from the recorded estimate past
  ``adaptive.replan.errorThreshold`` and downstream join stages remain,
  it raises :class:`ReplanRequested`. Session._execute_uncaptured
  catches it and re-executes — the re-optimize pass sees the fresh
  correction (observe ran first), so the replanned order reflects the
  measured cardinality. One replan per query (contextvar guard);
  literal-sweep batches never replan (members share scans mid-flight).

No jax imports here — the store must be importable from
serving/context.py, which sessions import without the execution stack.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from collections import OrderedDict
from typing import Optional, Tuple

from ..exceptions import HyperspaceException

# Clamp on the coarse-tier ratio: one wild observation must not swing
# every future estimate for the pair by more than this factor.
_RATIO_CLAMP = 64.0

_SUPPRESS: contextvars.ContextVar = contextvars.ContextVar(
    "hst_adaptive_replan_suppress", default=False)


class ReplanRequested(HyperspaceException):
    """Control-flow signal, not a failure: a stage boundary observed an
    actual cardinality far enough from its estimate that re-planning
    beats finishing the current plan. Raised only while
    ``adaptive.replan.enabled`` is on; always caught by
    Session._execute_uncaptured (typed as a HyperspaceException so an
    escape through an unexpected path still honors the serving tier's
    typed-error contract)."""

    def __init__(self, key: str, est_rows: float, actual_rows: int):
        super().__init__(
            f"re-plan requested: join {key!r} estimated ~{est_rows:.0f} "
            f"rows, observed {actual_rows}")
        self.key = key
        self.est_rows = float(est_rows)
        self.actual_rows = int(actual_rows)


def parse_key(key: str) -> Optional[Tuple[str, str, str]]:
    """Split one composite join key back into (condition repr,
    left signature, right signature); None for legacy/foreign keys."""
    try:
        head, right_sig = key.rsplit(" >< ", 1)
        cond, left_sig = head.rsplit(" @ ", 1)
    except ValueError:
        return None
    return cond, left_sig, right_sig


def pair_key(left_sig: str, right_sig: str) -> str:
    """Orientation-insensitive table-pair key: the same two inputs
    joined either way around have the same true cardinality."""
    a, b = sorted((left_sig, right_sig))
    return f"{a} || {b}"


class CorrectionStore:
    """Process-wide feedback accumulator. Every mutation and read holds
    ``_lock`` — the store is written from serving worker threads and
    read from whatever thread runs the optimizer (HS301)."""

    def __init__(self):
        self._lock = threading.Lock()
        # exact composite key -> EMA of observed output rows
        self._rows: "OrderedDict[str, float]" = OrderedDict()
        # pair key -> clamped EMA of actual/estimate ratio
        self._ratios: "OrderedDict[str, float]" = OrderedDict()
        self._observed = 0
        self._paired = 0
        self._replans = 0

    # -- writes ---------------------------------------------------------

    def observe(self, session, key: str, rows: int) -> None:
        parsed = parse_key(key)
        if parsed is None:
            return
        _, left_sig, right_sig = parsed
        conf = session.hs_conf
        alpha = conf.adaptive_feedback_alpha()
        cap = conf.adaptive_feedback_max_entries()
        est = lookup_estimate(session, key)
        with self._lock:
            self._observed += 1
            prev = self._rows.get(key)
            val = float(rows) if prev is None else \
                (1.0 - alpha) * prev + alpha * float(rows)
            self._rows[key] = val
            self._rows.move_to_end(key)
            while len(self._rows) > cap:
                self._rows.popitem(last=False)
            if est is not None and est > 0:
                self._paired += 1
                pk = pair_key(left_sig, right_sig)
                ratio = max(float(rows), 1.0) / max(est, 1.0)
                ratio = min(max(ratio, 1.0 / _RATIO_CLAMP), _RATIO_CLAMP)
                prev_r = self._ratios.get(pk)
                r = ratio if prev_r is None else \
                    (1.0 - alpha) * prev_r + alpha * ratio
                self._ratios[pk] = r
                self._ratios.move_to_end(pk)
                while len(self._ratios) > cap:
                    self._ratios.popitem(last=False)

    def note_replan(self) -> None:
        with self._lock:
            self._replans += 1

    # -- reads ----------------------------------------------------------

    def exact_rows(self, key: str) -> Optional[float]:
        with self._lock:
            v = self._rows.get(key)
        return None if v is None else max(1.0, v)

    def pair_ratio(self, left_sig: str, right_sig: str) -> Optional[float]:
        pk = pair_key(left_sig, right_sig)
        with self._lock:
            return self._ratios.get(pk)

    def corrected_rows(self, left_sig: str, right_sig: str,
                       est: float) -> float:
        """The coarse-tier correction the enumeration applies: the raw
        estimate scaled by the learned ratio for this table pair (the
        exact tier needs the rebuilt condition, so it applies at rebuild
        time in _reorder_chain instead)."""
        ratio = self.pair_ratio(left_sig, right_sig)
        if ratio is None:
            return est
        return max(1.0, est * ratio)

    def stats(self) -> dict:
        with self._lock:
            return {"exact_entries": len(self._rows),
                    "ratio_entries": len(self._ratios),
                    "observed": self._observed,
                    "paired": self._paired,
                    "replans": self._replans}

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._ratios.clear()
            self._observed = self._paired = self._replans = 0


_STORE: Optional[CorrectionStore] = None
_STORE_LOCK = threading.Lock()


def get_store() -> CorrectionStore:
    """The process singleton (double-checked, like slo.get_monitor)."""
    global _STORE
    if _STORE is None:
        with _STORE_LOCK:
            if _STORE is None:
                _STORE = CorrectionStore()
    return _STORE


def lookup_estimate(session, key: str) -> Optional[float]:
    """The reorderer's recorded estimate for one executed join, if the
    most recent reorder pass left one (reordered chains only — a chain
    kept in text order records no steps)."""
    records = getattr(session, "_last_join_order", None) or []
    for r in records:
        for s in (r.get("steps") or []):
            if s.get("key") == key:
                return float(s["est_rows"])
    return None


# ---------------------------------------------------------------------------
# Mid-query re-planning.
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def suppress_replans():
    """Scope guard for the re-executed attempt (and anything else that
    must run to completion): maybe_replan becomes a no-op inside."""
    token = _SUPPRESS.set(True)
    try:
        yield
    finally:
        _SUPPRESS.reset(token)


def maybe_replan(session, key: str, actual_rows: int) -> None:
    """The stage-boundary trigger (called by the staged executor right
    after the actual-rows write-back, which already fed the store): when
    the observed actual diverges from the recorded estimate past the
    threshold AND downstream join stages remain in the same chain, raise
    ReplanRequested so Session._execute_uncaptured re-optimizes with the
    fresh correction applied."""
    if _SUPPRESS.get():
        return
    from ..serving import batcher
    if batcher.active_sweep() is not None:
        # Sweep members share scans and a single vmapped program;
        # aborting one member mid-batch would strand the others.
        return
    records = getattr(session, "_last_join_order", None) or []
    est = None
    is_last = True
    for r in records:
        steps = r.get("steps") or []
        for i, s in enumerate(steps):
            if s.get("key") == key:
                est = float(s["est_rows"])
                is_last = i == len(steps) - 1
    if est is None or est <= 0 or is_last:
        # No estimate to diverge from, or no downstream join stage that
        # a corrected order could improve.
        return
    actual = max(float(actual_rows), 1.0)
    q = max(actual / est, est / actual)
    if q <= session.hs_conf.adaptive_replan_error_threshold():
        return
    get_store().note_replan()
    raise ReplanRequested(key, est, actual_rows)


def emit_replan_event(session, rr: ReplanRequested) -> None:
    try:
        from ..telemetry.events import ReplanEvent
        from ..telemetry.logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            ReplanEvent(
                message=(f"mid-query re-plan: estimated "
                         f"~{rr.est_rows:.0f} rows, observed "
                         f"{rr.actual_rows}"),
                key=rr.key, est_rows=round(rr.est_rows, 3),
                actual_rows=rr.actual_rows,
                threshold=session.hs_conf
                .adaptive_replan_error_threshold()))
    except Exception:
        pass  # observability must never fail a query
