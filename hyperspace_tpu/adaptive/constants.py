"""Adaptive control-plane config keys.

No reference analogue: the original project's advisor recommends but
never acts, and its planner never learns from execution. The design
here follows the adaptive-execution literature (PAPERS.md: approximate
answers under overload, "Approximate Distributed Joins", arxiv
1805.05874; autonomous index/sketch materialization, "Extensible Data
Skipping", arxiv 2009.08150).

Keys live under ``hyperspace.tpu.adaptive.*`` and are read exclusively
through config.py accessors (the scripts/lint.py env-read gate) and must
each appear in docs/configuration.md (the scripts/lint.py doc-drift
gate).
"""

from __future__ import annotations


class AdaptiveConstants:
    # Master switch for the whole control plane. Off (the default)
    # means feedback, re-planning, the background builder, and
    # SLO-driven admission are all inert and behavior is byte-identical
    # to a build without adaptive/.
    ENABLED = "hyperspace.tpu.adaptive.enabled"
    ENABLED_DEFAULT = "false"

    # Feedback-corrected optimization: accumulate per-join correction
    # factors from observed actual rows and apply them inside the join
    # reorderer's cardinality estimates.
    FEEDBACK_ENABLED = "hyperspace.tpu.adaptive.feedback.enabled"
    FEEDBACK_ENABLED_DEFAULT = "true"

    # Bound on distinct correction entries kept in the process-wide
    # store (exact join keys + coarse table-pair keys, counted
    # together); oldest entries drop first.
    FEEDBACK_MAX_ENTRIES = "hyperspace.tpu.adaptive.feedback.maxEntries"
    FEEDBACK_MAX_ENTRIES_DEFAULT = "4096"

    # EMA weight given to the newest observed est/actual ratio when a
    # correction entry already exists (1.0 = always replace).
    FEEDBACK_ALPHA = "hyperspace.tpu.adaptive.feedback.alpha"
    FEEDBACK_ALPHA_DEFAULT = "0.5"

    # Mid-query re-planning at stage boundaries.
    REPLAN_ENABLED = "hyperspace.tpu.adaptive.replan.enabled"
    REPLAN_ENABLED_DEFAULT = "true"

    # Trigger threshold: a stage whose observed actual rows diverge
    # from the optimizer's estimate by more than this factor (either
    # direction) aborts staged execution and re-plans with the fresh
    # correction applied.
    REPLAN_ERROR_THRESHOLD = "hyperspace.tpu.adaptive.replan.errorThreshold"
    REPLAN_ERROR_THRESHOLD_DEFAULT = "8.0"

    # Background builder: materialize top advisor recommendations and
    # run streaming maintenance during serving-pool idle windows.
    BUILDER_ENABLED = "hyperspace.tpu.adaptive.builder.enabled"
    BUILDER_ENABLED_DEFAULT = "true"

    # Byte budget for index data the builder may materialize over its
    # lifetime; a build whose predicted size would exceed the remaining
    # budget is skipped.
    BUILDER_MAX_BYTES = "hyperspace.tpu.adaptive.builder.maxBytes"
    BUILDER_MAX_BYTES_DEFAULT = "1073741824"

    # The serving frontend must have been idle (no queued entries, no
    # active workers) for at least this long before the builder spends
    # its budget.
    BUILDER_IDLE_MS = "hyperspace.tpu.adaptive.builder.idleMs"
    BUILDER_IDLE_MS_DEFAULT = "200"

    # Retirement guard: an ACTIVE index is only retired as a loser once
    # at least this many queries ran since the builder first saw it,
    # and its measured usageCount is still zero.
    BUILDER_RETIRE_MIN_QUERIES = \
        "hyperspace.tpu.adaptive.builder.retireMinQueries"
    BUILDER_RETIRE_MIN_QUERIES_DEFAULT = "32"

    # Poll interval of the optional background daemon loop.
    BUILDER_INTERVAL_MS = "hyperspace.tpu.adaptive.builder.intervalMs"
    BUILDER_INTERVAL_MS_DEFAULT = "1000"

    # SLO-driven admission: act on SloMonitor breach verdicts at the
    # serving frontend.
    ADMISSION_ENABLED = "hyperspace.tpu.adaptive.admission.enabled"
    ADMISSION_ENABLED_DEFAULT = "true"

    # What a breach does to new submissions: "shed" rejects at submit
    # with a typed ServingRejectedError; "degrade" admits but runs
    # eligible aggregate plans on a sampled file subset, attaching a
    # stated error bound to the (approximate) result.
    ADMISSION_MODE = "hyperspace.tpu.adaptive.admission.mode"
    ADMISSION_MODE_DEFAULT = "degrade"

    # Fraction of source files the approximate tier scans (per leaf,
    # deterministic prefix after sorting; at least one file).
    ADMISSION_SAMPLE_FRACTION = \
        "hyperspace.tpu.adaptive.admission.sampleFraction"
    ADMISSION_SAMPLE_FRACTION_DEFAULT = "0.25"
