"""adaptive/ — the self-driving control plane.

Closes the three feedback loops every sensor below it already feeds
(ROADMAP item 1: "Every sensor now exists; nothing acts on them yet"):

- feedback.py — a process-wide correction store pairs the optimizer's
  per-join cardinality estimates with the actual row counts the staged,
  fused, and SPMD executors record, feeds the learned correction
  factors back into join reordering, and triggers mid-query re-planning
  at stage boundaries when an observed actual blows past its estimate.
- builder.py — a budgeted background builder rides the serving pool's
  idle windows: materializes the advisor's top recommendations, retires
  indexes whose measured usageCount stays zero, and schedules streaming
  maintenance (optimize/compact) off the same idle-window ledger.
- admission.py — wires SloMonitor breach verdicts into the serving
  frontend: on breach, shed at submit or degrade eligible aggregate
  queries to a sampled approximate answer with a stated error bound,
  recovering to exact answers when health() clears.

Everything is off-able via ``hyperspace.tpu.adaptive.*`` conf (master
switch ``hyperspace.tpu.adaptive.enabled``, default false) read through
config.py only.
"""

from .constants import AdaptiveConstants  # noqa: F401


def emit_action(session, action: str, subject: str = "",
                detail: str = "") -> None:
    """One AdaptiveActionEvent per control-plane decision (builder
    build/retire/maintain, admission engage/recover). Best-effort —
    observability must never fail the control plane."""
    try:
        from ..telemetry.events import AdaptiveActionEvent
        from ..telemetry.logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            AdaptiveActionEvent(
                message=f"adaptive action: {action}"
                        + (f" ({subject})" if subject else ""),
                action=action, subject=subject, detail=detail))
    except Exception:
        pass
