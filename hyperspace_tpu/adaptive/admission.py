"""SLO-driven admission: breach → shed or degrade, recover on health.

r18 shipped the sensors (telemetry/slo.py: sliding-window monitors,
edge-triggered SloBreachEvent, ``Hyperspace.health()``) explicitly "not
yet wired to admission control". This wires them: the serving frontend
asks :class:`AdmissionController` at submit time, and while any armed
objective is breached the controller answers the configured
``adaptive.admission.mode``:

- ``shed`` — the submit raises the typed ServingRejectedError (clients
  see the same error queue-depth rejection raises today);
- ``degrade`` — the query is admitted, but if its plan is an eligible
  aggregation the worker runs it over a deterministic sampled subset of
  each scan's files and the result carries a stated error bound
  (``Table.approx_error_bound``) — an approximate answer beats an
  error under overload (PAPERS.md: arxiv 1805.05874). Ineligible plans
  run exact.

The controller re-evaluates the monitor at most once per second (the
verdict is cached between refreshes) and recovery is automatic: the
first healthy verdict flips back to exact answers and emits an
AdaptiveActionEvent("recover").
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import List, Optional, Tuple

from ..plan import expr as E
from ..plan.nodes import (Aggregate, Limit, LogicalPlan, Project, Scan,
                          Sort)

# Seconds between SloMonitor re-evaluations (between them, decide()
# answers from the cached verdict).
_REFRESH_S = 1.0


class AdmissionController:
    """Process-wide admission verdict, fed by the SLO monitor. All
    mutable state behind ``_lock`` (submits race from client threads;
    HS301)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._overloaded = False
        self._last_refresh = 0.0
        self._stats = {"breaches": 0, "recoveries": 0,
                       "sheds": 0, "degrades": 0, "ingest_pauses": 0}

    def refresh(self, session, force: bool = False) -> bool:
        """Re-evaluate the SLO monitor (rate-limited unless ``force``)
        and return the current overload verdict."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_refresh < _REFRESH_S:
                return self._overloaded
            self._last_refresh = now
        from ..telemetry.slo import get_monitor
        verdict = get_monitor().evaluate(session, now=now)
        breached = any(
            o.get("breached")
            for o in (verdict.get("objectives") or {}).values())
        action = None
        with self._lock:
            was = self._overloaded
            self._overloaded = bool(breached)
            if breached and not was:
                self._stats["breaches"] += 1
                action = "admission.engage"
            elif was and not breached:
                self._stats["recoveries"] += 1
                action = "admission.recover"
        if action is not None:
            from . import emit_action
            mode = session.hs_conf.adaptive_admission_mode()
            emit_action(session, action, subject=mode,
                        detail=("SLO breach: new submissions will "
                                f"{mode}" if action.endswith("engage")
                                else "health() clear: exact answers "
                                     "resume"))
        return bool(breached)

    def decide(self, session, force_refresh: bool = False) -> str:
        """'admit' | 'shed' | 'degrade' for one submission."""
        if not session.hs_conf.adaptive_admission_enabled():
            return "admit"
        if not self.refresh(session, force=force_refresh):
            return "admit"
        mode = session.hs_conf.adaptive_admission_mode()
        with self._lock:
            self._stats["sheds" if mode == "shed" else "degrades"] += 1
        return mode

    def overloaded(self) -> bool:
        with self._lock:
            return self._overloaded

    def should_pause_ingest(self, session) -> bool:
        """Continuous-source backpressure (streaming/sources.py): while
        any armed objective is breached, tailers stop pulling new input
        so serving drains first — ingest is the deferrable work. Counts
        one ``ingest_pauses`` per answered pause; admission disabled
        means never pause."""
        if not session.hs_conf.adaptive_admission_enabled():
            return False
        if not self.refresh(session):
            return False
        with self._lock:
            self._stats["ingest_pauses"] += 1
        return True

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["overloaded"] = self._overloaded
        return out

    def reset(self) -> None:
        with self._lock:
            self._overloaded = False
            self._last_refresh = 0.0
            for k in self._stats:
                self._stats[k] = 0


_CONTROLLER: Optional[AdmissionController] = None
_CONTROLLER_LOCK = threading.Lock()


def get_controller() -> AdmissionController:
    """The process singleton (double-checked, like slo.get_monitor)."""
    global _CONTROLLER
    if _CONTROLLER is None:
        with _CONTROLLER_LOCK:
            if _CONTROLLER is None:
                _CONTROLLER = AdmissionController()
    return _CONTROLLER


# ---------------------------------------------------------------------------
# The approximate tier: sampled scans + scaled aggregates + stated bound.
# ---------------------------------------------------------------------------

_SCALED = (E.Count, E.Sum)
_UNSCALED = (E.Min, E.Max, E.Avg)


def _agg_kind(a) -> Optional[type]:
    inner = a.child if isinstance(a, E.Alias) else a
    for kind in _SCALED + _UNSCALED:
        if type(inner) is kind:
            return kind
    return None


def _sample_relation(rel, fraction: float):
    """(sampled relation, kept bytes, total bytes, kept files) or None
    when the relation has nothing to drop. The kept prefix of the
    sorted listing is deterministic: the same plan degrades to the same
    approximate answer every time."""
    try:
        files = sorted(rel.all_files())
    except Exception:
        return None
    if len(files) < 2:
        return None
    keep_n = max(1, int(math.ceil(len(files) * fraction)))
    if keep_n >= len(files):
        return None

    def _size(f: str) -> int:
        try:
            return os.path.getsize(f)
        except OSError:
            return 0

    total = sum(_size(f) for f in files)
    kept_files = files[:keep_n]
    kept = sum(_size(f) for f in kept_files)
    if total <= 0 or kept <= 0:
        return None
    return rel.with_files(kept_files), kept, total, keep_n


def approximate_plan(session, plan: LogicalPlan
                     ) -> Optional[Tuple[LogicalPlan, dict]]:
    """The degraded rewrite, or None when ``plan`` is not an eligible
    aggregation (ineligible queries run exact even under breach).
    Eligible: optional Sort/Limit/Project wrappers over ONE Aggregate
    whose aggregates are Count/Sum/Min/Max/Avg and whose subtree scans
    at least one multi-file source. The rewrite samples a deterministic
    file prefix per scan, scales Count/Sum outputs by the inverse
    sampled-byte fraction (Avg is self-normalizing; Min/Max stay raw),
    and returns the stated error bound to attach to the result."""
    wrappers: List[LogicalPlan] = []
    node = plan
    while isinstance(node, (Sort, Limit)) or (
            isinstance(node, Project)
            and all(isinstance(e, E.Col) for e in node.exprs)):
        wrappers.append(node)
        node = node.children[0]
    if not isinstance(node, Aggregate):
        return None
    kinds = [_agg_kind(a) for a in node.aggs]
    if not kinds or any(k is None for k in kinds):
        return None

    fraction = session.hs_conf.adaptive_admission_sample_fraction()
    scale = 1.0
    kept_files_total = 0
    sampled = [0]

    def _swap(n: LogicalPlan) -> LogicalPlan:
        nonlocal scale, kept_files_total
        if not isinstance(n, Scan):
            return n
        rel = getattr(n, "relation", None)
        if rel is None:
            return n
        hit = _sample_relation(rel, fraction)
        if hit is None:
            return n
        new_rel, kept, total, keep_n = hit
        scale *= total / kept
        kept_files_total += keep_n
        sampled[0] += 1
        return Scan(new_rel)

    approx_child = node.child.transform_up(_swap)
    if sampled[0] == 0:
        return None  # nothing to sample — run exact
    agg = Aggregate(node.group_cols, node.aggs, approx_child)

    exprs: List[E.Expr] = [E.Col(g) for g in node.group_cols]
    for a, kind in zip(node.aggs, kinds):
        if kind in _SCALED and scale != 1.0:
            exprs.append(E.Alias(
                E.Multiply(E.Col(a.name), E.Lit(scale)), a.name))
        else:
            exprs.append(E.Col(a.name))
    out: LogicalPlan = Project(exprs, agg)
    for w in reversed(wrappers):
        out = w.with_children([out])

    effective = 1.0 / scale
    bound = {
        "kind": "relative",
        "confidence": 0.95,
        "sample_fraction": round(effective, 4),
        # CLT-flavored heuristic over the kept file count: wide enough
        # to be honest for sums/counts over roughly size-balanced
        # files, and explicitly a heuristic — the point is a STATED
        # bound on an answer that would otherwise be an error.
        "bound": round(min(1.0, 2.0 * math.sqrt(
            max(0.0, 1.0 - effective)
            / max(1, kept_files_total))), 4),
        "scaled": [a.name for a, k in zip(node.aggs, kinds)
                   if k in _SCALED],
    }
    return out, bound
