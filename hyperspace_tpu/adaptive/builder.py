"""The budgeted background builder: the advisor finally acts.

r08's advisor ranks index recommendations and r11's serving pool knows
when it is idle; until now a human had to connect them. This module
closes that loop with one ledger and one actor:

- :class:`BuilderLedger` — process-wide accounting: what was built,
  what was retired, bytes spent against ``adaptive.builder.maxBytes``,
  which build is in flight, and how long the serving tier has been
  idle. The ledger is the crash-visibility surface the chaos soak
  asserts on: ``in_progress`` must drain to empty.
- :class:`AdaptiveBuilder` — one maintenance pass per idle window
  (``run_once``), optionally self-scheduling on a daemon thread
  (``start``/``stop``; thread via the sanctioned
  :func:`parallel.io.spawn_daemon`). A pass only fires after every
  live serving frontend has been empty for ``adaptive.builder.idleMs``
  — in-flight queries never share the machine with a build. Each pass,
  in order: materialize the advisor's current top recommendation
  (within the byte budget, through the normal create path so op-log
  crash recovery covers it), retire ACTIVE indexes whose measured
  usageCount is still zero after ``retireMinQueries`` completed
  queries of observation, and run r17 streaming maintenance
  (op-log compaction) off the same idle window — compaction is
  documented "run it in a quiet window", and the ledger is precisely
  the thing that knows when the window is quiet.

Every action emits an AdaptiveActionEvent; everything is off-able via
``hyperspace.tpu.adaptive.builder.enabled``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

__all__ = ["BuilderLedger", "AdaptiveBuilder", "get_ledger",
           "get_builder"]


class BuilderLedger:
    """Process-wide builder accounting. All mutable state behind
    ``_lock`` (the daemon loop, explicit run_once callers, and stats
    readers race; HS301)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._built: List[str] = []
        self._retired: List[str] = []
        self._maintained: List[str] = []
        self._bytes_spent = 0
        self._in_progress: set = set()
        # index name -> SLO-monitor cumulative query total when the
        # builder first saw it ACTIVE with zero usage (retirement clock).
        self._first_seen: Dict[str, int] = {}
        self._idle_since: Optional[float] = None

    # -- idle-window tracking -------------------------------------------

    def note_activity(self, now: Optional[float] = None) -> None:
        """The serving tier is busy: restart the idle clock."""
        with self._lock:
            self._idle_since = None

    def idle_for(self, now: Optional[float] = None) -> float:
        """Seconds the serving tier has been continuously idle (starts
        the clock on the first idle observation)."""
        t = now if now is not None else time.monotonic()
        with self._lock:
            if self._idle_since is None:
                self._idle_since = t
            return t - self._idle_since

    # -- build accounting ------------------------------------------------

    def begin(self, names) -> None:
        with self._lock:
            self._in_progress.update(names)

    def finish(self, names, ok: bool, bytes_added: int = 0) -> None:
        with self._lock:
            self._in_progress.difference_update(names)
            if ok:
                self._built.extend(names)
                self._bytes_spent += max(int(bytes_added), 0)

    def bytes_spent(self) -> int:
        with self._lock:
            return self._bytes_spent

    # -- retirement clock ------------------------------------------------

    def observed_since(self, name: str, total_now: int) -> int:
        """Completed queries since the builder first saw ``name`` idle
        (first call starts the clock and returns 0)."""
        with self._lock:
            first = self._first_seen.setdefault(name, int(total_now))
            return int(total_now) - first

    def reset_observation(self, name: str) -> None:
        """``name`` was used (or removed): forget its retirement clock."""
        with self._lock:
            self._first_seen.pop(name, None)

    def note_retired(self, name: str) -> None:
        with self._lock:
            self._retired.append(name)
            self._first_seen.pop(name, None)

    def note_maintenance(self, action: str) -> None:
        with self._lock:
            self._maintained.append(action)

    # -- observability ---------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "built": list(self._built),
                "retired": list(self._retired),
                "maintained": list(self._maintained),
                "bytes_spent": self._bytes_spent,
                "in_progress": sorted(self._in_progress),
            }

    def clear(self) -> None:
        with self._lock:
            self._built.clear()
            self._retired.clear()
            self._maintained.clear()
            self._bytes_spent = 0
            self._in_progress.clear()
            self._first_seen.clear()
            self._idle_since = None


_LEDGER: Optional[BuilderLedger] = None
_LEDGER_LOCK = threading.Lock()


def get_ledger() -> BuilderLedger:
    """THE process builder ledger (double-checked singleton)."""
    global _LEDGER
    if _LEDGER is None:
        with _LEDGER_LOCK:
            if _LEDGER is None:
                _LEDGER = BuilderLedger()
    return _LEDGER


class AdaptiveBuilder:
    """One background maintenance actor over one Hyperspace handle."""

    def __init__(self, hyperspace, ledger: Optional[BuilderLedger] = None):
        self._hs = hyperspace
        self._ledger = ledger if ledger is not None else get_ledger()
        self._stop_event = threading.Event()
        # The daemon thread handle (spawned via parallel.io.spawn_daemon,
        # the package's one sanctioned thread-construction site).
        self._thread = None

    # -- idle detection --------------------------------------------------

    def _serving_busy(self) -> bool:
        """Any live frontend with queued or executing work."""
        from ..serving.frontend import all_frontends
        for front in all_frontends():
            try:
                st = front.stats()
            except Exception:
                continue
            if st.get("queued", 0) or st.get("active_workers", 0):
                return True
        return False

    # -- the pass --------------------------------------------------------

    def run_once(self, force: bool = False) -> dict:
        """One maintenance pass. ``force`` skips the idle-window wait
        (tests and operators); the busy check still applies — a build
        never overlaps in-flight serving work. Returns a summary dict
        ({ran, built, retired, maintained} + a reason when skipped)."""
        session = self._hs.session
        conf = session.hs_conf
        out: dict = {"ran": False, "built": [], "retired": [],
                     "maintained": []}
        if not conf.adaptive_builder_enabled():
            out["reason"] = "disabled"
            return out
        now = time.monotonic()
        led = self._ledger
        if self._serving_busy():
            led.note_activity(now)
            out["reason"] = "serving busy"
            return out
        if not force and \
                led.idle_for(now) * 1000.0 < conf.adaptive_builder_idle_ms():
            out["reason"] = "idle window still warming"
            return out
        out["ran"] = True
        out["built"] = self._build_top_recommendation(session, conf)
        out["retired"] = self._retire_unused(session, conf)
        out["maintained"] = self._streaming_maintenance(session)
        return out

    def _build_top_recommendation(self, session, conf) -> List[str]:
        """Materialize the advisor's current top recommendation whose
        indexes don't exist yet, within the byte budget."""
        led = self._ledger
        max_bytes = conf.adaptive_builder_max_bytes()
        if max_bytes and led.bytes_spent() >= max_bytes:
            return []
        try:
            report = self._hs.recommend(top_k=1)
            recos = list(report.recommendations)
        except Exception:
            recos = []
        if not recos:
            return []
        rec = recos[0]
        manager = session.index_collection_manager
        missing = [n for n in rec.names
                   if manager.get_index(n) is None]
        if not missing:
            return []
        led.begin(rec.names)
        ok = False
        try:
            self._hs.build_recommendation(rec)
            ok = True
        except Exception:
            pass
        finally:
            built_bytes = 0
            if ok:
                for name in missing:
                    try:
                        entry = manager.get_index(name)
                        if entry is not None:
                            built_bytes += entry.index_files_size_in_bytes
                    except Exception:
                        pass
            led.finish(rec.names, ok, built_bytes)
        if not ok:
            return []
        from . import emit_action
        for name in missing:
            emit_action(session, "builder.build", subject=name,
                        detail=(f"advisor top recommendation; "
                                f"{built_bytes} bytes against budget "
                                f"{max_bytes}"))
        return missing

    def _retire_unused(self, session, conf) -> List[str]:
        """Delete ACTIVE indexes whose measured usageCount is still zero
        after ``retireMinQueries`` completed queries of observation.
        Soft delete (``delete_index``) — ``restore_index`` undoes a
        wrong call; bytes go back only when an operator vacuums."""
        from ..index.constants import States
        from ..telemetry.slo import get_monitor
        led = self._ledger
        min_queries = conf.adaptive_builder_retire_min_queries()
        total = get_monitor().total
        manager = session.index_collection_manager
        with session._usage_counts_lock:
            usage = dict(session._index_usage_counts)
        retired: List[str] = []
        for entry in manager.get_indexes([States.ACTIVE]):
            if usage.get(entry.name, 0) > 0:
                led.reset_observation(entry.name)
                continue
            if led.observed_since(entry.name, total) < min_queries:
                continue
            try:
                self._hs.delete_index(entry.name)
            except Exception:
                continue
            led.note_retired(entry.name)
            retired.append(entry.name)
            from . import emit_action
            emit_action(session, "builder.retire", subject=entry.name,
                        detail=(f"usageCount 0 after "
                                f"{min_queries}+ completed queries"))
        return retired

    def _streaming_maintenance(self, session) -> List[str]:
        """r17 op-log compaction in the same quiet window. The
        compaction module's own min-entries threshold decides what is
        actually foldable, so an already-tight lake is a no-op."""
        led = self._ledger
        try:
            summary = self._hs.compact(None)
        except Exception:
            return []
        done = sorted((summary.get("compacted") or {}).keys())
        for name in done:
            led.note_maintenance(f"compact:{name}")
            from . import emit_action
            emit_action(session, "builder.maintain", subject=name,
                        detail="op-log compaction in idle window")
        return done

    # -- optional self-scheduling ---------------------------------------

    def start(self) -> None:
        """Run ``run_once`` every ``adaptive.builder.intervalMs`` on a
        daemon thread (idempotent while running)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_event.clear()
        from ..parallel import io as pio
        self._thread = pio.spawn_daemon("hst-adaptive-builder",
                                        self._loop)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop_event.is_set():
            try:
                interval_ms = self._hs.session.hs_conf \
                    .adaptive_builder_interval_ms()
            except Exception:
                interval_ms = 1000
            if self._stop_event.wait(interval_ms / 1000.0):
                return
            try:
                self.run_once()
            except Exception:
                pass  # the maintenance loop must outlive one bad pass


_BUILDER: Optional[AdaptiveBuilder] = None
_BUILDER_LOCK = threading.Lock()


def get_builder(hyperspace) -> AdaptiveBuilder:
    """The process-default builder, created on first use with
    ``hyperspace`` as its governing handle (later calls return the
    existing builder regardless of the handle, like get_frontend)."""
    global _BUILDER
    if _BUILDER is None:
        with _BUILDER_LOCK:
            if _BUILDER is None:
                _BUILDER = AdaptiveBuilder(hyperspace)
    return _BUILDER
