"""A minimal SQL SELECT front-end over registered temp views.

The reference's users write Spark SQL; this framework's primary surface is
the DataFrame IR, and `session.sql(...)` lowers a practical SELECT subset
onto it — so every index rewrite, skipping rule, and execution path behaves
exactly as for the equivalent DataFrame query.

Supported grammar (case-insensitive keywords):

    query      := select [UNION ALL select]*
    select     := SELECT [DISTINCT] <*| expr [AS name], ...>
                  FROM table_ref
                  [ [INNER|LEFT|RIGHT|FULL] JOIN table_ref
                    ON a = b [AND c = d] ]*
                  [WHERE <predicate>]
                  [GROUP BY col, ...] [HAVING <predicate>]
                  [ORDER BY col [ASC|DESC], ...] [LIMIT n]
    table_ref  := <view> | ( select ) [AS name]

Expressions: identifiers, integer/float/string literals, DATE 'yyyy-mm-dd',
+ - * /, comparisons (= != <> < <= > >=), BETWEEN x AND y, [NOT] IN (...),
AND/OR/NOT, and aggregates SUM/AVG/MIN/MAX/COUNT(*)/COUNT(x)/
COUNT(DISTINCT x). Everything else raises a clear error naming the token.
"""

from __future__ import annotations

import datetime
import re
from typing import List, Optional, Tuple

from .exceptions import HyperspaceException
from .plan import expr as E

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<date>DATE\s*'(\d{4}-\d{2}-\d{2})')
    | (?P<str>'(?:[^']|'')*')
    | (?P<num>\d+\.\d+|\d+)
    | (?P<ident>[A-Za-z_][A-Za-z_0-9.]*)
    | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|/|\+|-)
    )""", re.VERBOSE | re.IGNORECASE)

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "ON", "AS", "AND",
    "OR", "NOT", "IN", "BETWEEN", "ASC", "DESC", "DATE", "DISTINCT", "UNION", "ALL",
    "SUM", "AVG", "MIN", "MAX", "COUNT",
}


def _tokenize(text: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            rest = text[pos:].strip()
            if not rest:
                break
            raise HyperspaceException(
                f"SQL: cannot tokenize near {rest[:25]!r}")
        pos = m.end()
        if m.group("date"):
            out.append(("DATE_LIT", m.group(2)))
        elif m.group("str"):
            out.append(("STR", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("num"):
            out.append(("NUM", m.group("num")))
        elif m.group("ident"):
            word = m.group("ident")
            if word.upper() in _KEYWORDS:
                out.append(("KW", word.upper()))
            else:
                out.append(("IDENT", word))
        else:
            out.append(("OP", m.group("op")))
    out.append(("EOF", ""))
    return out


class _Parser:
    def __init__(self, session, text: str):
        self.session = session
        self.toks = _tokenize(text)
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def peek(self, kind: str = None, value: str = None) -> bool:
        k, v = self.toks[self.i]
        if kind is not None and k != kind:
            return False
        if value is not None and v != value:
            return False
        return True

    def take(self, kind: str = None, value: str = None) -> str:
        k, v = self.toks[self.i]
        if (kind is not None and k != kind) or \
                (value is not None and v != value):
            raise HyperspaceException(
                f"SQL: expected {value or kind} but found {v or k!r}")
        self.i += 1
        return v

    def accept(self, kind: str, value: str = None) -> bool:
        if self.peek(kind, value):
            self.i += 1
            return True
        return False

    # -- expressions -----------------------------------------------------
    def expr(self) -> E.Expr:
        return self._or()

    def _or(self) -> E.Expr:
        e = self._and()
        while self.accept("KW", "OR"):
            e = e | self._and()
        return e

    def _and(self) -> E.Expr:
        e = self._not()
        while self.accept("KW", "AND"):
            e = e & self._not()
        return e

    def _not(self) -> E.Expr:
        if self.accept("KW", "NOT"):
            return ~self._not()
        return self._comparison()

    def _comparison(self) -> E.Expr:
        left = self._additive()
        if self.accept("KW", "BETWEEN"):
            lo = self._additive()
            self.take("KW", "AND")
            hi = self._additive()
            return left.between(_lit_value(lo), _lit_value(hi))
        negated = False
        if self.peek("KW", "NOT"):
            # Only NOT IN reaches here (prefix NOT handled above).
            self.take("KW", "NOT")
            self.take("KW", "IN")
            negated = True
        elif self.accept("KW", "IN"):
            pass
        else:
            for op, make in (("=", lambda a, b: a == b),
                             ("!=", lambda a, b: a != b),
                             ("<>", lambda a, b: a != b),
                             ("<=", lambda a, b: a <= b),
                             (">=", lambda a, b: a >= b),
                             ("<", lambda a, b: a < b),
                             (">", lambda a, b: a > b)):
                if self.accept("OP", op):
                    return make(left, self._additive())
            return left
        self.take("OP", "(")
        values = [_lit_value(self._additive())]
        while self.accept("OP", ","):
            values.append(_lit_value(self._additive()))
        self.take("OP", ")")
        e = left.isin(values)
        return ~e if negated else e

    def _additive(self) -> E.Expr:
        e = self._multiplicative()
        while True:
            if self.accept("OP", "+"):
                e = _fold(e, self._multiplicative(), lambda a, b: a + b,
                          lambda a, b: a + b)
            elif self.accept("OP", "-"):
                e = _fold(e, self._multiplicative(), lambda a, b: a - b,
                          lambda a, b: a - b)
            else:
                return e

    def _multiplicative(self) -> E.Expr:
        e = self._atom()
        while True:
            if self.accept("OP", "*"):
                e = _fold(e, self._atom(), lambda a, b: a * b,
                          lambda a, b: a * b)
            elif self.accept("OP", "/"):
                e = _fold(e, self._atom(), lambda a, b: a / b,
                          lambda a, b: a / b)
            else:
                return e

    def _atom(self) -> E.Expr:
        if self.accept("OP", "-"):
            # Unary minus: folds for literals, 0 - x otherwise.
            return _fold(E.lit(0), self._atom(), lambda a, b: a - b,
                         lambda a, b: a - b)
        if self.accept("OP", "("):
            e = self.expr()
            self.take("OP", ")")
            return e
        if self.peek("KW") and self.toks[self.i][1] in (
                "SUM", "AVG", "MIN", "MAX", "COUNT"):
            return self._aggregate()
        if self.peek("IDENT"):
            return E.col(self.take("IDENT"))
        if self.peek("NUM"):
            raw = self.take("NUM")
            return E.lit(float(raw) if "." in raw else int(raw))
        if self.peek("STR"):
            return E.lit(self.take("STR"))
        if self.peek("DATE_LIT"):
            return E.lit(datetime.date.fromisoformat(self.take("DATE_LIT")))
        raise HyperspaceException(
            f"SQL: unexpected token {self.toks[self.i][1]!r}")

    def _aggregate(self) -> E.Expr:
        fn = self.take("KW")
        self.take("OP", "(")
        if fn == "COUNT":
            if self.accept("OP", "*"):
                self.take("OP", ")")
                return E.count(None)
            if self.accept("KW", "DISTINCT"):
                inner = self.expr()
                self.take("OP", ")")
                return E.count_distinct(inner)
            inner = self.expr()
            self.take("OP", ")")
            return E.count(inner)
        inner = self.expr()
        self.take("OP", ")")
        return {"SUM": E.sum_, "AVG": E.avg,
                "MIN": E.min_, "MAX": E.max_}[fn](inner)

    # -- query -----------------------------------------------------------
    def query(self):
        df = self._query_body()
        self.take("EOF")
        return df

    def _query_body(self):
        """select [UNION ALL select]* [ORDER BY ...] [LIMIT n] — a
        trailing ORDER BY/LIMIT binds to the WHOLE union (standard SQL),
        and the same production serves derived tables."""
        df = self._select_stmt()
        while self.peek("KW", "UNION"):
            self.take("KW", "UNION")
            self.take("KW", "ALL")
            df = df.union(self._select_stmt())
        return self._order_limit(df)

    def _order_limit(self, df):
        if self.accept("KW", "ORDER"):
            self.take("KW", "BY")
            orders = [self._order_item()]
            while self.accept("OP", ","):
                orders.append(self._order_item())
            df = df.sort(*orders)
        if self.accept("KW", "LIMIT"):
            raw = self.take("NUM")
            if "." in raw:
                raise HyperspaceException(
                    f"SQL: LIMIT takes an integer, found {raw!r}")
            df = df.limit(int(raw))
        return df

    def _table_ref(self):
        if self.accept("OP", "("):
            # Derived table: ( query-body ) [AS name] — may itself contain
            # UNION ALL and its own ORDER BY/LIMIT.
            inner = self._query_body()
            self.take("OP", ")")
            if self.accept("KW", "AS"):
                self.take("IDENT")
            elif self.peek("IDENT"):
                self.take("IDENT")
            return inner
        return self.session.table(self.take("IDENT"))

    def _select_stmt(self):
        self.take("KW", "SELECT")
        distinct = self.accept("KW", "DISTINCT")
        items: List[Tuple[Optional[E.Expr], Optional[str]]] = []
        star = False
        if self.accept("OP", "*"):
            star = True
        else:
            items.append(self._select_item())
            while self.accept("OP", ","):
                items.append(self._select_item())

        self.take("KW", "FROM")
        df = self._table_ref()

        while self.peek("KW") and self.toks[self.i][1] in (
                "JOIN", "INNER", "LEFT", "RIGHT", "FULL"):
            df = self._join(df)

        if self.accept("KW", "WHERE"):
            df = df.filter(self.expr())

        group_cols: List[str] = []
        if self.accept("KW", "GROUP"):
            self.take("KW", "BY")
            group_cols.append(self.take("IDENT"))
            while self.accept("OP", ","):
                group_cols.append(self.take("IDENT"))

        has_agg = any(_contains_agg(e) for e, _ in items if e is not None)
        if group_cols or has_agg:
            if star:
                raise HyperspaceException(
                    "SQL: SELECT * cannot be combined with aggregation")
            # Resolve spellings once (the API is case-insensitive; raw
            # string comparison here must be too).
            spell = df._spelling
            group_resolved = [spell(g) for g in group_cols]
            aggs, out_cols, out_names = [], [], []
            aliased = False
            for e, alias in items:
                if _contains_agg(e):
                    named = e.alias(alias) if alias else e
                    aggs.append(named)
                    out_cols.append(named.name)
                    out_names.append(named.name)
                else:
                    if not isinstance(e, E.Col):
                        raise HyperspaceException(
                            "SQL: non-aggregate select items must be "
                            "plain grouped columns")
                    spelled = spell(e.column)
                    if spelled not in group_resolved:
                        raise HyperspaceException(
                            f"SQL: column {e.column!r} must appear in "
                            "GROUP BY or inside an aggregate")
                    if alias:
                        # SELECT g AS grp: the output carries the alias.
                        aliased = True
                        out_cols.append(E.col(spelled).alias(alias))
                    else:
                        out_cols.append(spelled)
                    out_names.append(spelled)
            n_visible = len(aggs)
            visible_agg_names = [a.name for a in aggs]
            # HAVING may reference aggregates inline (standard SQL):
            # materialize them as hidden columns, filter, then project the
            # SELECT list (which also drops the hidden columns and fixes
            # the output order to the SELECT order).
            having: Optional[E.Expr] = None
            if self.accept("KW", "HAVING"):
                having = self.expr()
                having, hidden = _lift_having_aggs(having, n_visible)
                aggs.extend(hidden)
            df = (df.group_by(*group_cols).agg(*aggs) if group_cols
                  else df.agg(*aggs))
            if having is not None:
                df = df.filter(having)
            # Project only when the SELECT list differs from the
            # aggregate's natural output (group cols then aggregates) —
            # a redundant Project would make SQL plans diverge from the
            # equivalent DataFrame plans. Aliases on group columns and
            # hidden HAVING aggregates always force the projection.
            natural = group_resolved + visible_agg_names
            if aliased or out_names != natural or len(aggs) != n_visible:
                df = df.select(*out_cols)
        elif not star:
            df = df.select(*[e.alias(alias) if alias else e
                             for e, alias in items])
            if self.accept("KW", "HAVING"):
                raise HyperspaceException(
                    "SQL: HAVING requires GROUP BY or aggregates")

        if distinct:
            df = df.distinct()

        return df

    def _select_item(self):
        e = self.expr()
        alias = None
        if self.accept("KW", "AS"):
            alias = self.take("IDENT")
        elif self.peek("IDENT"):
            alias = self.take("IDENT")
        return e, alias

    def _order_item(self):
        name = self.take("IDENT")
        if self.accept("KW", "DESC"):
            return (name, False)
        self.accept("KW", "ASC")
        return (name, True)

    def _join(self, df):
        how = "inner"
        if self.accept("KW", "LEFT"):
            how = "left"
        elif self.accept("KW", "RIGHT"):
            how = "right"
        elif self.accept("KW", "FULL"):
            how = "full"
        else:
            self.accept("KW", "INNER")
        self.accept("KW", "OUTER")
        self.take("KW", "JOIN")
        other = self._table_ref()
        self.take("KW", "ON")
        cond = self._join_condition()
        return df.join(other, on=cond, how=how)

    def _join_condition(self) -> E.Expr:
        cond = self._join_eq()
        while self.accept("KW", "AND"):
            cond = cond & self._join_eq()
        return cond

    def _join_eq(self) -> E.Expr:
        left = E.col(self.take("IDENT"))
        self.take("OP", "=")
        return left == E.col(self.take("IDENT"))


def _fold(a: E.Expr, b: E.Expr, expr_op, py_op) -> E.Expr:
    """Constant-fold literal-literal arithmetic at parse time (e.g. the
    ``1 + 0.1`` inside ``price * (1 + 0.1)``) — the engine's evaluator
    deliberately rejects all-literal subtrees."""
    if isinstance(a, E.Lit) and isinstance(b, E.Lit) and \
            isinstance(a.value, (int, float)) and \
            isinstance(b.value, (int, float)):
        return E.lit(py_op(a.value, b.value))
    return expr_op(a, b)


def _contains_agg(e: Optional[E.Expr]) -> bool:
    if e is None:
        return False
    if isinstance(e, E.AggExpr):
        return True
    return any(_contains_agg(c) for c in e.children)


def _lift_having_aggs(e: E.Expr, start: int):
    """Replace every aggregate inside a HAVING predicate with a reference
    to a hidden output column, returning (rewritten predicate, the hidden
    aliased aggregates to append to the agg list)."""
    hidden: List[E.Expr] = []

    def rec(node: E.Expr) -> E.Expr:
        if isinstance(node, E.AggExpr):
            name = f"__having_{start + len(hidden)}"
            hidden.append(node.alias(name))
            return E.col(name)
        if isinstance(node, E.Col) or isinstance(node, E.Lit):
            return node
        if isinstance(node, E.Not):
            return ~rec(node.child)
        if isinstance(node, E.In):
            return E.In(rec(node.value), list(node.options))
        if isinstance(node, E.Alias):
            return rec(node.child).alias(node.alias_name)
        if isinstance(node, E._Binary):
            return type(node)(rec(node.left), rec(node.right))
        raise HyperspaceException(
            f"SQL: unsupported HAVING expression {node!r}")

    return rec(e), hidden


def _lit_value(e: E.Expr):
    if not isinstance(e, E.Lit):
        raise HyperspaceException(
            f"SQL: expected a literal, found {e!r}")
    return e.value


def sql(session, text: str):
    """Parse and lower one SELECT statement to a DataFrame."""
    return _Parser(session, text).query()
