"""A SQL SELECT front-end over registered temp views.

The reference's users write Spark SQL; this framework's primary surface is
the DataFrame IR, and `session.sql(...)` lowers a practical SELECT subset
onto it — so every index rewrite, skipping rule, and execution path behaves
exactly as for the equivalent DataFrame query. The grammar is wide enough
to run the verbatim TPC-H texts the reference exercises through Spark
(goldstandard/TPCDSBase.scala pattern; tests/test_tpch_sql.py runs the
actual query texts).

Supported grammar (case-insensitive keywords):

    query      := select [UNION ALL select]*
    select     := SELECT [DISTINCT] <*| expr [AS name], ...>
                  FROM table_ref [[AS] alias] [, table_ref [[AS] alias]]*
                  [ [INNER|LEFT|RIGHT|FULL] JOIN table_ref ON a = b [AND ...] ]*
                  [WHERE <predicate>]
                  [GROUP BY col, ...] [HAVING <predicate>]
                  [ORDER BY col|expr [ASC|DESC], ...] [LIMIT n]
                  (an ORDER BY expression must restate a SELECT item)
    table_ref  := <view> | ( select ) [AS name]

Comma-separated FROM lists are lowered to inner joins using the WHERE
clause's equality predicates (single-table conjuncts pre-filter their
table; predicates common to every branch of a top-level OR are factored
out first, so the TPC-H Q19 shape finds its join key).

Expressions: identifiers (optionally alias-qualified: ``l.l_orderkey``),
integer/float/string literals, DATE 'yyyy-mm-dd', INTERVAL n|'n'
DAY[S]|MONTH[S]|YEAR[S] (folded into date literals at parse time),
CAST(x AS DATE|INT|BIGINT|DOUBLE) (literals fold; date-typed expressions
pass through), + - * /, comparisons
(= != <> < <= > >=), [NOT] BETWEEN x AND y, [NOT] IN (...), [NOT] LIKE,
IS [NOT] NULL, CASE [x] WHEN ... THEN ... [ELSE ...] END (ELSE NULL END
elides to the no-ELSE form),
EXTRACT(YEAR|MONTH|DAY|QUARTER FROM x), SUBSTRING/SUBSTR(x FROM a [FOR b])
or SUBSTRING/SUBSTR(x, a, b), UPPER/LOWER/TRIM, AND/OR/NOT, and aggregates
SUM/AVG/MIN/MAX/COUNT(*)/COUNT(x)/COUNT(DISTINCT x) — including
arithmetic OVER aggregates (``100 * sum(a) / sum(b)``).

Subqueries in WHERE (as top-level conjuncts):
  * ``x [NOT] IN (SELECT col FROM t [WHERE ...])``      → semi/anti join
  * ``[NOT] EXISTS (SELECT ... FROM t WHERE corr)``     → semi/anti join
  * ``expr <op> (SELECT <agg> FROM t WHERE corr)``      → decorrelated
    group-by + join (the TPC-H Q17 shape)
Correlation must be equality predicates; the subquery body is a single
optionally-filtered table. Everything else raises a clear error naming
the unsupported construct.

NOT IN follows the non-null convention (a null in the subquery result
does not veto every row) — documented divergence from three-valued SQL,
matching the TPC-H data contract where join keys are non-null.
"""

from __future__ import annotations

import datetime
import re
from typing import Dict, List, Optional, Tuple

from .exceptions import HyperspaceException
from .plan import expr as E

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<date>DATE\s*'(\d{4}-\d{2}-\d{2})')
    | (?P<str>'(?:[^']|'')*')
    | (?P<num>\d+\.\d+|\.\d+|\d+)
    | (?P<bident>`[^`]*`)
    | (?P<ident>[A-Za-z_][A-Za-z_0-9.]*)
    | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\*|/|\+|-|;)
    )""", re.VERBOSE | re.IGNORECASE)

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "ON", "AS", "AND",
    "OR", "NOT", "IN", "BETWEEN", "ASC", "DESC", "DATE", "DISTINCT",
    "UNION", "ALL", "WITH", "INTERSECT", "EXCEPT", "ROLLUP", "GROUPING",
    "SUM", "AVG", "MIN", "MAX", "COUNT",
    "LIKE", "IS", "NULL", "CASE", "WHEN", "THEN", "ELSE", "END",
    "EXTRACT", "INTERVAL", "DAY", "MONTH", "YEAR", "QUARTER",
    "EXISTS", "SUBSTRING", "SUBSTR", "FOR", "UPPER", "LOWER", "TRIM",
    "CAST", "COALESCE",
    "OVER", "PARTITION", "ROWS", "RANGE", "UNBOUNDED", "PRECEDING",
    "FOLLOWING", "CURRENT", "ROW", "RANK", "DENSE_RANK", "ROW_NUMBER",
    "ABS", "STDDEV", "STDDEV_SAMP", "SQRT", "CONCAT",
}

# Words that are only meaningful in specific grammar positions (EXTRACT's
# field, INTERVAL's unit, SUBSTRING's FOR, function names before '(').
# Everywhere else they are ordinary identifiers — Spark SQL reserves almost
# nothing, so a column named ``year`` must stay reachable.
_SOFT_KEYWORDS = {
    "YEAR", "MONTH", "DAY", "QUARTER", "FOR",
    "UPPER", "LOWER", "TRIM", "SUBSTRING", "SUBSTR", "EXTRACT", "CAST",
    "COALESCE", "OVER", "PARTITION", "ROWS", "RANGE", "UNBOUNDED",
    "PRECEDING", "FOLLOWING", "CURRENT", "ROW", "RANK", "DENSE_RANK",
    "ROW_NUMBER", "ABS", "STDDEV", "STDDEV_SAMP", "SQRT", "GROUPING",
    "ROLLUP", "CONCAT",
}


def _tokenize(text: str) -> List[Tuple[str, str]]:
    # SQL line comments (``-- ...``): stripped before tokenizing, except
    # inside string literals (a '--' in a LIKE pattern must survive).
    text = re.sub(r"('(?:[^']|'')*')|--[^\n]*",
                  lambda m: m.group(1) or " ", text)
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            rest = text[pos:].strip()
            if not rest:
                break
            raise HyperspaceException(
                f"SQL: cannot tokenize near {rest[:25]!r}")
        pos = m.end()
        if m.group("date"):
            out.append(("DATE_LIT", m.group(2)))
        elif m.group("str"):
            out.append(("STR", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("num"):
            out.append(("NUM", m.group("num")))
        elif m.group("bident"):
            # Backtick-quoted identifier: spaces and symbols allowed,
            # never a keyword (the TPC-DS house style for aliases).
            out.append(("IDENT", m.group("bident")[1:-1]))
        elif m.group("ident"):
            # KW tokens keep the RAW spelling: soft keywords double as
            # identifiers (take_name) and must preserve the user's case
            # for output aliases. Comparisons normalize in the helpers.
            word = m.group("ident")
            if word.upper() in _KEYWORDS:
                out.append(("KW", word))
            else:
                out.append(("IDENT", word))
        else:
            out.append(("OP", m.group("op")))
    # Statement terminator: legal only in the trailing position. A ';'
    # anywhere else stays a token the grammar will reject — silently
    # dropping it would splice two statements into one.
    while out and out[-1] == ("OP", ";"):
        out.pop()
    out.append(("EOF", ""))
    return out


# ---------------------------------------------------------------------------
# Subquery / interval parse-time markers (never reach the execution engine).
# ---------------------------------------------------------------------------

class _SubQ:
    """Structural (unanalyzed) subquery: SELECT items FROM one table
    [WHERE expr]. Kept unresolved because correlated references would not
    validate against the inner schema until the transform classifies them."""

    def __init__(self, items, star: bool, table: str, alias: Optional[str],
                 where: Optional[E.Expr]):
        self.items = items  # [(expr, alias)]
        self.star = star
        self.table = table
        self.alias = alias
        self.where = where


class _ScalarSubquery(E.Expr):
    def __init__(self, subq: _SubQ):
        self.subq = subq

    def __repr__(self):
        return "(scalar subquery)"


class _InSubquery(E.Expr):
    def __init__(self, value: E.Expr, subq: _SubQ, negated: bool):
        self.value = value
        self.subq = subq
        self.negated = negated

    @property
    def children(self):
        return [self.value]

    def __repr__(self):
        return f"{self.value!r} {'NOT ' if self.negated else ''}IN (subquery)"


class _ExistsSubquery(E.Expr):
    def __init__(self, subq: _SubQ, negated: bool):
        self.subq = subq
        self.negated = negated

    def __repr__(self):
        return f"{'NOT ' if self.negated else ''}EXISTS (subquery)"


_SUBQUERY_MARKERS = (_ScalarSubquery, _InSubquery, _ExistsSubquery)


def _contains_subquery(e: E.Expr) -> bool:
    if isinstance(e, _SUBQUERY_MARKERS):
        return True
    return any(_contains_subquery(c) for c in e.children)


class _IntervalLit(E.Expr):
    """INTERVAL 'n' DAY|MONTH|YEAR — only valid added to / subtracted from
    a date literal, folded at parse time."""

    def __init__(self, n: int, unit: str):
        self.n = n
        self.unit = unit

    def __repr__(self):
        return f"INTERVAL '{self.n}' {self.unit}"


def _shift_date(d: datetime.date, n: int, unit: str) -> datetime.date:
    if unit == "DAY":
        return d + datetime.timedelta(days=n)
    months = n * (12 if unit == "YEAR" else 1)
    m0 = d.month - 1 + months
    y, m = d.year + m0 // 12, m0 % 12 + 1
    # Clamp to month length (SQL date arithmetic convention).
    last = [31, 29 if (y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)) else 28,
            31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m - 1]
    return datetime.date(y, m, min(d.day, last))


class _Scope:
    """Alias/table-name → DataFrame bindings (chained for subqueries).
    ``renames`` maps (alias, column) → mangled output name for duplicate
    table instances in one FROM list (``date_dim d1, date_dim d2`` — the
    q25/q29/q50 shape), where the later instances' columns are renamed to
    keep the join output unambiguous."""

    def __init__(self, parent: Optional["_Scope"] = None):
        self.bindings: Dict[str, object] = {}
        self.renames: Dict[str, Dict[str, str]] = {}
        self.parent = parent

    def bind(self, name: str, df) -> None:
        self.bindings[name.lower()] = df

    def lookup(self, prefix: str):
        s = self
        while s is not None:
            if prefix.lower() in s.bindings:
                return s.bindings[prefix.lower()]
            s = s.parent
        return None

    def rename_for(self, prefix: str) -> Optional[Dict[str, str]]:
        s = self
        while s is not None:
            if prefix.lower() in s.renames:
                return s.renames[prefix.lower()]
            s = s.parent
        return None


def _has_col(df, name: str) -> bool:
    return df._spelling(name) in df.plan.schema.names


class _Parser:
    def __init__(self, session, text: str):
        self.session = session
        self.toks = _tokenize(text)
        self.i = 0
        self._sq_counter = 0
        self._win_counter = 0
        # WITH-clause bindings (CTEs): name → DataFrame. Checked before
        # session temp views everywhere a table name resolves.
        self._ctes: Dict[str, object] = {}

    def _table(self, name: str):
        """Resolve a table reference: CTE bindings shadow temp views
        (standard SQL scoping; the reference inherits WITH from Spark —
        its first TPC-DS golden needs it, tpcds/queries/q1.sql)."""
        df = self._ctes.get(name.lower())
        if df is not None:
            return df
        return self.session.table(name)

    # -- token helpers ---------------------------------------------------
    @staticmethod
    def _norm(k: str, v: str) -> str:
        """Comparison form of a token value (keywords case-fold)."""
        return v.upper() if k == "KW" else v

    def peek(self, kind: str = None, value: str = None) -> bool:
        k, v = self.toks[self.i]
        if kind is not None and k != kind:
            return False
        if value is not None and self._norm(k, v) != value:
            return False
        return True

    def peek2(self, kind: str, value: str = None) -> bool:
        if self.i + 1 >= len(self.toks):
            return False
        k, v = self.toks[self.i + 1]
        return k == kind and (value is None or self._norm(k, v) == value)

    def take(self, kind: str = None, value: str = None) -> str:
        k, v = self.toks[self.i]
        if (kind is not None and k != kind) or \
                (value is not None and self._norm(k, v) != value):
            raise HyperspaceException(
                f"SQL: expected {value or kind} but found {v or k!r}")
        self.i += 1
        return self._norm(k, v)

    def accept(self, kind: str, value: str = None) -> bool:
        if self.peek(kind, value):
            self.i += 1
            return True
        return False

    def peek_name(self) -> bool:
        """True when the next token can serve as an identifier — a plain
        IDENT or a soft keyword used outside its special position."""
        k, v = self.toks[self.i]
        return k == "IDENT" or (k == "KW" and v.upper() in _SOFT_KEYWORDS)

    def take_name(self) -> str:
        k, v = self.toks[self.i]
        if k == "KW" and v.upper() in _SOFT_KEYWORDS:
            self.i += 1
            return v  # raw spelling: identifiers keep the user's case
        return self.take("IDENT")

    # -- expressions -----------------------------------------------------
    def expr(self) -> E.Expr:
        return self._or()

    def _or(self) -> E.Expr:
        e = self._and()
        while self.accept("KW", "OR"):
            e = e | self._and()
        return e

    def _and(self) -> E.Expr:
        e = self._not()
        while self.accept("KW", "AND"):
            e = e & self._not()
        return e

    def _not(self) -> E.Expr:
        if self.peek("KW", "NOT") and self.peek2("KW", "EXISTS"):
            self.take("KW", "NOT")
            self.take("KW", "EXISTS")
            return _ExistsSubquery(self._exists_body(), negated=True)
        if self.accept("KW", "EXISTS"):
            return _ExistsSubquery(self._exists_body(), negated=False)
        if self.accept("KW", "NOT"):
            return ~self._not()
        return self._comparison()

    def _exists_body(self) -> _SubQ:
        self.take("OP", "(")
        sub = self._subquery_struct()
        self.take("OP", ")")
        return sub

    def _comparison(self) -> E.Expr:
        left = self._additive()
        if self.accept("KW", "IS"):
            negated = self.accept("KW", "NOT")
            self.take("KW", "NULL")
            return E.IsNull(left, negated)
        if self.accept("KW", "LIKE"):
            return E.Like(left, self.take("STR"))
        if self.accept("KW", "BETWEEN"):
            lo = self._additive()
            self.take("KW", "AND")
            hi = self._additive()
            return left.between(_lit_value(lo), _lit_value(hi))
        negated = False
        if self.peek("KW", "NOT"):
            # Postfix negations: NOT IN / NOT LIKE / NOT BETWEEN (prefix
            # NOT is handled one level up).
            self.take("KW", "NOT")
            if self.accept("KW", "LIKE"):
                return E.Like(left, self.take("STR"), negated=True)
            if self.accept("KW", "BETWEEN"):
                lo = self._additive()
                self.take("KW", "AND")
                hi = self._additive()
                return ~left.between(_lit_value(lo), _lit_value(hi))
            self.take("KW", "IN")
            negated = True
        elif self.accept("KW", "IN"):
            pass
        else:
            for op, make in (("=", lambda a, b: a == b),
                             ("!=", lambda a, b: a != b),
                             ("<>", lambda a, b: a != b),
                             ("<=", lambda a, b: a <= b),
                             (">=", lambda a, b: a >= b),
                             ("<", lambda a, b: a < b),
                             (">", lambda a, b: a > b)):
                if self.accept("OP", op):
                    return make(left, self._additive())
            return left
        self.take("OP", "(")
        if self.peek("KW", "SELECT"):
            sub = self._subquery_struct()
            self.take("OP", ")")
            return _InSubquery(left, sub, negated)
        values = [_lit_value(self._additive())]
        while self.accept("OP", ","):
            values.append(_lit_value(self._additive()))
        self.take("OP", ")")
        e = left.isin(values)
        return ~e if negated else e

    def _additive(self) -> E.Expr:
        e = self._multiplicative()
        while True:
            if self.accept("OP", "+"):
                e = self._add_or_shift(e, self._multiplicative(), +1)
            elif self.accept("OP", "-"):
                e = self._add_or_shift(e, self._multiplicative(), -1)
            else:
                return e

    def _add_or_shift(self, a: E.Expr, b: E.Expr, sign: int) -> E.Expr:
        if isinstance(b, _IntervalLit):
            if not (isinstance(a, E.Lit)
                    and isinstance(a.value, datetime.date)):
                raise HyperspaceException(
                    "SQL: INTERVAL arithmetic is only supported against "
                    "DATE literals")
            return E.lit(_shift_date(a.value, sign * b.n, b.unit))
        if isinstance(a, _IntervalLit):
            raise HyperspaceException(
                "SQL: INTERVAL must follow a DATE literal")
        if sign > 0:
            return _fold(a, b, lambda x, y: x + y, lambda x, y: x + y)
        return _fold(a, b, lambda x, y: x - y, lambda x, y: x - y)

    def _multiplicative(self) -> E.Expr:
        e = self._atom()
        while True:
            if self.accept("OP", "*"):
                e = _fold(e, self._atom(), lambda a, b: a * b,
                          lambda a, b: a * b)
            elif self.accept("OP", "/"):
                e = _fold(e, self._atom(), lambda a, b: a / b,
                          lambda a, b: a / b)
            else:
                return e

    def _atom(self) -> E.Expr:
        if self.accept("OP", "-"):
            # Unary minus: folds for literals, 0 - x otherwise.
            return _fold(E.lit(0), self._atom(), lambda a, b: a - b,
                         lambda a, b: a - b)
        if self.accept("OP", "("):
            if self.peek("KW", "SELECT"):
                sub = self._subquery_struct()
                self.take("OP", ")")
                return _ScalarSubquery(sub)
            e = self.expr()
            self.take("OP", ")")
            return e
        if self.accept("KW", "CASE"):
            return self._case()
        # Function-named soft keywords act as functions only when a '('
        # follows; bare they fall through to the identifier branch below
        # (a column named ``extract`` or ``trim`` stays reachable).
        if self.peek("KW", "EXTRACT") and self.peek2("OP", "("):
            self.take("KW")
            self.take("OP", "(")
            part = self.take("KW")
            if part not in ("YEAR", "MONTH", "DAY", "QUARTER"):
                raise HyperspaceException(
                    f"SQL: EXTRACT supports YEAR/MONTH/DAY/QUARTER, "
                    f"got {part}")
            self.take("KW", "FROM")
            inner = self.expr()
            self.take("OP", ")")
            return E.DatePart(part.lower(), inner)
        if (self.peek("KW", "SUBSTRING") or self.peek("KW", "SUBSTR")) \
                and self.peek2("OP", "("):
            self.take("KW")
            return self._substring()
        if self.peek("KW", "CAST") and self.peek2("OP", "("):
            self.take("KW")
            return self._cast()
        for fn in ("UPPER", "LOWER", "TRIM"):
            if self.peek("KW", fn) and self.peek2("OP", "("):
                self.take("KW")
                self.take("OP", "(")
                inner = self.expr()
                self.take("OP", ")")
                return E.StringTransform(fn.lower(), inner)
        if self.peek("KW", "ABS") and self.peek2("OP", "("):
            self.take("KW")
            self.take("OP", "(")
            inner = self.expr()
            self.take("OP", ")")
            # Parse-time rewrite: abs(x) = CASE WHEN x < 0 THEN -x ELSE x
            # END (null in → null out, via CaseWhen's null propagation).
            return E.CaseWhen([(E.LessThan(inner, E.lit(0)),
                                _fold(E.lit(0), inner, lambda a, b: a - b,
                                      lambda a, b: a - b))], inner)
        if self.peek("KW", "COALESCE") and self.peek2("OP", "("):
            self.take("KW")
            self.take("OP", "(")
            args = [self.expr()]
            while self.accept("OP", ","):
                args.append(self.expr())
            self.take("OP", ")")
            if len(args) < 2:
                raise HyperspaceException(
                    "SQL: COALESCE takes at least two arguments")
            # Parse-time rewrite onto CASE (first non-null argument).
            e = args[-1]
            for a in reversed(args[:-1]):
                e = E.CaseWhen([(E.IsNull(a, negated=True), a)], e)
            return e
        if self.peek("KW", "GROUPING") and self.peek2("OP", "("):
            self.take("KW")
            self.take("OP", "(")
            name = self.take_name()
            self.take("OP", ")")
            # GROUPING(c) is a per-grouping-set constant; the ROLLUP
            # lowering materializes it as a hidden 0/1 column per branch
            # (q27/q36/q70/q86). The double-underscore suffix keeps it
            # inside the SELECT-* hidden-name filter.
            return E.col(f"__grouping__{name.split('.')[-1].lower()}__")
        if self.peek("KW", "CONCAT") and self.peek2("OP", "("):
            self.take("KW")
            self.take("OP", "(")
            parts = [self.expr()]
            while self.accept("OP", ","):
                parts.append(self.expr())
            self.take("OP", ")")
            return E.Concat(parts)
        if self.peek("KW", "SQRT") and self.peek2("OP", "("):
            self.take("KW")
            self.take("OP", "(")
            inner = self.expr()
            self.take("OP", ")")
            return E.Sqrt(inner)
        for sd in ("STDDEV", "STDDEV_SAMP"):
            if self.peek("KW", sd) and self.peek2("OP", "("):
                self.take("KW")
                self.take("OP", "(")
                x = self.expr()
                self.take("OP", ")")
                # Parse-time rewrite onto decomposable aggregates (the
                # q17/q39 shape): stddev_samp(x) =
                # sqrt((sum(x*x) - sum(x)^2/n) / (n - 1)), NULL for n < 2
                # (matching SQL; the n=1 denominator would divide by 0).
                # Computed in float64 like Spark — sum(x)^2 over an int
                # column would silently wrap int64.
                xf = E.Multiply(x, E.lit(1.0))
                n = E.count(x)
                sx = E.sum_(xf)
                sxx = E.sum_(E.Multiply(xf, xf))
                var = E.Divide(E.Subtract(sxx, E.Divide(
                    E.Multiply(sx, sx), n)), E.Subtract(n, E.lit(1)))
                # Clamp float cancellation error: a variance of -1e-12
                # must yield 0, not NULL-from-sqrt(-x).
                var = E.CaseWhen([(E.LessThan(var, E.lit(0)), E.lit(0.0))],
                                 var)
                return E.CaseWhen(
                    [(E.GreaterThan(n, E.lit(1)), E.Sqrt(var))], None)
        for rank_fn in ("RANK", "DENSE_RANK", "ROW_NUMBER"):
            if self.peek("KW", rank_fn) and self.peek2("OP", "("):
                self.take("KW")
                self.take("OP", "(")
                self.take("OP", ")")
                return self._window_spec(rank_fn.lower(), None)
        if self.accept("KW", "INTERVAL"):
            if self.peek("STR"):
                raw = self.take("STR")
                if not raw.strip().lstrip("-").isdigit():
                    raise HyperspaceException(
                        f"SQL: INTERVAL takes an integer, got {raw!r}")
                n = int(raw)
            else:
                n = self._int_literal("INTERVAL expects")
            # Unit: keyword (DAY) or identifier (days — the TPC-DS
            # spelling), singular or plural.
            unit = self.take().upper().rstrip("S")
            if unit not in ("DAY", "MONTH", "YEAR"):
                raise HyperspaceException(
                    f"SQL: INTERVAL unit must be DAY/MONTH/YEAR, got {unit}")
            return _IntervalLit(n, unit)
        if self.peek("KW") and self.toks[self.i][1].upper() in (
                "SUM", "AVG", "MIN", "MAX", "COUNT"):
            agg = self._aggregate()
            if self.peek("KW", "OVER"):
                # ``agg(x) OVER (...)`` is a window, not a group aggregate:
                # the aggregate's argument becomes the window argument
                # (``avg(sum(x)) OVER`` keeps the inner sum as the arg —
                # it is lifted to a hidden aggregate column at lowering).
                base = agg
                if isinstance(base, E.CountDistinct):
                    raise HyperspaceException(
                        "SQL: COUNT(DISTINCT ...) OVER is not supported")
                fn = {E.Sum: "sum", E.Avg: "avg", E.Min: "min",
                      E.Max: "max", E.Count: "count"}[type(base)]
                return self._window_spec(fn, base.child)
            return agg
        if self.peek_name():
            return E.col(self.take_name())
        if self.peek("NUM"):
            raw = self.take("NUM")
            return E.lit(float(raw) if "." in raw else int(raw))
        if self.peek("STR"):
            return E.lit(self.take("STR"))
        if self.peek("DATE_LIT"):
            return E.lit(datetime.date.fromisoformat(self.take("DATE_LIT")))
        if self.peek("KW", "NULL"):
            self.take("KW", "NULL")
            return E.lit(None)
        raise HyperspaceException(
            f"SQL: unexpected token {self.toks[self.i][1]!r}")

    def _case(self) -> E.Expr:
        operand = None
        if not self.peek("KW", "WHEN"):
            operand = self.expr()  # simple CASE: CASE x WHEN v THEN r ...
        branches = []
        while self.accept("KW", "WHEN"):
            c = self.expr()
            if operand is not None:
                c = E.EqualTo(operand, c)
            self.take("KW", "THEN")
            branches.append((c, self.expr()))
        if not branches:
            raise HyperspaceException("SQL: CASE requires at least one WHEN")
        else_v = None
        if self.accept("KW", "ELSE"):
            if self.peek("KW", "NULL") and self.peek2("KW", "END"):
                self.take("KW")  # ELSE NULL END ≡ no ELSE (SQL: both null)
            else:
                else_v = self.expr()
        self.take("KW", "END")
        return E.CaseWhen(branches, else_v)

    def _substring(self) -> E.Expr:
        self.take("OP", "(")
        inner = self.expr()
        length = None
        if self.accept("KW", "FROM"):
            start = self._int_literal()
            if self.accept("KW", "FOR"):
                length = self._int_literal()
        else:
            self.take("OP", ",")
            start = self._int_literal()
            if self.accept("OP", ","):
                length = self._int_literal()
        self.take("OP", ")")
        return E.Substring(inner, start, length)

    def _cast(self) -> E.Expr:
        """CAST(x AS type). DATE casts fold string literals to date
        literals and pass date-typed expressions through (the TPC-DS
        texts cast already-date columns defensively); INT/BIGINT and
        DOUBLE casts fold numeric literals. Anything else is a clear
        error naming the unsupported target."""
        self.take("OP", "(")
        inner = self.expr()
        self.take("KW", "AS")
        ty = self.take().upper()
        if ty == "DECIMAL" and self.peek("OP", "("):
            # DECIMAL(p,s): both engine paths compute in float64, so the
            # cast is an identity here (same-engine disable-and-compare
            # keeps the oracle sound); literals fold to float below.
            self.take("OP", "(")
            self._int_literal("DECIMAL precision expects")
            if self.accept("OP", ","):
                self._int_literal("DECIMAL scale expects")
            self.take("OP", ")")
            ty = "DOUBLE"
        elif self.peek("OP", "("):
            # Other parameterized targets (CHAR(16), VARCHAR(20), ...):
            # name the target in the error instead of a bare parse failure.
            raise HyperspaceException(
                f"SQL: unsupported CAST target {ty}(...)")
        self.take("OP", ")")
        if ty == "DATE":
            if isinstance(inner, E.Lit):
                if not isinstance(inner.value, str):
                    raise HyperspaceException(
                        f"SQL: CAST({inner.value!r} AS DATE): only "
                        "yyyy-mm-dd string literals fold to dates")
                try:
                    y, m, d = inner.value.split("-")
                    return E.lit(datetime.date(int(y), int(m), int(d)))
                except ValueError:
                    raise HyperspaceException(
                        f"SQL: CAST({inner.value!r} AS DATE): not a "
                        "yyyy-mm-dd literal")
            return inner  # date-typed expression: identity
        if ty in ("INT", "INTEGER", "BIGINT", "DOUBLE", "FLOAT"):
            conv = int if ty in ("INT", "INTEGER", "BIGINT") else float
            if isinstance(inner, E.Lit):
                try:
                    return E.lit(conv(inner.value))
                except (TypeError, ValueError):
                    raise HyperspaceException(
                        f"SQL: CAST({inner.value!r} AS {ty}): literal "
                        "does not convert")
            return inner
        raise HyperspaceException(f"SQL: unsupported CAST target {ty}")

    def _int_literal(self, what: str = "") -> int:
        neg = self.accept("OP", "-")
        raw = self.take("NUM")
        if "." in raw:
            raise HyperspaceException(
                f"SQL: {what or 'expected'} an integer, found {raw!r}")
        return -int(raw) if neg else int(raw)

    def _aggregate(self) -> E.Expr:
        fn = self.take("KW")
        self.take("OP", "(")
        if fn == "COUNT":
            if self.accept("OP", "*"):
                self.take("OP", ")")
                return E.count(None)
            if self.accept("KW", "DISTINCT"):
                inner = self.expr()
                self.take("OP", ")")
                return E.count_distinct(inner)
            inner = self.expr()
            self.take("OP", ")")
            return E.count(inner)
        inner = self.expr()
        self.take("OP", ")")
        return {"SUM": E.sum_, "AVG": E.avg,
                "MIN": E.min_, "MAX": E.max_}[fn](inner)

    def _window_spec(self, fn: str, arg: Optional[E.Expr]) -> E.Expr:
        """OVER ( [PARTITION BY e, ...] [ORDER BY e [ASC|DESC], ...]
        [ROWS|RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW] )."""
        self.take("KW", "OVER")
        self.take("OP", "(")
        partition: List[E.Expr] = []
        orders: List[Tuple[E.Expr, bool]] = []
        frame = None
        if self.peek("KW", "PARTITION"):
            self.take("KW")
            self.take("KW", "BY")
            partition.append(self.expr())
            while self.accept("OP", ","):
                partition.append(self.expr())
        if self.accept("KW", "ORDER"):
            self.take("KW", "BY")
            while True:
                e = self.expr()
                asc = True
                if self.accept("KW", "DESC"):
                    asc = False
                else:
                    self.accept("KW", "ASC")
                orders.append((e, asc))
                if not self.accept("OP", ","):
                    break
        if self.peek("KW", "ROWS") or self.peek("KW", "RANGE"):
            kind = self.take("KW")
            self.take("KW", "BETWEEN")
            if not (self.accept("KW", "UNBOUNDED")
                    and self.accept("KW", "PRECEDING")):
                raise HyperspaceException(
                    "SQL: only BETWEEN UNBOUNDED PRECEDING AND CURRENT "
                    "ROW window frames are supported")
            self.take("KW", "AND")
            self.take("KW", "CURRENT")
            self.take("KW", "ROW")
            frame = "rows" if kind == "ROWS" else "range"
        self.take("OP", ")")
        return E.WindowExpr(fn, arg, partition, orders, frame)

    # -- subquery structure ----------------------------------------------
    def _subquery_struct(self) -> _SubQ:
        """SELECT <*|items> FROM <table> [[AS] alias] [WHERE expr] — the
        body stays structural (no DataFrame ops yet: correlated references
        would not resolve against the inner schema)."""
        self.take("KW", "SELECT")
        items, star = [], False
        if self.accept("OP", "*"):
            star = True
        else:
            items.append(self._select_item())
            while self.accept("OP", ","):
                items.append(self._select_item())
        self.take("KW", "FROM")
        if self.peek("OP", "("):
            raise HyperspaceException(
                "SQL: subqueries over derived tables are not supported")
        table = self.take_name()
        alias = None
        if self.accept("KW", "AS"):
            alias = self.take_name()
        elif self.peek_name():
            alias = self.take_name()
        where = self.expr() if self.accept("KW", "WHERE") else None
        if self.peek("KW") and self.toks[self.i][1].upper() in ("GROUP", "ORDER",
                                                        "HAVING", "JOIN"):
            raise HyperspaceException(
                f"SQL: {self.toks[self.i][1]} inside subqueries is not "
                "supported (single filtered table only)")
        return _SubQ(items, star, table, alias, where)

    # -- qualified-name resolution ----------------------------------------
    def _resolve_quals(self, e: E.Expr, scope: _Scope) -> E.Expr:
        """Strip alias qualifiers (``l.l_orderkey`` → ``l_orderkey``) once
        the FROM clause has bound them. Unknown prefixes pass through (they
        may be flattened struct leaves like ``detail.price``)."""
        if isinstance(e, E.Col):
            return E.Col(self._resolve_qual_name(e.column, scope))
        if isinstance(e, (_ScalarSubquery, _ExistsSubquery)):
            return e  # inner names resolve at transform time
        if isinstance(e, _InSubquery):
            return _InSubquery(self._resolve_quals(e.value, scope),
                               e.subq, e.negated)
        return E.map_children(e, lambda c: self._resolve_quals(c, scope))

    def _resolve_qual_name(self, name: str, scope: _Scope) -> str:
        if "." not in name:
            return name
        prefix, rest = name.split(".", 1)
        rename = scope.rename_for(prefix)
        if rename is not None and rest.lower() in rename:
            return rename[rest.lower()]
        df = scope.lookup(prefix)
        if df is None:
            return name  # struct leaf or unknown: downstream error names it
        if not _has_col(df, rest):
            raise HyperspaceException(
                f"SQL: {name!r}: table alias {prefix!r} has no column "
                f"{rest!r}; available: {df.plan.schema.names}")
        return df._spelling(rest)

    # -- query -----------------------------------------------------------
    def query(self):
        self._with_clause()
        df = self._query_body()
        self.take("EOF")
        return df

    def _with_clause(self):
        """WITH name AS ( query-body ) [, name2 AS ( ... )]* — each body
        is any supported query (joins, group-by, unions, windows, its own
        ORDER BY/LIMIT); later CTEs may reference earlier ones."""
        if not self.accept("KW", "WITH"):
            return
        while True:
            name = self.take_name()
            self.take("KW", "AS")
            self.take("OP", "(")
            df = self._query_body()
            self.take("OP", ")")
            self._ctes[name.lower()] = df
            if not self.accept("OP", ","):
                break

    def _query_body(self):
        """select [UNION ALL | INTERSECT | EXCEPT select]*
        [ORDER BY ...] [LIMIT n] — a trailing ORDER BY/LIMIT binds to the
        WHOLE compound (standard SQL), INTERSECT binds tighter than
        UNION/EXCEPT, and the same production serves derived tables."""
        df = self._intersect_term()
        while True:
            if self.peek("KW", "UNION"):
                self.take("KW", "UNION")
                if self.accept("KW", "ALL"):
                    df = df.union(self._intersect_term())
                else:
                    # UNION without ALL deduplicates (standard SQL;
                    # positional — a later UNION ALL may re-add rows).
                    df = df.union(self._intersect_term()).distinct()
            elif self.accept("KW", "EXCEPT"):
                df = self._set_op(df, self._intersect_term(), anti=True)
            else:
                break
        return self._order_limit(df)

    def _intersect_term(self):
        df = self._set_operand()
        while self.accept("KW", "INTERSECT"):
            df = self._set_op(df, self._set_operand(), anti=False)
        return df

    def _set_operand(self):
        # Parenthesized set-op operands: ``(SELECT ...) EXCEPT
        # (SELECT ...)`` — the q8/q87 house style.
        if self.peek("OP", "(") and self.peek2("KW", "SELECT"):
            self.take("OP", "(")
            inner = self._query_body()
            self.take("OP", ")")
            return inner
        return self._select_stmt()

    def _set_op(self, left, right, anti: bool):
        """INTERSECT / EXCEPT with SQL's DISTINCT semantics, lowered to
        distinct + semi/anti join on every column positionally (the
        q8/q14/q38/q87 shapes). Divergence from three-valued SQL: set
        ops treat NULL keys as equal, the join's equality never matches
        them — same documented convention as NOT IN; the conformance
        corpus' set-op keys are non-null."""
        lnames = left.plan.schema.names
        rnames = right.plan.schema.names
        if len(lnames) != len(rnames):
            raise HyperspaceException(
                f"SQL: {'EXCEPT' if anti else 'INTERSECT'} sides have "
                f"{len(lnames)} vs {len(rnames)} columns")
        i = self._sq_counter
        self._sq_counter += 1
        sel = [E.col(rn).alias(f"__set{i}_k{j}")
               for j, rn in enumerate(rnames)]
        probe = right.select(*sel)
        cond = None
        for j, ln in enumerate(lnames):
            eq = E.col(ln) == E.col(f"__set{i}_k{j}")
            cond = eq if cond is None else (cond & eq)
        return left.distinct().join(probe, on=cond,
                                    how="anti" if anti else "semi")

    def _order_limit(self, df):
        if self.accept("KW", "ORDER"):
            self.take("KW", "BY")
            orders = [self._order_item()]
            while self.accept("OP", ","):
                orders.append(self._order_item())
            df = self._sort_maybe_hidden(df, orders)
        if self.accept("KW", "LIMIT"):
            n = self._int_literal("LIMIT expects")
            if n < 0:
                raise HyperspaceException(
                    f"SQL: LIMIT expects a non-negative integer, got {n}")
            df = df.limit(n)
        return df

    def _sort_maybe_hidden(self, df, orders):
        """ORDER BY may reference input/grouping columns the SELECT list
        dropped (standard SQL; the q98/q20 shape sorts by a grouped
        i_item_id that is not projected) or an arbitrary expression over
        output columns (the q89 shape). Both lower to hidden columns:
        widen, sort, re-project."""
        exprs = [(n, asc) for n, asc in orders if isinstance(n, E.Expr)]
        if exprs:
            out_names = list(df.plan.schema.names)
            resolved = []
            hidden_i = 0
            for n, asc in orders:
                if isinstance(n, E.Expr):
                    hn = f"__sort{hidden_i}"
                    hidden_i += 1
                    df = df.with_column(hn, n)
                    resolved.append((hn, asc))
                else:
                    resolved.append((n, asc))
            return df.sort(*resolved).select(*out_names)
        have = set(df.plan.schema.names)
        missing = [n for n, _ in orders if df._spelling(n) not in have]
        if not missing:
            return df.sort(*orders)
        parent = getattr(self, "_sortable_parent", None)
        if parent is None or parent[2] is not df:
            return df.sort(*orders)  # original error names the column
        child_df, out_cols, _ = parent
        hidden = []
        for n in missing:
            sp = child_df._spelling(n)
            if sp not in child_df.plan.schema.names:
                return df.sort(*orders)  # truly unknown: clear error below
            if sp not in hidden:
                hidden.append(sp)
        out_names = list(df.plan.schema.names)
        widened = child_df.select(*(list(out_cols) + hidden))
        return widened.sort(*orders).select(*out_names)

    def _table_ref(self, scope: _Scope):
        """One FROM-list entry: returns (df, bound-name or None). The
        binding (alias if given, else the table name) feeds qualified-name
        resolution."""
        if self.accept("OP", "("):
            # Derived table: ( query-body ) [AS name] — may itself contain
            # UNION ALL and its own ORDER BY/LIMIT.
            inner = self._query_body()
            self.take("OP", ")")
            alias = None
            if self.accept("KW", "AS"):
                alias = self.take_name()
            elif self.peek_name():
                alias = self.take_name()
            if alias:
                scope.bind(alias, inner)
            return inner, alias
        name = self.take_name()
        df = self._table(name)
        alias = None
        if self.accept("KW", "AS"):
            alias = self.take_name()
        elif self.peek_name():
            alias = self.take_name()
        scope.bind(alias or name, df)
        return df, alias or name

    def _select_stmt(self):
        self.take("KW", "SELECT")
        distinct = self.accept("KW", "DISTINCT")
        items: List[Tuple[Optional[E.Expr], Optional[str]]] = []
        star = False
        if self.accept("OP", "*"):
            star = True
        else:
            items.append(self._select_item())
            while self.accept("OP", ","):
                items.append(self._select_item())

        scope = _Scope()
        self.take("KW", "FROM")
        refs = [self._table_ref(scope)]
        while self.accept("OP", ","):
            refs.append(self._table_ref(scope))

        if len(refs) == 1:
            df = refs[0][0]
            while self.peek("KW") and self.toks[self.i][1].upper() in (
                    "JOIN", "INNER", "LEFT", "RIGHT", "FULL"):
                df = self._join(df, scope)
            if self.accept("KW", "WHERE"):
                cond = self._resolve_quals(self.expr(), scope)
                if _contains_subquery(cond):
                    df = self._apply_where_with_subqueries(df, cond, scope)
                else:
                    df = df.filter(cond)
        else:
            if self.peek("KW") and self.toks[self.i][1].upper() in (
                    "JOIN", "INNER", "LEFT", "RIGHT", "FULL"):
                raise HyperspaceException(
                    "SQL: mixing comma-joins with explicit JOIN syntax is "
                    "not supported")
            cond = None
            if self.accept("KW", "WHERE"):
                # Resolution happens inside _build_implicit_joins, after
                # duplicate-table instances are renamed (qualifiers must
                # survive until then — the q25 ``date_dim d1, d2`` shape).
                cond = self.expr()
            df = self._build_implicit_joins(refs, cond, scope)

        # Resolve alias-qualified names in the select list now that the
        # FROM clause has bound the aliases. An unaliased qualified column
        # of a renamed duplicate-table instance (``SELECT d2.d_moy``) keeps
        # its user-visible name as the output alias — the mangled internal
        # spelling must never surface in results.
        def _item_resolve(e, alias):
            if e is None:
                return None, alias
            r = self._resolve_quals(e, scope)
            if alias is None and isinstance(e, E.Col) and "." in e.column \
                    and isinstance(r, E.Col) and r.column != e.column \
                    and r.column.startswith("__"):
                alias = e.column.split(".", 1)[1]
            return r, alias

        items = [_item_resolve(e, alias) for e, alias in items]

        group_cols: List[str] = []
        group_exprs: List[Tuple[E.Expr, str]] = []

        def group_item() -> str:
            # Parse a full expression: a plain [qualified] column is the
            # fast path that falls out of it, and anything else
            # (``GROUP BY substr(x, 1, 20)``, ``GROUP BY a + b`` — the
            # TPC-DS house style) must restate a SELECT item; it is
            # materialized by a pre-projection under that item's output
            # name and grouped as a plain column.
            e = self._resolve_quals(self.expr(), scope)
            if isinstance(e, E.Col):
                return e.column
            for ie, alias in items:
                if ie is not None and repr(ie) == repr(e):
                    name = alias or ie.name
                    if all(nm != name for _, nm in group_exprs):
                        group_exprs.append((e, name))
                    return name
            raise HyperspaceException(
                f"SQL: GROUP BY expression {e!r} must restate an item "
                "of the SELECT list")

        rollup_cols: List[str] = []
        if self.accept("KW", "GROUP"):
            self.take("KW", "BY")

            def one_group_entry():
                # ROLLUP(c1, ..., cn): the trailing keys become grouping
                # sets (prefixes) — lowered below as a union of per-set
                # aggregations (the reference inherits ROLLUP from Spark
                # SQL; TPC-DS q5/q18/q22/q27/q67/q77/q80 use it).
                if self.peek("KW", "ROLLUP") and self.peek2("OP", "("):
                    self.take("KW")
                    self.take("OP", "(")
                    rollup_cols.append(group_item())
                    while self.accept("OP", ","):
                        rollup_cols.append(group_item())
                    self.take("OP", ")")
                    return
                g = group_item()
                if g not in group_cols:
                    group_cols.append(g)

            one_group_entry()
            while self.accept("OP", ","):
                one_group_entry()
            # A key listed BOTH plainly and inside ROLLUP stays grouped
            # in every grouping set (Spark: GROUP BY a, ROLLUP(a, b)
            # never rolls `a` up): it leaves the rollup list.
            rollup_cols = [c for c in rollup_cols if c not in group_cols]
            for g in rollup_cols:
                group_cols.append(g)

        orig_items = items
        if group_exprs:
            # Materialize the expression keys; existing columns pass
            # through (later column pruning drops the dead ones) except
            # ones SHADOWED by a synthesized key name (the q8 shape:
            # ``SELECT substr(ca_zip, 1, 5) AS ca_zip``). The expressions
            # still read the pre-projection INPUT, so shadowing only
            # hides the original from stages above — which is why an
            # aggregate referencing the shadowed original is refused.
            synth = {nm for _, nm in group_exprs}
            for ie, _alias in items:
                if ie is not None and _contains_agg(ie) \
                        and synth & set(ie.references) \
                        & set(df.plan.schema.names):
                    raise HyperspaceException(
                        "SQL: an aggregate references a column shadowed "
                        f"by a GROUP BY expression alias ({sorted(synth & set(ie.references))})")
            df = df.select(*(
                [E.col(n) for n in df.plan.schema.names if n not in synth]
                + [e.alias(nm) for e, nm in group_exprs]))
            by_repr = {repr(e): nm for e, nm in group_exprs}
            items = [(E.col(by_repr[repr(e)])
                      if e is not None and repr(e) in by_repr else e, alias)
                     for e, alias in items]

        has_agg = any(_contains_agg(e) for e, _ in items if e is not None)
        if group_cols or has_agg:
            if star:
                raise HyperspaceException(
                    "SQL: SELECT * cannot be combined with aggregation")
            # Resolve spellings once (the API is case-insensitive; raw
            # string comparison here must be too).
            spell = df._spelling
            group_resolved = [spell(g) for g in group_cols]
            aggs, out_cols, out_names = [], [], []
            aliased = False
            compound = False
            for e, alias in items:
                if _contains_agg(e) or _contains_window(e):
                    base = e.child if isinstance(e, E.Alias) else e
                    if isinstance(base, E.AggExpr):
                        named = e.alias(alias) if alias else e
                        aggs.append(named)
                        out_cols.append(named.name)
                        out_names.append(named.name)
                    else:
                        # Arithmetic over aggregates (``100*sum(a)/sum(b)``):
                        # materialize each aggregate as a hidden column and
                        # compute the arithmetic in a post-projection.
                        compound = True
                        rewritten, hidden = _lift_aggs(
                            e, f"__item_{len(out_cols)}")
                        aggs.extend(hidden)
                        named = rewritten.alias(alias) if alias \
                            else rewritten.alias(e.name)
                        out_cols.append(named)
                        out_names.append(named.name)
                else:
                    if isinstance(e, E.Lit):
                        # Constant select item in a grouped query
                        # (``'s' sale_type`` — the q4/q11/q74 style):
                        # projected after aggregation.
                        compound = True
                        named = e.alias(alias) if alias else e.alias(e.name)
                        out_cols.append(named)
                        out_names.append(named.name)
                        continue
                    if not isinstance(e, E.Col) or \
                            e.column.startswith("__grouping__"):
                        # Non-aggregate EXPRESSIONS over grouping keys /
                        # GROUPING() flags (standard SQL; the q27
                        # ``grouping(a) + grouping(b) AS lochierarchy``
                        # shape): projected after aggregation.
                        refs = set(e.references)
                        if refs and all(
                                spell(r) in group_resolved
                                or r.startswith("__grouping__")
                                for r in refs):
                            compound = True
                            named = e.alias(alias) if alias \
                                else e.alias(e.name)
                            out_cols.append(named)
                            out_names.append(named.name)
                            continue
                        raise HyperspaceException(
                            "SQL: non-aggregate select items must be "
                            "plain grouped columns or expressions over "
                            "them")
                    spelled = spell(e.column)
                    if spelled not in group_resolved:
                        raise HyperspaceException(
                            f"SQL: column {e.column!r} must appear in "
                            "GROUP BY or inside an aggregate")
                    if alias:
                        # SELECT g AS grp: the output carries the alias.
                        aliased = True
                        out_cols.append(E.col(spelled).alias(alias))
                    else:
                        out_cols.append(spelled)
                    out_names.append(spelled)
            n_visible = len(aggs)
            visible_agg_names = [a.name for a in aggs]
            # HAVING may reference aggregates inline (standard SQL):
            # materialize them as hidden columns, filter, then project the
            # SELECT list (which also drops the hidden columns and fixes
            # the output order to the SELECT order).
            having: Optional[E.Expr] = None
            if self.accept("KW", "HAVING"):
                if rollup_cols:
                    raise HyperspaceException(
                        "SQL: HAVING with ROLLUP is not supported")
                having = self._resolve_quals(self.expr(), scope)
                having, hidden = _lift_aggs(having, f"__having_{n_visible}")
                aggs.extend(hidden)
            if rollup_cols:
                df = self._rollup_union(
                    df, [g for g in group_cols if g not in rollup_cols],
                    rollup_cols, aggs)
            else:
                df = (df.group_by(*group_cols).agg(*aggs) if group_cols
                      else df.agg(*aggs))
            if having is not None:
                df = df.filter(having)
            # Window functions evaluate AFTER grouping (standard SQL): by
            # now every inner aggregate is a hidden column, so the window
            # specs reference plain aggregate outputs / group columns.
            windowed = any(isinstance(c, E.Expr) and _contains_window(c)
                           for c in out_cols)
            if windowed:
                df, out_cols = self._apply_windows_mixed(df, out_cols)
            # Project only when the SELECT list differs from the
            # aggregate's natural output (group cols then aggregates) —
            # a redundant Project would make SQL plans diverge from the
            # equivalent DataFrame plans. Aliases on group columns,
            # compound aggregate items, and hidden HAVING aggregates
            # always force the projection.
            natural = group_resolved + visible_agg_names
            if aliased or compound or windowed or bool(rollup_cols) \
                    or out_names != natural or len(aggs) != n_visible:
                pre = df
                df = df.select(*out_cols)
                self._sortable_parent = (pre, list(out_cols), df)
        elif not star:
            sel = [e.alias(alias) if alias else e for e, alias in items]
            if any(_contains_window(e) for e in sel):
                df, sel = self._apply_windows_mixed(df, sel)
            pre = df
            df = df.select(*sel)
            self._sortable_parent = (pre, list(sel), df)
            if self.accept("KW", "HAVING"):
                raise HyperspaceException(
                    "SQL: HAVING requires GROUP BY or aggregates")

        if star:
            # Hidden helper columns must not surface through SELECT *:
            # scalar-subquery keys (__sqN_*) and duplicate-table renames
            # (__<alias>__<col>).
            hidden_re = r"__sq\d+_|__\w+__"
            leaked = [n for n in df.plan.schema.names
                      if re.match(hidden_re, n)]
            if leaked:
                df = df.select(*[n for n in df.plan.schema.names
                                 if not re.match(hidden_re, n)])

        if distinct:
            df = df.distinct()

        # ORDER BY resolution state. Assigned on the way OUT so a derived
        # table's inner select (which runs this method re-entrantly
        # mid-FROM) can't leave ITS scope/items behind as the binding for
        # the outer query's ORDER BY.
        self._last_scope = scope
        # ORDER BY matches against the ORIGINAL spellings (a GROUP BY
        # expression rewrite must not hide ``ORDER BY substr(...)``).
        self._last_items = orig_items if not star else []
        return df

    def _select_item(self):
        e = self.expr()
        alias = None
        if self.accept("KW", "AS"):
            alias = self.take_name()
        elif self.peek_name():
            alias = self.take_name()
        return e, alias

    def _order_item(self):
        scope = getattr(self, "_last_scope", None) or _Scope()
        # Parse a full expression. A plain [qualified] column (or output
        # alias, which resolves to itself) is the common case; any other
        # expression (``ORDER BY sum(x) DESC``, ``ORDER BY a * b`` — the
        # TPC-DS house style) must restate a SELECT item, and the sort
        # key is that item's output column.
        e = self._resolve_quals(self.expr(), scope)
        if isinstance(e, E.Col):
            name = e.column
        else:
            name = None
            for item, alias in getattr(self, "_last_items", []):
                if item is not None and repr(item) == repr(e):
                    name = alias or item.name
                    break
            if name is None:
                # Arbitrary sort expression over output columns (the q89
                # ``ORDER BY sum_sales - avg_monthly_sales`` shape):
                # materialized as a hidden column by _sort_maybe_hidden.
                name = e
        if self.accept("KW", "DESC"):
            return (name, False)
        self.accept("KW", "ASC")
        return (name, True)

    def _join(self, df, scope: _Scope):
        how = "inner"
        if self.accept("KW", "LEFT"):
            how = "left"
        elif self.accept("KW", "RIGHT"):
            how = "right"
        elif self.accept("KW", "FULL"):
            how = "full"
        else:
            self.accept("KW", "INNER")
        self.accept("KW", "OUTER")
        self.take("KW", "JOIN")
        other, alias2 = self._table_ref(scope)
        overlap = set(n.lower() for n in df.plan.schema.names) & \
            set(n.lower() for n in other.plan.schema.names)
        if overlap:
            # Columns shared by both JOIN sides (CTEs joined to CTEs —
            # the q77 ``ss LEFT JOIN sr ON ss.s_store_sk =
            # sr.s_store_sk`` shape): rename the right side's shared
            # columns internally; qualified references resolve through
            # scope.renames, unqualified references to them would be
            # ambiguous SQL anyway.
            if alias2 is None:
                raise HyperspaceException(
                    "SQL: JOIN sides share columns "
                    f"{sorted(overlap)}; alias the right side")
            other = self._mangle_columns(other, alias2, overlap, scope)
        self.take("KW", "ON")
        cond = self._resolve_quals(self._join_condition(), scope)
        return df.join(other, on=cond, how=how)

    def _mangle_columns(self, df, label: str, cols_lower, scope: _Scope):
        """Rename ``df``'s columns in ``cols_lower`` to
        ``__<label>__<col>`` and register the mapping with the scope —
        the ONE rename convention shared by duplicate-table comma joins
        and overlapping explicit JOIN sides."""
        mapping = {}
        sel = []
        for c in df.plan.schema.names:
            if c.lower() in cols_lower:
                mangled = f"__{label.lower()}__{c}"
                mapping[c.lower()] = mangled
                sel.append(E.col(c).alias(mangled))
            else:
                sel.append(E.col(c))
        out = df.select(*sel)
        scope.bind(label, out)
        scope.renames[label.lower()] = mapping
        return out

    def _join_condition(self) -> E.Expr:
        cond = self._join_term()
        while self.accept("KW", "AND"):
            cond = cond & self._join_term()
        return cond

    def _join_term(self) -> E.Expr:
        # Parentheses at any level (``ON (a.k = b.k AND a.j = b.j)``,
        # ``ON (a.k = b.k) AND (a.j = b.j)`` — both appear in the TPC-DS
        # texts, e.g. q97).
        if self.accept("OP", "("):
            inner = self._join_condition()
            self.take("OP", ")")
            return inner
        return self._join_eq()

    def _join_eq(self) -> E.Expr:
        left = E.col(self.take_name())
        self.take("OP", "=")
        return left == E.col(self.take_name())

    # -- implicit joins (comma-separated FROM) ---------------------------
    def _build_implicit_joins(self, refs, cond: Optional[E.Expr],
                              scope: _Scope):
        """Lower ``FROM a, b, c WHERE ...`` to inner joins: single-table
        conjuncts pre-filter their table, two-table equality conjuncts
        become join conditions, the rest (and subquery conjuncts) apply
        after the joins. Predicates common to all branches of a top-level
        OR are factored out first (the Q19 shape: the join key equality
        is repeated inside each OR branch)."""
        dfs = [r[0] for r in refs]
        labels = [r[1] or f"table#{i}" for i, r in enumerate(refs)]
        # Duplicate table instances (``date_dim d1, date_dim d2, ...``):
        # rename the later instances' columns so the join output stays
        # unambiguous; qualified references resolve through scope.renames.
        seen_cols: set = set()
        for i, d in enumerate(dfs):
            cols = list(d.plan.schema.names)
            if set(cols) & seen_cols:
                label = refs[i][1]
                if label is None:
                    raise HyperspaceException(
                        "SQL: duplicate table in FROM list requires an "
                        f"alias (columns {sorted(set(cols) & seen_cols)} "
                        "repeat)")
                dfs[i] = self._mangle_columns(
                    d, label, {c.lower() for c in cols}, scope)
            seen_cols.update(dfs[i].plan.schema.names)
        if cond is not None:
            cond = self._resolve_quals(cond, scope)
        conjuncts: List[E.Expr] = []
        if cond is not None:
            for c in E.split_conjunctive_predicates(cond):
                conjuncts.extend(_factor_common_or(c))

        def owner(refs_set):
            """Index of the unique table containing all refs, else None
            (ambiguous references stay post-join, where the Join
            constructor's duplicate-column check gives a clear error)."""
            hits = [i for i, d in enumerate(dfs)
                    if all(_has_col(d, r) for r in refs_set)]
            return hits[0] if len(hits) == 1 else None

        pre: Dict[int, List[E.Expr]] = {}
        edges: List[Tuple[int, int, E.Expr]] = []
        post: List[E.Expr] = []
        subs: List[E.Expr] = []
        for c in conjuncts:
            if _contains_subquery(c):
                subs.append(c)
                continue
            refs_set = set(c.references)
            if isinstance(c, E.EqualTo) and isinstance(c.left, E.Col) \
                    and isinstance(c.right, E.Col):
                li = owner({c.left.column})
                ri = owner({c.right.column})
                if li is not None and ri is not None and li != ri:
                    edges.append((li, ri, c))
                    continue
            o = owner(refs_set) if refs_set else None
            if o is not None:
                pre.setdefault(o, []).append(c)
            else:
                post.append(c)

        for i, preds in pre.items():
            dfs[i] = dfs[i].filter(_conjoin(preds))

        joined = {0}
        cur = dfs[0]
        remaining = set(range(1, len(dfs)))
        while remaining:
            pick = None
            for t in sorted(remaining):
                conds = [p for (a, b, p) in edges
                         if (a in joined and b == t)
                         or (b in joined and a == t)]
                if conds:
                    pick = (t, conds)
                    break
            if pick is None:
                # Single-row cross join: comma-joined global aggregates
                # carry no join keys (the q28/q61/q88/q90 shape — derived
                # tables that are each one aggregate row). General cross
                # joins stay rejected.
                singles = [t for t in sorted(remaining)
                           if _is_single_row(dfs[t].plan)]
                if singles:
                    t = singles[0]
                    cur = cur.cross_join(dfs[t])
                    joined.add(t)
                    remaining.remove(t)
                    continue
                missing = ", ".join(labels[t] for t in sorted(remaining))
                raise HyperspaceException(
                    f"SQL: no equality predicate joins {missing} to the "
                    "rest of the FROM list (cross joins are not supported)")
            t, conds = pick
            cur = cur.join(dfs[t], on=_conjoin(conds), how="inner")
            joined.add(t)
            remaining.remove(t)

        for c in post:
            cur = cur.filter(c)
        for c in subs:
            cur = self._apply_subquery_conjunct(cur, c, scope)
        return cur

    # -- ROLLUP lowering ---------------------------------------------------
    def _rollup_union(self, df, plain: List[str], roll: List[str], aggs):
        """GROUP BY [plain,] ROLLUP(r1..rn) as a UNION ALL of the n+1
        grouping sets (prefixes of the rollup list), each aggregated
        from the SAME pre-aggregation input — exact for every aggregate
        (including avg and count-distinct, which cannot be re-aggregated
        from the finest set). Rolled-up keys become typed NULL columns;
        per-branch constant ``__grouping__<col>__`` flag columns carry
        GROUPING() (dropped by the hidden-name filter unless selected).
        Parity: Spark SQL's rollup, inherited by the reference — TPC-DS
        q5/q18/q22/q27/q67/q77/q80 and the grouping() family."""
        schema = df.plan.schema
        agg_names = [a.name for a in aggs]
        flag_names = [f"__grouping__{c.lower()}__" for c in roll]
        out_names = plain + roll + agg_names + flag_names
        branches = []
        for k in range(len(roll), -1, -1):
            keys = plain + roll[:k]
            part = (df.group_by(*keys).agg(*aggs) if keys
                    else df.agg(*aggs))
            for c in roll[k:]:
                sp = df._spelling(c)
                part = part.with_column(
                    c, E.NullLit(schema.field(sp).dtype))
            for j, c in enumerate(roll):
                part = part.with_column(flag_names[j],
                                        E.lit(1 if j >= k else 0))
            branches.append(part.select(*out_names))
        out = branches[0]
        for b in branches[1:]:
            out = out.union(b)
        return out

    # -- window lowering ---------------------------------------------------
    def _apply_windows_mixed(self, df, cols):
        """Rewrite a projection list (strings or exprs) so every embedded
        WindowExpr becomes a reference to a hidden window output column.
        All specs land in ONE Window plan node, so exprs sharing a
        (partition, order) spec share one sort in the executor."""
        specs: List[Tuple[str, E.WindowExpr]] = []

        def rewrite(node: E.Expr) -> E.Expr:
            if isinstance(node, E.WindowExpr):
                name = f"__win{self._win_counter}"
                self._win_counter += 1
                specs.append((name, node))
                return E.col(name)
            return E.map_children(node, rewrite)

        out = [rewrite(c) if isinstance(c, E.Expr) and _contains_window(c)
               else c for c in cols]
        if specs:
            df = self._attach_windows(df, specs)
        return df, out

    def _attach_windows(self, df, specs):
        """Materialize non-column window sub-expressions (argument,
        partition keys, order keys) as hidden projected columns, then add
        one Window node carrying every spec. Hidden columns are dropped
        by the enclosing SELECT's final projection."""
        from .plan.nodes import Window

        def mat(sub, name, tag):
            nonlocal df
            if sub is None or isinstance(sub, E.Col):
                return sub
            hidden = f"{name}_{tag}"
            df = df.with_column(hidden, sub)
            return E.col(hidden)

        prepared = []
        for name, w in specs:
            arg = mat(w.arg, name, "a")
            part = [mat(p, name, f"p{i}") for i, p in enumerate(w.partition)]
            orders = [(mat(o, name, f"o{i}"), asc)
                      for i, (o, asc) in enumerate(w.orders)]
            prepared.append((name, df._resolve_expr(
                E.WindowExpr(w.fn, arg, part, orders, w.frame))))
        return type(df)(df.session, Window(prepared, df.plan))

    # -- subquery lowering ------------------------------------------------
    def _apply_where_with_subqueries(self, df, cond: E.Expr, scope: _Scope):
        plain: List[E.Expr] = []
        subs: List[E.Expr] = []
        for c in E.split_conjunctive_predicates(cond):
            (subs if _contains_subquery(c) else plain).append(c)
        if plain:
            df = df.filter(_conjoin(plain))
        for c in subs:
            df = self._apply_subquery_conjunct(df, c, scope)
        return df

    def _apply_subquery_conjunct(self, df, c: E.Expr, scope: _Scope):
        if isinstance(c, _ExistsSubquery):
            return self._lower_semi_anti(df, c.subq, scope,
                                         value=None, negated=c.negated)
        if isinstance(c, _InSubquery):
            if not isinstance(c.value, E.Col):
                raise HyperspaceException(
                    "SQL: [NOT] IN (SELECT ...) requires a plain column "
                    f"on the left, got {c.value!r}")
            return self._lower_semi_anti(df, c.subq, scope,
                                         value=c.value, negated=c.negated)
        if isinstance(c, E._Binary) and not isinstance(c, (E.And, E.Or)):
            sides = [c.left, c.right]
            marks = [isinstance(s, _ScalarSubquery) for s in sides]
            if sum(marks) == 1:
                return self._lower_scalar(df, c, scope)
        raise HyperspaceException(
            "SQL: subqueries are only supported as top-level WHERE "
            f"conjuncts (EXISTS / IN / scalar comparison); got {c!r}")

    def _analyze_subquery(self, subq: _SubQ, scope: _Scope, outer_df):
        """Split the subquery's WHERE into local predicates and correlated
        equality pairs (inner column, outer column).

        Side classification happens on the still-QUALIFIED names: when the
        subquery reads the same table as the outer query (the TPC-H Q21
        family), ``t2.g = t.g`` must stay a correlation even though both
        sides strip to the same bare column."""
        inner = self._table(subq.table)
        child = _Scope(parent=scope)
        inner_name = (subq.alias or subq.table).lower()
        child.bind(inner_name, inner)

        def side(col: E.Col) -> str:
            """'inner' | 'outer' | 'unknown' for one column reference,
            honoring explicit qualifiers before schema membership."""
            name = col.column
            if "." in name:
                prefix, rest = name.split(".", 1)
                if prefix.lower() == inner_name:
                    return "inner" if _has_col(inner, rest) else "unknown"
                if scope.lookup(prefix) is not None:
                    d = scope.lookup(prefix)
                    return "outer" if _has_col(d, rest) else "unknown"
                # Unknown prefix: maybe a struct leaf of the inner table.
                return "inner" if _has_col(inner, name) else (
                    "outer" if _has_col(outer_df, name) else "unknown")
            if _has_col(inner, name):
                return "inner"  # inner scope shadows outer (SQL scoping)
            if _has_col(outer_df, name):
                return "outer"
            return "unknown"

        def bare(col: E.Col) -> str:
            return self._resolve_qual_name(col.column, child)

        local: List[E.Expr] = []
        corr: List[Tuple[str, str]] = []
        conjuncts = [] if subq.where is None else \
            E.split_conjunctive_predicates(subq.where)
        for c in conjuncts:
            if _contains_subquery(c):
                raise HyperspaceException(
                    "SQL: nested subqueries are not supported")
            if isinstance(c, E.EqualTo) and isinstance(c.left, E.Col) \
                    and isinstance(c.right, E.Col):
                ls, rs = side(c.left), side(c.right)
                if ls == "inner" and rs == "outer":
                    corr.append((inner._spelling(bare(c.left)),
                                 outer_df._spelling(bare(c.right))))
                    continue
                if ls == "outer" and rs == "inner":
                    corr.append((inner._spelling(bare(c.right)),
                                 outer_df._spelling(bare(c.left))))
                    continue
            resolved = self._resolve_quals(c, child)
            refs = set(resolved.references)
            cols = _collect_cols(c)
            if all(_has_col(inner, r) for r in refs) \
                    and all(side(col) != "outer" for col in cols):
                local.append(resolved)
                continue
            raise HyperspaceException(
                "SQL: unsupported correlated predicate in subquery "
                f"(only equality correlation): {c!r}")
        if local:
            inner = inner.filter(_conjoin(local))
        return inner, corr, child

    def _lower_semi_anti(self, df, subq: _SubQ, scope: _Scope,
                         value: Optional[E.Col], negated: bool):
        """[NOT] IN / [NOT] EXISTS → semi/anti join (the TPU engine's
        existence probe keeps the left side's row and bucket order)."""
        inner, corr, child = self._analyze_subquery(subq, scope, df)
        i = self._sq_counter
        self._sq_counter += 1
        keys: List[Tuple[str, str]] = []  # (inner col, outer col)
        if value is not None:
            if subq.star or len(subq.items) != 1 \
                    or not isinstance(subq.items[0][0], E.Col):
                raise HyperspaceException(
                    "SQL: IN subqueries must select exactly one column")
            inner_col = self._resolve_qual_name(subq.items[0][0].column,
                                                child)
            if not _has_col(inner, inner_col):
                raise HyperspaceException(
                    f"SQL: subquery selects unknown column {inner_col!r}")
            keys.append((inner._spelling(inner_col),
                         df._spelling(value.column)))
        keys.extend(corr)
        if not keys:
            raise HyperspaceException(
                "SQL: EXISTS subqueries must be correlated by at least "
                "one equality predicate")
        sel = [E.col(k_in).alias(f"__sq{i}_k{j}")
               for j, (k_in, _) in enumerate(keys)]
        sub = inner.select(*sel)
        cond = None
        for j, (_, k_out) in enumerate(keys):
            eq = E.col(k_out) == E.col(f"__sq{i}_k{j}")
            cond = eq if cond is None else (cond & eq)
        return df.join(sub, on=cond, how="anti" if negated else "semi")

    def _lower_scalar(self, df, comparison: E._Binary, scope: _Scope):
        """``expr <op> (SELECT agg FROM t WHERE corr)`` — the TPC-H Q17
        shape. Decorrelated exactly as the reference's users hand-write it
        in DataFrames: group the inner table by its correlation keys,
        compute the aggregate per group, join back on the keys, compare.
        Rows with no group fall out of the inner join — the same result
        as comparing against a NULL scalar (comparison yields unknown)."""
        flipped = isinstance(comparison.left, _ScalarSubquery)
        marker = comparison.left if flipped else comparison.right
        outer_expr = comparison.right if flipped else comparison.left
        subq = marker.subq
        if subq.star or len(subq.items) != 1:
            raise HyperspaceException(
                "SQL: scalar subqueries must select exactly one expression")
        inner, corr, child = self._analyze_subquery(subq, scope, df)
        if not corr:
            raise HyperspaceException(
                "SQL: uncorrelated scalar subqueries are not supported")
        # The select item may be alias-qualified (``AVG(l2.qty)``) — the
        # same resolution the WHERE conjuncts already got.
        item = self._resolve_quals(subq.items[0][0], child)
        aggs_found: List[E.AggExpr] = []

        def collect(node):
            if isinstance(node, E.AggExpr):
                aggs_found.append(node)
            for ch in node.children:
                collect(ch)

        collect(item)
        if len(aggs_found) != 1:
            raise HyperspaceException(
                "SQL: scalar subqueries must contain exactly one aggregate")
        i = self._sq_counter
        self._sq_counter += 1
        agg_name = f"__sq{i}_agg"
        val_name = f"__sq{i}_val"

        def replace_agg(node):
            if isinstance(node, E.AggExpr):
                return E.col(agg_name)
            return E.map_children(node, replace_agg)

        keys_in = [k for k, _ in corr]
        sub = inner.group_by(*keys_in).agg(aggs_found[0].alias(agg_name))
        sel = [E.col(k).alias(f"__sq{i}_k{j}")
               for j, k in enumerate(keys_in)]
        sel.append(replace_agg(item).alias(val_name))
        sub = sub.select(*sel)
        cond = None
        for j, (_, k_out) in enumerate(corr):
            eq = E.col(k_out) == E.col(f"__sq{i}_k{j}")
            cond = eq if cond is None else (cond & eq)
        joined = df.join(sub, on=cond, how="inner")
        val = E.col(val_name)
        pred = type(comparison)(val, outer_expr) if flipped \
            else type(comparison)(outer_expr, val)
        return joined.filter(pred)


_conjoin = E.conjoin


def _collect_cols(e: E.Expr) -> List[E.Col]:
    out: List[E.Col] = []
    if isinstance(e, E.Col):
        out.append(e)
    for c in e.children:
        out.extend(_collect_cols(c))
    return out


def _split_disjuncts(e: E.Expr) -> List[E.Expr]:
    if isinstance(e, E.Or):
        return _split_disjuncts(e.left) + _split_disjuncts(e.right)
    return [e]


def _factor_common_or(c: E.Expr) -> List[E.Expr]:
    """For an OR conjunct, hoist predicates that appear in EVERY branch:
    ``(j AND a1) OR (j AND a2)`` → ``j`` + ``(a1 OR a2)``. Purely a
    parse-time normalization (sound by distributivity); it is what lets
    the Q19 text's repeated ``p_partkey = l_partkey`` become a join edge."""
    if not isinstance(c, E.Or):
        return [c]
    branches = [E.split_conjunctive_predicates(b)
                for b in _split_disjuncts(c)]
    if any(any(_contains_subquery(p) for p in br) for br in branches):
        return [c]
    rep_sets = [{repr(p) for p in br} for br in branches]
    common = set.intersection(*rep_sets)
    if not common:
        return [c]
    out: List[E.Expr] = [p for p in branches[0] if repr(p) in common]
    residuals = [[p for p in br if repr(p) not in common]
                 for br in branches]
    if all(residuals):
        ors = [_conjoin(r) for r in residuals]
        rest = ors[0]
        for o in ors[1:]:
            rest = rest | o
        out.append(rest)
    # else: some branch is exactly the common set → the OR is implied by
    # the common predicates alone.
    return out


def _fold(a: E.Expr, b: E.Expr, expr_op, py_op) -> E.Expr:
    """Constant-fold literal-literal arithmetic at parse time (e.g. the
    ``1 + 0.1`` inside ``price * (1 + 0.1)``) — the engine's evaluator
    deliberately rejects all-literal subtrees.

    Folding with floats involved goes through Decimal: Spark parses
    ``.06 - 0.01`` as DECIMAL arithmetic yielding exactly 0.05, while
    float64 yields 0.04999999999999999 — a bound that silently excludes
    the 0.05 data values TPC-H Q6 selects."""
    if isinstance(a, E.Lit) and isinstance(b, E.Lit) and \
            isinstance(a.value, (int, float)) and \
            isinstance(b.value, (int, float)):
        if isinstance(a.value, float) or isinstance(b.value, float):
            from decimal import Decimal, InvalidOperation
            try:
                return E.lit(float(py_op(Decimal(str(a.value)),
                                         Decimal(str(b.value)))))
            except (InvalidOperation, ZeroDivisionError):
                pass
        return E.lit(py_op(a.value, b.value))
    return expr_op(a, b)


def _contains_agg(e: Optional[E.Expr]) -> bool:
    if e is None:
        return False
    if isinstance(e, E.AggExpr):
        return True
    return any(_contains_agg(c) for c in e.children)


def _is_single_row(plan) -> bool:
    """True when the plan provably yields at most one row: a global
    aggregate (no group columns), possibly under projections, or LIMIT 1."""
    from .plan.nodes import Aggregate, Limit, Project
    if isinstance(plan, Aggregate):
        return not plan.group_cols
    if isinstance(plan, Limit):
        return plan.n == 1 or _is_single_row(plan.child)
    if isinstance(plan, Project):
        return _is_single_row(plan.child)
    return False


def _contains_window(e: Optional[E.Expr]) -> bool:
    if e is None:
        return False
    if isinstance(e, E.WindowExpr):
        return True
    return any(_contains_window(c) for c in e.children)


def _lift_aggs(e: E.Expr, prefix: str):
    """Replace every aggregate inside ``e`` with a reference to a hidden
    output column, returning (rewritten expression, the hidden aliased
    aggregates to append to the agg list). Serves both HAVING predicates
    and compound select items like ``100 * sum(a) / sum(b)``. Repeated
    aggregates dedupe by structure (the STDDEV rewrite repeats sum/count
    several times; each distinct aggregate is computed once)."""
    hidden: List[E.Expr] = []
    by_repr: Dict[str, str] = {}

    def rec(node: E.Expr) -> E.Expr:
        if isinstance(node, E.AggExpr):
            key = repr(node)
            name = by_repr.get(key)
            if name is None:
                name = f"{prefix}_{len(hidden)}"
                by_repr[key] = name
                hidden.append(node.alias(name))
            return E.col(name)
        return E.map_children(node, rec)

    return rec(e), hidden


def _lit_value(e: E.Expr):
    if not isinstance(e, E.Lit):
        raise HyperspaceException(
            f"SQL: expected a literal, found {e!r}")
    return e.value


def sql(session, text: str):
    """Parse and lower one SELECT statement to a DataFrame."""
    return _Parser(session, text).query()
