"""Index rankers: pick the best candidate(s).

Parity reference: rankers/FilterIndexRanker.scala:43 (Hybrid Scan → max
common source bytes, else min index size; ties broken lexicographically by
name) and rankers/JoinIndexRanker.scala:52 (prefer equal bucket counts, then
more buckets, then more common source bytes).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..index.log_entry import IndexLogEntry
from .rule_utils import common_source_bytes


class FilterIndexRanker:
    @staticmethod
    def rank(session, relation, candidates: List[IndexLogEntry]
             ) -> Optional[IndexLogEntry]:
        if not candidates:
            return None
        # min() with negated numeric components so the name tiebreak is a
        # plain lexicographic ascending compare (a -ord() tuple under max()
        # mis-orders names of different lengths that share a prefix).
        if session.hs_conf.hybrid_scan_enabled():
            return min(candidates,
                       key=lambda e: (-common_source_bytes(e, relation),
                                      e.name))
        return min(candidates,
                   key=lambda e: (e.index_files_size_in_bytes, e.name))


class JoinIndexRanker:
    @staticmethod
    def rank(session, left_relation, right_relation,
             pairs: List[Tuple[IndexLogEntry, IndexLogEntry]]
             ) -> Optional[Tuple[IndexLogEntry, IndexLogEntry]]:
        if not pairs:
            return None
        hybrid = session.hs_conf.hybrid_scan_enabled()

        def score(pair):
            l, r = pair
            equal_buckets = 0 if l.num_buckets == r.num_buckets else 1
            fewer_buckets = -(l.num_buckets + r.num_buckets)
            common = 0
            if hybrid:
                common = (common_source_bytes(l, left_relation)
                          + common_source_bytes(r, right_relation))
            return (equal_buckets, fewer_buckets, -common, l.name, r.name)

        return min(pairs, key=score)
