"""Index rankers: pick the best candidate(s).

Parity reference: rankers/FilterIndexRanker.scala:43 (Hybrid Scan → max
common source bytes, else min index size; ties broken lexicographically by
name) and rankers/JoinIndexRanker.scala:52 (prefer equal bucket counts, then
more buckets, then more common source bytes).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..index.log_entry import IndexLogEntry
from .rule_utils import common_source_bytes


class FilterIndexRanker:
    @staticmethod
    def rank(session, relation, candidates: List[IndexLogEntry]
             ) -> Optional[IndexLogEntry]:
        if not candidates:
            return None
        if session.hs_conf.hybrid_scan_enabled():
            return max(candidates,
                       key=lambda e: (common_source_bytes(e, relation),
                                      _neg_name(e.name)))
        return min(candidates,
                   key=lambda e: (e.index_files_size_in_bytes, e.name))


def _neg_name(name: str):
    # max() with lexicographically-smallest-name tiebreak.
    return tuple(-ord(c) for c in name)


class JoinIndexRanker:
    @staticmethod
    def rank(session, left_relation, right_relation,
             pairs: List[Tuple[IndexLogEntry, IndexLogEntry]]
             ) -> Optional[Tuple[IndexLogEntry, IndexLogEntry]]:
        if not pairs:
            return None
        hybrid = session.hs_conf.hybrid_scan_enabled()

        def score(pair):
            l, r = pair
            equal_buckets = 1 if l.num_buckets == r.num_buckets else 0
            more_buckets = l.num_buckets + r.num_buckets
            common = 0
            if hybrid:
                common = (common_source_bytes(l, left_relation)
                          + common_source_bytes(r, right_relation))
            return (equal_buckets, more_buckets, common,
                    _neg_names(l.name, r.name))

        return max(pairs, key=score)


def _neg_names(a: str, b: str):
    return tuple(-ord(c) for c in a + "\x00" + b)
