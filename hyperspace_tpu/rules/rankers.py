"""Index rankers: pick the best candidate(s).

Parity reference: rankers/FilterIndexRanker.scala:43 (Hybrid Scan → max
common source bytes, else min index size; ties broken lexicographically by
name) and rankers/JoinIndexRanker.scala:52-92 (equal bucket counts first;
under Hybrid Scan common source bytes dominate within each
equal/unequal class, with bucket count as the tiebreak).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..index.log_entry import IndexLogEntry
from .rule_utils import common_source_bytes


class FilterIndexRanker:
    @staticmethod
    def rank(session, relation, candidates: List[IndexLogEntry]
             ) -> Optional[IndexLogEntry]:
        if not candidates:
            return None
        # min() with negated numeric components so the name tiebreak is a
        # plain lexicographic ascending compare (a -ord() tuple under max()
        # mis-orders names of different lengths that share a prefix).
        if session.hs_conf.hybrid_scan_enabled():
            return min(candidates,
                       key=lambda e: (-common_source_bytes(e, relation),
                                      e.name))
        return min(candidates,
                   key=lambda e: (e.index_files_size_in_bytes, e.name))


class JoinIndexRanker:
    """Reference-matching priority (JoinIndexRanker.scala:72-92):

    1. Pairs with EQUAL bucket counts outrank unequal pairs (zero
       exchange in the aligned merge join).
    2. Among equal-bucket pairs under Hybrid Scan, MORE common source
       bytes wins (less on-the-fly merging); bucket count breaks the
       common-bytes tie. Without Hybrid Scan, more buckets wins outright
       (better join parallelism).
    3. Among unequal-bucket pairs under Hybrid Scan, more common bytes
       wins; without Hybrid Scan the input order is kept, as the
       reference's sortWith does.

    Deliberate extension: among EQUAL-bucket pairs, full ties break
    lexicographically by index names so the chosen plan is reproducible
    across candidate enumeration orders. Unequal-bucket pairs keep the
    reference's input-order behavior exactly (sortWith stability).
    """

    @staticmethod
    def rank(session, left_relation, right_relation,
             pairs: List[Tuple[IndexLogEntry, IndexLogEntry]]
             ) -> Optional[Tuple[IndexLogEntry, IndexLogEntry]]:
        if not pairs:
            return None
        hybrid = session.hs_conf.hybrid_scan_enabled()

        def score(pos_pair):
            pos, (l, r) = pos_pair
            common = 0
            if hybrid:
                common = (common_source_bytes(l, left_relation)
                          + common_source_bytes(r, right_relation))
            if l.num_buckets == r.num_buckets:
                return (0, -common, -l.num_buckets, l.name, r.name)
            return (1, -common, pos)  # common is 0 when hybrid is off

        return min(enumerate(pairs), key=score)[1]
