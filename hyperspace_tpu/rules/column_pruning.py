"""Column pruning: narrow each subtree to the columns its ancestors need.

The reference's rules run inside Spark's optimizer *after* ColumnPruning has
already narrowed join sides to the referenced columns — JoinIndexRule's
coverage check (getUsableIndexes) depends on that. This pass is our
equivalent: it inserts Projects at the top of join inputs (and below
aggregates/projects) so the hyperspace rules see the true referenced-column
sets. Executor-level IO pruning exists independently; this pass is about
making rule decisions correct.
"""

from __future__ import annotations

from typing import Optional, Set

from ..plan.nodes import (Aggregate, BucketUnion, Filter, IndexScan, Join, Limit,
                          LogicalPlan, Project, Scan, Sort, Union)


def prune_columns(plan: LogicalPlan, required: Optional[Set[str]] = None
                  ) -> LogicalPlan:
    if required is None:
        required = set(plan.schema.names)

    if isinstance(plan, (Scan, IndexScan)):
        return plan
    if isinstance(plan, Project):
        child_req: Set[str] = set()
        for e in plan.exprs:
            child_req.update(e.references)
        return Project(plan.exprs, prune_columns(plan.child, child_req))
    if isinstance(plan, Filter):
        child_req = required | set(plan.condition.references)
        return Filter(plan.condition, prune_columns(plan.child, child_req))
    if isinstance(plan, Aggregate):
        child_req = set(plan.group_cols)
        for a in plan.aggs:
            child_req.update(a.references)
        # Narrow like Spark's ColumnPruning does under Aggregate — the
        # FilterIndexRule coverage check sees only the referenced columns.
        child = _narrow(prune_columns(plan.child, child_req), child_req)
        return Aggregate(plan.group_cols, plan.aggs, child)
    if isinstance(plan, Sort):
        child_req = required | {c for c, _ in plan.orders}
        return Sort(plan.orders, prune_columns(plan.child, child_req))
    if isinstance(plan, Limit):
        return Limit(plan.n, prune_columns(plan.child, required))
    if isinstance(plan, (Union, BucketUnion)):
        children = [prune_columns(c, set(required)) for c in plan.children]
        return plan.with_children(children)
    if isinstance(plan, Join):
        cond_refs = set(plan.condition.references) \
            if plan.condition is not None else set()
        left_names = set(plan.left.schema.names)
        right_names = set(plan.right.schema.names)
        lreq = (required | cond_refs) & left_names
        rreq = (required | cond_refs) & right_names
        left = prune_columns(plan.left, lreq)
        right = prune_columns(plan.right, rreq)
        left = _narrow(left, lreq)
        right = _narrow(right, rreq)
        return Join(left, right, plan.condition, plan.join_type)
    return plan


def _narrow(plan: LogicalPlan, required: Set[str]) -> LogicalPlan:
    """Insert a Project if the plan outputs more than required."""
    names = plan.schema.names
    keep = [n for n in names if n in required]
    if len(keep) == len(names) or not keep:
        return plan
    if isinstance(plan, Project):
        return Project([e for e in plan.exprs if e.name in required], plan.child)
    return Project(keep, plan)
