"""FilterIndexRule: rewrite Scan→Filter(→Project) to probe a covering index.

Parity reference: rules/FilterIndexRule.scala:38-197. Applicability
(indexCoversPlan, FilterIndexRule.scala:144-155):

  1. the index's *first* indexed column appears in the filter predicate
     (the sort order within buckets makes that column cheap to probe), and
  2. the index covers every column the plan touches (project + filter).

``try_rewrite_filter`` is the shared core used both by this legacy-style rule
and by the score-based optimizer (rules/disabled/FilterIndexRule.scala:34-144
filter-chain semantics), with whyNot reasons recorded into a ReasonCollector.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..index.log_entry import IndexLogEntry
from ..plan.nodes import Filter, LogicalPlan, Project, Scan
from .index_filters import ReasonCollector
from .rankers import FilterIndexRanker
from .rule_utils import (collect_filter_project_columns, get_candidate_indexes,
                         get_relation, log_index_usage,
                         transform_plan_to_use_index)


def _extract_filter_node(plan: LogicalPlan):
    """Match Project(Filter(Scan)) / Filter(Scan); returns (scan, filter) or
    None (parity: ExtractFilterNode, FilterIndexRule.scala:165)."""
    node = plan
    if isinstance(node, Project):
        node = node.child
    if not isinstance(node, Filter):
        return None
    if not isinstance(node.child, Scan):
        return None
    return node.child, node


def try_rewrite_filter(session, plan: LogicalPlan,
                       ctx: Optional[ReasonCollector] = None,
                       candidates_for=None
                       ) -> Optional[Tuple[LogicalPlan, IndexLogEntry]]:
    """Attempt the filter-index rewrite at this plan root. Returns
    (new plan, applied index) or None; filter-out reasons go to ``ctx``."""
    ctx = ctx or ReasonCollector(enabled=False)
    matched = _extract_filter_node(plan)
    if matched is None:
        return None
    scan, _ = matched
    relation = get_relation(session, scan)
    if relation is None:
        return None

    project_cols, filter_cols = collect_filter_project_columns(plan)
    if not filter_cols:
        return None

    from .apply_hyperspace import active_indexes
    if candidates_for is not None:
        pool = candidates_for(scan)
    else:
        pool = get_candidate_indexes(
            session, active_indexes(session), scan, ctx)

    candidates = []
    for e in pool:
        if e.derivedDataset.kind != "CoveringIndex":
            continue
        if e.indexed_columns[0] not in filter_cols:
            ctx.add("NO_FIRST_INDEXED_COL_COND", e,
                    f"The first indexed column '{e.indexed_columns[0]}' does "
                    f"not appear in the filter condition columns {sorted(set(filter_cols))}.")
            continue
        covered = set(e.indexed_columns) | set(e.included_columns)
        missing = (set(project_cols) | set(filter_cols)) - covered
        if missing:
            ctx.add("MISSING_REQUIRED_COL", e,
                    f"Index does not cover required columns {sorted(missing)}.")
            continue
        candidates.append(e)

    best = FilterIndexRanker.rank(session, relation, candidates)
    if best is None:
        return None
    for e in candidates:
        if e is not best:
            ctx.add("ANOTHER_INDEX_APPLIED", e,
                    f"Another candidate index '{best.name}' was ranked higher.")

    use_bucket_spec = session.hs_conf.use_bucket_spec_for_filter_rule()
    new_plan = transform_plan_to_use_index(session, best, plan, use_bucket_spec)
    return new_plan, best


class FilterIndexRule:
    name = "FilterIndexRule"

    def apply(self, session, plan: LogicalPlan,
              ctx: Optional[ReasonCollector] = None) -> LogicalPlan:
        result = try_rewrite_filter(session, plan, ctx)
        if result is None:
            return plan
        new_plan, best = result
        log_index_usage(session, ctx, [best.name], new_plan.tree_string(),
                        "Filter index applied.")
        return new_plan
