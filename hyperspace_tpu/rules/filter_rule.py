"""FilterIndexRule: rewrite Scan→Filter(→Project) to probe a covering index.

Parity reference: rules/FilterIndexRule.scala:38-197. Applicability
(indexCoversPlan, FilterIndexRule.scala:144-155):

  1. the index's *first* indexed column appears in the filter predicate
     (the sort order within buckets makes that column cheap to probe), and
  2. the index covers every column the plan touches (project + filter).
"""

from __future__ import annotations

from typing import List, Optional

from ..index.constants import States
from ..index.log_entry import IndexLogEntry
from ..plan.nodes import Filter, LogicalPlan, Project, Scan
from ..telemetry.events import HyperspaceIndexUsageEvent
from ..telemetry.logging import get_logger
from .rankers import FilterIndexRanker
from .rule_utils import (collect_filter_project_columns, get_candidate_indexes,
                         get_relation, transform_plan_to_use_index)


def _extract_filter_node(plan: LogicalPlan):
    """Match Project(Filter(Scan)) / Filter(Scan); returns (scan, filter) or
    None (parity: ExtractFilterNode, FilterIndexRule.scala:165)."""
    node = plan
    if isinstance(node, Project):
        node = node.child
    if not isinstance(node, Filter):
        return None
    if not isinstance(node.child, Scan):
        return None
    return node.child, node


def index_covers_plan(entry: IndexLogEntry, project_cols: List[str],
                      filter_cols: List[str]) -> bool:
    first_indexed = entry.indexed_columns[0]
    if first_indexed not in filter_cols:
        return False
    covered = set(entry.indexed_columns) | set(entry.included_columns)
    return set(project_cols) | set(filter_cols) <= covered


class FilterIndexRule:
    name = "FilterIndexRule"

    def apply(self, session, plan: LogicalPlan) -> LogicalPlan:
        matched = _extract_filter_node(plan)
        if matched is None:
            return plan
        scan, _ = matched
        relation = get_relation(session, scan)
        if relation is None:
            return plan

        project_cols, filter_cols = collect_filter_project_columns(plan)
        if not filter_cols:
            return plan

        from .apply_hyperspace import active_indexes
        candidates = [e for e in active_indexes(session)
                      if e.derivedDataset.kind == "CoveringIndex"
                      and index_covers_plan(e, project_cols, filter_cols)]
        candidates = get_candidate_indexes(session, candidates, scan)
        best = FilterIndexRanker.rank(session, relation, candidates)
        if best is None:
            return plan

        use_bucket_spec = session.hs_conf.use_bucket_spec_for_filter_rule()
        new_plan = transform_plan_to_use_index(session, best, plan, use_bucket_spec)
        get_logger(session.hs_conf.event_logger_class()).log_event(
            HyperspaceIndexUsageEvent(
                index_names=[best.name], plan_string=new_plan.tree_string(),
                message="Filter index applied."))
        return new_plan
