"""GroupByIndexRule: probe a covering index under an unfiltered group-by.

No direct reference analogue (the reference's FilterIndexRule requires a
Filter node — rules/FilterIndexRule.scala:165); this rule EXCEEDS it the
way the working score-based optimizer does: an Aggregate whose grouping
keys equal an index's indexed columns can scan the index instead of the
source, and the executor then skips the group-by sort entirely because the
covering-index bucket order makes equal key tuples contiguous
(execution/executor.py GROUPBY_SORT_SKIPPED fast path). This is what makes
the TPC-H Q17 shape (avg-per-partkey subquery over the full fact table)
profit from its l_partkey index.
"""

from __future__ import annotations

from typing import Optional

from ..plan import expr as E
from ..plan.nodes import Aggregate, Filter, LogicalPlan, Project, Scan
from .index_filters import ReasonCollector
from .rankers import FilterIndexRanker
from .rule_utils import (get_candidate_indexes, get_relation,
                         log_index_usage, transform_plan_to_use_index)


def _chain_to_scan(node: LogicalPlan):
    """(chain nodes top-down, scan) for a linear Project/Filter chain, or
    None. Projects must pass the needed columns through unrenamed — an
    alias would decouple the grouping keys from the index's columns."""
    chain = []
    cur = node
    while isinstance(cur, (Project, Filter)):
        chain.append(cur)
        cur = cur.child
    if not isinstance(cur, Scan):
        return None
    return chain, cur


def _scan_level_needed(chain, needed) -> Optional[set]:
    """Walk the chain top-down: filters add their references, projects must
    pass the currently-needed names through unrenamed (an alias would
    decouple the grouping keys from the index's columns). Returns the
    column set needed at the scan, or None when a project renames."""
    needed = set(needed)
    for node in chain:
        if isinstance(node, Filter):
            needed |= set(node.condition.references)
            continue
        by_name = {e.name: e for e in node.exprs}
        for n in needed:
            e = by_name.get(n)
            if e is None:
                return None
            inner = e.child if isinstance(e, E.Alias) else e
            if not (isinstance(inner, E.Col) and inner.column == n):
                return None
    return needed


class GroupByIndexRule:
    name = "GroupByIndexRule"

    def apply(self, session, plan: LogicalPlan,
              ctx: Optional[ReasonCollector] = None) -> LogicalPlan:
        from .apply_hyperspace import active_indexes

        ctx = ctx or ReasonCollector(enabled=False)
        applied = []

        def rewrite(node: LogicalPlan) -> LogicalPlan:
            if not isinstance(node, Aggregate) or not node.group_cols:
                return node
            matched = _chain_to_scan(node.child)
            if matched is None:
                return node
            chain, scan = matched
            relation = get_relation(session, scan)
            if relation is None:
                return node
            top_needed = set(node.group_cols)
            for a in node.aggs:
                top_needed |= set(a.references)
            needed = _scan_level_needed(chain, top_needed)
            if needed is None:
                return node
            pool = get_candidate_indexes(
                session, active_indexes(session), scan, ctx)
            group_set = set(node.group_cols)
            candidates = []
            for e in pool:
                if e.derivedDataset.kind != "CoveringIndex":
                    continue
                if set(e.indexed_columns) != group_set:
                    ctx.add("NO_GROUPBY_KEY_MATCH", e,
                            f"Indexed columns {e.indexed_columns} do not "
                            f"equal grouping keys {sorted(group_set)}.")
                    continue
                covered = set(e.indexed_columns) | set(e.included_columns)
                missing = needed - covered
                if missing:
                    ctx.add("MISSING_REQUIRED_COL", e,
                            f"Index does not cover required columns "
                            f"{sorted(missing)}.")
                    continue
                candidates.append(e)
            best = FilterIndexRanker.rank(session, relation, candidates)
            if best is None:
                return node
            new_child = transform_plan_to_use_index(
                session, best, node.child, use_bucket_spec=True)
            applied.append(best.name)
            return Aggregate(node.group_cols, node.aggs, new_child)

        new_plan = plan.transform_up(rewrite)
        if applied:
            log_index_usage(session, ctx, sorted(set(applied)),
                            new_plan.tree_string(),
                            "Group-by index applied.")
        return new_plan
