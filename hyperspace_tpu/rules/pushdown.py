"""Filter pushdown through Project and inner Join — Catalyst-parity plan
normalization.

The reference's index rules match ``Scan → Filter (→ Project)`` shapes
(rules/FilterIndexRule.scala:165) and get away with that narrow pattern
ONLY because Spark's own optimizer batch (PushDownPredicate) has already
pushed every pushable predicate below projections by the time hyperspace's
extra rules run. Our pipeline owns the whole optimizer, so without this
rule a query written ``select(...).where(...)`` — a Filter above a Project
— would silently never be rewritten to an index scan while the logically
identical ``where(...).select(...)`` would.

The transform substitutes the projection's expressions into the predicate
(all our expressions are pure, so duplication is safe), then re-parents:

    Filter(cond, Project(exprs, child))
      → Project(exprs, Filter(subst(cond), child))

and recurses, so a filter sinks through arbitrarily many projections until
it sits directly on the scan where the index rules can see it.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..exceptions import HyperspaceException
from ..plan import expr as E
from ..plan.nodes import Filter, Join, LogicalPlan, Project


class _NotPushable(Exception):
    pass


def _substitute(e: E.Expr, mapping: Dict[str, E.Expr]) -> Optional[E.Expr]:
    """Rebuild ``e`` with every Col reference replaced by the projection
    expression that produces it. Returns None for expression kinds we
    don't know how to rebuild (the filter then stays where it is).
    Structural recursion rides on E.map_children, so every scalar
    expression kind (LIKE, CASE, EXTRACT, ...) is pushable by default;
    aggregates and unknown kinds are not."""

    def rec(node: E.Expr) -> E.Expr:
        if isinstance(node, E.Col):
            return mapping.get(node.column, node)
        if isinstance(node, E.Lit):
            return node
        if isinstance(node, E.AggExpr):
            raise _NotPushable
        return E.map_children(node, rec)

    try:
        return rec(e)
    except (_NotPushable, HyperspaceException):
        return None


_conjoin = E.conjoin


def push_filters(plan: LogicalPlan) -> LogicalPlan:
    """Bottom-up: sink every Filter below the Projects beneath it, and
    split conjuncts of a Filter above an INNER Join to the side whose
    columns they reference (Catalyst's PushDownPredicate — a WHERE written
    above a join then prunes each input BEFORE the join and becomes
    visible to the per-side index rules). Outer joins are left alone: a
    predicate on the null-producing side is not semantics-preserving
    below the join."""
    children = plan.children
    if children:
        plan = plan.with_children([push_filters(c) for c in children])
    if isinstance(plan, Filter) and isinstance(plan.child, Project):
        proj = plan.child
        mapping: Dict[str, E.Expr] = {}
        for ex in proj.exprs:
            inner = ex.child if isinstance(ex, E.Alias) else ex
            if isinstance(inner, E.AggExpr):
                return plan  # not a scalar projection; leave untouched
            mapping[ex.name] = inner
        cond = _substitute(plan.condition, mapping)
        if cond is not None:
            # Recurse: the sunk filter may sit above another Project.
            return Project(proj.exprs, push_filters(Filter(cond, proj.child)))
    if isinstance(plan, Filter) and isinstance(plan.child, Join) \
            and plan.child.join_type == "inner":
        join = plan.child
        l_names = set(join.left.schema.names)
        r_names = set(join.right.schema.names)
        to_left: List[E.Expr] = []
        to_right: List[E.Expr] = []
        stay: List[E.Expr] = []
        for conj in E.split_conjunctive_predicates(plan.condition):
            refs = set(conj.references)
            if refs and refs <= l_names:
                to_left.append(conj)
            elif refs and refs <= r_names:
                to_right.append(conj)
            else:
                stay.append(conj)
        if to_left or to_right:
            left = push_filters(Filter(_conjoin(to_left), join.left)) \
                if to_left else join.left
            right = push_filters(Filter(_conjoin(to_right), join.right)) \
                if to_right else join.right
            out: LogicalPlan = Join(left, right, join.condition,
                                    join.join_type)
            if stay:
                out = Filter(_conjoin(stay), out)
            return out
    if isinstance(plan, Filter) and isinstance(plan.child, Filter):
        # CombineFilters: adjacent filters (user chains, or a pushed
        # conjunct landing on an already-filtered side) merge into ONE
        # node — the index rules match Filter(Scan), not Filter(Filter(...)).
        inner = plan.child
        return push_filters(
            Filter(plan.condition & inner.condition, inner.child))
    return plan
