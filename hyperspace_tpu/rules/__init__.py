from .apply_hyperspace import apply_hyperspace  # noqa: F401
from .filter_rule import FilterIndexRule  # noqa: F401
from .join_rule import JoinIndexRule  # noqa: F401
from .rankers import FilterIndexRanker, JoinIndexRanker  # noqa: F401
