"""DataSkippingIndexRule: prune a scan's file list using per-file sketches.

No parity in the mounted reference snapshot (DataSkippingIndex landed in
later Hyperspace versions — SURVEY.md version note); behaviorally this is
the later reference's ApplyDataSkippingIndex: the source relation is kept,
but its file listing is narrowed to the files whose sketches cannot refute
the filter predicate. Covering-index rules run first; this rule only touches
Scan leaves they left in place.

Sketch probing is host-side numpy over the (one row per file) sketch table;
unknown predicate shapes conservatively keep all files.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import numpy as np
import pyarrow.parquet as pq

from ..index.log_entry import IndexLogEntry, Sketch
from ..plan import expr as E
from ..plan.nodes import Filter, LogicalPlan, Scan
from .rule_utils import _plan_signature, get_relation


class DataSkippingIndexRule:
    name = "DataSkippingIndexRule"

    def apply(self, session, plan: LogicalPlan, ctx=None) -> LogicalPlan:
        from .apply_hyperspace import active_indexes
        candidates = [e for e in active_indexes(session)
                      if e.derivedDataset.kind == "DataSkippingIndex"]
        if not candidates:
            return plan

        applied: List[str] = []

        def rewrite(node: LogicalPlan) -> LogicalPlan:
            if isinstance(node, Filter) and isinstance(node.child, Scan):
                pruned = self._try_prune(session, node.child, node.condition,
                                         candidates, applied, ctx)
                if pruned is not None:
                    return Filter(node.condition, pruned)
            return node

        new_plan = plan.transform_up(rewrite)
        if applied:
            from .rule_utils import log_index_usage
            log_index_usage(session, ctx, sorted(set(applied)),
                            new_plan.tree_string(),
                            "Data skipping index applied.")
            if ctx is not None:
                ctx.applied.extend(sorted(set(applied)))
        return new_plan

    def _try_prune(self, session, scan: Scan, condition: E.Expr,
                   candidates: List[IndexLogEntry],
                   applied: List[str], ctx=None) -> Optional[Scan]:
        relation = get_relation(session, scan)
        if relation is None:
            return None
        all_files = relation.all_files()
        keep = np.ones(len(all_files), dtype=bool)
        hit_names: List[str] = []
        for entry in candidates:
            sig = _plan_signature(entry, scan)
            recorded = entry.signature.signatures[0].value \
                if entry.signature.signatures else None
            if sig is None or recorded is None or sig != recorded:
                if ctx is not None:
                    ctx.add("SOURCE_DATA_CHANGED", entry,
                            "Source fingerprint mismatch; refresh the "
                            "data-skipping index.")
                continue
            verdict = evaluate_sketch_predicate(entry, condition, all_files,
                                                relation.schema)
            if verdict is None:
                if ctx is not None:
                    sketched = sorted({s.column for s in
                                       entry.derivedDataset.sketches})
                    ctx.add("NO_APPLICABLE_SKETCH", entry,
                            f"No filter conjunct is refutable by the "
                            f"index's sketches (sketched columns: "
                            f"{sketched}); only literal comparisons and "
                            f"IN lists on a sketched column can prune.")
                continue
            keep &= verdict
            hit_names.append(entry.name)
        if not hit_names or keep.all():
            return None  # nothing pruned → no rewrite, no usage event.
        applied.extend(hit_names)
        kept_files = [f for f, k in zip(all_files, keep) if k]
        # The note makes the pruning visible in golden plans + explain:
        # without it a skipped scan prints identically to the full scan.
        return Scan(relation.with_files(kept_files),
                    skipping_note=(f"{len(kept_files)}/{len(all_files)} "
                                   f"files after skipping"))


def evaluate_sketch_predicate(entry: IndexLogEntry, condition: E.Expr,
                              all_files: Sequence[str],
                              relation_schema) -> Optional[np.ndarray]:
    """Per-file keep mask from the entry's sketch table, or None when the
    predicate has no evaluable conjunct."""
    table = _load_sketch_table(entry)
    by_file = {name: i for i, name in enumerate(table["_file"])}
    n_sketch = len(table["_file"])

    sketch_by_col = {}
    for s in entry.derivedDataset.sketches:
        sketch_by_col.setdefault(s.column, []).append(s)

    mask_rows: Optional[np.ndarray] = None
    for conjunct in E.split_conjunctive_predicates(condition):
        verdict = _eval_node(conjunct, table, sketch_by_col, relation_schema,
                             n_sketch)
        if verdict is not None:
            mask_rows = verdict if mask_rows is None else (mask_rows & verdict)
    if mask_rows is None:
        return None

    # Map sketch-row verdicts onto the scan's file list; files without a
    # sketch row (shouldn't happen on signature match) are kept.
    out = np.ones(len(all_files), dtype=bool)
    for i, f in enumerate(all_files):
        j = by_file.get(f)
        if j is not None:
            out[i] = bool(mask_rows[j])
    return out


def _eval_node(e: E.Expr, table, sketch_by_col, relation_schema,
               n: int) -> Optional[np.ndarray]:
    """Keep mask over sketch rows for one predicate node; None = unknown."""
    if isinstance(e, E.And):
        l = _eval_node(e.left, table, sketch_by_col, relation_schema, n)
        r = _eval_node(e.right, table, sketch_by_col, relation_schema, n)
        if l is None:
            return r
        if r is None:
            return l
        return l & r
    if isinstance(e, E.Or):
        l = _eval_node(e.left, table, sketch_by_col, relation_schema, n)
        r = _eval_node(e.right, table, sketch_by_col, relation_schema, n)
        if l is None or r is None:
            return None  # one side unprunable → the OR can't prune.
        return l | r
    if isinstance(e, E.In) and isinstance(e.value, E.Col) \
            and all(isinstance(o, E.Lit) for o in e.options):
        verdicts = [_eval_compare(e.value.column, "EqualTo", o.value, table,
                                  sketch_by_col, relation_schema, n)
                    for o in e.options]
        if any(v is None for v in verdicts) or not verdicts:
            return None
        out = verdicts[0]
        for v in verdicts[1:]:
            out = out | v
        return out
    if isinstance(e, (E.EqualTo, E.LessThan, E.LessThanOrEqual,
                      E.GreaterThan, E.GreaterThanOrEqual)):
        left, right = e.left, e.right
        flipped = False
        if isinstance(left, E.Lit) and isinstance(right, E.Col):
            left, right = right, left
            flipped = True
        if not (isinstance(left, E.Col) and isinstance(right, E.Lit)):
            return None
        op = type(e).__name__
        if flipped:
            op = {"EqualTo": "EqualTo", "LessThan": "GreaterThan",
                  "LessThanOrEqual": "GreaterThanOrEqual",
                  "GreaterThan": "LessThan",
                  "GreaterThanOrEqual": "LessThanOrEqual"}[op]
        return _eval_compare(left.column, op, right.value, table,
                             sketch_by_col, relation_schema, n)
    return None


def _eval_compare(column: str, op: str, value, table, sketch_by_col,
                  relation_schema, n: int) -> Optional[np.ndarray]:
    from ..actions.create_skipping import (bloom_col, minmax_cols,
                                           valuelist_col)

    sketches: List[Sketch] = sketch_by_col.get(column, [])
    if not sketches:
        return None
    out: Optional[np.ndarray] = None

    def apply_mask(m: np.ndarray):
        nonlocal out
        out = m if out is None else (out & m)

    from .. import native

    # Probe-ready arrays (bitset matrices, converted min/max columns) are
    # assembled once per sketch table and cached inside it — at lake scale
    # (thousands of files) the per-probe Python assembly otherwise costs
    # more than the scan it would save (r4 lake bench: 230 ms/probe at
    # 1600 files, vs microseconds prepared).
    prep_cache = table.setdefault("__prepared__", {})

    for s in sketches:
        if s.kind == "MinMax":
            lo_name, hi_name = minmax_cols(column)
            lo, hi = table[lo_name], table[hi_name]
            dtype = relation_schema.field(column).dtype
            # Native (or vectorized) prune over all files in one call; the
            # generic Python loop remains for unsupported dtypes (strings).
            pr = prep_cache.get((column, "MinMax"))
            if pr is None:
                pr = native.prepare_minmax(lo, hi, dtype)
                prep_cache[(column, "MinMax")] = \
                    pr if pr is not None else "unsupported"
            m = None if pr in (None, "unsupported") else \
                native.minmax_prune_prepared(pr, op, value, dtype)
            if m is None:
                m = np.ones(n, dtype=bool)
                for i in range(n):
                    if lo[i] is None or hi[i] is None:
                        continue  # all-null file: only IS NULL matches; keep.
                    if op == "EqualTo":
                        m[i] = lo[i] <= value <= hi[i]
                    elif op == "LessThan":
                        m[i] = lo[i] < value
                    elif op == "LessThanOrEqual":
                        m[i] = lo[i] <= value
                    elif op == "GreaterThan":
                        m[i] = hi[i] > value
                    elif op == "GreaterThanOrEqual":
                        m[i] = hi[i] >= value
            apply_mask(m)
        elif s.kind == "ValueList" and op == "EqualTo":
            lists = table[valuelist_col(column)]
            m = np.ones(n, dtype=bool)
            for i, vals in enumerate(lists):
                if vals is None:
                    continue  # over-cardinality file: no information, keep
                m[i] = value in vals  # exact membership, no false positives
            apply_mask(m)
        elif s.kind == "BloomFilter" and op == "EqualTo":
            dtype = relation_schema.field(column).dtype
            num_bits = int(s.properties["numBits"])
            num_hashes = int(s.properties["numHashes"])
            pr = prep_cache.get((column, "BloomFilter"))
            if pr is None:
                pr = native.prepare_bloom(table[bloom_col(column)],
                                          num_bits)
                prep_cache[(column, "BloomFilter")] = pr
                # The raw bitset pylist is equal-sized to the prepared
                # matrix and never read again — drop it so the cached
                # table doesn't hold bloom bytes twice.
                table[bloom_col(column)] = None
            m = native.bloom_probe_prepared(pr[0], pr[1], value, dtype,
                                            num_bits, num_hashes)
            apply_mask(m)
    return out


# Tiny per-entry cache keyed on (index name, log id): sketch tables are small
# and reread per query otherwise.
_SKETCH_CACHE: dict = {}


def _load_sketch_table(entry: IndexLogEntry) -> dict:
    from ..actions.create_skipping import SKETCH_FILE_NAME

    key = (entry.name, entry.id)
    cached = _SKETCH_CACHE.get(key)
    if cached is not None:
        return cached
    files = [f for f in entry.content.files
             if os.path.basename(f) == SKETCH_FILE_NAME]
    from ..index import data_store
    _fs, _p0 = data_store.fs_and_path(files[0])
    t = pq.read_table(_p0, filesystem=_fs)
    table = {name: t.column(name).to_pylist() for name in t.column_names}
    if len(_SKETCH_CACHE) >= 8:  # keep at most a handful of entries alive.
        _SKETCH_CACHE.pop(next(iter(_SKETCH_CACHE)))
    _SKETCH_CACHE[key] = table
    return table
