"""Shared rule machinery: candidate selection + plan transformation.

Parity reference: rules/RuleUtils.scala:52-569.

- ``get_candidate_indexes``: signature match in the common case; with Hybrid
  Scan enabled, file-overlap selection bounded by appended/deleted byte-ratio
  thresholds (RuleUtils.scala:52-190).
- ``transform_plan_to_use_index``: swap the source Scan for an IndexScan —
  index-only scan when the file sets match exactly, otherwise a Hybrid Scan
  (appended files merged in, deleted rows masked via the lineage column)
  (RuleUtils.scala:193-567). On TPU the BucketUnion of index + re-bucketed
  appended rows is a shard-aligned concatenation (SURVEY §5 item 3).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..index.constants import IndexConstants
from ..index.log_entry import FileInfo, IndexLogEntry
from ..index.signatures import LogicalPlanSignatureProvider
from ..plan import expr as E
from ..plan.nodes import Filter, IndexScan, LogicalPlan, Project, Scan
from ..schema import Schema


def log_index_usage(session, ctx, index_names: List[str], plan_string: str,
                    message: str) -> None:
    """Emit an index-usage telemetry event unless this is a silent
    (diagnostic, e.g. why_not) pass — the single enforcement point of the
    'diagnostic passes emit no telemetry' invariant. The same point
    tallies per-index applied counts (session._index_usage_counts), which
    statistics/advisor surface to spot hot and dead indexes."""
    if ctx is not None and getattr(ctx, "silent", False):
        return
    with session._usage_counts_lock:
        counts = session._index_usage_counts
        for name in index_names:
            counts[name] = counts.get(name, 0) + 1
    from ..telemetry.events import HyperspaceIndexUsageEvent
    from ..telemetry.logging import get_logger
    get_logger(session.hs_conf.event_logger_class()).log_event(
        HyperspaceIndexUsageEvent(index_names=index_names,
                                  plan_string=plan_string, message=message))


def get_relation(session, plan: LogicalPlan):
    """The single supported file-based relation leaf of a linear plan, or
    None (parity: RuleUtils.getRelation — exactly one relation required)."""
    leaves = plan.collect_leaves()
    if len(leaves) != 1 or not isinstance(leaves[0], Scan):
        return None
    if not session.source_provider_manager.is_supported_relation(leaves[0]):
        return None
    return leaves[0].relation


def _plan_signature(entry: IndexLogEntry, scan: Scan) -> Optional[str]:
    recorded = entry.signature.signatures
    if not recorded:
        return None
    provider = LogicalPlanSignatureProvider.create(recorded[0].provider)
    return provider.signature(scan)


def _current_file_infos(relation) -> List[FileInfo]:
    return [FileInfo(p, size, mtime, IndexConstants.UNKNOWN_FILE_ID)
            for p, size, mtime in relation.all_file_infos()]


def resolve_time_travel_entry(session, entry: IndexLogEntry, relation
                              ) -> IndexLogEntry:
    """For versioned sources (delta/iceberg analogues), swap the latest index
    entry for the log version built closest to the *scanned* table version
    (parity: DeltaLakeRelation.closestIndex:187 — time-travel-aware index
    selection). Non-versioned relations pass through unchanged."""
    closest_fn = getattr(relation, "closest_index_log_version", None)
    if closest_fn is None:
        return entry
    # History pairs are keyed by op-log id (entry.id), the version an
    # action's final commit was written at.
    target = closest_fn(entry.derivedDataset.properties)
    if target is None or target == entry.id:
        return entry
    from ..index.constants import States
    older = session.index_collection_manager.log_manager_for(
        entry.name).get_log(target)
    if older is not None and older.state == States.ACTIVE:
        return older
    return entry


def get_candidate_indexes(session, indexes: List[IndexLogEntry],
                          scan: Scan, ctx=None) -> List[IndexLogEntry]:
    """Indexes applicable to this scan. Signature equality, or — with Hybrid
    Scan on — bounded file-overlap. ``ctx`` (a ReasonCollector) records why
    stale indexes were dropped (parity: FileSignatureFilter,
    ApplyHyperspace.scala:54-67)."""
    hybrid = session.hs_conf.hybrid_scan_enabled()
    out = []
    for entry in indexes:
        entry = resolve_time_travel_entry(session, entry, scan.relation)
        if not hybrid:
            sig = _plan_signature(entry, scan)
            recorded = entry.signature.signatures[0].value \
                if entry.signature.signatures else None
            if sig is not None and recorded is not None and sig == recorded:
                out.append(entry)
            elif ctx is not None:
                ctx.add("SOURCE_DATA_CHANGED", entry,
                        "Source fingerprint mismatch (files were added, "
                        "removed, or modified since the index was built); "
                        "enable hybrid scan or refresh the index.")
            continue
        ok, appended, deleted = hybrid_scan_file_diff(
            session, entry, scan.relation)
        if ok:
            out.append(entry)
        elif ctx is not None:
            ctx.add("SOURCE_DATA_CHANGED", entry,
                    f"Hybrid Scan not applicable: {len(appended)} appended"
                    f" / {len(deleted)} deleted files exceed thresholds, "
                    "no common files, or deletes without lineage.")
    return out


def hybrid_scan_file_diff(session, entry: IndexLogEntry, relation
                          ) -> Tuple[bool, List[FileInfo], List[FileInfo]]:
    """(applicable?, appended files, deleted files) under Hybrid Scan rules
    (parity: RuleUtils.scala:96-160)."""
    current = set(_current_file_infos(relation))
    logged = entry.source_file_info_set
    common = current & logged
    if not common:
        return False, [], []
    appended = sorted(current - logged, key=lambda f: f.name)
    deleted = sorted(logged - common, key=lambda f: f.name)
    if deleted and not entry.has_lineage_column():
        return False, [], []
    common_bytes = sum(f.size for f in common)
    appended_bytes = sum(f.size for f in appended)
    deleted_bytes = sum(f.size for f in deleted)
    appended_ratio = appended_bytes / (appended_bytes + common_bytes) \
        if appended_bytes else 0.0
    deleted_ratio = deleted_bytes / (deleted_bytes + common_bytes) \
        if deleted_bytes else 0.0
    if appended_ratio > session.hs_conf.hybrid_scan_appended_ratio_threshold():
        return False, [], []
    if deleted_ratio > session.hs_conf.hybrid_scan_deleted_ratio_threshold():
        return False, [], []
    return True, appended, deleted


def common_source_bytes(entry: IndexLogEntry, relation) -> int:
    current = set(_current_file_infos(relation))
    return sum(f.size for f in (current & entry.source_file_info_set))


def index_scan_schema(entry: IndexLogEntry,
                      like: "Schema" = None) -> Schema:
    """The index schema exposed to the plan (lineage column hidden).

    ``like``: order columns as that schema does (the replaced Scan's) —
    the rewrite must not change the plan's output column ORDER, only its
    physical source (a select-free query returns relation-ordered columns
    either way; Spark keeps the original output attributes too)."""
    if like is not None:
        inner = set(entry.schema.names)
        ordered = [n for n in like.names if n in inner]
        ordered += [n for n in entry.schema.names if n not in set(ordered)]
        names = [n for n in ordered
                 if n != IndexConstants.DATA_FILE_NAME_ID]
        return entry.schema.select(names)
    names = [n for n in entry.schema.names
             if n != IndexConstants.DATA_FILE_NAME_ID]
    return entry.schema.select(names)


def transform_plan_to_use_index(session, entry: IndexLogEntry,
                                plan: LogicalPlan,
                                use_bucket_spec: bool) -> LogicalPlan:
    """Replace the plan's Scan leaf with an IndexScan over ``entry``.

    Exact-match source → index-only scan; otherwise Hybrid Scan state
    (appended file paths + deleted file ids) is attached to the IndexScan
    and realized by the executor (concat + lineage mask).
    """

    def replace(node: LogicalPlan) -> LogicalPlan:
        if isinstance(node, Scan):
            appended_paths: List[str] = []
            deleted_ids: List[int] = []
            if session.hs_conf.hybrid_scan_enabled():
                ok, appended, deleted = hybrid_scan_file_diff(
                    session, entry, node.relation)
                if ok:
                    appended_paths = [f.name for f in appended]
                    if deleted:
                        by_key = {(f.name, f.size, f.modifiedTime): f.id
                                  for f in entry.source_file_info_set}
                        deleted_ids = [
                            by_key[(f.name, f.size, f.modifiedTime)]
                            for f in deleted]
            return IndexScan(entry, index_scan_schema(entry, node.schema),
                             use_bucket_spec=use_bucket_spec,
                             deleted_file_ids=deleted_ids,
                             appended_files=appended_paths)
        return node

    return plan.transform_up(replace)


def is_plan_linear(plan: LogicalPlan) -> bool:
    """Scan/Filter/Project chain with single children all the way down
    (parity: JoinIndexRule.isPlanLinear)."""
    node = plan
    while True:
        if isinstance(node, Scan):
            return True
        if not isinstance(node, (Filter, Project)):
            return False
        children = node.children
        if len(children) != 1:
            return False
        node = children[0]


def _walk_base_references(plan: LogicalPlan):
    """(output name → base column map, all base columns the chain reads)
    for a linear Scan/Filter/Project chain, tracing Alias renames level by
    level so every node's references are translated through the mapping *at
    its depth*. Computed expressions map to None as outputs (not direct base
    attributes — parity with JoinIndexRule.scala:234; Spark gets this from
    exprIds) but their inputs still count toward the read set. Returns None
    for non-linear plans."""
    if isinstance(plan, Scan):
        return {n: n for n in plan.schema.names}, set()
    if isinstance(plan, Filter):
        walked = _walk_base_references(plan.child)
        if walked is None:
            return None
        mapping, refs = walked
        refs = set(refs)
        for r in plan.condition.references:
            base = mapping.get(r)
            if base is not None:
                refs.add(base)
        return mapping, refs
    if isinstance(plan, Project):
        walked = _walk_base_references(plan.child)
        if walked is None:
            return None
        mapping, refs = walked
        refs = set(refs)
        out = {}
        for e in plan.exprs:
            for r in e.references:
                base = mapping.get(r)
                if base is not None:
                    refs.add(base)
            inner = e.child if isinstance(e, E.Alias) else e
            out[e.name] = mapping.get(inner.column) \
                if isinstance(inner, E.Col) else None
        return out, refs
    return None


def output_to_base_mapping(plan: LogicalPlan) -> Optional[dict]:
    """Output column name → base relation column through a linear chain."""
    walked = _walk_base_references(plan)
    return None if walked is None else walked[0]


def collect_base_references(plan: LogicalPlan) -> Optional[set]:
    """Every base relation column a linear chain reads plus its direct base
    outputs — the coverage-check input, all in base namespace. None for
    non-linear plans."""
    walked = _walk_base_references(plan)
    if walked is None:
        return None
    mapping, refs = walked
    return refs | {b for b in mapping.values() if b is not None}


def collect_filter_project_columns(plan: LogicalPlan) -> Tuple[List[str], List[str]]:
    """(project/output columns, filter columns) referenced by a linear plan."""
    project_cols: List[str] = []
    filter_cols: List[str] = []
    node = plan
    saw_project = False
    while not isinstance(node, Scan):
        if isinstance(node, Project):
            if not saw_project:
                for e in node.exprs:
                    project_cols.extend(e.references)
                saw_project = True
        elif isinstance(node, Filter):
            filter_cols.extend(node.condition.references)
        node = node.children[0]
    if not saw_project:
        project_cols = list(plan.schema.names)
    return project_cols, filter_cols
