"""Score-based index plan optimizer (next-gen rule framework, complete).

Parity reference: rules/ApplyHyperspace.scala:69-101
(ScoreBasedIndexPlanOptimizer — the reference ships it as a placeholder with
only NoOpRule registered; here it is the fully-working version the design
anticipates, with the disabled filter-chain rules re-enabled:
rules/disabled/JoinIndexRule.scala:45-618 and
rules/disabled/FilterIndexRule.scala:34-144).

Each HyperspaceRule proposes a rewrite of a plan node together with a score;
the optimizer recurses over the tree (memoized) and picks, at every node, the
max of (best rewrite at this node) vs (sum of the children's best scores).
Scores follow the reference's scale (disabled/FilterIndexRule.scala:166-188,
disabled/JoinIndexRule.scala:668-698): a filter rewrite is worth
50 × (common-bytes / relation-bytes); a join rewrite 70 per side — so a join
rewrite (up to 140) beats filter-rewriting both sides (up to 100)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..index.log_entry import IndexLogEntry
from ..plan.nodes import IndexScan, Join, LogicalPlan, Scan
from .index_filters import ReasonCollector
from .rule_utils import common_source_bytes, get_relation


def _coverage_ratio(session, entry: IndexLogEntry, relation,
                    cache: Optional[dict] = None) -> float:
    """Fraction of the relation's current bytes covered by the index — 1.0
    when the source is unchanged, lower under Hybrid Scan with appends
    (parity: commonBytes / allFileSizeInBytes in the reference's scores).

    ``cache`` (one per optimizer pass) memoizes the per-(entry, relation)
    ratio so repeated rule invocations don't re-list the relation's files."""
    key = (entry.name, entry.log_version, id(relation))
    if cache is not None and key in cache:
        return cache[key]
    total = sum(size for _, size, _ in relation.all_file_infos())
    ratio = 1.0 if total <= 0 else \
        min(1.0, common_source_bytes(entry, relation) / total)
    if cache is not None:
        cache[key] = ratio
    return ratio


def _plan_index_bytes(plan: LogicalPlan) -> int:
    """Total index bytes a plan reads — the tie-break between alternatives
    with EQUAL scores: a wide and a slim covering index that both fully
    satisfy the query score identically (50 x 1.0), and the optimizer should
    pick the plan scanning fewer bytes. Kept out of the score itself so the
    reference's 50/70 scale is never perturbed in non-tie cases."""
    return sum(leaf.index_entry.index_files_size_in_bytes
               for leaf in plan.collect_leaves()
               if isinstance(leaf, IndexScan))


class HyperspaceRule:
    """A candidate-plan rewrite with a score (parity:
    rules/HyperspaceRule.scala:27-83)."""

    name = "HyperspaceRule"

    def apply(self, session, plan: LogicalPlan, candidates, ctx, cache=None
              ) -> Tuple[Optional[LogicalPlan], float]:
        """Return (rewritten plan, score>0) or (None, 0.0) if inapplicable.
        ``candidates`` maps id(scan) -> (scan, [indexes]) from
        CandidateIndexCollector; ``cache`` memoizes per-relation file stats
        for the duration of one optimizer pass."""
        raise NotImplementedError


class NoOpRule(HyperspaceRule):
    """Keeps the plan as-is (parity: NoOpRule, rules/HyperspaceRule.scala:83)."""

    name = "NoOpRule"

    def apply(self, session, plan, candidates, ctx, cache=None):
        return None, 0.0


def _candidates_for(candidates):
    def lookup(scan: Scan) -> List[IndexLogEntry]:
        entry = candidates.get(id(scan))
        return entry[1] if entry else []
    return lookup


class FilterIndexRuleNG(HyperspaceRule):
    """Filter rewrite as a scored rule. Score: 50 × covered-bytes ratio
    (parity: rules/disabled/FilterIndexRule.scala:124-144 FilterRankFilter)."""

    name = "FilterIndexRule"

    def apply(self, session, plan, candidates, ctx, cache=None):
        from .filter_rule import try_rewrite_filter
        result = try_rewrite_filter(session, plan, ctx,
                                    candidates_for=_candidates_for(candidates))
        if result is None:
            return None, 0.0
        new_plan, entry = result
        scan = plan.collect_leaves()[0]
        relation = get_relation(session, scan)
        score = 50.0 * _coverage_ratio(session, entry, relation, cache)
        return new_plan, score


class JoinIndexRuleNG(HyperspaceRule):
    """Join rewrite as a scored rule. Score: 70 × covered-bytes ratio per
    side, summed (parity: rules/disabled/JoinIndexRule.scala:668-698)."""

    name = "JoinIndexRule"

    def apply(self, session, plan, candidates, ctx, cache=None):
        if not isinstance(plan, Join):
            return None, 0.0
        from .join_rule import try_rewrite_join
        result = try_rewrite_join(session, plan, ctx,
                                  candidates_for=_candidates_for(candidates))
        if result is None:
            return None, 0.0
        new_plan, (l_entry, r_entry) = result
        l_rel = get_relation(session, plan.left.collect_leaves()[0])
        r_rel = get_relation(session, plan.right.collect_leaves()[0])
        score = (70.0 * _coverage_ratio(session, l_entry, l_rel, cache)
                 + 70.0 * _coverage_ratio(session, r_entry, r_rel, cache))
        return new_plan, score


class ScoreBasedIndexPlanOptimizer:
    """Recursive, memoized, score-maximizing index selection (parity:
    ApplyHyperspace.scala:69-101)."""

    def __init__(self, rules: Optional[List[HyperspaceRule]] = None):
        self.rules = rules or [JoinIndexRuleNG(), FilterIndexRuleNG(),
                               NoOpRule()]

    def apply(self, session, plan: LogicalPlan, candidates,
              ctx: ReasonCollector) -> LogicalPlan:
        from .apply_hyperspace import _applied_index_names

        memo: Dict[int, Tuple[LogicalPlan, float]] = {}
        file_stats_cache: Dict = {}

        def rec(node: LogicalPlan) -> Tuple[LogicalPlan, float]:
            cached = memo.get(id(node))
            if cached is not None:
                return cached

            # Option A: keep this node, recurse into children.
            children = node.children
            if children:
                rec_children = [rec(c) for c in children]
                base_plan = node.with_children([p for p, _ in rec_children])
                base_score = sum(s for _, s in rec_children)
            else:
                base_plan, base_score = node, 0.0

            # Option B: a rule rewrite rooted at this node (the rewrite
            # consumes the whole subtree, e.g. both join sides). Usage
            # telemetry for the winning plan is emitted by apply_hyperspace
            # once the search is over — rewrites scored here may lose to a
            # higher-scoring rewrite further up the tree.
            alternatives = [(base_plan, base_score)]
            best_plan, best_score = base_plan, base_score
            best_bytes = None  # lazy: only ties need the leaf walk
            for rule in self.rules:
                rewritten, score = rule.apply(session, node, candidates, ctx,
                                              file_stats_cache)
                if rewritten is None:
                    continue
                alternatives.append((rewritten, score))
                if score > best_score:
                    best_plan, best_score = rewritten, score
                    best_bytes = None
                elif score == best_score:
                    if best_bytes is None:
                        best_bytes = _plan_index_bytes(best_plan)
                    rw_bytes = _plan_index_bytes(rewritten)
                    if rw_bytes < best_bytes:
                        best_plan, best_bytes = rewritten, rw_bytes

            # Indexes used only in out-scored alternatives get a whyNot
            # reason — otherwise "why wasn't my filter index used" has no
            # answer when a join rewrite won the subtree.
            if ctx.enabled and len(alternatives) > 1:
                winner_names = set(_applied_index_names(best_plan))
                for alt_plan, alt_score in alternatives:
                    if alt_plan is best_plan:
                        continue
                    for name in set(_applied_index_names(alt_plan)) - winner_names:
                        if alt_score == best_score:
                            reason = (
                                f"A rewrite using this index tied the "
                                f"chosen plan's score ({best_score:.0f}) "
                                f"and lost the tie-break (fewer index "
                                f"bytes read wins; equal plans keep the "
                                f"first found).")
                        else:
                            reason = (
                                f"A rewrite using this index scored "
                                f"{alt_score:.0f}, below the chosen "
                                f"plan's {best_score:.0f}.")
                        ctx.add_name("OUTSCORED", name, reason)

            memo[id(node)] = (best_plan, best_score)
            return best_plan, best_score

        final_plan, _ = rec(plan)
        return final_plan
