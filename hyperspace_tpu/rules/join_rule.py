"""JoinIndexRule: rewrite hint-less equi-joins into shuffle-free bucketed
sort-merge joins over a compatible pair of covering indexes.

Parity reference: rules/JoinIndexRule.scala:53-532. Applicability:

  - join condition is a conjunction of column=column equalities
    (isJoinConditionSupported, :135)
  - both sides are linear Scan/Filter/Project chains (isPlanLinear, :166)
  - every join column comes directly from a base relation, and the mapping
    between left and right join columns is 1:1 (ensureAttributeRequirements,
    :234)
  - each side has an index whose indexed columns are exactly the side's join
    columns in an order compatible with the other side's under the column
    mapping (getCompatibleIndexPairs, :484), and which covers that side's
    referenced columns (getUsableIndexes, :449)

Both sides are then rewritten with use_bucket_spec=True: co-partitioned
buckets (same hash, same count) let the executor merge per bucket with zero
exchange — the TPU analogue of presenting bucketSpec to Spark's SMJ.

``try_rewrite_join`` is the shared core used both by the legacy-style rule and
the score-based optimizer (rules/disabled/JoinIndexRule.scala:45-618 filter
chain), recording whyNot reasons into a ReasonCollector.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..index.log_entry import IndexLogEntry
from ..plan import expr as E
from ..plan.nodes import Join, LogicalPlan, Scan
from .index_filters import ReasonCollector
from .rankers import JoinIndexRanker
from .rule_utils import (collect_base_references, get_candidate_indexes,
                         get_relation, is_plan_linear, log_index_usage,
                         output_to_base_mapping, transform_plan_to_use_index)


def _ensure_one_to_one(pairs) -> Optional[Tuple[List[str], List[str]]]:
    """Order-preserving dedup of (l, r) pairs + 1:1 check: no left column may
    map to two right columns or vice versa (parity:
    ensureAttributeRequirements, JoinIndexRule.scala:234). Applied once in
    output namespace and again after base-column translation."""
    l_to_r: Dict[str, str] = {}
    r_to_l: Dict[str, str] = {}
    uniq: List[Tuple[str, str]] = []
    for l, r in pairs:
        if l_to_r.get(l, r) != r or r_to_l.get(r, l) != l:
            return None
        if l not in l_to_r:
            uniq.append((l, r))
        l_to_r[l] = r
        r_to_l[r] = l
    return [p[0] for p in uniq], [p[1] for p in uniq]


def _column_mapping(join: Join, pairs) -> Optional[Tuple[List[str], List[str]]]:
    """Normalize pairs to (left cols, right cols) under a 1:1 mapping."""
    left_names = set(join.left.schema.names)
    right_names = set(join.right.schema.names)
    sided = []
    for a, b in pairs:
        if a in left_names and b in right_names:
            sided.append((a, b))
        elif b in left_names and a in right_names:
            sided.append((b, a))
        else:
            return None
    return _ensure_one_to_one(sided)


def _usable_indexes(session, side_plan: LogicalPlan, scan: Scan,
                    join_cols: List[str], ctx: ReasonCollector,
                    candidates_for=None) -> List[IndexLogEntry]:
    """Indexes on this side whose indexed columns are exactly the join
    columns (any order) and which cover all referenced columns (parity:
    getUsableIndexes, JoinIndexRule.scala:449). ``join_cols`` and the
    coverage set are both in base-relation namespace (alias renames
    resolved)."""
    base_refs = collect_base_references(side_plan)
    if base_refs is None:
        return []
    referenced = base_refs | set(join_cols)

    from .apply_hyperspace import active_indexes
    if candidates_for is not None:
        pool = candidates_for(scan)
    else:
        pool = get_candidate_indexes(session, active_indexes(session), scan,
                                     ctx)

    out = []
    for entry in pool:
        if entry.derivedDataset.kind != "CoveringIndex":
            continue
        if sorted(entry.indexed_columns) != sorted(join_cols):
            ctx.add("NOT_ALL_JOIN_COL_INDEXED", entry,
                    f"Indexed columns {list(entry.indexed_columns)} are not "
                    f"exactly the join columns {sorted(join_cols)}.")
            continue
        covered = set(entry.indexed_columns) | set(entry.included_columns)
        if not referenced <= covered:
            ctx.add("MISSING_REQUIRED_COL", entry,
                    f"Index does not cover required columns "
                    f"{sorted(referenced - covered)}.")
            continue
        out.append(entry)
    return out


def _compatible_pairs(l_usable, r_usable, col_map: Dict[str, str]
                      ) -> List[Tuple[IndexLogEntry, IndexLogEntry]]:
    """Pairs whose indexed-column order matches under the mapping
    (parity: getCompatibleIndexPairs/isCompatible, JoinIndexRule.scala:484)."""
    out = []
    for le in l_usable:
        mapped = [col_map[c] for c in le.indexed_columns]
        for re_ in r_usable:
            if list(re_.indexed_columns) == mapped:
                out.append((le, re_))
    return out


def try_rewrite_join(session, join: Join,
                     ctx: Optional[ReasonCollector] = None,
                     candidates_for=None
                     ) -> Optional[Tuple[LogicalPlan,
                                         Tuple[IndexLogEntry, IndexLogEntry]]]:
    """Attempt the shuffle-free-join rewrite of this Join node. Returns
    (new plan, (left index, right index)) or None."""
    ctx = ctx or ReasonCollector(enabled=False)
    if join.join_type != "inner":
        return None
    pairs = E.extract_equi_join_keys(join.condition)
    if not pairs:
        return None
    if not (is_plan_linear(join.left) and is_plan_linear(join.right)):
        return None
    l_rel = get_relation(session, join.left.collect_leaves()[0])
    r_rel = get_relation(session, join.right.collect_leaves()[0])
    if l_rel is None or r_rel is None:
        return None

    mapping = _column_mapping(join, pairs)
    if mapping is None:
        return None
    l_cols, r_cols = mapping

    # Trace output names to base relation columns (Alias renames — e.g.
    # self-joins — keep working; computed join keys disqualify the side).
    l_base = output_to_base_mapping(join.left)
    r_base = output_to_base_mapping(join.right)
    if l_base is None or r_base is None:
        return None
    l_cols = [l_base.get(c) for c in l_cols]
    r_cols = [r_base.get(c) for c in r_cols]
    if any(c is None for c in l_cols) or any(c is None for c in r_cols):
        return None
    # Re-establish the dedup + 1:1 invariant in base space: two alias pairs
    # of the same base pair collapse to one; conflicting base mappings
    # disqualify the join.
    based = _ensure_one_to_one(zip(l_cols, r_cols))
    if based is None:
        return None
    l_cols, r_cols = based

    l_scan = join.left.collect_leaves()[0]
    r_scan = join.right.collect_leaves()[0]
    l_usable = _usable_indexes(session, join.left, l_scan, l_cols, ctx,
                               candidates_for)
    r_usable = _usable_indexes(session, join.right, r_scan, r_cols, ctx,
                               candidates_for)
    if not l_usable or not r_usable:
        return None

    col_map = dict(zip(l_cols, r_cols))
    compatible = _compatible_pairs(l_usable, r_usable, col_map)
    if not compatible:
        for e in l_usable + r_usable:
            ctx.add("NO_AVAIL_JOIN_INDEX_PAIR", e,
                    "No compatible index pair: indexed-column order does not "
                    "match the other side's under the join-column mapping.")
        return None
    best = JoinIndexRanker.rank(session, l_rel, r_rel, compatible)
    if best is None:
        return None
    l_entry, r_entry = best
    for le, re_ in compatible:
        for e in (le, re_):
            if e is not l_entry and e is not r_entry:
                ctx.add("ANOTHER_INDEX_APPLIED", e,
                        f"Pair ('{l_entry.name}', '{r_entry.name}') was "
                        "ranked higher.")

    new_left = transform_plan_to_use_index(
        session, l_entry, join.left, use_bucket_spec=True)
    new_right = transform_plan_to_use_index(
        session, r_entry, join.right, use_bucket_spec=True)
    return (Join(new_left, new_right, join.condition, join.join_type),
            (l_entry, r_entry))


class JoinIndexRule:
    name = "JoinIndexRule"

    def apply(self, session, plan: LogicalPlan,
              ctx: Optional[ReasonCollector] = None) -> LogicalPlan:
        def rewrite(node: LogicalPlan) -> LogicalPlan:
            if isinstance(node, Join):
                out = try_rewrite_join(session, node, ctx)
                if out is not None:
                    new_plan, (l_entry, r_entry) = out
                    log_index_usage(session, ctx,
                                    [l_entry.name, r_entry.name],
                                    node.simple_string(),
                                    "Join index applied.")
                    return new_plan
            return node

        return plan.transform_up(rewrite)
