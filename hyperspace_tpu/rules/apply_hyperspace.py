"""The hyperspace rewrite batch.

Parity reference: package.scala:35-46 — enableHyperspace injects the batch
``JoinIndexRule :: FilterIndexRule`` into the optimizer; ApplyHyperspace
(rules/ApplyHyperspace.scala:103) is the next-gen single entry point that
collects candidate indexes once per plan (CandidateIndexCollector) and picks
rewrites with ScoreBasedIndexPlanOptimizer. Both paths exist here: the
score-based optimizer is the default; the legacy ordered batch (join first —
it constrains both sides — then filter) is kept behind
``hyperspace.optimizer.scoreBased.enabled=false``.

Each pass records whyNot filter reasons into a ReasonCollector (enabled via
``hyperspace.index.filterReason.enabled``) that the session retains for the
``Hyperspace.why_not`` API.
"""

from __future__ import annotations

from typing import List

from ..index.constants import States
from ..index.log_entry import IndexLogEntry
from ..plan.nodes import IndexScan, LogicalPlan
from .index_filters import CandidateIndexCollector, ReasonCollector


def active_indexes(session) -> List[IndexLogEntry]:
    """ACTIVE indexes from the session's shared caching index manager."""
    return session.index_collection_manager.get_indexes([States.ACTIVE])


def _applied_index_names(plan: LogicalPlan) -> List[str]:
    return [leaf.index_entry.name for leaf in plan.collect_leaves()
            if isinstance(leaf, IndexScan)]


def apply_hyperspace(session, plan: LogicalPlan,
                     ctx: ReasonCollector = None) -> LogicalPlan:
    from ..telemetry import span_names as SN
    from ..telemetry import trace as _trace
    with _trace.span(SN.INDEX_REWRITE) as sp:
        plan = _apply_hyperspace(session, plan, ctx)
        if sp is not None:
            sp.attrs["applied"] = len(_applied_index_names(plan))
        return plan


def _apply_hyperspace(session, plan: LogicalPlan,
                      ctx: ReasonCollector = None) -> LogicalPlan:
    from .data_skipping_rule import DataSkippingIndexRule
    from .filter_rule import FilterIndexRule
    from .join_rule import JoinIndexRule
    from .score_optimizer import ScoreBasedIndexPlanOptimizer

    if ctx is None:
        ctx = ReasonCollector(session.hs_conf.filter_reason_enabled())

    score_based = session.hs_conf.score_based_optimizer_enabled()
    if score_based:
        covering = [e for e in active_indexes(session)
                    if e.derivedDataset.kind == "CoveringIndex"]
        candidates = CandidateIndexCollector.collect(
            session, plan, covering, ctx)
        plan = ScoreBasedIndexPlanOptimizer().apply(
            session, plan, candidates, ctx)
    else:
        plan = JoinIndexRule().apply(session, plan, ctx)
        plan = FilterIndexRule().apply(session, plan, ctx)

    # ``applied`` reflects the final plan, not every rewrite the optimizer
    # scored along the way; the data-skipping rule appends its own names
    # below (it narrows Scan leaves in place rather than swapping them).
    ctx.applied = _applied_index_names(plan)
    if score_based and ctx.applied:
        from .rule_utils import log_index_usage
        log_index_usage(session, ctx, sorted(set(ctx.applied)),
                        plan.tree_string(), "Hyperspace indexes applied.")

    # Group-by indexes: unfiltered aggregations over remaining Scan leaves
    # probe a covering index whose bucket order lets the executor skip the
    # group-by sort (no reference analogue — see rules/groupby_rule.py).
    from .groupby_rule import GroupByIndexRule
    plan = GroupByIndexRule().apply(session, plan, ctx)
    ctx.applied = _applied_index_names(plan)

    # Data skipping last: it only narrows Scan leaves the covering rules
    # left in place (the covering rewrite is the better win when it applies).
    plan = DataSkippingIndexRule().apply(session, plan, ctx)

    if not ctx.silent:
        session._last_reason_collector = ctx
    return plan
