"""The hyperspace rewrite batch.

Parity reference: package.scala:35-46 — enableHyperspace injects the batch
``JoinIndexRule :: FilterIndexRule`` into the optimizer; ApplyHyperspace
(rules/ApplyHyperspace.scala:103) is the next-gen single entry point that
collects candidate indexes once per plan. We follow the same order: join
rewrites first (they constrain both sides), then filter rewrites.
"""

from __future__ import annotations

from typing import List

from ..index.constants import States
from ..index.log_entry import IndexLogEntry
from ..plan.nodes import LogicalPlan


def active_indexes(session) -> List[IndexLogEntry]:
    """ACTIVE indexes from the session's shared caching index manager."""
    return session.index_collection_manager.get_indexes([States.ACTIVE])


def apply_hyperspace(session, plan: LogicalPlan) -> LogicalPlan:
    from .data_skipping_rule import DataSkippingIndexRule
    from .filter_rule import FilterIndexRule
    from .join_rule import JoinIndexRule
    plan = JoinIndexRule().apply(session, plan)
    plan = FilterIndexRule().apply(session, plan)
    # Data skipping last: it only narrows Scan leaves the covering rules
    # left in place (the covering rewrite is the better win when it applies).
    plan = DataSkippingIndexRule().apply(session, plan)
    return plan
