"""Entry point of the rewrite batch (placeholder until rules land)."""

from __future__ import annotations


def apply_hyperspace(session, plan):
    return plan
