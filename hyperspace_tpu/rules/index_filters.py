"""Next-gen rule framework: index filter chain with "whyNot" reason tagging.

Parity reference: rules/IndexFilter.scala:30-204 (IndexFilter /
SourcePlanIndexFilter / QueryPlanIndexFilter / IndexRankFilter, withFilterReasonTag),
rules/ApplyHyperspace.scala:34-101 (CandidateIndexCollector: per-source-relation
chain ColumnSchemaFilter -> FileSignatureFilter), and the FILTER_REASONS tag
(index/IndexLogEntryTags.scala:57-63).

Reasons are collected into a per-optimization :class:`ReasonCollector` instead
of mutable tags on the log entry (entries here are immutable dataclasses); the
session keeps the collector of the last rewrite for the ``whyNot`` API.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..index.log_entry import IndexLogEntry
from ..plan.nodes import LogicalPlan, Scan


@dataclass(frozen=True)
class FilterReason:
    """One recorded reason why an index was filtered out of a plan rewrite
    (parity: the FILTER_REASONS tag values, IndexFilter.scala:41-52)."""

    code: str
    index_name: str
    message: str

    def __str__(self) -> str:
        return f"[{self.index_name}] {self.code}: {self.message}"


class ReasonCollector:
    """Accumulates FilterReasons during one rewrite pass. ``enabled`` mirrors
    the reference conf ``spark.hyperspace.index.filterReason.enabled`` — when
    off, reason strings are never materialized (IndexFilter.scala:37-39)."""

    def __init__(self, enabled: bool = True, silent: bool = False):
        self.enabled = enabled
        # ``silent`` suppresses index-usage telemetry for diagnostic passes
        # (why_not) that optimize a plan without executing it.
        self.silent = silent
        self.reasons: List[FilterReason] = []
        # Indexes that were actually applied somewhere in the final plan.
        self.applied: List[str] = []

    def add(self, code: str, entry: IndexLogEntry, message: str) -> None:
        self.add_name(code, entry.name, message)

    def add_name(self, code: str, index_name: str, message: str) -> None:
        if self.enabled:
            reason = FilterReason(code, index_name, message)
            # The optimizer scores overlapping patterns (e.g. Filter(Scan)
            # and Project(Filter(Scan))) — record each distinct reason once.
            if reason not in self.reasons:
                self.reasons.append(reason)

    def for_index(self, index_name: str) -> List[FilterReason]:
        return [r for r in self.reasons if r.index_name == index_name]

    def format(self, index_name: Optional[str] = None) -> str:
        applied = sorted(set(self.applied))
        if index_name is not None:
            if index_name in applied:
                return f"Index '{index_name}' was applied."
            rows = self.for_index(index_name)
            if not rows:
                return f"No reasons recorded for index '{index_name}'."
            return "\n".join(str(r) for r in rows)
        # Exploratory scoring can record transient failure reasons for an
        # index that the chosen plan ultimately uses — don't report those.
        rows = [r for r in self.reasons if r.index_name not in applied]
        lines = [str(r) for r in rows]
        if applied:
            lines.append("Applied indexes: " + ", ".join(applied))
        return "\n".join(lines) if lines else "No reason recorded."


class SourcePlanIndexFilter:
    """Filters candidates using only the source relation (parity:
    IndexFilter.scala:117 SourcePlanIndexFilter)."""

    def apply(self, session, scan: Scan, indexes: List[IndexLogEntry],
              ctx: ReasonCollector) -> List[IndexLogEntry]:
        raise NotImplementedError


class ColumnSchemaFilter(SourcePlanIndexFilter):
    """Keep indexes whose indexed + included columns all exist in the
    relation's schema (parity: rules/IndexFilter... ColumnSchemaFilter,
    ApplyHyperspace.scala:44-52)."""

    def apply(self, session, scan: Scan, indexes, ctx):
        available = {n.lower() for n in scan.relation.schema.names}
        out = []
        for entry in indexes:
            needed = list(entry.indexed_columns) + list(entry.included_columns)
            missing = [c for c in needed if c.lower() not in available]
            if missing:
                ctx.add("COL_SCHEMA_MISMATCH", entry,
                        f"Index columns {missing} not found in source schema "
                        f"{sorted(scan.relation.schema.names)}.")
                continue
            out.append(entry)
        return out


class FileSignatureFilter(SourcePlanIndexFilter):
    """Keep indexes whose recorded source fingerprint matches the current
    relation — exactly, or within the Hybrid Scan appended/deleted thresholds
    when Hybrid Scan is on (parity: FileSignatureFilter,
    ApplyHyperspace.scala:54-67 + RuleUtils.scala:52-160). Delegates to the
    single implementation in rule_utils.get_candidate_indexes."""

    def apply(self, session, scan: Scan, indexes, ctx):
        from .rule_utils import get_candidate_indexes
        return get_candidate_indexes(session, indexes, scan, ctx)


class CandidateIndexCollector:
    """Initial per-source-relation candidate selection: the chain
    ColumnSchemaFilter -> FileSignatureFilter applied to every supported Scan
    leaf (parity: ApplyHyperspace.scala:34-67 CandidateIndexCollector)."""

    filters = (ColumnSchemaFilter(), FileSignatureFilter())

    @classmethod
    def collect(cls, session, plan: LogicalPlan,
                indexes: List[IndexLogEntry], ctx: ReasonCollector
                ) -> Dict[int, Tuple[Scan, List[IndexLogEntry]]]:
        """Map of id(scan-leaf) -> (scan, surviving candidate indexes)."""
        out: Dict[int, Tuple[Scan, List[IndexLogEntry]]] = {}
        for leaf in plan.collect_leaves():
            if not isinstance(leaf, Scan):
                continue
            if not session.source_provider_manager.is_supported_relation(leaf):
                continue
            remaining = list(indexes)
            for f in cls.filters:
                if not remaining:
                    break
                remaining = f.apply(session, leaf, remaining, ctx)
            if remaining:
                out[id(leaf)] = (leaf, remaining)
        return out
