from .action import Action  # noqa: F401
from .create import CreateAction, CreateActionBase  # noqa: F401
from .lifecycle import CancelAction, DeleteAction, RestoreAction, VacuumAction  # noqa: F401
