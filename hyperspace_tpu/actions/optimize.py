"""OptimizeAction: compact small index files, one file per bucket.

Parity reference: actions/OptimizeAction.scala:58-172. Partitions the index's
files into small (< ``hyperspace.index.optimize.fileSizeThreshold``, quick
mode) vs all (full mode) candidates, skips buckets that already hold a single
candidate file, and rewrites each remaining bucket's candidate rows —
re-sorted by the indexed columns on device — into one file at a new data
version. Untouched files keep their place in the merged content.

This is the action that restores the one-sorted-file-per-bucket layout
invariant after incremental refreshes, re-enabling the executor's
shuffle-free bucketed merge join fast path.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from ..util import file_utils
from ..exceptions import HyperspaceException, NoChangesException
from ..execution.columnar import read_parquet, write_parquet
from ..index.constants import IndexConstants, States
from ..index.log_entry import Content, FileIdTracker, FileInfo, IndexLogEntry
from ..ops import index_build, kernels
from ..telemetry.events import OptimizeActionEvent
from .refresh import ExistingIndexActionBase

import os


class OptimizeAction(ExistingIndexActionBase):
    transient_state = States.OPTIMIZING
    final_state = States.ACTIVE

    def __init__(self, session, log_manager, data_manager, mode: str):
        super().__init__(session, log_manager, data_manager)
        self.mode = mode
        self._partition: Optional[Tuple[Dict[int, List[FileInfo]],
                                        List[FileInfo]]] = None

    # ------------------------------------------------------------------
    # Candidate selection (parity: OptimizeAction.filesToOptimize).
    # ------------------------------------------------------------------

    def _files_to_optimize(self) -> Tuple[Dict[int, List[FileInfo]],
                                          List[FileInfo]]:
        """(bucket → files to compact, files left untouched)."""
        if self._partition is not None:
            return self._partition
        threshold = self.session.hs_conf.optimize_file_size_threshold()
        by_bucket: Dict[int, List[FileInfo]] = defaultdict(list)
        skipped: List[FileInfo] = []
        for info in sorted(self.previous_entry.content.file_infos,
                           key=lambda f: f.name):
            bucket = index_build.bucket_id_from_file(info.name)
            small = self.mode == IndexConstants.OPTIMIZE_MODE_FULL \
                or info.size < threshold
            if bucket is None or not small:
                skipped.append(info)
            else:
                by_bucket[bucket].append(info)
        # Single-candidate buckets have nothing to merge.
        compact = {}
        for bucket, files in by_bucket.items():
            if len(files) > 1:
                compact[bucket] = files
            else:
                skipped.extend(files)
        self._partition = (compact, skipped)
        return self._partition

    def validate(self) -> None:
        latest = self.log_manager.get_latest_log()
        if latest is None or latest.state != States.ACTIVE:
            raise HyperspaceException(
                f"Optimize is only supported in {States.ACTIVE} state; "
                f"found {latest.state if latest else 'no log'}")
        if self.previous_entry.derivedDataset.kind != "CoveringIndex":
            raise HyperspaceException(
                "Optimize is only supported on covering indexes.")
        compact, _ = self._files_to_optimize()
        if not compact:
            raise NoChangesException(
                "Optimize aborted as no optimizable index files smaller than "
                f"{self.session.hs_conf.optimize_file_size_threshold()} found.")

    # ------------------------------------------------------------------
    # Work: per-bucket merge + rewrite.
    # ------------------------------------------------------------------

    def op(self) -> None:
        prev = self.previous_entry
        compact, skipped = self._files_to_optimize()
        version = self._new_version()
        out_dir = self.data_manager.get_path(version)
        file_utils.makedirs(out_dir)
        row_group_size = self.session.hs_conf.index_row_group_size()
        new_paths: List[str] = []
        for bucket in sorted(compact):
            files = [f.name for f in compact[bucket]]
            table = read_parquet(files, list(prev.schema.names))
            # Restore the within-bucket sort order over the indexed columns.
            perm = kernels.lex_sort_indices(
                [table.column(c).data for c in prev.indexed_columns])
            out_path = os.path.join(
                out_dir, index_build.bucket_file_name(bucket))
            write_parquet(table.take(perm), out_path,
                          row_group_size=row_group_size)
            new_paths.append(out_path)

        tracker = FileIdTracker()
        tracker.add_file_info(prev.source_file_info_set)
        final_paths = [f.name for f in skipped] + new_paths
        index_content = Content.from_leaf_files(final_paths, tracker)
        entry = IndexLogEntry.create(
            prev.name, prev.derivedDataset, index_content, prev.source,
            {k: v for k, v in prev.properties.items()})
        self._entry = entry.with_log_version(version)

    def event(self, message: str) -> OptimizeActionEvent:
        return OptimizeActionEvent(message=message,
                                   index_name=self.previous_entry.name)
