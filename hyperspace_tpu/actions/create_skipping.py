"""Data-skipping index actions: create + refresh of per-file sketch tables.

No direct reference parity: the mounted snapshot has no DataSkippingIndex
(SURVEY.md version note); this implements the BASELINE.json target capability
in the same action/log framework as the covering index. The sketch table is
one row per source data file:

    _file (string, full path) | _file_id (int64)
    | minmax__<col>__min / minmax__<col>__max   (source column type)
    | bloom__<col>                              (binary packed bitset)

stored as a single parquet file per index data version. Sketch values are
computed as device reductions (ops/sketches.py); the table itself is tiny
(one row per file) and lives host-side at plan time.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from ..exceptions import HyperspaceException
from ..index import data_store
from ..util import file_utils
from ..execution.columnar import read_parquet
from ..index.constants import States
from ..index.log_entry import (Content, DataSkippingIndex, FileIdTracker,
                               IndexLogEntry, Sketch)
from ..ops import sketches as sk
from ..plan.nodes import Scan
from ..schema import INT64, STRING, Field, Schema
from ..telemetry.events import (CreateActionEvent, RefreshActionEvent,
                                RefreshIncrementalActionEvent)
from ..util.resolver import resolve_all
from .create import CreateActionBase
from .refresh import RefreshActionBase

SKETCH_FILE_NAME = "sketches.parquet"
FILE_COL = "_file"
FILE_ID_COL = "_file_id"


def minmax_cols(column: str) -> tuple:
    return f"minmax__{column}__min", f"minmax__{column}__max"


def bloom_col(column: str) -> str:
    return f"bloom__{column}"


def valuelist_col(column: str) -> str:
    return f"valuelist__{column}"


def build_sketch_rows(relation, sketch_list: List[Sketch],
                      files: List[str], tracker: FileIdTracker) -> Dict[str, list]:
    """One sketch row per file; device reductions per (file, sketch).

    Reads pipeline through the shared pool (parallel/io.py): file k+1
    (and deeper, to the pool width) reads+decodes while file k's device
    reductions run. The consumer loop walks ``files`` in order, so
    ``tracker`` id assignment and row order — and therefore the sketch
    table bytes — are identical at any thread count."""
    from ..parallel import io as pio
    needed = sorted({s.column for s in sketch_list})
    rows: Dict[str, list] = {FILE_COL: [], FILE_ID_COL: []}
    for s in sketch_list:
        if s.kind == "MinMax":
            lo, hi = minmax_cols(s.column)
            rows[lo] = []
            rows[hi] = []
        elif s.kind == "BloomFilter":
            rows[bloom_col(s.column)] = []
        elif s.kind == "ValueList":
            rows[valuelist_col(s.column)] = []
        else:
            raise HyperspaceException(f"Unknown sketch kind: {s.kind}")
    from ..util.file_utils import file_info_triple
    fmt = getattr(relation, "data_file_format", relation.file_format)
    def _weight(f) -> int:
        # Local stat only (cheap, runs on the submit thread); store-backed
        # paths fall to 0 rather than paying a metadata RPC per file twice
        # (tracker.add_file needs the full info triple later anyway).
        import os
        try:
            return int(os.path.getsize(f))
        except OSError:
            return 0

    for path, table in pio.zip_prefetch(
            files, lambda f: read_parquet([f], needed, fmt),
            weight=_weight, label="sketch_build"):
        rows[FILE_COL].append(path)
        rows[FILE_ID_COL].append(tracker.add_file(*file_info_triple(path)))
        for s in sketch_list:
            col = table.column(s.column)
            if s.kind == "MinMax":
                lo, hi = minmax_cols(s.column)
                mn, mx = sk.minmax_values(col)
                rows[lo].append(mn)
                rows[hi].append(mx)
            elif s.kind == "ValueList":
                rows[valuelist_col(s.column)].append(
                    sk.value_list(col, int(s.properties["maxValues"])))
            else:
                num_bits = int(s.properties["numBits"])
                num_hashes = int(s.properties["numHashes"])
                rows[bloom_col(s.column)].append(
                    sk.bloom_build(col, num_bits, num_hashes).tobytes())
    return rows


def sketch_arrow_schema(relation_schema: Schema,
                        sketch_list: List[Sketch]) -> pa.Schema:
    fields = [pa.field(FILE_COL, pa.string()),
              pa.field(FILE_ID_COL, pa.int64())]
    for s in sketch_list:
        if s.kind == "MinMax":
            src = relation_schema.field(s.column)
            arrow_t = Schema([src]).to_arrow().field(0).type
            lo, hi = minmax_cols(s.column)
            fields.append(pa.field(lo, arrow_t))
            fields.append(pa.field(hi, arrow_t))
        elif s.kind == "ValueList":
            src = relation_schema.field(s.column)
            arrow_t = Schema([src]).to_arrow().field(0).type
            # A null list (over-cardinality file) means "no information".
            fields.append(pa.field(valuelist_col(s.column),
                                   pa.list_(arrow_t)))
        else:
            fields.append(pa.field(bloom_col(s.column), pa.binary()))
    return pa.schema(fields)


def write_sketch_table(rows: Dict[str, list], arrow_schema: pa.Schema,
                       out_dir: str) -> str:
    file_utils.makedirs(out_dir)
    table = pa.table({f.name: pa.array(rows[f.name], type=f.type)
                      for f in arrow_schema}, schema=arrow_schema)
    path = os.path.join(out_dir, SKETCH_FILE_NAME)
    fs, norm = data_store.fs_and_path(path)
    pq.write_table(table, norm, filesystem=fs)
    return path


def logical_sketch_schema(relation_schema: Schema,
                          sketch_list: List[Sketch]) -> Schema:
    """The part of the sketch table describable in the logical type system
    (bloom binary columns are carried by sketch properties instead)."""
    fields = [Field(FILE_COL, STRING, False), Field(FILE_ID_COL, INT64, False)]
    for s in sketch_list:
        if s.kind == "MinMax":
            src = relation_schema.field(s.column)
            lo, hi = minmax_cols(s.column)
            fields.append(Field(lo, src.dtype, True))
            fields.append(Field(hi, src.dtype, True))
    return Schema(fields)


class CreateDataSkippingAction(CreateActionBase):
    transient_state = States.CREATING
    final_state = States.ACTIVE

    def __init__(self, session, df, index_config, log_manager, data_manager):
        super().__init__(session, log_manager, data_manager)
        self.df = df
        self.index_config = index_config
        self._entry: Optional[IndexLogEntry] = None
        self._sketches: Optional[List[Sketch]] = None

    def _resolved_sketches(self) -> List[Sketch]:
        if self._sketches is None:
            names = self.df.plan.schema.names
            out = []
            cs = self.session.hs_conf.case_sensitive()
            for spec in self.index_config.sketches:
                column = resolve_all(names, [spec.column],
                                     case_sensitive=cs)[0]
                out.append(Sketch(spec.kind, column, spec.properties()))
            self._sketches = out
        return self._sketches

    def validate(self) -> None:
        plan = self.df.plan
        if not isinstance(plan, Scan):
            raise HyperspaceException(
                "Only creating an index over a plain scan of a file-based "
                "relation is supported")
        if not self.session.source_provider_manager.is_supported_relation(plan):
            raise HyperspaceException(
                f"Relation is not supported: {plan.relation.describe()}")
        self._resolved_sketches()
        latest = self.log_manager.get_latest_log()
        if latest is not None and latest.state != States.DOESNOTEXIST:
            raise HyperspaceException(
                f"Another index with name {self.index_config.index_name} "
                "already exists")

    def op(self) -> None:
        relation = self.df.plan.relation
        sketch_list = self._resolved_sketches()
        tracker = FileIdTracker()
        rows = build_sketch_rows(relation, sketch_list,
                                 relation.all_files(), tracker)
        out_dir = self.data_manager.get_path(0)
        write_sketch_table(
            rows, sketch_arrow_schema(relation.schema, sketch_list), out_dir)
        index_content = Content.from_directory(out_dir, tracker)
        derived = DataSkippingIndex(
            sketches=sketch_list,
            schema=logical_sketch_schema(relation.schema, sketch_list))
        source = self._build_source(relation, self.df.plan, tracker)
        entry = IndexLogEntry.create(
            self.index_config.index_name, derived, index_content, source, {})
        self._entry = entry.with_log_version(0)

    @property
    def log_entry(self) -> IndexLogEntry:
        if self._entry is not None:
            return self._entry
        relation = self.df.plan.relation
        sketch_list = self._resolved_sketches()
        tracker = FileIdTracker()
        derived = DataSkippingIndex(
            sketches=sketch_list,
            schema=logical_sketch_schema(relation.schema, sketch_list))
        from ..index.log_entry import Directory
        placeholder = Content(root=Directory("/"))
        source = self._build_source(relation, self.df.plan, tracker)
        entry = IndexLogEntry.create(
            self.index_config.index_name, derived, placeholder, source, {})
        return entry.with_log_version(0)

    def event(self, message: str) -> CreateActionEvent:
        return CreateActionEvent(
            message=message, index_name=self.index_config.index_name,
            index_config=self.index_config)


class RefreshDataSkippingAction(RefreshActionBase):
    """Full refresh of a data-skipping index: rebuild the whole sketch table
    over the current file listing at a new data version."""

    def op(self) -> None:
        prev = self.previous_entry
        tracker = FileIdTracker()
        sketch_list = prev.derivedDataset.sketches
        rows = build_sketch_rows(self.relation, sketch_list,
                                 self.relation.all_files(), tracker)
        version = self._new_version()
        out_dir = self.data_manager.get_path(version)
        write_sketch_table(
            rows, sketch_arrow_schema(self.relation.schema, sketch_list),
            out_dir)
        index_content = Content.from_directory(out_dir, tracker)
        source = self._build_source(self.relation, Scan(self.relation), tracker)
        entry = IndexLogEntry.create(
            prev.name, prev.derivedDataset, index_content, source, {})
        self._entry = entry.with_log_version(version)

    def event(self, message: str) -> RefreshActionEvent:
        return RefreshActionEvent(message=message,
                                  index_name=self.previous_entry.name)


class RefreshDataSkippingIncrementalAction(RefreshDataSkippingAction):
    """Incremental refresh: keep sketch rows of unchanged files, drop rows of
    deleted files, sketch only the appended files. (Sketch rows are keyed by
    file, so deletes never require lineage here.)"""

    def op(self) -> None:
        prev = self.previous_entry
        tracker = self._seeded_tracker()
        sketch_list = prev.derivedDataset.sketches
        deleted_names = {f.name for f in self.deleted_files}
        _sf = _sketch_file(prev)
        _fs, _sfp = data_store.fs_and_path(_sf)
        # partitioning=None: the sketch file lives under a "v__=<n>"
        # version directory, and this image's pyarrow otherwise
        # hive-infers a phantom "v__" partition column from the path,
        # breaking the cast-to-sketch-schema below.
        old = pq.read_table(_sfp, filesystem=_fs, partitioning=None)
        keep_mask = [name not in deleted_names
                     for name in old.column(FILE_COL).to_pylist()]
        kept = old.filter(pa.array(keep_mask))
        arrow_schema = sketch_arrow_schema(self.relation.schema, sketch_list)
        new_rows = build_sketch_rows(
            self.relation, sketch_list,
            [f.name for f in self.appended_files], tracker)
        appended_tbl = pa.table(
            {f.name: pa.array(new_rows[f.name], type=f.type)
             for f in arrow_schema}, schema=arrow_schema)
        merged = pa.concat_tables([kept.cast(arrow_schema), appended_tbl])

        version = self._new_version()
        out_dir = self.data_manager.get_path(version)
        file_utils.makedirs(out_dir)
        _mp = os.path.join(out_dir, SKETCH_FILE_NAME)
        _fs2, _mpn = data_store.fs_and_path(_mp)
        pq.write_table(merged, _mpn, filesystem=_fs2)
        index_content = Content.from_directory(out_dir, tracker)
        source = self._build_source(self.relation, Scan(self.relation), tracker)
        entry = IndexLogEntry.create(
            prev.name, prev.derivedDataset, index_content, source, {})
        self._entry = entry.with_log_version(version)

    def event(self, message: str) -> RefreshIncrementalActionEvent:
        return RefreshIncrementalActionEvent(
            message=message, index_name=self.previous_entry.name)


def _sketch_file(entry: IndexLogEntry) -> str:
    files = [f for f in entry.content.files
             if os.path.basename(f) == SKETCH_FILE_NAME]
    if len(files) != 1:
        raise HyperspaceException(
            f"Data-skipping index {entry.name} must have exactly one sketch "
            f"table file; found {len(files)}")
    return files[0]
