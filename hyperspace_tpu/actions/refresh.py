"""Refresh actions: full rebuild, incremental append/delete, quick metadata.

Parity reference: actions/RefreshActionBase.scala:37-155 (reloaded source +
file diffs), RefreshAction.scala:33-59 (full rebuild at a new data version),
RefreshIncrementalAction.scala:47-147 (index only appended files; drop rows
from deleted files via the lineage column), RefreshQuickAction.scala:32-80
(metadata-only: record appended/deleted in the log entry, defer the work to
Hybrid Scan at query time).

TPU-native notes: the incremental append path reuses the device build
pipeline (hash → bucket → sort) with the *previous entry's* bucket count so
the appended index files stay bucket-aligned with the existing ones; deletes
rebuild from masked index rows (a vectorized isin on the lineage column)
rather than a row-by-row anti-join.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException, NoChangesException
from ..execution.columnar import Table, read_parquet
from ..index.constants import IndexConstants, States
from ..index.log_entry import (Content, Directory, FileIdTracker, FileInfo,
                               IndexLogEntry, Update)
from ..ops import kernels
from ..plan.nodes import Scan
from ..telemetry.events import (RefreshActionEvent,
                                RefreshIncrementalActionEvent,
                                RefreshQuickActionEvent)
from .create import CreateActionBase


class ExistingIndexActionBase(CreateActionBase):
    """Base for actions over an already-created index (refresh, optimize):
    resolves the previous stable entry and follows ITS bucketing/lineage
    settings, and allocates the next immutable data-version directory."""

    def __init__(self, session, log_manager, data_manager):
        super().__init__(session, log_manager, data_manager)
        self._entry: Optional[IndexLogEntry] = None
        self._previous: Optional[IndexLogEntry] = None

    @property
    def previous_entry(self) -> IndexLogEntry:
        if self._previous is None:
            entry = self.log_manager.get_latest_stable_log()
            if entry is None:
                raise HyperspaceException("Could not read latest stable log")
            self._previous = entry
        return self._previous

    def _num_buckets(self) -> int:
        return self.previous_entry.num_buckets

    def _lineage_enabled(self) -> bool:
        return self.previous_entry.has_lineage_column()

    def _new_version(self) -> int:
        latest = self.data_manager.get_latest_version_id()
        return 0 if latest is None else latest + 1

    def _base_index_properties(self, relation) -> dict:
        """Carry forward the previous entry's properties (e.g. the delta
        version history accumulates across refreshes) before recomputing the
        standard ones."""
        props = dict(self.previous_entry.derivedDataset.properties)
        props.update(super()._base_index_properties(relation))
        return props

    @property
    def log_entry(self) -> IndexLogEntry:
        if self._entry is not None:
            return self._entry
        # begin() runs before op(): the previous entry is the placeholder.
        return self.previous_entry


def content_from_file_infos(infos: List[FileInfo]) -> Optional[Content]:
    """A Content over already-known FileInfos (no stat calls — the files may
    no longer exist, e.g. deleted source files recorded by quick refresh)."""
    if not infos:
        return None
    return Content(Directory("/", files=sorted(infos, key=lambda f: f.name)))


class RefreshActionBase(ExistingIndexActionBase):
    """Shared refresh machinery: previous entry + reloaded relation + diffs."""

    transient_state = States.REFRESHING
    final_state = States.ACTIVE

    def __init__(self, session, log_manager, data_manager):
        super().__init__(session, log_manager, data_manager)
        self._relation = None
        self._diff: Optional[Tuple[List[FileInfo], List[FileInfo]]] = None

    @property
    def relation(self):
        """The source relation re-listed now (parity: RefreshActionBase.df —
        the reference reloads the DataFrame from the logged relation).
        ``refresh()`` strips version pinning (versionAsOf/snapshotId) so an
        index created over a time-traveled read tracks the live table."""
        if self._relation is None:
            rel = self.previous_entry.relation
            built = self.session.source_provider_manager.build_relation(
                rel.rootPaths, rel.fileFormat, rel.options)
            self._relation = built.refresh()
        return self._relation

    @property
    def indexed_columns(self) -> List[str]:
        return self.previous_entry.indexed_columns

    @property
    def included_columns(self) -> List[str]:
        return self.previous_entry.included_columns

    # ------------------------------------------------------------------
    # File diffs (parity: RefreshActionBase.scala:125-149).
    # ------------------------------------------------------------------

    def _file_diff(self) -> Tuple[List[FileInfo], List[FileInfo]]:
        """(appended, deleted) vs the files recorded in the previous entry."""
        if self._diff is None:
            current = {FileInfo(p, size, mtime)
                       for p, size, mtime in self.relation.all_file_infos()}
            logged = self.previous_entry.source_file_info_set
            appended = sorted(current - logged, key=lambda f: f.name)
            deleted = sorted(logged - current, key=lambda f: f.name)
            self._diff = (appended, deleted)
        return self._diff

    @property
    def appended_files(self) -> List[FileInfo]:
        return self._file_diff()[0]

    @property
    def deleted_files(self) -> List[FileInfo]:
        return self._file_diff()[1]

    def _seeded_tracker(self) -> FileIdTracker:
        """Tracker pre-loaded with the previous source file ids so unchanged
        files keep their lineage ids and appended files get fresh ones."""
        tracker = FileIdTracker()
        tracker.add_file_info(self.previous_entry.source_file_info_set)
        return tracker

    def validate(self) -> None:
        latest = self.log_manager.get_latest_log()
        if latest is None or latest.state != States.ACTIVE:
            raise HyperspaceException(
                f"Refresh is only supported in {States.ACTIVE} state; "
                f"found {latest.state if latest else 'no log'}")
        if not self.appended_files and not self.deleted_files:
            raise NoChangesException(
                "Refresh aborted as no source data change found.")

    def _rebuilt_entry(self, tracker: FileIdTracker, index_content: Content,
                       version: int) -> IndexLogEntry:
        """A fresh entry over the *current* relation state."""
        prev = self.previous_entry
        index_schema = prev.schema
        entry = self._build_entry(
            prev.name, self.relation, Scan(self.relation),
            list(prev.indexed_columns), list(prev.included_columns),
            index_schema, tracker, index_content)
        return entry.with_log_version(version)


class RefreshAction(RefreshActionBase):
    """Full refresh: rebuild the entire index from the current source at a
    new data version (parity: RefreshAction.scala:33-59)."""

    def op(self) -> None:
        tracker = FileIdTracker()
        table = self._load_projected(
            self.relation, self.indexed_columns, self.included_columns, tracker)
        version = self._new_version()
        out_dir = self._write_index_files(table, self.indexed_columns, version)
        index_content = Content.from_directory(out_dir, tracker)
        self._entry = self._rebuilt_entry(tracker, index_content, version)

    def event(self, message: str) -> RefreshActionEvent:
        return RefreshActionEvent(message=message,
                                  index_name=self.previous_entry.name)


class RefreshIncrementalAction(RefreshActionBase):
    """Incremental refresh (parity: RefreshIncrementalAction.scala:47-147):

    - appends only: build bucket-aligned index files over just the appended
      source files at a new version; final content = old ∪ new files. Buckets
      may then hold several files each (compacted later by optimize).
    - with deletes: read the old index rows, mask out rows whose lineage id
      is in the deleted set, merge with the appended rows, and rebuild — the
      new version holds the whole index again (one sorted file per bucket).
    """

    def validate(self) -> None:
        super().validate()
        if self.deleted_files and not self.previous_entry.has_lineage_column():
            raise HyperspaceException(
                "Index refresh (to handle deleted source data) is only "
                "supported on an index with lineage.")

    def _deleted_ids(self) -> List[int]:
        # deleted_files are the logged FileInfos (set difference preserves
        # them), so their recorded lineage ids are already populated.
        return [f.id for f in self.deleted_files]

    def op(self) -> None:
        prev = self.previous_entry
        tracker = self._seeded_tracker()
        appended_paths = [f.name for f in self.appended_files]
        version = self._new_version()

        if self.deleted_files:
            # Masked old rows ∪ appended rows → full rebuild at new version.
            old = read_parquet(sorted(prev.content.files),
                               list(prev.schema.names))
            lineage = old.column(IndexConstants.DATA_FILE_NAME_ID)
            deleted = jnp.asarray(
                np.sort(np.asarray(self._deleted_ids(), dtype=np.int64)))
            old = old.filter(
                ~kernels.isin_sorted(lineage.data.astype(jnp.int64), deleted))
            parts = [old]
            if appended_paths:
                appended = self._load_projected(
                    self.relation, self.indexed_columns, self.included_columns,
                    tracker, files=appended_paths)
                parts.append(appended.select(old.names))
            table = Table.concat(parts) if len(parts) > 1 else parts[0]
            out_dir = self._write_index_files(
                table, self.indexed_columns, version)
            index_content = Content.from_directory(out_dir, tracker)
        else:
            appended = self._load_projected(
                self.relation, self.indexed_columns, self.included_columns,
                tracker, files=appended_paths)
            out_dir = self._write_index_files(
                appended, self.indexed_columns, version)
            index_content = prev.content.merge(
                Content.from_directory(out_dir, tracker))

        self._entry = self._rebuilt_entry(tracker, index_content, version)

    def event(self, message: str) -> RefreshIncrementalActionEvent:
        return RefreshIncrementalActionEvent(
            message=message, index_name=self.previous_entry.name)


class RefreshQuickAction(RefreshActionBase):
    """Quick refresh: metadata-only. Records the appended/deleted file sets in
    the log entry's source Update and leaves the index data untouched; Hybrid
    Scan applies the delta at query time (parity: RefreshQuickAction.scala:
    32-80)."""

    def validate(self) -> None:
        super().validate()
        # Deletes recorded without lineage would make the index permanently
        # inapplicable (hybrid scan rejects deletes on lineage-less indexes);
        # fail loudly like the incremental path (RefreshQuickAction.scala:54).
        if self.deleted_files and not self.previous_entry.has_lineage_column():
            raise HyperspaceException(
                "Index refresh (to handle deleted source data) is only "
                "supported on an index with lineage.")

    def op(self) -> None:
        pass  # metadata-only by design.

    @property
    def log_entry(self) -> IndexLogEntry:
        if self._entry is None:
            prev = self.previous_entry
            tracker = self._seeded_tracker()
            appended_infos = [
                FileInfo(f.name, f.size, f.modifiedTime,
                         tracker.add_file(f.name, f.size, f.modifiedTime))
                for f in self.appended_files]
            # Deleted files keep their recorded ids; they can't be stat'ed.
            update = Update(
                appendedFiles=content_from_file_infos(appended_infos),
                deletedFiles=content_from_file_infos(list(self.deleted_files)))
            prev.relation.data.update = update
            self._entry = prev.with_log_version(prev.log_version)
        return self._entry

    def event(self, message: str) -> RefreshQuickActionEvent:
        return RefreshQuickActionEvent(
            message=message, index_name=self.previous_entry.name)
