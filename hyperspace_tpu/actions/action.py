"""Transactional action framework: the 2-phase protocol over the op log.

Parity reference: actions/Action.scala:34-108. Every index mutation runs as

    validate() → begin(): write transient state at baseId+1
               → op():    do the work
               → end():   write final state at baseId+2, refresh latestStable

Concurrency control is optimistic: the transient write fails if another
action already claimed baseId+1 ("Could not acquire proper state").
``NoChangesException`` from validate() records a no-op and returns quietly.
"""

from __future__ import annotations

import time
from typing import Optional

from ..exceptions import HyperspaceException, NoChangesException
from ..index.log_entry import IndexLogEntry
from ..index.log_manager import IndexLogManager
from ..telemetry.events import HyperspaceEvent
from ..telemetry.logging import get_logger


class Action:
    transient_state: str = ""
    final_state: str = ""

    def __init__(self, session, log_manager: IndexLogManager):
        self.session = session
        self.log_manager = log_manager
        self._base_id: Optional[int] = None

    @property
    def base_id(self) -> int:
        if self._base_id is None:
            latest = self.log_manager.get_latest_id()
            self._base_id = -1 if latest is None else latest
        return self._base_id

    @property
    def end_id(self) -> int:
        return self.base_id + 2

    @property
    def log_entry(self) -> IndexLogEntry:
        """The entry to persist; evaluated at begin() and again at end(), so
        create-style actions can reflect work done by op()."""
        raise NotImplementedError

    def validate(self) -> None:
        pass

    def op(self) -> None:
        raise NotImplementedError

    def event(self, message: str) -> HyperspaceEvent:
        raise NotImplementedError

    def run(self) -> None:
        logger = get_logger(self.session.hs_conf.event_logger_class())
        # Shape-class scope: build/refresh/optimize kernels (sorts, hashes,
        # sketch reductions) read the session's shapeBucketing conf. The
        # parallel-io scope does the same for the reader pool (sketch
        # builds, chunked-build streams, spill merges under this action).
        from ..execution import shapes
        from ..parallel import io as pio
        from ..robustness import fault_names as _fn
        from ..robustness import faults as _faults
        try:
            logger.log_event(self.event("Operation started."))
            # The fault scope arms this session's robustness.faults.*
            # conf for exactly this action run (the crash-recovery
            # harness kill -9s inside these boundaries); disarmed it
            # costs one conf-dict scan.
            with shapes.use_conf(self.session.hs_conf), \
                    pio.use_session(self.session), \
                    _faults.scope_for(self.session.hs_conf):
                self.validate()
                self._begin()
                _faults.fault_point(_fn.ACTION_OP)
                self.op()
                self._end()
            logger.log_event(self.event("Operation succeeded."))
        except NoChangesException as e:
            logger.log_event(self.event(f"No-op operation recorded: {e}"))
        except Exception as e:
            logger.log_event(self.event(f"Operation failed: {e}"))
            raise

    def _begin(self) -> None:
        entry = self.log_entry
        entry.state = self.transient_state
        self._save_entry(self.base_id + 1, entry)

    def _end(self) -> None:
        entry = self.log_entry
        entry.state = self.final_state
        if not self.log_manager.delete_latest_stable_log():
            raise HyperspaceException("Could not delete latest stable log")
        self._save_entry(self.end_id, entry)
        self.log_manager.create_latest_stable_log(self.end_id)

    def _save_entry(self, log_id: int, entry: IndexLogEntry) -> None:
        entry.timestamp = int(time.time() * 1000)
        if not self.log_manager.write_log(log_id, entry):
            raise HyperspaceException(
                "Could not acquire proper state; another concurrent operation "
                f"may be running on this index (log id {log_id} exists)")
