"""CreateAction: validate + build a covering index on device.

Parity reference: actions/CreateAction.scala:29-86 (validation: supported
relation, resolvable columns, name free) and actions/CreateActionBase.scala
(write pipeline: project indexed+included columns, optional lineage column,
repartition by indexed columns, bucketed+sorted write; log-entry assembly
with source fingerprint).

TPU-native differences: the repartition+sort runs as one XLA program
(ops/index_build.py) instead of a Spark shuffle; lineage ids are attached as
a device column built from per-file row counts instead of a broadcast join
over input_file_name().
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax.numpy as jnp
import numpy as np
import pyarrow.parquet as pq

from ..exceptions import HyperspaceException
from ..util import file_utils
from ..execution.columnar import Column, Table, write_parquet
from ..index.constants import IndexConstants, States
from ..index.data_manager import IndexDataManager
from ..index.log_entry import (Content, CoveringIndex, Directory, FileIdTracker,
                               Hdfs, IndexLogEntry, LogicalPlanFingerprint,
                               Relation, Signature, Source, SourcePlan)
from ..index.log_manager import IndexLogManager
from ..index.signatures import IndexSignatureProvider
from ..ops import index_build
from ..plan.nodes import Scan
from ..schema import INT64, Field, Schema
from ..telemetry.events import CreateActionEvent
from ..util.resolver import resolve_all
from .action import Action


class CreateActionBase(Action):
    """Shared machinery for create + full/incremental refresh."""

    def __init__(self, session, log_manager: IndexLogManager,
                 data_manager: IndexDataManager):
        super().__init__(session, log_manager)
        self.data_manager = data_manager

    # ------------------------------------------------------------------
    # Build pipeline.
    # ------------------------------------------------------------------

    def _num_buckets(self) -> int:
        return self.session.hs_conf.num_bucket_count()

    def _lineage_enabled(self) -> bool:
        return self.session.hs_conf.index_lineage_enabled()

    def _load_projected(self, relation, indexed: List[str], included: List[str],
                        file_id_tracker: FileIdTracker,
                        files: Optional[List[str]] = None) -> Table:
        """Read only the index columns; attach the lineage column when
        enabled (file id per row, from per-file row counts)."""
        cols = indexed + included
        files = list(files) if files is not None else relation.all_files()
        data_fmt = getattr(relation, "data_file_format", relation.file_format)
        from ..sources.partitions import read_relation_files
        table = read_relation_files(relation, files, cols, data_fmt)
        if self._lineage_enabled():
            if data_fmt != "parquet":
                raise HyperspaceException(
                    "Lineage requires parquet sources in this version")
            from ..execution.columnar import parquet_row_counts
            counts = parquet_row_counts(files)
            ids = [file_id_tracker.add_file(
                *_file_triple(f)) for f in files]
            lineage = np.repeat(np.asarray(ids, np.int64),
                                np.asarray(counts, np.int64))
            table = table.with_column(
                IndexConstants.DATA_FILE_NAME_ID,
                Column(INT64, jnp.asarray(lineage)))
        return table

    def _write_index_files(self, table: Table, indexed: List[str],
                           version: int) -> str:
        """Hash-partition + sort on device, then one parquet per bucket.

        When >1 device is visible the build runs over the whole mesh
        (radix partition + all-to-all bucket exchange + per-device sort,
        parallel/distributed_build.py) — the product-path analogue of the
        reference's always-distributed Spark build
        (actions/CreateActionBase.scala:118-121)."""
        num_buckets = self._num_buckets()
        row_group_size = self.session.hs_conf.index_row_group_size()
        out_dir = self.data_manager.get_path(version)
        file_utils.makedirs(out_dir)
        if self._use_mesh_build(table):
            self._write_index_files_distributed(
                table, indexed, num_buckets, out_dir, row_group_size)
            return out_dir
        sorted_table, bounds = index_build.build_sorted_buckets(
            table, indexed, num_buckets)
        # One wholesale fetch; the 200 per-bucket writes below then slice
        # host numpy instead of issuing 200×n_cols device round-trips.
        _write_bucket_files(sorted_table.to_host(), bounds, 0, num_buckets,
                            out_dir, row_group_size)
        return out_dir

    def _build_chunked(self, relation, indexed: List[str],
                       included: List[str], file_id_tracker: FileIdTracker,
                       version: int,
                       files: Optional[List[str]] = None) -> bool:
        """Streaming build when the source exceeds the device-footprint
        budget (hyperspace.tpu.maxChunkRows): parquet row-groups flow
        chunk→bucket-spill→per-bucket merge with only one chunk or bucket
        in HBM at a time (ops/index_build.build_sorted_buckets_chunked).
        Returns False when the in-memory path should run instead."""
        from ..execution.columnar import parquet_row_counts
        from ..ops.index_build import build_sorted_buckets_chunked

        data_fmt = getattr(relation, "data_file_format", relation.file_format)
        if data_fmt != "parquet":
            return False
        files = list(files) if files is not None else relation.all_files()
        if not files:
            return False
        # Dotted struct leaves aren't physical top-level parquet columns;
        # the streaming reader can't project them — in-memory path only.
        physical = set(pq.read_schema(files[0]).names)
        if any(c not in physical for c in indexed + included):
            return False
        chunk_rows = self.session.hs_conf.max_chunk_rows()
        if sum(parquet_row_counts(files)) <= chunk_rows:
            return False
        lineage_ids = None
        if self._lineage_enabled():
            lineage_ids = [file_id_tracker.add_file(*_file_triple(f))
                           for f in files]
        out_dir = self.data_manager.get_path(version)
        file_utils.makedirs(out_dir)
        build_sorted_buckets_chunked(
            files, indexed + included, indexed,
            self._num_buckets(), chunk_rows, out_dir,
            self.session.hs_conf.index_row_group_size(),
            lineage_ids=lineage_ids,
            lineage_col=IndexConstants.DATA_FILE_NAME_ID)
        return True

    def _use_mesh_build(self, table: Table) -> bool:
        import jax
        if not self.session.hs_conf.distributed_enabled():
            return False
        if len(jax.devices()) <= 1:
            return False
        if table.num_rows == 0:
            from ..telemetry.logging import emit_distributed_fallback
            emit_distributed_fallback(self.session, "index_build",
                                      "empty source table")
            return False
        # The same cost gate the SPMD query dispatch applies
        # (distributed.minStreamRows): exchanging a few hundred rows
        # over an N-device mesh pays compile + collective overhead for
        # zero scaling win. 0 disables.
        min_rows = self.session.hs_conf.distributed_min_stream_rows()
        if 0 < table.num_rows < min_rows:
            from ..telemetry.logging import emit_distributed_fallback
            emit_distributed_fallback(
                self.session, "index_build",
                f"source {table.num_rows} rows below "
                f"distributed.minStreamRows {min_rows}")
            return False
        return True

    def _write_index_files_distributed(self, table: Table, indexed: List[str],
                                       num_buckets: int, out_dir: str,
                                       row_group_size: int) -> None:
        """Mesh build: after the exchange, device i holds exactly the buckets
        in its contiguous range, each sorted by the indexed columns — so the
        per-bucket parquet write is a straight per-shard slice (no second
        shuffle, matching the one-file-per-bucket layout of the
        single-device path)."""
        import jax
        from ..parallel.distributed_build import distributed_build_sorted_buckets
        from ..parallel.mesh import make_mesh

        mesh = make_mesh()
        n_dev = mesh.devices.size
        out, valid, bids = distributed_build_sorted_buckets(
            table, indexed, num_buckets, mesh)
        # One host fetch for the whole result (per-bucket slicing below is
        # pure numpy — no per-bucket device transfers).
        bids_h = np.asarray(jax.device_get(bids))
        host_table = out.to_host()
        n_padded = bids_h.shape[0]
        shard = n_padded // n_dev
        for d in range(n_dev):
            sb = bids_h[d * shard:(d + 1) * shard]
            # Within a shard: valid rows first (bucket ids ascending), then
            # padding rows carrying the sentinel id == num_buckets — so the
            # shard is globally ascending and searchsorted yields bounds.
            bounds = np.searchsorted(sb, np.arange(num_buckets + 1))
            _write_bucket_files(host_table, bounds, d * shard, num_buckets,
                                out_dir, row_group_size)

    # ------------------------------------------------------------------
    # Log entry assembly (parity: CreateActionBase.getIndexLogEntry).
    # ------------------------------------------------------------------

    def _base_index_properties(self, relation) -> dict:
        props = {}
        if self._lineage_enabled():
            props[IndexConstants.LINEAGE_PROPERTY] = "true"
        if getattr(relation, "data_file_format",
                   relation.file_format) == "parquet":
            props[IndexConstants.HAS_PARQUET_AS_SOURCE_FORMAT_PROPERTY] = "true"
        return props

    def _index_properties(self, relation) -> dict:
        # Source-specific enrichment (e.g. delta version history keyed by the
        # final log version this action will commit).
        return relation.enrich_index_properties(
            self._base_index_properties(relation), self.end_id)

    def _build_source(self, relation, plan,
                      file_id_tracker: FileIdTracker) -> Source:
        source_content = Content.from_leaf_files(
            relation.all_files(), file_id_tracker)
        rel_meta = Relation(
            rootPaths=relation.root_paths,
            data=Hdfs(source_content),
            dataSchema=relation.schema,
            fileFormat=relation.file_format,
            options=relation.options)
        provider = IndexSignatureProvider()
        sig_value = provider.signature(plan)
        fingerprint = LogicalPlanFingerprint(
            [Signature(provider.name(), sig_value)])
        return Source(SourcePlan([rel_meta], fingerprint))

    def _build_entry(self, name: str, relation, plan, indexed: List[str],
                     included: List[str], index_schema: Schema,
                     file_id_tracker: FileIdTracker,
                     index_content: Content) -> IndexLogEntry:
        source = self._build_source(relation, plan, file_id_tracker)
        derived = CoveringIndex(
            indexed_columns=indexed, included_columns=included,
            schema=index_schema, num_buckets=self._num_buckets(),
            properties=self._index_properties(relation))
        return IndexLogEntry.create(name, derived, index_content, source, {})


def _file_triple(path: str):
    from ..util.file_utils import file_info_triple
    return file_info_triple(path)


def _write_bucket_files(table: Table, bounds, base: int, num_buckets: int,
                        out_dir: str, row_group_size: int,
                        file_name=None) -> None:
    """One parquet per non-empty bucket from bucket-contiguous rows.
    ``bounds[b]``..``bounds[b+1]`` (plus ``base``) delimit bucket b; the
    single shared layout rule for the single-device and mesh builds AND
    for user-facing bucketed writes (session.py ``bucket_by``, which
    passes ``file_name`` to add its per-write uniqueness suffix).

    Deliberately serial: the writes are host-side (the build fetched the
    table wholesale already) and measured GIL/IO-bound — a thread pool
    over the per-bucket writes changed nothing at SF1 (1.12 s either
    way), so the simple loop stays."""
    if file_name is None:
        file_name = index_build.bucket_file_name
    for b in range(num_buckets):
        lo, hi = int(bounds[b]), int(bounds[b + 1])
        if hi <= lo:
            continue  # empty buckets produce no file.
        write_parquet(table.slice(base + lo, base + hi),
                      os.path.join(out_dir, file_name(b)),
                      row_group_size=row_group_size)


class CreateAction(CreateActionBase):
    transient_state = States.CREATING
    final_state = States.ACTIVE

    def __init__(self, session, df, index_config, log_manager: IndexLogManager,
                 data_manager: IndexDataManager):
        super().__init__(session, log_manager, data_manager)
        self.df = df
        self.index_config = index_config
        self._entry: Optional[IndexLogEntry] = None
        self._resolved: Optional[tuple] = None

    # ------------------------------------------------------------------
    # Validation (parity: CreateAction.scala:44-77).
    # ------------------------------------------------------------------

    def validate(self) -> None:
        plan = self.df.plan
        if not isinstance(plan, Scan):
            raise HyperspaceException(
                "Only creating an index over a plain scan of a file-based "
                "relation is supported (no filters/joins under createIndex)")
        if not self.session.source_provider_manager.is_supported_relation(plan):
            raise HyperspaceException(
                f"Relation is not supported: {plan.relation.describe()}")
        self._resolve_columns()
        latest = self.log_manager.get_latest_log()
        if latest is not None and latest.state != States.DOESNOTEXIST:
            raise HyperspaceException(
                f"Another index with name {self.index_config.index_name} already exists")

    def _resolve_columns(self):
        if self._resolved is None:
            schema_names = self.df.plan.schema.names
            cs = self.session.hs_conf.case_sensitive()
            indexed = resolve_all(schema_names,
                                  self.index_config.indexed_columns,
                                  case_sensitive=cs)
            included = resolve_all(schema_names,
                                   self.index_config.included_columns,
                                   case_sensitive=cs)
            dup = set(indexed) & set(included)
            if dup:
                raise HyperspaceException(
                    f"Columns in both indexed and included: {sorted(dup)}")
            self._resolved = (indexed, included)
        return self._resolved

    # ------------------------------------------------------------------
    # Work.
    # ------------------------------------------------------------------

    def op(self) -> None:
        indexed, included = self._resolve_columns()
        relation = self.df.plan.relation
        tracker = FileIdTracker()
        if not self._build_chunked(relation, indexed, included, tracker,
                                   version=0):
            table = self._load_projected(relation, indexed, included, tracker)
            self._write_index_files(table, indexed, version=0)
        # Assemble the final entry now that index files exist.
        index_content = Content.from_directory(
            self.data_manager.get_path(0), tracker)
        index_schema = Schema(
            [self.df.plan.schema.field(c) for c in indexed + included])
        if self._lineage_enabled():
            index_schema = index_schema.append(
                Field(IndexConstants.DATA_FILE_NAME_ID, INT64, False))
        self._entry = self._build_entry(
            self.index_config.index_name, relation, self.df.plan, indexed,
            included, index_schema, tracker, index_content)
        self._entry = self._entry.with_log_version(0)

    @property
    def log_entry(self) -> IndexLogEntry:
        if self._entry is not None:
            return self._entry
        # begin() runs before op(): write a minimal placeholder entry.
        indexed, included = self._resolve_columns()
        relation = self.df.plan.relation
        tracker = FileIdTracker()
        source_content = Content.from_leaf_files(relation.all_files(), tracker)
        index_schema = Schema(
            [self.df.plan.schema.field(c) for c in indexed + included])
        rel_meta = Relation(relation.root_paths, Hdfs(source_content),
                            relation.schema, relation.file_format, relation.options)
        provider = IndexSignatureProvider()
        fingerprint = LogicalPlanFingerprint(
            [Signature(provider.name(), provider.signature(self.df.plan))])
        derived = CoveringIndex(indexed, included, index_schema,
                                self._num_buckets(),
                                self._index_properties(relation))
        placeholder = Content(root=Directory("/"))
        entry = IndexLogEntry.create(
            self.index_config.index_name, derived, placeholder,
            Source(SourcePlan([rel_meta], fingerprint)), {})
        return entry.with_log_version(0)

    def event(self, message: str) -> CreateActionEvent:
        return CreateActionEvent(
            message=message, index_name=self.index_config.index_name,
            index_config=self.index_config)
