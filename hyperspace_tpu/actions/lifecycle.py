"""State-transition actions: Delete, Restore, Vacuum, Cancel.

Parity reference: actions/DeleteAction.scala, RestoreAction.scala,
VacuumAction.scala, CancelAction.scala:

  Delete  — ACTIVE → DELETED (soft; queries stop considering the index)
  Restore — DELETED → ACTIVE
  Vacuum  — DELETED → DOESNOTEXIST (hard: physically removes every index
            data version directory)
  Cancel  — reset a stuck transient state back to the last stable entry
            (crash recovery; see SURVEY §5 failure detection)
"""

from __future__ import annotations

from typing import Optional

from ..exceptions import HyperspaceException
from ..index.constants import STABLE_STATES, States
from ..index.data_manager import IndexDataManager
from ..index.log_entry import IndexLogEntry
from ..index.log_manager import IndexLogManager
from ..telemetry.events import (CancelActionEvent, DeleteActionEvent,
                                RestoreActionEvent, VacuumActionEvent)
from .action import Action


class _TransitionAction(Action):
    """An action whose entry is the latest stable entry with a new state."""

    expected_states = ()

    def __init__(self, session, log_manager: IndexLogManager,
                 data_manager: Optional[IndexDataManager] = None):
        super().__init__(session, log_manager)
        self.data_manager = data_manager
        self._prev: Optional[IndexLogEntry] = None

    @property
    def prev_entry(self) -> IndexLogEntry:
        if self._prev is None:
            entry = self.log_manager.get_latest_stable_log()
            if entry is None:
                raise HyperspaceException("No stable log entry found")
            self._prev = entry
        return self._prev

    def validate(self) -> None:
        if self.prev_entry.state not in self.expected_states:
            raise HyperspaceException(
                f"{type(self).__name__} is only supported in states "
                f"{self.expected_states}; index is {self.prev_entry.state}")

    @property
    def log_entry(self) -> IndexLogEntry:
        return IndexLogEntry.from_json(self.prev_entry.to_json())

    def op(self) -> None:
        pass


class DeleteAction(_TransitionAction):
    transient_state = States.DELETING
    final_state = States.DELETED
    expected_states = (States.ACTIVE,)

    def event(self, message: str) -> DeleteActionEvent:
        return DeleteActionEvent(message=message, index_name=self.prev_entry.name)


class RestoreAction(_TransitionAction):
    transient_state = States.RESTORING
    final_state = States.ACTIVE
    expected_states = (States.DELETED,)

    def event(self, message: str) -> RestoreActionEvent:
        return RestoreActionEvent(message=message, index_name=self.prev_entry.name)


class VacuumAction(_TransitionAction):
    transient_state = States.VACUUMING
    final_state = States.DOESNOTEXIST
    expected_states = (States.DELETED,)

    def op(self) -> None:
        # Physically remove every index data version (parity:
        # VacuumAction.op — deletes all version directories).
        assert self.data_manager is not None
        for version in self.data_manager.get_all_version_ids():
            self.data_manager.delete(version)

    def event(self, message: str) -> VacuumActionEvent:
        return VacuumActionEvent(message=message, index_name=self.prev_entry.name)


class CancelAction(_TransitionAction):
    """Roll a stuck transient state back to the last stable entry.

    Parity: CancelAction.scala — begin/end write the *stable* entry's state
    as both transient and final, re-pointing latestStable past the wreck.
    """

    transient_state = States.CANCELLING
    final_state = ""  # set dynamically from the stable entry in validate().

    @property
    def prev_entry(self) -> IndexLogEntry:
        if self._prev is None:
            entry = self.log_manager.get_latest_stable_log()
            if entry is None:
                # Cancelling a first create that never committed: the only
                # stable state to return to is DOESNOTEXIST.
                latest = self.log_manager.get_latest_log()
                if latest is None:
                    raise HyperspaceException("No log entry found for index")
                entry = IndexLogEntry.from_json(latest.to_json())
                entry.state = States.DOESNOTEXIST
            self._prev = entry
        return self._prev

    def validate(self) -> None:
        latest = self.log_manager.get_latest_log()
        if latest is None:
            raise HyperspaceException("No log entry found for index")
        if latest.state in STABLE_STATES:
            raise HyperspaceException(
                f"Cancel is not needed: index is in stable state {latest.state}")
        # Roll back to the last stable state.
        self.final_state = self.prev_entry.state

    def event(self, message: str) -> CancelActionEvent:
        return CancelActionEvent(message=message, index_name=self.prev_entry.name)
