"""Candidate generation: index configs the workload's shapes could use.

From column co-occurrence in the workload log, three candidate families
(the decisions arxiv 1208.0287 / 2009.08150 automate):

  filter — per filter column ``f`` of a chain shape, a covering index
           ``indexed=[f], included = (project + filter) - {f}`` (the
           FilterIndexRule applicability surface: first indexed column in
           the predicate, full column coverage);
  join   — per rewritable equi-join shape, a PAIR of covering indexes
           (one per side, indexed exactly on the join columns in mapped
           order, covering the side's read set) proposed as ONE group —
           the JoinIndexRule needs both sides or neither;
  sketch — per table, one DataSkippingIndexConfig: MinMax for
           range-compared columns, BloomFilter for equality/IN columns
           (the per-column sketch-kind decision).

Groups are deduplicated by content, support-counted per captured query,
filtered against already-existing ACTIVE indexes, and name-stamped
deterministically (same workload -> same names -> reproducible
recommendations).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

from ..api import (BloomFilterSketch, DataSkippingIndexConfig, IndexConfig,
                   MinMaxSketch)
from ..util import hashing
from .constants import AdvisorConstants
from .workload import WorkloadRecord


@dataclass(frozen=True)
class CandidateSpec:
    """One proposed index plus the table it belongs to."""

    config: object  # IndexConfig | DataSkippingIndexConfig
    root_paths: Tuple[str, ...]
    file_format: str


@dataclass
class CandidateGroup:
    """Indexes that only pay off together (a join pair) or alone (a
    singleton). ``support`` counts captured queries exhibiting the
    generating shape."""

    key: tuple
    kind: str  # "filter" | "join" | "sketch"
    specs: Tuple[CandidateSpec, ...]
    support: int = 0


def _slug(root_paths: Tuple[str, ...]) -> str:
    import os
    base = os.path.basename(root_paths[0].rstrip("/")) if root_paths else "t"
    cleaned = "".join(ch if ch.isalnum() else "_" for ch in base.lower())
    return (cleaned or "t")[:24]


def _name(kind: str, root_paths: Tuple[str, ...], detail: tuple) -> str:
    h = hashing.md5_hex((kind, root_paths, detail))[:6]
    return f"{AdvisorConstants.CANDIDATE_NAME_PREFIX}_{kind}_" \
           f"{_slug(root_paths)}_{h}"


def _covering_spec(kind: str, root_paths, file_format,
                   indexed: Tuple[str, ...],
                   included: Tuple[str, ...]) -> CandidateSpec:
    name = _name(kind, tuple(root_paths), (indexed, included))
    return CandidateSpec(
        IndexConfig(name, list(indexed), list(included)),
        tuple(root_paths), file_format)


def _spec_key(spec: CandidateSpec) -> tuple:
    cfg = spec.config
    if isinstance(cfg, IndexConfig):
        return (spec.root_paths, "ci", tuple(cfg.indexed_columns),
                tuple(cfg.included_columns))
    return (spec.root_paths, "ds",
            tuple(sorted((s.kind, s.column) for s in cfg.sketches)))


def _groups_from_record(record: WorkloadRecord) -> List[CandidateGroup]:
    out: List[CandidateGroup] = []
    for shape in record.scan_shapes:
        referenced = tuple(sorted(set(shape.project_cols)
                                  | set(shape.filter_cols)))
        for f in shape.filter_cols:
            included = tuple(c for c in referenced if c != f)
            spec = _covering_spec("ci", shape.root_paths, shape.file_format,
                                  (f,), included)
            out.append(CandidateGroup(("filter", _spec_key(spec)),
                                      "filter", (spec,)))
        sketches = [MinMaxSketch(c) for c in shape.range_cols]
        sketches += [BloomFilterSketch(c) for c in shape.equality_cols
                     if c not in set(shape.range_cols)]
        if sketches:
            name = _name("ds", shape.root_paths,
                         tuple(sorted((s.kind, s.column) for s in sketches)))
            spec = CandidateSpec(DataSkippingIndexConfig(name, sketches),
                                 shape.root_paths, shape.file_format)
            out.append(CandidateGroup(("sketch", _spec_key(spec)),
                                      "sketch", (spec,)))
    for js in record.join_shapes:
        l_inc = tuple(c for c in js.left.referenced_cols
                      if c not in set(js.left.join_cols))
        r_inc = tuple(c for c in js.right.referenced_cols
                      if c not in set(js.right.join_cols))
        l_spec = _covering_spec("ji", js.left.root_paths,
                                js.left.file_format, js.left.join_cols, l_inc)
        r_spec = _covering_spec("ji", js.right.root_paths,
                                js.right.file_format, js.right.join_cols,
                                r_inc)
        specs = (l_spec,) if _spec_key(l_spec) == _spec_key(r_spec) \
            else (l_spec, r_spec)  # self-join: one index serves both sides
        out.append(CandidateGroup(
            ("join", tuple(sorted(_spec_key(s) for s in specs))),
            "join", specs))
    return out


def _covered_by_existing(spec: CandidateSpec, actives) -> bool:
    cfg = spec.config
    for entry in actives:
        if tuple(entry.relation.rootPaths) != spec.root_paths:
            continue
        if isinstance(cfg, IndexConfig):
            if entry.derivedDataset.kind != "CoveringIndex":
                continue
            if list(entry.indexed_columns) != list(cfg.indexed_columns):
                continue
            covered = set(entry.indexed_columns) | set(entry.included_columns)
            if set(cfg.included_columns) <= covered:
                return True
        else:
            if entry.derivedDataset.kind != "DataSkippingIndex":
                continue
            have = {(s.kind, s.column)
                    for s in entry.derivedDataset.sketches}
            if {(s.kind, s.column) for s in cfg.sketches} <= have:
                return True
    return False


def generate(session, records: List[WorkloadRecord]) -> List[CandidateGroup]:
    """Deduplicated, support-counted, existing-index-filtered candidate
    groups, highest support first, capped at
    ``hyperspace.tpu.advisor.maxCandidates``."""
    from ..index.constants import States
    groups: Dict[tuple, CandidateGroup] = {}
    for record in records:
        seen_in_record = set()
        for g in _groups_from_record(record):
            existing = groups.get(g.key)
            if existing is None:
                groups[g.key] = existing = g
            if g.key not in seen_in_record:
                existing.support += 1
                seen_in_record.add(g.key)

    actives = session.index_collection_manager.get_indexes([States.ACTIVE])
    min_support = session.hs_conf.advisor_min_support()
    out = [g for g in groups.values()
           if g.support >= min_support
           and not all(_covered_by_existing(s, actives) for s in g.specs)]
    out.sort(key=lambda g: (-g.support, g.key))
    return out[:session.hs_conf.advisor_max_candidates()]
