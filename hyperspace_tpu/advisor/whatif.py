"""What-if planning: would this index rewrite the plan, without building it?

A *hypothetical* IndexLogEntry is assembled from an IndexConfig and a
live Scan exactly the way actions/create.py assembles a real one —
source content, signature, derived-dataset descriptor — except its
content tree holds a single synthetic FileInfo whose size is the COST
MODEL'S predicted index size (cost.predicted_index_size_bytes). That one
trick makes the existing machinery rank hypotheticals fairly with zero
special cases: FilterIndexRanker's min-size compare, the score
optimizer's index-bytes tie-break, and cost.plan_cost_bytes all read
``index_files_size_in_bytes`` and see the prediction.

Injection goes through the rules' ``candidates_for`` hooks
(rules/filter_rule.try_rewrite_filter, rules/join_rule.try_rewrite_join
— dormant outside the score optimizer until now): the what-if pass hands
the ScoreBasedIndexPlanOptimizer a candidate map that merges the real
CandidateIndexCollector output with the hypothetical entries, so the
chosen plan is exactly what ``Session.optimize`` would pick if the
indexes existed.

Lifecycle invariant: hypothetical entries are function-local values.
They are never handed to a log manager, a data manager, the metadata
cache, or the executor — the index log store's byte-state is unchanged
by any number of what-if/recommend calls (asserted in
tests/test_advisor.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api import DataSkippingIndexConfig, IndexConfig
from ..exceptions import HyperspaceException
from ..index.log_entry import (Content, CoveringIndex, Directory, FileInfo,
                               FileIdTracker, Hdfs, IndexLogEntry,
                               LogicalPlanFingerprint, Relation, Signature,
                               Source, SourcePlan)
from ..index.signatures import IndexSignatureProvider
from ..plan import expr as E
from ..plan.nodes import Filter, IndexScan, LogicalPlan, Scan
from ..schema import Schema
from .constants import AdvisorConstants
from . import cost


def build_hypothetical_entry(session, config: IndexConfig,
                             scan: Scan) -> Optional[IndexLogEntry]:
    """Metadata-only ACTIVE entry for ``config`` over ``scan``'s
    relation, or None when the config's columns don't resolve there."""
    from ..index.constants import States
    from ..util.resolver import resolve_all
    relation = scan.relation
    names = relation.schema.names
    cs = session.hs_conf.case_sensitive()
    try:
        indexed = resolve_all(names, config.indexed_columns, cs)
        included = resolve_all(names, config.included_columns, cs)
    except HyperspaceException:
        return None
    tracker = FileIdTracker()
    source_content = Content.from_leaf_files(relation.all_files(), tracker)
    rel_meta = Relation(
        rootPaths=list(relation.root_paths), data=Hdfs(source_content),
        dataSchema=relation.schema, fileFormat=relation.file_format,
        options=dict(relation.options))
    provider = IndexSignatureProvider()
    fingerprint = LogicalPlanFingerprint(
        [Signature(provider.name(), provider.signature(scan))])
    predicted = cost.predicted_index_size_bytes(
        relation, len(indexed) + len(included))
    derived = CoveringIndex(
        indexed_columns=indexed, included_columns=included,
        schema=Schema([relation.schema.field(c)
                       for c in indexed + included]),
        num_buckets=session.hs_conf.num_bucket_count(),
        properties={AdvisorConstants.HYPOTHETICAL_PROPERTY: "true"})
    content = Content(Directory("/", files=[
        FileInfo(AdvisorConstants.HYPOTHETICAL_FILE_NAME, predicted, 0, 0)]))
    entry = IndexLogEntry.create(
        config.index_name, derived, content,
        Source(SourcePlan([rel_meta], fingerprint)),
        {AdvisorConstants.HYPOTHETICAL_PROPERTY: "true"})
    entry.state = States.ACTIVE
    return entry


def is_hypothetical(entry: IndexLogEntry) -> bool:
    return entry.properties.get(
        AdvisorConstants.HYPOTHETICAL_PROPERTY, "false") == "true"


def sketch_statically_applicable(plan: LogicalPlan,
                                 config: DataSkippingIndexConfig,
                                 table: Optional[Tuple[str, ...]] = None
                                 ) -> bool:
    """Structural applicability of a sketch set: some Filter conjunct is
    a literal compare the sketch kind could refute on the sketched
    column. (The real prunability needs built sketch tables; this is
    the metadata-only half the what-if planner can promise.)

    ``table``: when the sketch candidate is pinned to a table, only
    Filters whose subtree scans that table contribute conjuncts — a
    same-named column filtered on a DIFFERENT table of a join must not
    make this candidate look applicable."""
    from .workload import _classify_conjunct
    equality, rng = set(), set()

    def over_pinned_table(node: LogicalPlan) -> bool:
        if table is None:
            return True
        return any(tuple(leaf.relation.root_paths) == table
                   for leaf in node.collect_leaves()
                   if hasattr(leaf, "relation"))

    def visit(node: LogicalPlan):
        if isinstance(node, Filter) and over_pinned_table(node):
            for conj in E.split_conjunctive_predicates(node.condition):
                classified = _classify_conjunct(conj)
                if classified is not None:
                    (equality if classified[0] == "equality"
                     else rng).add(classified[1])
        for c in node.children:
            visit(c)
    visit(plan)
    for s in config.sketches:
        if s.kind == "MinMax" and s.column in (equality | rng):
            return True
        if s.kind in ("BloomFilter", "ValueList") and s.column in equality:
            return True
    return False


@dataclass
class WhatIfOutcome:
    """One what-if pass over one plan."""

    applied: Tuple[str, ...]           # hypothetical names in the plan
    applied_existing: Tuple[str, ...]  # real indexes the plan also uses
    cost_before_bytes: int
    cost_after_bytes: int
    plan_before: str
    plan_after: str
    sketch_applicable: Dict[str, bool]

    @property
    def rewritten(self) -> bool:
        return bool(self.applied)

    @property
    def predicted_speedup(self) -> float:
        if self.cost_after_bytes <= 0:
            return 1.0
        return self.cost_before_bytes / self.cost_after_bytes

    def explain(self) -> str:
        lines = ["=== What-If Analysis ==="]
        if self.applied:
            lines.append("Hypothetical indexes applied: "
                         + ", ".join(self.applied))
        else:
            lines.append("No hypothetical index would rewrite this plan.")
        if self.applied_existing:
            lines.append("Existing indexes in the plan: "
                         + ", ".join(self.applied_existing))
        lines.append(f"Input bytes: {self.cost_before_bytes} -> "
                     f"{self.cost_after_bytes} "
                     f"(predicted speedup {self.predicted_speedup:.2f}x)")
        for name, ok in sorted(self.sketch_applicable.items()):
            lines.append(
                f"Sketch set '{name}': "
                + ("statically applicable (prunability needs a build)"
                   if ok else "no refutable predicate in this plan"))
        lines.append("")
        lines.append("--- Plan without the hypothetical indexes ---")
        lines.append(self.plan_before)
        lines.append("--- Plan with the hypothetical indexes ---")
        lines.append(self.plan_after)
        return "\n".join(lines)


@dataclass
class WhatIfBaseline:
    """The config-independent half of a what-if pass over one plan: the
    normalized tree, the REAL candidate map, the plan the optimizer
    picks today, and its cost. `recommend` evaluates many candidate
    groups against one captured record — computing this once per record
    instead of once per (group, record) removes the dominant repeated
    work (optimizer passes + source-file listings)."""

    norm: LogicalPlan
    base: dict
    before_plan: LogicalPlan
    cost_before_bytes: int
    # Predicate-selectivity discounts keyed by cost.SelectivityKey —
    # (source root-paths tuple, Filter-condition repr) — from
    # cost.filter_selectivity_map over the normalized plan: the SAME
    # map prices before- and after-rewrite plans, so the benefit ratio
    # reflects how selective the served predicate actually is.
    selectivities: Optional[Dict[Tuple[Tuple[str, ...], str],
                                 float]] = None


def prepare_baseline(session, plan: LogicalPlan,
                     include_existing: bool = True) -> WhatIfBaseline:
    from ..rules.apply_hyperspace import active_indexes
    from ..rules.index_filters import (CandidateIndexCollector,
                                       ReasonCollector)
    from ..rules.score_optimizer import ScoreBasedIndexPlanOptimizer
    from ..serving import fingerprint as fp

    norm = fp.normalize(plan)
    if session.hs_conf.join_reorder_enabled():
        # Mirror Session.optimize: reorder AFTER normalization, BEFORE
        # the index rules, so the advisor prices rewrites against the
        # tree execution will actually run (a benefit predicted for a
        # join the reorderer demotes from leaf level would never
        # materialize). Diagnostic pass: no telemetry; restore the
        # session's chain records so explain/bench still read the last
        # *executed* reorder, not this planning probe's.
        from ..optimizer.join_order import reorder_joins
        saved = getattr(session, "_last_join_order", None)
        norm = reorder_joins(session, norm, diagnostic=True)
        session._last_join_order = saved
    real: List[IndexLogEntry] = []
    if include_existing:
        real = [e for e in active_indexes(session)
                if e.derivedDataset.kind == "CoveringIndex"]
    ctx = ReasonCollector(enabled=False, silent=True)
    base = CandidateIndexCollector.collect(session, norm, real, ctx)
    before_plan = ScoreBasedIndexPlanOptimizer().apply(
        session, norm, base, ctx)
    selectivities = cost.filter_selectivity_map(session, norm)
    return WhatIfBaseline(norm, base, before_plan,
                          cost.plan_cost_bytes(before_plan, selectivities),
                          selectivities)


def what_if_plan(session, plan: LogicalPlan, configs,
                 include_existing: bool = True,
                 config_tables: Optional[Dict[str, Tuple[str, ...]]] = None,
                 baseline: Optional[WhatIfBaseline] = None,
                 entry_cache: Optional[dict] = None) -> WhatIfOutcome:
    """Re-run the index-selection search with hypothetical entries for
    ``configs`` injected next to the real candidates. Pure planning: no
    telemetry, no usage counters, no reason-collector mutation, nothing
    persisted.

    ``config_tables`` (index name → root-path tuple) pins a config to
    ITS table: without it a config is injected at every scan whose
    schema resolves its columns — right for the user-facing API, where
    no table was declared, but wrong for generated candidates (two
    tables sharing column names would cross-match and inflate benefit).
    ``baseline``: pass prepare_baseline(...) when evaluating many
    config sets against one plan. ``entry_cache``: a dict shared across
    calls memoizing hypothetical entries per (config name, relation) —
    building one stats every source file, and `recommend` would
    otherwise rebuild identical entries per candidate group."""
    from ..rules.index_filters import ReasonCollector
    from ..rules.score_optimizer import ScoreBasedIndexPlanOptimizer

    if baseline is None:
        baseline = prepare_baseline(session, plan, include_existing)
    norm = baseline.norm
    covering_cfgs = [c for c in configs if isinstance(c, IndexConfig)]
    sketch_cfgs = [c for c in configs
                   if isinstance(c, DataSkippingIndexConfig)]

    merged = {k: (scan, list(entries))
              for k, (scan, entries) in baseline.base.items()}
    hypo_names: List[str] = []
    for leaf in norm.collect_leaves():
        if not isinstance(leaf, Scan):
            continue
        if not session.source_provider_manager.is_supported_relation(leaf):
            continue
        for cfg in covering_cfgs:
            pinned = (config_tables or {}).get(cfg.index_name)
            if pinned is not None and \
                    tuple(leaf.relation.root_paths) != pinned:
                continue
            if entry_cache is not None:
                cache_key = (cfg.index_name, id(leaf.relation))
                if cache_key not in entry_cache:
                    entry_cache[cache_key] = \
                        build_hypothetical_entry(session, cfg, leaf)
                entry = entry_cache[cache_key]
            else:
                entry = build_hypothetical_entry(session, cfg, leaf)
            if entry is None:
                continue
            scan, entries = merged.get(id(leaf), (leaf, []))
            merged[id(leaf)] = (scan, entries + [entry])
            hypo_names.append(entry.name)
    ctx2 = ReasonCollector(enabled=False, silent=True)
    after_plan = ScoreBasedIndexPlanOptimizer().apply(
        session, norm, merged, ctx2)

    used = {leaf.index_entry.name for leaf in after_plan.collect_leaves()
            if isinstance(leaf, IndexScan)}
    return WhatIfOutcome(
        applied=tuple(sorted(used & set(hypo_names))),
        applied_existing=tuple(sorted(used - set(hypo_names))),
        cost_before_bytes=baseline.cost_before_bytes,
        cost_after_bytes=cost.plan_cost_bytes(after_plan,
                                              baseline.selectivities),
        plan_before=baseline.before_plan.tree_string(),
        plan_after=after_plan.tree_string(),
        sketch_applicable={c.index_name: sketch_statically_applicable(
                               norm, c,
                               (config_tables or {}).get(c.index_name))
                           for c in sketch_cfgs})


def what_if(session, plan: LogicalPlan, configs) -> WhatIfOutcome:
    """The user-facing entry (`Hyperspace.what_if`): one what-if pass
    plus its telemetry event."""
    outcome = what_if_plan(session, plan, configs)
    from ..telemetry.events import AdvisorWhatIfEvent
    from ..telemetry.logging import get_logger
    get_logger(session.hs_conf.event_logger_class()).log_event(
        AdvisorWhatIfEvent(
            message="what-if analysis "
                    + ("rewrote the plan" if outcome.rewritten
                       else "did not rewrite the plan"),
            index_names=[getattr(c, "index_name", "?") for c in configs],
            applied_names=list(outcome.applied)))
    return outcome
