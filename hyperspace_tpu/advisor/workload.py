"""Workload capture: one record per executed plan.

``Session.execute`` calls :func:`capture_execution` (behind
``hyperspace.tpu.advisor.capture.enabled``) after the result is back, so
the record carries the *observed* latency of whatever path actually ran
(rewritten, cached, or plain). The captured plan is the canonical
normalized plan — the same prefix the serving fingerprint uses
(serving/fingerprint.normalize) — so syntactic variants of one query
fold onto one fingerprint, and the what-if planner can re-optimize the
exact tree later.

Shape extraction reuses the rules' own pattern matchers (linear-chain
walks, equi-key extraction, base-column translation) so the candidate
generator proposes exactly what the rules could consume.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..plan import expr as E
from ..plan.nodes import Filter, Join, LogicalPlan, Project, Scan


@dataclass(frozen=True)
class ScanShape:
    """Columns one linear Scan/Filter/Project chain touches, split by
    role. ``equality_cols``/``range_cols`` classify the literal-compare
    conjuncts (the sketch-kind decision input); all names are restricted
    to the relation's own schema."""

    root_paths: Tuple[str, ...]
    file_format: str
    project_cols: Tuple[str, ...]
    filter_cols: Tuple[str, ...]
    equality_cols: Tuple[str, ...]
    range_cols: Tuple[str, ...]


@dataclass(frozen=True)
class JoinSideShape:
    root_paths: Tuple[str, ...]
    file_format: str
    join_cols: Tuple[str, ...]        # base namespace, join order
    referenced_cols: Tuple[str, ...]  # base namespace, full read set


@dataclass(frozen=True)
class JoinShape:
    """One rewritable equi-join occurrence (both sides linear, keys 1:1,
    base-translated — the exact JoinIndexRule applicability surface)."""

    left: JoinSideShape
    right: JoinSideShape


@dataclass
class WorkloadRecord:
    fingerprint: Optional[str]
    plan: LogicalPlan                 # normalized; in-session only
    scan_shapes: Tuple[ScanShape, ...]
    join_shapes: Tuple[JoinShape, ...]
    latency_s: float
    applied_indexes: Tuple[str, ...]
    rules_fired: Tuple[str, ...]


class WorkloadLog:
    """Bounded, thread-safe, in-session record list (the serving path is
    multi-threaded). Oldest records drop first when the bound is hit."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: List[WorkloadRecord] = []
        self.dropped = 0

    def add(self, record: WorkloadRecord, max_entries: int) -> None:
        with self._lock:
            self._records.append(record)
            while max_entries > 0 and len(self._records) > max_entries:
                self._records.pop(0)
                self.dropped += 1

    def snapshot(self) -> List[WorkloadRecord]:
        with self._lock:
            return list(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def to_rows(self) -> List[dict]:
        with self._lock:
            return [{
                "fingerprint": r.fingerprint,
                "tables": [",".join(s.root_paths) for s in r.scan_shapes],
                "latency_s": r.latency_s,
                "appliedIndexes": list(r.applied_indexes),
                "rulesFired": list(r.rules_fired),
            } for r in self._records]


def log_for(session) -> WorkloadLog:
    """The session's workload log (created eagerly in Session.__init__
    so concurrent captures share one instance)."""
    return session._workload_log


# ---------------------------------------------------------------------------
# Shape extraction.
# ---------------------------------------------------------------------------

def _iter_nodes(plan: LogicalPlan):
    yield plan
    for c in plan.children:
        yield from _iter_nodes(c)


def _classify_conjunct(conjunct: E.Expr):
    """("equality"|"range", column) for a supported literal-compare
    conjunct, else None — mirrors what the sketch probes can evaluate
    (rules/data_skipping_rule._eval_node)."""
    if isinstance(conjunct, E.In) and isinstance(conjunct.value, E.Col) \
            and all(isinstance(o, E.Lit) for o in conjunct.options):
        return "equality", conjunct.value.column
    if isinstance(conjunct, (E.EqualTo, E.LessThan, E.LessThanOrEqual,
                             E.GreaterThan, E.GreaterThanOrEqual)):
        left, right = conjunct.left, conjunct.right
        if isinstance(left, E.Lit) and isinstance(right, E.Col):
            left, right = right, left
        if isinstance(left, E.Col) and isinstance(right, E.Lit):
            kind = "equality" if isinstance(conjunct, E.EqualTo) else "range"
            return kind, left.column
    return None


def _chain_scan_shape(session, root: LogicalPlan) -> Optional[ScanShape]:
    from ..rules.rule_utils import (collect_filter_project_columns,
                                    get_relation)
    relation = get_relation(session, root.collect_leaves()[0]) \
        if root.collect_leaves() else None
    if relation is None:
        return None
    project_cols, filter_cols = collect_filter_project_columns(root)
    schema_names = set(relation.schema.names)
    equality, rng = [], []
    node = root
    while not isinstance(node, Scan):
        if isinstance(node, Filter):
            for conj in E.split_conjunctive_predicates(node.condition):
                classified = _classify_conjunct(conj)
                if classified is not None and classified[1] in schema_names:
                    (equality if classified[0] == "equality"
                     else rng).append(classified[1])
        node = node.children[0]

    def clean(cols) -> Tuple[str, ...]:
        return tuple(sorted({c for c in cols if c in schema_names}))

    return ScanShape(
        root_paths=tuple(relation.root_paths),
        file_format=relation.file_format,
        project_cols=clean(project_cols),
        filter_cols=clean(filter_cols),
        equality_cols=clean(equality),
        range_cols=clean(rng))


def _join_shape(session, join: Join) -> Optional[JoinShape]:
    from ..rules.join_rule import _column_mapping, _ensure_one_to_one
    from ..rules.rule_utils import (collect_base_references, get_relation,
                                    is_plan_linear, output_to_base_mapping)
    if join.join_type != "inner" or join.condition is None:
        return None
    pairs = E.extract_equi_join_keys(join.condition)
    if not pairs:
        return None
    if not (is_plan_linear(join.left) and is_plan_linear(join.right)):
        return None
    l_rel = get_relation(session, join.left.collect_leaves()[0])
    r_rel = get_relation(session, join.right.collect_leaves()[0])
    if l_rel is None or r_rel is None:
        return None
    mapping = _column_mapping(join, pairs)
    if mapping is None:
        return None
    l_cols, r_cols = mapping
    l_base = output_to_base_mapping(join.left)
    r_base = output_to_base_mapping(join.right)
    if l_base is None or r_base is None:
        return None
    l_cols = [l_base.get(c) for c in l_cols]
    r_cols = [r_base.get(c) for c in r_cols]
    if any(c is None for c in l_cols) or any(c is None for c in r_cols):
        return None
    based = _ensure_one_to_one(zip(l_cols, r_cols))
    if based is None:
        return None
    l_cols, r_cols = based
    l_refs = collect_base_references(join.left)
    r_refs = collect_base_references(join.right)
    if l_refs is None or r_refs is None:
        return None
    return JoinShape(
        left=JoinSideShape(tuple(l_rel.root_paths), l_rel.file_format,
                           tuple(l_cols),
                           tuple(sorted(l_refs | set(l_cols)))),
        right=JoinSideShape(tuple(r_rel.root_paths), r_rel.file_format,
                            tuple(r_cols),
                            tuple(sorted(r_refs | set(r_cols)))))


def extract_shapes(session, plan: LogicalPlan
                   ) -> Tuple[Tuple[ScanShape, ...], Tuple[JoinShape, ...]]:
    """All linear-chain scan shapes and rewritable join shapes in a
    (normalized) plan. A chain root is the topmost Filter/Project/Scan
    of each maximal linear chain."""
    from ..rules.rule_utils import is_plan_linear

    parents = {}
    for node in _iter_nodes(plan):
        for c in node.children:
            parents[id(c)] = node

    scan_shapes: List[ScanShape] = []
    join_shapes: List[JoinShape] = []
    for node in _iter_nodes(plan):
        if isinstance(node, Join):
            js = _join_shape(session, node)
            if js is not None:
                join_shapes.append(js)
        if isinstance(node, (Scan, Filter, Project)) and is_plan_linear(node):
            parent = parents.get(id(node))
            if isinstance(parent, (Filter, Project)) and is_plan_linear(parent):
                continue  # not the chain root
            shape = _chain_scan_shape(session, node)
            if shape is not None:
                scan_shapes.append(shape)
    return tuple(scan_shapes), tuple(join_shapes)


# ---------------------------------------------------------------------------
# Capture (the Session.execute hook).
# ---------------------------------------------------------------------------

def _rules_fired(session, applied: Tuple[str, ...]) -> Tuple[str, ...]:
    """Rule-family attribution from the applied entries' kinds (goes
    through the TTL metadata cache — one listing per capture at most)."""
    if not applied:
        return ()
    from ..index.constants import States
    kinds = {}
    for entry in session.index_collection_manager.get_indexes(
            [States.ACTIVE]):
        kinds[entry.name] = entry.derivedDataset.kind
    fired = set()
    for name in applied:
        kind = kinds.get(name)
        if kind == "CoveringIndex":
            fired.add("CoveringIndexRules")
        elif kind == "DataSkippingIndex":
            fired.add("DataSkippingIndexRule")
    return tuple(sorted(fired))


def capture_execution(session, plan: LogicalPlan, latency_s: float) -> None:
    """Append one WorkloadRecord for an executed plan. The caller
    (Session.execute) reset ``_last_reason_collector`` before running, so
    ``applied`` reflects THIS execution — empty on a result-cache hit
    (no rewrite pass ran) or when hyperspace is disabled."""
    from ..serving import fingerprint as fp
    norm = fp.normalize(plan)
    collector = session._last_reason_collector
    applied = tuple(sorted(set(collector.applied))) if collector else ()
    scan_shapes, join_shapes = extract_shapes(session, norm)
    record = WorkloadRecord(
        fingerprint=fp.plan_fingerprint(plan, normalized=norm),
        plan=norm,
        scan_shapes=scan_shapes,
        join_shapes=join_shapes,
        latency_s=latency_s,
        applied_indexes=applied,
        rules_fired=_rules_fired(session, applied))
    log_for(session).add(
        record, session.hs_conf.advisor_capture_max_entries())
