"""Workload-capture, what-if planning, and cost-ranked index recommendation.

Modules (imported lazily by the API facade so that ``import
hyperspace_tpu`` stays light):

  constants   — ``hyperspace.tpu.advisor.*`` keys + hypothetical markers
  workload    — in-session workload log wired into Session.execute
  candidates  — candidate IndexConfig / sketch-set generation from the log
  whatif      — hypothetical IndexLogEntry injection through the rules'
                ``candidates_for`` hooks (metadata only, no build)
  cost        — input-byte cost model seeded from file/index statistics
  recommend   — cost-ranked recommendations (`Hyperspace.recommend`)

Invariant: hypothetical entries are in-memory values only — they never
reach a log store, a data manager, or the executor.
"""
