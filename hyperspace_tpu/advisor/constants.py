"""Advisor config keys + metadata property names.

No reference analogue: the original project's roadmap headlines index
recommendation but never shipped it; the design here follows the
cost-based, workload-adaptive selection literature (PAPERS.md: "Only
Aggressive Elephants are Fast Elephants", arxiv 1208.0287; sketch choice
as a per-column decision, "Extensible Data Skipping", arxiv 2009.08150).

Keys live under ``hyperspace.tpu.advisor.*`` and are read exclusively
through config.py accessors (the scripts/lint.py env-read gate) and must
each appear in docs/configuration.md (the scripts/lint.py doc-drift
gate).
"""

from __future__ import annotations


class AdvisorConstants:
    # Workload capture: when true, every Session.execute records a
    # WorkloadRecord (fingerprint, shapes, latency, applied indexes)
    # into the in-session workload log.
    CAPTURE_ENABLED = "hyperspace.tpu.advisor.capture.enabled"
    CAPTURE_ENABLED_DEFAULT = "false"

    # Bound on the in-session workload log; oldest records drop first.
    CAPTURE_MAX_ENTRIES = "hyperspace.tpu.advisor.capture.maxEntries"
    CAPTURE_MAX_ENTRIES_DEFAULT = "10000"

    # Bound on candidate groups the recommender evaluates with the
    # what-if planner (highest-support groups first).
    MAX_CANDIDATES = "hyperspace.tpu.advisor.maxCandidates"
    MAX_CANDIDATES_DEFAULT = "32"

    # Minimum number of captured queries that must exhibit a shape
    # before a candidate derived from it is considered.
    MIN_SUPPORT = "hyperspace.tpu.advisor.minSupport"
    MIN_SUPPORT_DEFAULT = "1"

    # derivedDataset property marking a metadata-only what-if entry.
    # Anything carrying it must never reach a log store or executor.
    HYPOTHETICAL_PROPERTY = "advisor.hypothetical"

    # Synthetic content-file name carrying the predicted index size so
    # the rankers' index_files_size_in_bytes comparisons stay meaningful
    # for entries that have no data files.
    HYPOTHETICAL_FILE_NAME = "__advisor_hypothetical__"

    # Deterministic candidate-name prefix.
    CANDIDATE_NAME_PREFIX = "adv"
