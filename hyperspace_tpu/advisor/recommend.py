"""Cost-ranked index recommendation over the captured workload.

For every candidate group the generator proposes, the recommender runs
the what-if planner against every captured plan the group's tables
appear in, and accumulates predicted benefit:

    benefit = sum over matching records of
              observed latency x (1 - rewritten bytes / baseline bytes)

so a candidate is worth exactly what the workload would have saved had
the index existed — frequency-weighted (hot queries captured often count
often), coverage-aware (what-if uses the real selection search), and
strictly zero for candidates whose rewrite never fires. Sketch sets
cannot promise bytes without building, so they carry zero predicted
benefit and rank on static applicability + support behind any covering
candidate with real benefit (documented in docs/configuration.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

from .candidates import CandidateGroup, _covered_by_existing, generate
from .whatif import prepare_baseline, what_if_plan
from . import workload


@dataclass
class Recommendation:
    """One ranked proposal: every config in ``configs`` should be built
    together (a join pair pays only as a pair)."""

    rank: int
    kind: str                      # "filter" | "join" | "sketch"
    names: Tuple[str, ...]
    configs: Tuple[object, ...]    # IndexConfig | DataSkippingIndexConfig
    tables: Tuple[Tuple[Tuple[str, ...], str], ...]  # (root_paths, format)
    predicted_benefit_s: float
    predicted_speedup: float
    support: int
    queries_matched: int
    record_indices: Tuple[int, ...] = ()


@dataclass
class AdvisorReport:
    recommendations: List[Recommendation] = field(default_factory=list)
    candidates_evaluated: int = 0
    records_considered: int = 0

    def explain(self) -> str:
        lines = ["=== Index Recommendations ===",
                 f"Workload records considered: {self.records_considered}",
                 f"Candidate groups evaluated: {self.candidates_evaluated}"]
        if not self.recommendations:
            lines.append("No recommendations (capture a workload first: "
                         "hyperspace.tpu.advisor.capture.enabled=true).")
        for r in self.recommendations:
            lines.append(
                f"#{r.rank} [{r.kind}] {', '.join(r.names)}: "
                f"predicted benefit {r.predicted_benefit_s:.4f}s over "
                f"{r.queries_matched} matched queries "
                f"(predicted speedup {r.predicted_speedup:.2f}x, "
                f"support {r.support})")
            for cfg in r.configs:
                if hasattr(cfg, "indexed_columns"):
                    lines.append(f"    create_index: indexed="
                                 f"{list(cfg.indexed_columns)} included="
                                 f"{list(cfg.included_columns)}")
                else:
                    lines.append(
                        "    create_index (sketches): "
                        + ", ".join(f"{s.kind}({s.column})"
                                    for s in cfg.sketches))
        return "\n".join(lines)


def _tables_overlap(group: CandidateGroup, record) -> bool:
    group_tables = {s.root_paths for s in group.specs}
    record_tables = {s.root_paths for s in record.scan_shapes}
    return bool(group_tables & record_tables)


def _evaluate(session, group: CandidateGroup, records, baseline_for,
              entry_cache, actives) -> Recommendation:
    configs = tuple(s.config for s in group.specs)
    config_tables = {s.config.index_name: s.root_paths for s in group.specs}
    # A join pair pays only as a pair: benefit counts when every side
    # not already served by an existing index actually applied —
    # otherwise a one-sided filter rewrite would credit the whole pair
    # and build_recommendation would materialize a useless second index.
    required = {s.config.index_name for s in group.specs
                if not _covered_by_existing(s, actives)}
    benefit = 0.0
    total_before = 0
    total_after = 0
    matched: List[int] = []
    for i, record in enumerate(records):
        if record.plan is None or not _tables_overlap(group, record):
            continue
        outcome = what_if_plan(session, record.plan, configs,
                               config_tables=config_tables,
                               baseline=baseline_for(i),
                               entry_cache=entry_cache)
        if group.kind == "sketch":
            if any(outcome.sketch_applicable.values()):
                matched.append(i)
            continue
        if not outcome.applied:
            continue
        if group.kind == "join" and not required <= set(outcome.applied):
            continue
        matched.append(i)
        total_before += outcome.cost_before_bytes
        total_after += outcome.cost_after_bytes
        if outcome.cost_before_bytes > 0:
            ratio = outcome.cost_after_bytes / outcome.cost_before_bytes
            benefit += record.latency_s * max(0.0, 1.0 - ratio)
    speedup = (total_before / total_after) \
        if (matched and total_after > 0) else 1.0
    return Recommendation(
        rank=0, kind=group.kind,
        names=tuple(s.config.index_name for s in group.specs),
        configs=configs,
        tables=tuple((s.root_paths, s.file_format) for s in group.specs),
        predicted_benefit_s=benefit,
        predicted_speedup=speedup,
        support=group.support,
        queries_matched=len(matched),
        record_indices=tuple(matched))


def recommend(session, top_k: int = 5) -> AdvisorReport:
    """Rank candidate groups by predicted benefit (what-if-confirmed),
    deterministic for a given workload + source state. Pure planning —
    nothing is built and the index log store is untouched."""
    from ..index.constants import States
    records = workload.log_for(session).snapshot()
    groups = generate(session, records)
    # The baseline (real candidates, today's plan, its cost) and the
    # hypothetical entries are config-set/record-independent halves of a
    # what-if pass: memoize each lazily — one baseline per record that a
    # group actually matches (not per group x record, and none at all
    # when every shape is already indexed), one hypothetical entry per
    # (config, relation).
    baselines: list = [None] * len(records)

    def baseline_for(i: int):
        if baselines[i] is None:
            baselines[i] = prepare_baseline(session, records[i].plan)
        return baselines[i]

    entry_cache: dict = {}
    actives = session.index_collection_manager.get_indexes([States.ACTIVE])
    recos = [_evaluate(session, g, records, baseline_for, entry_cache,
                       actives) for g in groups]
    # Benefit first; then matched-query count (sketch sets have benefit
    # 0.0 by construction but matched > 0 when applicable); then support;
    # names last for full determinism. Groups that never applied anywhere
    # sink to the bottom and are cut by top_k.
    recos.sort(key=lambda r: (-r.predicted_benefit_s, -r.queries_matched,
                              -r.support, r.names))
    recos = [r for r in recos if r.queries_matched > 0][:max(0, top_k)]
    for i, r in enumerate(recos):
        r.rank = i + 1
    report = AdvisorReport(
        recommendations=recos,
        candidates_evaluated=len(groups),
        records_considered=len(records))
    from ..telemetry.events import AdvisorRecommendationEvent
    from ..telemetry.logging import get_logger
    get_logger(session.hs_conf.event_logger_class()).log_event(
        AdvisorRecommendationEvent(
            message=f"{len(recos)} recommendation(s) from "
                    f"{len(records)} workload record(s)",
            recommended=[n for r in recos for n in r.names],
            candidates_evaluated=len(groups),
            records_considered=len(records)))
    return report


def build_recommendation(hyperspace, recommendation: Recommendation) -> None:
    """Materialize one recommendation's configs through the normal
    create path (this DOES write index data and log entries, unlike
    everything else in this package). Configs an existing ACTIVE index
    already covers are skipped — a half-covered join pair builds only
    its missing side."""
    from ..index.constants import States
    from .candidates import CandidateSpec
    session = hyperspace.session
    actives = session.index_collection_manager.get_indexes([States.ACTIVE])
    for cfg, (root_paths, file_format) in zip(recommendation.configs,
                                              recommendation.tables):
        spec = CandidateSpec(cfg, root_paths, file_format)
        if _covered_by_existing(spec, actives):
            continue
        df = session.read.format(file_format).load(*root_paths)
        hyperspace.create_index(df, cfg)
