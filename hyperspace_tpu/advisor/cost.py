"""Input-byte cost model for plans, real and hypothetical.

The cost of a plan is the bytes its leaves would read — the same proxy
the serving admission policy uses (serving/fingerprint.
estimate_recompute_bytes) and the score optimizer's coverage ratios are
built on (index/statistics.py sizes). It is deliberately simple and
fully deterministic: file sizes for relation leaves, index content sizes
for IndexScan leaves. Hypothetical entries carry their *predicted* size
as a synthetic content file (whatif.build_hypothetical_entry), so one
accounting covers both.

Predicted benefit combines this with the workload log's observed
latencies: a rewrite that reads ``r`` of the baseline bytes is predicted
to save ``(1 - r) x observed latency`` per captured occurrence — cheap,
monotone in coverage, and honest about appends (Hybrid Scan coverage
lowers it the same way it lowers the optimizer's scores).
"""

from __future__ import annotations

from ..plan.nodes import IndexScan, LogicalPlan


def relation_bytes(relation) -> int:
    return sum(size for _, size, _ in relation.all_file_infos())


def predicted_index_size_bytes(relation, n_index_columns: int) -> int:
    """Size estimate for a covering index over ``n_index_columns`` of
    ``relation``: the source bytes scaled by the covered-column fraction.
    Ignores sort/bucket recompression (unknowable without building) —
    good enough to rank a slim index under a wide one under a full
    scan, which is all the recommender needs."""
    total = relation_bytes(relation)
    n_cols = max(1, len(relation.schema.names))
    return int(total * min(1.0, n_index_columns / n_cols))


# An IndexScan serving a bucketed merge join (use_bucket_spec) saves
# more than bytes: the executor skips the shuffle+sort a plain scan
# would pay. Modeled as an effective-bytes discount mirroring the rule
# scores' own 70:50 join:filter asymmetry (rules/score_optimizer.py) —
# without it, a join index covering every column of a table predicts
# zero benefit and loses to candidates the measured workload ranks
# strictly worse (observed on the TPC-H mini q3 pair).
BUCKET_JOIN_DISCOUNT = 50.0 / 70.0


def plan_cost_bytes(plan: LogicalPlan) -> int:
    """Total effective leaf input bytes of an optimized (possibly
    what-if) plan. Appended hybrid files are not stat'ed here
    (hypothetical entries never have them; for real entries they are
    bounded by the hybrid append ratio, a second-order term for ranking
    purposes)."""
    total = 0
    for leaf in plan.collect_leaves():
        relation = getattr(leaf, "relation", None)
        if relation is not None:
            total += relation_bytes(relation)
        elif isinstance(leaf, IndexScan):
            nbytes = leaf.index_entry.index_files_size_in_bytes
            if leaf.use_bucket_spec:
                nbytes = int(nbytes * BUCKET_JOIN_DISCOUNT)
            total += nbytes
    return total
