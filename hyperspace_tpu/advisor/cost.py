"""Input-byte cost model for plans, real and hypothetical.

The cost of a plan is the bytes its leaves would read — the same proxy
the serving admission policy uses (serving/fingerprint.
estimate_recompute_bytes) and the score optimizer's coverage ratios are
built on (index/statistics.py sizes). It is deliberately simple and
fully deterministic: file sizes for relation leaves, index content sizes
for IndexScan leaves. Hypothetical entries carry their *predicted* size
as a synthetic content file (whatif.build_hypothetical_entry), so one
accounting covers both.

Predicted benefit combines this with the workload log's observed
latencies: a rewrite that reads ``r`` of the baseline bytes is predicted
to save ``(1 - r) x observed latency`` per captured occurrence — cheap,
monotone in coverage, and honest about appends (Hybrid Scan coverage
lowers it the same way it lowers the optimizer's scores).

Since the statistics layer landed (optimizer/stats.py), leaf bytes under
a Filter are additionally discounted by the predicate's estimated
selectivity (filter_selectivity_map) — predicted index benefit follows
predicate selectivity rather than the pure size-ratio proxy. The 50/70
bucketed-join weighting (BUCKET_JOIN_DISCOUNT) is unchanged.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..plan.nodes import Filter, IndexScan, LogicalPlan, Scan


def relation_bytes(relation) -> int:
    return sum(size for _, size, _ in relation.all_file_infos())


def predicted_index_size_bytes(relation, n_index_columns: int) -> int:
    """Size estimate for a covering index over ``n_index_columns`` of
    ``relation``: the source bytes scaled by the covered-column fraction.
    Ignores sort/bucket recompression (unknowable without building) —
    good enough to rank a slim index under a wide one under a full
    scan, which is all the recommender needs."""
    total = relation_bytes(relation)
    n_cols = max(1, len(relation.schema.names))
    return int(total * min(1.0, n_index_columns / n_cols))


# An IndexScan serving a bucketed merge join (use_bucket_spec) saves
# more than bytes: the executor skips the shuffle+sort a plain scan
# would pay. Modeled as an effective-bytes discount mirroring the rule
# scores' own 70:50 join:filter asymmetry (rules/score_optimizer.py) —
# without it, a join index covering every column of a table predicts
# zero benefit and loses to candidates the measured workload ranks
# strictly worse (observed on the TPC-H mini q3 pair).
BUCKET_JOIN_DISCOUNT = 50.0 / 70.0


# Selectivity floor for the effective-bytes discount: a filter can never
# talk a leaf's cost all the way to zero (footer/IO fixed costs remain,
# and estimates this small are noise).
MIN_COST_SELECTIVITY = 0.01


SelectivityKey = Tuple[Tuple[str, ...], str]


def _leaf_source_key(leaf: LogicalPlan) -> Optional[Tuple[str, ...]]:
    """Source identity of a leaf that survives the IndexScan swap: the
    relation's root paths (the same identity candidates.py uses to match
    an entry to its source). A Scan reads them off the live relation; an
    IndexScan reads the source relation recorded in its log entry."""
    relation = getattr(leaf, "relation", None)
    if relation is not None:
        return tuple(relation.root_paths)
    if isinstance(leaf, IndexScan):
        return tuple(leaf.index_entry.relation.rootPaths)
    return None


def filter_selectivity_map(session,
                           plan: LogicalPlan) -> Dict[SelectivityKey, float]:
    """(source root paths, condition repr) -> estimated selectivity for
    every Filter directly above a Scan leaf of ``plan``, from the
    statistics layer (optimizer/stats.py + optimizer/cardinality.py).
    Empty when the stats conf family is disabled or no statistics exist —
    in which case plan_cost_bytes degrades to the pure size-ratio proxy.
    Scoping by source identity keeps identically-spelled predicates over
    different tables from colliding, while the SAME map still prices the
    before- and after-rewrite plans (an IndexScan swap keeps the Filter
    condition, and its log entry records the source root paths)."""
    if not session.hs_conf.optimizer_stats_enabled():
        return {}
    from ..optimizer import cardinality
    from ..optimizer.stats import provider_for
    provider = provider_for(session)
    out: Dict[SelectivityKey, float] = {}

    def walk(node: LogicalPlan) -> None:
        if isinstance(node, Filter) and isinstance(node.child, Scan):
            ts = provider.table_stats(node.child.relation)
            if ts is not None:
                cap = provider.sketch_row_fraction(node.child.relation,
                                                   node.condition)
                key = (tuple(node.child.relation.root_paths),
                       repr(node.condition))
                out[key] = cardinality.filter_selectivity(
                    ts, node.condition, cap)
        for c in node.children:
            walk(c)

    walk(plan)
    return out


def plan_cost_bytes(
        plan: LogicalPlan,
        selectivities: Optional[Dict[SelectivityKey, float]] = None) -> int:
    """Total effective leaf input bytes of an optimized (possibly
    what-if) plan. ``selectivities`` (filter_selectivity_map) discounts
    leaves under a matching Filter by the predicate's estimated
    selectivity — an index whose rewrite serves a highly selective
    predicate is predicted to save proportionally more than raw bytes
    alone say. Appended hybrid files are not stat'ed here (hypothetical
    entries never have them; for real entries they are bounded by the
    hybrid append ratio, a second-order term for ranking purposes)."""
    total = 0

    def leaf_bytes(leaf: LogicalPlan) -> int:
        relation = getattr(leaf, "relation", None)
        if relation is not None:
            return relation_bytes(relation)
        if isinstance(leaf, IndexScan):
            nbytes = leaf.index_entry.index_files_size_in_bytes
            if leaf.use_bucket_spec:
                nbytes = int(nbytes * BUCKET_JOIN_DISCOUNT)
            return nbytes
        return 0

    def walk(node: LogicalPlan, conds) -> None:
        nonlocal total
        if isinstance(node, Filter) and selectivities:
            conds = conds + [repr(node.condition)]
        if not node.children:
            sel = 1.0
            source_key = _leaf_source_key(node) if conds else None
            if source_key is not None:
                for cond_repr in conds:
                    sel *= selectivities.get((source_key, cond_repr), 1.0)
            total += int(leaf_bytes(node)
                         * max(MIN_COST_SELECTIVITY, min(1.0, sel)))
            return
        for c in node.children:
            walk(c, conds)

    walk(plan, [])
    return total
