"""Vectorized expression evaluation over device tables.

Null semantics follow SQL-for-filters: a comparison touching a null evaluates
to null, and Filter keeps only rows whose predicate is true-and-valid. We
track validity alongside values and fold it in at mask time.
"""

from __future__ import annotations

import datetime
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException
from ..plan import expr as E
from ..schema import BOOL, DATE, FLOAT32, FLOAT64, INT64, STRING
from .columnar import (Column, Table, dictionaries_equal, literal_to_device,
                       translate_codes)

_COMPARISONS = (E.EqualTo, E.LessThan, E.LessThanOrEqual, E.GreaterThan,
                E.GreaterThanOrEqual)


def eval_predicate_mask(table: Table, condition: E.Expr) -> jnp.ndarray:
    """Boolean keep-mask for a filter condition."""
    col = eval_expr(table, condition)
    if col.dtype != BOOL:
        raise HyperspaceException(f"Filter condition is not boolean: {condition!r}")
    mask = col.data
    if col.validity is not None:
        mask = mask & col.validity
    return mask


# ---------------------------------------------------------------------------
# Fused predicate programs (shape-class execution). The executor's Filter
# operator compiles ONE program per predicate STRUCTURE covering the whole
# mask-eval + validity + pad-tail-mask + survivor-count chain; literal
# values are runtime scalar arguments, so sweeping literals (the serving
# workload) reuses one compiled program. Unsupported expression shapes
# return None and take the eager per-op path above.
# ---------------------------------------------------------------------------

class _NotFusable(Exception):
    pass


def _pred_structure(table: Table, e: E.Expr, col_ix: dict, lits: list):
    """(hashable structure, literal slot values) for the supported subset:
    Col/Lit comparisons (incl. STRING-vs-literal via dictionary bounds),
    numeric col-vs-col comparisons, And/Or/Not, In over literals, IsNull.
    Raises _NotFusable for anything else (LIKE, CASE, arithmetic, string
    col-col — those keep the eager path)."""
    if isinstance(e, (E.And, E.Or)):
        return (("and" if isinstance(e, E.And) else "or"),
                _pred_structure(table, e.left, col_ix, lits),
                _pred_structure(table, e.right, col_ix, lits))
    if isinstance(e, E.Not):
        return ("not", _pred_structure(table, e.child, col_ix, lits))
    if isinstance(e, E.IsNull):
        if not isinstance(e.child, E.Col):
            raise _NotFusable()
        return ("isnull", col_ix[e.child.column], bool(e.negated))
    if isinstance(e, E.In):
        if not isinstance(e.value, E.Col) \
                or not all(isinstance(o, E.Lit) for o in e.options):
            raise _NotFusable()
        i = col_ix[e.value.column]
        slots = tuple(_lit_slot(table, e.value.column, "EqualTo",
                                o.value, lits) for o in e.options)
        return ("in", i, slots)
    if isinstance(e, _COMPARISONS):
        left, right = e.left, e.right
        flipped = False
        if isinstance(left, E.Lit) and not isinstance(right, E.Lit):
            left, right = right, left
            flipped = True
        if not isinstance(left, E.Col):
            raise _NotFusable()
        if isinstance(right, E.Lit):
            op = _op_name(e, flipped)
            i = col_ix[left.column]
            slot = _lit_slot(table, left.column, op, right.value, lits)
            return ("cmp", op, i, slot)
        if not isinstance(right, E.Col):
            raise _NotFusable()
        lc, rc = table.column(left.column), table.column(right.column)
        if lc.dtype == STRING or rc.dtype == STRING:
            raise _NotFusable()  # dictionary translation is host work
        return ("colcmp", _op_name(e, False), col_ix[left.column],
                col_ix[right.column])
    raise _NotFusable()


def _lit_slot(table: Table, column: str, op: str, value, lits: list):
    """Append the encoded literal(s) to the slot list; return a hashable
    slot descriptor carrying the python-type tag (part of the program
    structure — it determines the traced scalar dtype)."""
    c = table.column(column)
    if c.dtype == STRING:
        lo, hi = literal_to_device(value, STRING, c.dictionary)
        j = len(lits)
        lits.extend([lo, hi])
        return ("slit", j)
    lit = literal_to_device(value, c.dtype, None)
    j = len(lits)
    lits.append(lit)
    return ("lit", j, type(lit).__name__)


def _pred_eval(spec, cols, lits):
    """Evaluate a predicate structure over traced (data, validity) pairs.
    Returns (bool data, validity-or-None) with the eager evaluator's
    exact semantics (Kleene logic, STRING dictionary-bound compares)."""
    kind = spec[0]
    if kind in ("and", "or"):
        ld, lv = _pred_eval(spec[1], cols, lits)
        rd, rv = _pred_eval(spec[2], cols, lits)
        from ..ops import kernels
        true, known = kernels.kleene_and_or(ld, lv, rd, rv,
                                            is_and=kind == "and")
        return true, None if (lv is None and rv is None) else known
    if kind == "not":
        d, v = _pred_eval(spec[1], cols, lits)
        return ~d, v
    if kind == "isnull":
        _, i, negated = spec
        data, validity = cols[i]
        n = data.shape[0]
        if validity is None:
            return jnp.full(n, negated, jnp.bool_), None
        return (validity if negated else ~validity), None
    if kind == "in":
        _, i, slots = spec
        data, validity = cols[i]
        mask = _pred_cmp_slot("EqualTo", data, slots[0], lits) \
            if slots else jnp.zeros(data.shape[0], jnp.bool_)
        for s in slots[1:]:
            mask = mask | _pred_cmp_slot("EqualTo", data, s, lits)
        return mask, validity
    if kind == "cmp":
        _, op, i, slot = spec
        data, validity = cols[i]
        return _pred_cmp_slot(op, data, slot, lits), validity
    if kind == "colcmp":
        _, op, i, j = spec
        ld, lv = cols[i]
        rd, rv = cols[j]
        data = {
            "EqualTo": lambda: ld == rd,
            "LessThan": lambda: ld < rd,
            "LessThanOrEqual": lambda: ld <= rd,
            "GreaterThan": lambda: ld > rd,
            "GreaterThanOrEqual": lambda: ld >= rd,
        }[op]()
        return data, _merge_validity(lv, rv)
    raise HyperspaceException(f"bad predicate spec {spec!r}")


def _pred_cmp_slot(op: str, data, slot, lits):
    if slot[0] == "slit":
        # STRING: (lo, hi) dictionary bounds as traced scalars. Same op
        # table as compare_literal; the lo==hi "literal absent" case for
        # equality folds in as a runtime conjunct.
        lo, hi = lits[slot[1]], lits[slot[1] + 1]
        if op == "EqualTo":
            return (data == lo) & (jnp.asarray(lo) != jnp.asarray(hi))
        if op == "LessThan":
            return data < lo
        if op == "LessThanOrEqual":
            return data < hi
        if op == "GreaterThan":
            return data >= hi
        if op == "GreaterThanOrEqual":
            return data >= lo
        raise HyperspaceException(f"Unknown op {op}")
    lit = lits[slot[1]]
    return {
        "EqualTo": lambda: data == lit,
        "LessThan": lambda: data < lit,
        "LessThanOrEqual": lambda: data <= lit,
        "GreaterThan": lambda: data > lit,
        "GreaterThanOrEqual": lambda: data >= lit,
    }[op]()


def _arith_structure(table: Table, e: E.Expr, col_ix: dict, lits: list):
    """Structure for arithmetic trees over Col/Lit (the Project / agg-child
    hot shape, e.g. revenue = price * (1 - discount))."""
    if isinstance(e, E.Alias):
        return _arith_structure(table, e.child, col_ix, lits)
    if isinstance(e, E.Col):
        c = table.column(e.column)
        if c.dtype == STRING:
            raise _NotFusable()
        return ("col", col_ix[e.column])
    if isinstance(e, E.Lit):
        v = e.value
        if not isinstance(v, (int, float, bool)) or isinstance(v, bool):
            raise _NotFusable()
        j = len(lits)
        lits.append(v)
        return ("alit", j, type(v).__name__)
    if isinstance(e, (E.Add, E.Subtract, E.Multiply, E.Divide)):
        return ("arith", type(e).__name__,
                _arith_structure(table, e.left, col_ix, lits),
                _arith_structure(table, e.right, col_ix, lits))
    raise _NotFusable()


def _arith_eval(spec, cols, lits):
    """Mirror of _eval_arith over traced operands. Returns
    (data, validity-or-None); the caller applies the final output
    widening exactly as the eager path does."""
    kind = spec[0]
    if kind == "col":
        return cols[spec[1]]
    if kind == "alit":
        return lits[spec[1]], None
    _, op, ls, rs = spec
    ld, lv = _arith_eval(ls, cols, lits)
    rd, rv = _arith_eval(rs, cols, lits)
    if op == "Add":
        data = ld + rd
    elif op == "Subtract":
        data = ld - rd
    elif op == "Multiply":
        data = ld * rd
    else:
        data = jnp.asarray(ld, jnp.float64) / rd
    # The eager evaluator widens at EVERY arith node (each nested result
    # is a FLOAT64/INT64 Column); mirror it so nesting promotes (and
    # overflows) identically.
    data = data.astype(jnp.float64 if jnp.issubdtype(data.dtype,
                                                     jnp.floating)
                       else jnp.int64)
    return data, _merge_validity(lv, rv)


def eval_expr_fused(table: Table, e: E.Expr) -> Optional[Column]:
    """Fused arithmetic expression evaluation: ONE compiled program per
    expression structure (literal values as runtime arguments), matching
    _eval_arith's semantics bit for bit. None when the expression isn't a
    pure Col/Lit arithmetic tree (the eager evaluator handles it)."""
    from ..ops import kernels, pallas_kernels
    if pallas_kernels.enabled():
        return None
    inner = e.child if isinstance(e, E.Alias) else e
    if not isinstance(inner, (E.Add, E.Subtract, E.Multiply, E.Divide)):
        return None
    names = sorted(set(e.references))
    if not names or table.data_rows == 0:
        return None
    col_objs = []
    for nm in names:
        c = table.column(nm)
        if isinstance(c.data, jax.core.Tracer):
            return None
        col_objs.append(c)
    col_ix = {nm: i for i, nm in enumerate(names)}
    lits: list = []
    try:
        spec = _arith_structure(table, e, col_ix, lits)
    except _NotFusable:
        return None
    key = ("arith", spec,
           tuple((c.dtype, c.validity is not None) for c in col_objs))

    def builder(cols, lit_args, _n):
        data, validity = _arith_eval(spec, cols, lit_args)
        target = jnp.float64 \
            if jnp.issubdtype(data.dtype, jnp.floating) else jnp.int64
        return data.astype(target), validity

    cols = tuple((c.data, c.validity) for c in col_objs)
    data, validity = kernels.run_fused_predicate(key, builder, cols,
                                                 tuple(lits), 0)
    dtype = FLOAT64 if jnp.issubdtype(data.dtype, jnp.floating) else INT64
    return Column(dtype, data, validity)


def eval_expr_maybe_fused(table: Table, e: E.Expr) -> Column:
    fused = eval_expr_fused(table, e)
    return fused if fused is not None else eval_expr(table, e)


def predicate_slots(table: Table, condition: E.Expr):
    """(structure spec, encoded literal slot values) for a fusable
    predicate against ``table``, or None. The literal-batching sweep
    (serving/batcher.py) uses this to encode EVERY batch member's
    literals against the shared table with the exact semantics of the
    single-query path below."""
    names = sorted(set(condition.references))
    if not names:
        return None
    col_ix = {nm: i for i, nm in enumerate(names)}
    lits: list = []
    try:
        return _pred_structure(table, condition, col_ix, lits), lits
    except (_NotFusable, KeyError):
        return None


def predicate_slot_dtypes(spec, col_dtypes, n_slots):
    """Per-slot numpy dtype for a STACKED literal matrix (the serving
    literal sweep) such that comparisons reproduce the single-query
    path's weak-scalar promotion. There a python float literal is a
    weak-typed jit scalar that casts DOWN to a float32 column, while a
    strong float64 matrix would promote the COLUMN and flip comparisons
    near the f32 rounding boundary. None = numpy's default encoding is
    already value-preserving (ints/bools/float64/string bounds)."""
    out = [None] * n_slots
    _mark_slot_dtypes(spec, col_dtypes, out)
    return out


def _mark_slot_dtypes(spec, col_dtypes, out) -> None:
    tag = spec[0]
    if tag in ("and", "or"):
        _mark_slot_dtypes(spec[1], col_dtypes, out)
        _mark_slot_dtypes(spec[2], col_dtypes, out)
    elif tag == "not":
        _mark_slot_dtypes(spec[1], col_dtypes, out)
    elif tag == "cmp":
        _mark_one_slot(spec[3], col_dtypes[spec[2]], out)
    elif tag == "in":
        for slot in spec[2]:
            _mark_one_slot(slot, col_dtypes[spec[1]], out)


def _mark_one_slot(slot, col_dtype, out) -> None:
    if slot[0] == "lit" and col_dtype == FLOAT32:
        out[slot[1]] = np.float32


def eval_predicate_mask_counted(table: Table, condition: E.Expr):
    """Fused filter front-end: (pad-masked keep mask, survivor count) from
    ONE compiled program per predicate structure, or None when the
    condition (or backend path) requires the eager evaluator."""
    from ..ops import kernels, pallas_kernels
    if pallas_kernels.enabled():
        return None  # the eager path fuses differently (Pallas kernels)
    names = sorted(set(condition.references))
    if not names or table.data_rows == 0:
        return None
    col_objs = []
    for nm in names:
        c = table.column(nm)
        if isinstance(c.data, jax.core.Tracer):
            return None  # SPMD evaluates inside its own jit
        col_objs.append(c)
    col_ix = {nm: i for i, nm in enumerate(names)}
    lits: list = []
    try:
        spec = _pred_structure(table, condition, col_ix, lits)
    except _NotFusable:
        return None
    key = (spec,
           tuple((c.dtype, c.validity is not None) for c in col_objs))

    def builder(cols, lit_args, n):
        data, validity = _pred_eval(spec, cols, lit_args)
        mask = data if validity is None else (data & validity)
        phys = mask.shape[0]
        mask = mask & (jnp.arange(phys, dtype=jnp.int32) < jnp.int32(n))
        return mask, jnp.sum(mask)

    cols = tuple((c.data, c.validity) for c in col_objs)
    # Cross-query literal sweep (serving/batcher.py): when this filter
    # position belongs to an active batch over a shared table, ONE
    # vmapped invocation computes every member's mask; this member's row
    # comes out of the memo.
    from ..serving import batcher
    sweep = batcher.active_sweep()
    if sweep is not None:
        swept = sweep.try_masked_count(table, condition, key, builder,
                                       cols)
        if swept is not None:
            return swept
    mask, cnt = kernels.run_fused_predicate(key, builder, cols,
                                            tuple(lits), table.num_rows)
    return mask, int(cnt)  # HOST SYNC (single scalar)


def eval_expr(table: Table, e: E.Expr) -> Column:
    if isinstance(e, E.Col):
        return table.column(e.column)
    if isinstance(e, E.Alias):
        return eval_expr(table, e.child)
    if isinstance(e, E.Lit):
        # Constant projection (SQL: SELECT 's' sale_type ... — the TPC-DS
        # q4/q11/q74 house style): broadcast to a constant column. A bare
        # NULL has no type and stays rejected. Materializations use the
        # PHYSICAL length: on a class-padded table every column (and so
        # every evaluated expression) is padded to the same class.
        n = table.data_rows
        v = e.value
        if isinstance(v, bool):
            return Column(BOOL, jnp.full(n, v, jnp.bool_))
        if isinstance(v, int):
            return Column(INT64, jnp.full(n, v, jnp.int64))
        if isinstance(v, float):
            return Column(FLOAT64, jnp.full(n, v, jnp.float64))
        if isinstance(v, str):
            return Column(STRING, jnp.zeros(n, jnp.int32),
                          None, np.array([v], dtype=object))
        if isinstance(v, datetime.date):
            days = (v - datetime.date(1970, 1, 1)).days
            return Column(DATE, jnp.full(n, days, jnp.int32))
        raise HyperspaceException(
            f"Cannot project literal {v!r} as a column")
    if isinstance(e, _COMPARISONS):
        return _eval_comparison(table, e)
    if isinstance(e, (E.And, E.Or)):
        if isinstance(e, E.And):
            fused = _try_fused_range(table, e)
            if fused is not None:
                return fused
        left = eval_expr(table, e.left)
        right = eval_expr(table, e.right)
        # Kleene 3-valued logic: TRUE OR NULL = TRUE, FALSE AND NULL =
        # FALSE. One fused program (ops/kernels.py) instead of ~8 eager
        # ops per distinct length class.
        from ..ops import kernels
        true, known = kernels.kleene_and_or(
            left.data, left.validity, right.data, right.validity,
            is_and=isinstance(e, E.And))
        validity = None if (left.validity is None and right.validity is None) \
            else known
        return Column(BOOL, true, validity)
    if isinstance(e, E.Not):
        c = eval_expr(table, e.child)
        return Column(BOOL, ~c.data, c.validity)
    if isinstance(e, E.In):
        return _eval_in(table, e)
    if isinstance(e, (E.Add, E.Subtract, E.Multiply, E.Divide)):
        return _eval_arith(table, e)
    if isinstance(e, E.Concat):
        lits = [p.value for p in e.parts if isinstance(p, E.Lit)]
        cols = [p for p in e.parts if not isinstance(p, E.Lit)]
        if not cols:
            return Column(STRING, jnp.zeros(table.data_rows, jnp.int32),
                          None, np.array(["".join(map(str, lits))],
                                         dtype=object))
        c = eval_expr(table, cols[0])
        if c.dtype != STRING:
            raise HyperspaceException("concat() over non-string column")
        pre, post, seen = [], [], False
        for p in e.parts:
            if isinstance(p, E.Lit):
                (post if seen else pre).append(str(p.value))
            else:
                seen = True
        prefix, suffix = "".join(pre), "".join(post)
        dic = np.array([f"{prefix}{s}{suffix}" for s in c.dictionary],
                       dtype=object)
        # Dictionaries must stay SORTED (codes compare like the strings —
        # columnar.py's invariant). A prefix preserves order; a suffix can
        # break it (['a','ab'] + 'z' → ['az','abz']), so re-sort + remap.
        if dic.size > 1 and any(dic[i] > dic[i + 1]
                                for i in range(dic.size - 1)):
            order = np.argsort(dic)
            remap = np.empty(dic.size, np.int32)
            remap[order] = np.arange(dic.size, dtype=np.int32)
            data = jnp.take(jnp.asarray(remap),
                            jnp.clip(c.data, 0, dic.size - 1))
            data = jnp.where(c.data >= 0, data, c.data)
            return Column(STRING, data, c.validity, dic[order])
        return Column(STRING, c.data, c.validity, dic)
    if isinstance(e, E.NullLit):
        n = table.data_rows
        from .columnar import _DEVICE_DTYPE
        dic = np.array([""], dtype=object) if e.dtype == STRING else None
        return Column(e.dtype, jnp.zeros(n, _DEVICE_DTYPE[e.dtype]),
                      jnp.zeros(n, jnp.bool_), dic)
    if isinstance(e, E.Sqrt):
        c = eval_expr(table, e.child)
        x = c.data.astype(jnp.float64)
        # sqrt of a negative is NULL in SQL, not NaN (no host sync: the
        # validity bitmap is carried unconditionally).
        nonneg = x >= 0
        validity = nonneg if c.validity is None else (c.validity & nonneg)
        return Column(FLOAT64, jnp.sqrt(jnp.maximum(x, 0.0)), validity)
    if isinstance(e, E.Like):
        return _eval_like(table, e)
    if isinstance(e, E.IsNull):
        return _eval_is_null(table, e)
    if isinstance(e, E.CaseWhen):
        return _eval_case_when(table, e)
    if isinstance(e, E.DatePart):
        return _eval_date_part(table, e)
    if isinstance(e, (E.Substring, E.StringTransform)):
        return _eval_string_transform(table, e)
    raise HyperspaceException(f"Cannot evaluate expression: {e!r}")


_RANGE_LO = (E.GreaterThan, E.GreaterThanOrEqual)
_RANGE_HI = (E.LessThan, E.LessThanOrEqual)


def _try_fused_range(table: Table, e: "E.And") -> Optional[Column]:
    """BETWEEN fast path: And(col >(=) lo, col <(=) hi) over one 32-bit
    column evaluates as a single fused Pallas range kernel on TPU (one HBM
    pass instead of two compare passes + an AND)."""
    from ..ops import pallas_kernels

    if not pallas_kernels.enabled():
        return None
    lo_cmp, hi_cmp = e.left, e.right
    if isinstance(lo_cmp, _RANGE_HI) and isinstance(hi_cmp, _RANGE_LO):
        lo_cmp, hi_cmp = hi_cmp, lo_cmp
    if not (isinstance(lo_cmp, _RANGE_LO) and isinstance(hi_cmp, _RANGE_HI)):
        return None
    if not (isinstance(lo_cmp.left, E.Col) and isinstance(hi_cmp.left, E.Col)
            and isinstance(lo_cmp.right, E.Lit)
            and isinstance(hi_cmp.right, E.Lit)
            and lo_cmp.left.column == hi_cmp.left.column):
        return None
    col = table.column(lo_cmp.left.column)
    if col.dtype == STRING or col.data.shape[0] == 0 \
            or col.data.dtype not in (jnp.int32, jnp.float32, jnp.uint32):
        return None
    lo = literal_to_device(lo_cmp.right.value, col.dtype, None)
    hi = literal_to_device(hi_cmp.right.value, col.dtype, None)
    if jnp.issubdtype(col.data.dtype, jnp.integer) \
            and not (isinstance(lo, int) and isinstance(hi, int)):
        return None  # fractional bound against int data: general path
    mask = pallas_kernels.fused_range_mask(
        col.data, lo, hi,
        lo_incl=isinstance(lo_cmp, E.GreaterThanOrEqual),
        hi_incl=isinstance(hi_cmp, E.LessThanOrEqual))
    return Column(BOOL, mask, col.validity)


def _merge_validity(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _eval_comparison(table: Table, e) -> Column:
    left, right = e.left, e.right
    flipped = False
    if isinstance(left, E.Lit) and not isinstance(right, E.Lit):
        left, right = right, left
        flipped = True
    if isinstance(right, E.Lit):
        col = eval_expr(table, left)
        op = _op_name(e, flipped)
        data = compare_literal(col, op, right.value)
        return Column(BOOL, data, col.validity)
    # column vs column.
    lc = eval_expr(table, left)
    rc = eval_expr(table, right)
    ld, rd = _align_for_compare(lc, rc, type(e).__name__)
    op = _op_name(e, False)
    data = {
        "EqualTo": lambda: ld == rd,
        "LessThan": lambda: ld < rd,
        "LessThanOrEqual": lambda: ld <= rd,
        "GreaterThan": lambda: ld > rd,
        "GreaterThanOrEqual": lambda: ld >= rd,
    }[op]()
    return Column(BOOL, data, _merge_validity(lc.validity, rc.validity))


def _op_name(e, flipped: bool) -> str:
    name = type(e).__name__
    if not flipped:
        return name
    return {
        "EqualTo": "EqualTo",
        "LessThan": "GreaterThan",
        "LessThanOrEqual": "GreaterThanOrEqual",
        "GreaterThan": "LessThan",
        "GreaterThanOrEqual": "LessThanOrEqual",
    }[name]


def compare_literal(col: Column, op: str, value) -> jnp.ndarray:
    """Compare a device column against a host literal.

    Strings use searchsorted (lo, hi) bounds into the order-preserving
    dictionary, so every op is an integer comparison on codes.
    """
    if col.dtype == STRING:
        lo, hi = literal_to_device(value, STRING, col.dictionary)
        codes = col.data
        if op == "EqualTo":
            if lo == hi:  # literal not present.
                return jnp.zeros(codes.shape[0], jnp.bool_)
            return codes == lo
        if op == "LessThan":
            return codes < lo
        if op == "LessThanOrEqual":
            return codes < hi
        if op == "GreaterThan":
            return codes >= hi
        if op == "GreaterThanOrEqual":
            return codes >= lo
        raise HyperspaceException(f"Unknown op {op}")
    lit = literal_to_device(value, col.dtype, None)
    data = col.data
    # 32-bit lanes: one-pass fused Pallas compare on TPU. A fractional
    # literal against an int column must NOT enter the fused kernel (it
    # casts the literal to the column dtype, truncating 5.5 → 5); the jnp
    # path below promotes the column instead.
    from ..ops import pallas_kernels
    if (pallas_kernels.enabled() and data.shape[0] > 0
            and data.dtype in (jnp.int32, jnp.float32, jnp.uint32)
            and not (jnp.issubdtype(data.dtype, jnp.integer)
                     and not isinstance(lit, (int, bool)))):
        sym = {"EqualTo": "==", "LessThan": "<", "LessThanOrEqual": "<=",
               "GreaterThan": ">", "GreaterThanOrEqual": ">="}[op]
        return pallas_kernels.fused_compare_mask(data, sym, lit)
    return {
        "EqualTo": lambda: data == lit,
        "LessThan": lambda: data < lit,
        "LessThanOrEqual": lambda: data <= lit,
        "GreaterThan": lambda: data > lit,
        "GreaterThanOrEqual": lambda: data >= lit,
    }[op]()


def _align_for_compare(lc: Column, rc: Column, op_name: str):
    if lc.dtype == STRING or rc.dtype == STRING:
        if lc.dtype != STRING or rc.dtype != STRING:
            raise HyperspaceException("Cannot compare string with non-string")
        if dictionaries_equal(lc.dictionary, rc.dictionary):
            return lc.data, rc.data
        if op_name != "EqualTo":
            raise HyperspaceException(
                "Ordering comparison across different string dictionaries "
                "is not supported yet")
        return lc.data, translate_codes(lc.dictionary, rc)
    return lc.data, rc.data


def _eval_in(table: Table, e: E.In) -> Column:
    col = eval_expr(table, e.value)
    values = [opt.value for opt in e.options]
    if not values:
        return Column(BOOL, jnp.zeros(len(col), jnp.bool_), col.validity)
    mask = compare_literal(col, "EqualTo", values[0])
    for v in values[1:]:
        mask = mask | compare_literal(col, "EqualTo", v)
    return Column(BOOL, mask, col.validity)


def like_pattern_to_regex(pattern: str) -> str:
    """SQL LIKE → anchored regex: % = any run, _ = any one char, the rest
    literal."""
    import re as _re

    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(_re.escape(ch))
    return "".join(out)


def _eval_like(table: Table, e: "E.Like") -> Column:
    """LIKE over the order-preserving dictionary: match each distinct
    string ONCE on the host, then one device gather maps codes → bool.
    Cost is O(|dict|) host regex + O(n) gather — the dictionary-encoding
    analogue of Spark evaluating LIKE per row."""
    import re as _re

    import numpy as np

    col = eval_expr(table, e.child)
    if col.dtype != STRING:
        raise HyperspaceException(f"LIKE requires a string operand: {e!r}")
    # DOTALL: SQL's % and _ match newlines too (Spark wraps in (?s)).
    rx = _re.compile(like_pattern_to_regex(e.pattern), _re.DOTALL)
    dict_mask = np.fromiter(
        (rx.fullmatch(s) is not None for s in col.dictionary),
        dtype=np.bool_, count=len(col.dictionary))
    if e.negated:
        dict_mask = ~dict_mask
    if dict_mask.all() or not dict_mask.any():
        # Constant over the dictionary: skip the gather entirely.
        data = jnp.full(len(col), bool(dict_mask.all()) if len(dict_mask)
                        else e.negated, jnp.bool_)
        return Column(BOOL, data, col.validity)
    data = jnp.take(jnp.asarray(dict_mask), col.data)
    return Column(BOOL, data, col.validity)


def _eval_is_null(table: Table, e: "E.IsNull") -> Column:
    col = eval_expr(table, e.child)
    if col.validity is None:
        data = jnp.full(len(col), e.negated, jnp.bool_)
    else:
        data = col.validity if e.negated else ~col.validity
    return Column(BOOL, data, None)  # IS NULL itself is never null.


def _eval_case_when(table: Table, e: "E.CaseWhen") -> Column:
    """First-true-condition-wins where-chain. A null condition falls
    through (SQL: null is not true); the selected branch's own validity
    carries; no match and no ELSE yields null."""
    import numpy as np

    n = table.data_rows
    conds = []
    for c, _ in e.branches:
        cc = eval_expr(table, c)
        if cc.dtype != BOOL:
            raise HyperspaceException(f"CASE condition is not boolean: {c!r}")
        t = cc.data
        if cc.validity is not None:
            t = t & cc.validity
        conds.append(t)

    def value_col(v) -> Optional[Column]:
        if isinstance(v, E.Lit):
            if v.value is None:
                return None  # typed after unification (all-null column)
            # Materialize the literal as a constant column of the right
            # logical type (strings get a one-entry dictionary, unified
            # below).
            import datetime as _dt
            if isinstance(v.value, str):
                return Column(STRING, jnp.zeros(n, jnp.int32), None,
                              np.asarray([v.value]))
            if isinstance(v.value, bool):
                return Column(BOOL, jnp.full(n, v.value, jnp.bool_), None)
            if isinstance(v.value, int):
                return Column(INT64, jnp.full(n, v.value, jnp.int64), None)
            if isinstance(v.value, float):
                return Column(FLOAT64, jnp.full(n, v.value, jnp.float64), None)
            if isinstance(v.value, _dt.date):
                days = (v.value - _dt.date(1970, 1, 1)).days
                from ..schema import DATE
                return Column(DATE, jnp.full(n, days, jnp.int32), None)
            raise HyperspaceException(f"Unsupported CASE literal {v.value!r}")
        return eval_expr(table, v)

    vals = [value_col(v) for _, v in e.branches]
    if e.else_value is not None:
        vals.append(value_col(e.else_value))
    vals = _unify_branch_columns(vals, n)
    # Fold right-to-left so the FIRST true condition wins.
    if e.else_value is not None:
        acc = vals[-1]
        branch_vals = vals[:-1]
    else:
        proto = vals[0]
        acc = Column(proto.dtype,
                     jnp.zeros(n, proto.data.dtype),
                     jnp.zeros(n, jnp.bool_), proto.dictionary)
        branch_vals = vals
    data, validity = acc.data, acc.validity
    for cond, v in zip(reversed(conds), reversed(branch_vals)):
        data = jnp.where(cond, v.data, data)
        v_valid = v.validity if v.validity is not None \
            else jnp.ones(n, jnp.bool_)
        a_valid = validity if validity is not None else jnp.ones(n, jnp.bool_)
        new_valid = jnp.where(cond, v_valid, a_valid)
        validity = None if (v.validity is None and validity is None) \
            else new_valid
    return Column(vals[0].dtype, data, validity, vals[0].dictionary)


def _unify_branch_columns(vals, n: int):
    """Bring all CASE branch values into one dtype (+ one dictionary for
    strings) so the where-chain operates on compatible arrays. ``None``
    entries (explicit NULL branches) materialize as all-null columns of
    the unified type."""
    import numpy as np

    typed = [v for v in vals if v is not None]
    if not typed:
        raise HyperspaceException("CASE with only NULL branches has no type")
    if len(typed) < len(vals):
        typed = _unify_branch_columns(typed, n)
        proto = typed[0]
        null_col = Column(proto.dtype, jnp.zeros(n, proto.data.dtype),
                          jnp.zeros(n, jnp.bool_), proto.dictionary)
        it = iter(typed)
        return [null_col if v is None else next(it) for v in vals]
    kinds = {v.dtype for v in vals}
    if kinds == {STRING}:
        dicts = [v.dictionary for v in vals]
        if all(dictionaries_equal(dicts[0], d) for d in dicts[1:]):
            return vals
        union = np.unique(np.concatenate(dicts))
        return [Column(STRING, translate_codes(union, v), v.validity, union)
                for v in vals]
    if len(kinds) == 1:
        return vals
    if STRING in kinds:
        raise HyperspaceException(
            f"CASE branches mix string and non-string types: {sorted(kinds)}")
    target = jnp.float64 if any(
        jnp.issubdtype(v.data.dtype, jnp.floating) for v in vals) \
        else jnp.int64
    dtype = FLOAT64 if target == jnp.float64 else INT64
    return [Column(dtype, v.data.astype(target), v.validity) for v in vals]


def _eval_date_part(table: Table, e: "E.DatePart") -> Column:
    """EXTRACT over date32 days: the branch-free civil-from-days algorithm
    (integer ops only — vectorizes onto the VPU with no host round-trip)."""
    from ..schema import DATE

    col = eval_expr(table, e.child)
    if col.dtype != DATE:
        raise HyperspaceException(f"EXTRACT requires a date operand: {e!r}")
    z = col.data.astype(jnp.int64) + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    year = y + (m <= 2)
    out = {"year": year, "month": m, "day": d,
           "quarter": (m - 1) // 3 + 1}[e.part]
    return Column(INT64, out.astype(jnp.int64), col.validity)


def _eval_string_transform(table: Table, e) -> Column:
    """SUBSTRING/UPPER/LOWER/TRIM: transform each distinct dictionary
    entry once on the host, re-encode (the transform can collapse or
    reorder entries), then remap codes with one gather."""
    import numpy as np

    col = eval_expr(table, e.child)
    if col.dtype != STRING:
        raise HyperspaceException(f"{e.op_name} requires a string operand")
    if isinstance(e, E.Substring):
        # Spark/Hive semantics: 1-based positive start; negative start
        # counts from the END of the string; start 0 behaves like 1. A
        # virtual start before the beginning still consumes length
        # (substring('abc', -5, 4) = 'ab'), so clamp AFTER computing the
        # window — never Python's negative-index slicing.
        def fn(s):
            n = len(s)
            p = e.start
            start = p - 1 if p > 0 else (n + p if p < 0 else 0)
            end = n if e.length is None else start + max(e.length, 0)
            lo = min(max(start, 0), n)
            return s[lo:max(end, lo)]
    else:
        fn = {"upper": str.upper, "lower": str.lower,
              "trim": str.strip}[e.fn]
    transformed = np.asarray([fn(s) for s in col.dictionary])
    if len(transformed) == 0:
        return Column(STRING, col.data, col.validity, transformed)
    union, inverse = np.unique(transformed, return_inverse=True)
    codes = jnp.take(jnp.asarray(inverse.astype(np.int32)), col.data)
    return Column(STRING, codes, col.validity, union)


def _eval_arith(table: Table, e) -> Column:
    def operand(x) -> Tuple:
        if isinstance(x, E.Lit):
            return None, x.value
        c = eval_expr(table, x)
        if c.dtype == STRING:
            raise HyperspaceException("Arithmetic on string column")
        return c, None

    lcol, lval = operand(e.left)
    rcol, rval = operand(e.right)
    if lcol is None and rcol is None:
        raise HyperspaceException("Arithmetic between two literals")
    ld = lcol.data if lcol is not None else lval
    rd = rcol.data if rcol is not None else rval
    if isinstance(e, E.Add):
        data = ld + rd
    elif isinstance(e, E.Subtract):
        data = ld - rd
    elif isinstance(e, E.Multiply):
        data = ld * rd
    else:
        data = jnp.asarray(ld, jnp.float64) / rd
    validity = _merge_validity(
        lcol.validity if lcol is not None else None,
        rcol.validity if rcol is not None else None)
    dtype = FLOAT64 if jnp.issubdtype(data.dtype, jnp.floating) else INT64
    data = data.astype(jnp.float64 if dtype == FLOAT64 else jnp.int64)
    return Column(dtype, data, validity)
