"""Vectorized expression evaluation over device tables.

Null semantics follow SQL-for-filters: a comparison touching a null evaluates
to null, and Filter keeps only rows whose predicate is true-and-valid. We
track validity alongside values and fold it in at mask time.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from ..exceptions import HyperspaceException
from ..plan import expr as E
from ..schema import BOOL, FLOAT64, INT64, STRING
from .columnar import (Column, Table, dictionaries_equal, literal_to_device,
                       translate_codes)

_COMPARISONS = (E.EqualTo, E.LessThan, E.LessThanOrEqual, E.GreaterThan,
                E.GreaterThanOrEqual)


def eval_predicate_mask(table: Table, condition: E.Expr) -> jnp.ndarray:
    """Boolean keep-mask for a filter condition."""
    col = eval_expr(table, condition)
    if col.dtype != BOOL:
        raise HyperspaceException(f"Filter condition is not boolean: {condition!r}")
    mask = col.data
    if col.validity is not None:
        mask = mask & col.validity
    return mask


def eval_expr(table: Table, e: E.Expr) -> Column:
    if isinstance(e, E.Col):
        return table.column(e.column)
    if isinstance(e, E.Alias):
        return eval_expr(table, e.child)
    if isinstance(e, E.Lit):
        raise HyperspaceException(
            "Bare literals must appear inside a comparison/arithmetic expression")
    if isinstance(e, _COMPARISONS):
        return _eval_comparison(table, e)
    if isinstance(e, (E.And, E.Or)):
        if isinstance(e, E.And):
            fused = _try_fused_range(table, e)
            if fused is not None:
                return fused
        left = eval_expr(table, e.left)
        right = eval_expr(table, e.right)
        # Kleene 3-valued logic: TRUE OR NULL = TRUE, FALSE AND NULL = FALSE.
        lv = left.validity if left.validity is not None \
            else jnp.ones(len(left), jnp.bool_)
        rv = right.validity if right.validity is not None \
            else jnp.ones(len(right), jnp.bool_)
        lt, lf = lv & left.data, lv & ~left.data
        rt, rf = rv & right.data, rv & ~right.data
        if isinstance(e, E.And):
            true, false = lt & rt, lf | rf
        else:
            true, false = lt | rt, lf & rf
        known = true | false
        validity = None if (left.validity is None and right.validity is None) \
            else known
        return Column(BOOL, true, validity)
    if isinstance(e, E.Not):
        c = eval_expr(table, e.child)
        return Column(BOOL, ~c.data, c.validity)
    if isinstance(e, E.In):
        return _eval_in(table, e)
    if isinstance(e, (E.Add, E.Subtract, E.Multiply, E.Divide)):
        return _eval_arith(table, e)
    raise HyperspaceException(f"Cannot evaluate expression: {e!r}")


_RANGE_LO = (E.GreaterThan, E.GreaterThanOrEqual)
_RANGE_HI = (E.LessThan, E.LessThanOrEqual)


def _try_fused_range(table: Table, e: "E.And") -> Optional[Column]:
    """BETWEEN fast path: And(col >(=) lo, col <(=) hi) over one 32-bit
    column evaluates as a single fused Pallas range kernel on TPU (one HBM
    pass instead of two compare passes + an AND)."""
    from ..ops import pallas_kernels

    if not pallas_kernels.enabled():
        return None
    lo_cmp, hi_cmp = e.left, e.right
    if isinstance(lo_cmp, _RANGE_HI) and isinstance(hi_cmp, _RANGE_LO):
        lo_cmp, hi_cmp = hi_cmp, lo_cmp
    if not (isinstance(lo_cmp, _RANGE_LO) and isinstance(hi_cmp, _RANGE_HI)):
        return None
    if not (isinstance(lo_cmp.left, E.Col) and isinstance(hi_cmp.left, E.Col)
            and isinstance(lo_cmp.right, E.Lit)
            and isinstance(hi_cmp.right, E.Lit)
            and lo_cmp.left.column == hi_cmp.left.column):
        return None
    col = table.column(lo_cmp.left.column)
    if col.dtype == STRING or col.data.shape[0] == 0 \
            or col.data.dtype not in (jnp.int32, jnp.float32, jnp.uint32):
        return None
    lo = literal_to_device(lo_cmp.right.value, col.dtype, None)
    hi = literal_to_device(hi_cmp.right.value, col.dtype, None)
    if jnp.issubdtype(col.data.dtype, jnp.integer) \
            and not (isinstance(lo, int) and isinstance(hi, int)):
        return None  # fractional bound against int data: general path
    mask = pallas_kernels.fused_range_mask(
        col.data, lo, hi,
        lo_incl=isinstance(lo_cmp, E.GreaterThanOrEqual),
        hi_incl=isinstance(hi_cmp, E.LessThanOrEqual))
    return Column(BOOL, mask, col.validity)


def _merge_validity(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _eval_comparison(table: Table, e) -> Column:
    left, right = e.left, e.right
    flipped = False
    if isinstance(left, E.Lit) and not isinstance(right, E.Lit):
        left, right = right, left
        flipped = True
    if isinstance(right, E.Lit):
        col = eval_expr(table, left)
        op = _op_name(e, flipped)
        data = compare_literal(col, op, right.value)
        return Column(BOOL, data, col.validity)
    # column vs column.
    lc = eval_expr(table, left)
    rc = eval_expr(table, right)
    ld, rd = _align_for_compare(lc, rc, type(e).__name__)
    op = _op_name(e, False)
    data = {
        "EqualTo": lambda: ld == rd,
        "LessThan": lambda: ld < rd,
        "LessThanOrEqual": lambda: ld <= rd,
        "GreaterThan": lambda: ld > rd,
        "GreaterThanOrEqual": lambda: ld >= rd,
    }[op]()
    return Column(BOOL, data, _merge_validity(lc.validity, rc.validity))


def _op_name(e, flipped: bool) -> str:
    name = type(e).__name__
    if not flipped:
        return name
    return {
        "EqualTo": "EqualTo",
        "LessThan": "GreaterThan",
        "LessThanOrEqual": "GreaterThanOrEqual",
        "GreaterThan": "LessThan",
        "GreaterThanOrEqual": "LessThanOrEqual",
    }[name]


def compare_literal(col: Column, op: str, value) -> jnp.ndarray:
    """Compare a device column against a host literal.

    Strings use searchsorted (lo, hi) bounds into the order-preserving
    dictionary, so every op is an integer comparison on codes.
    """
    if col.dtype == STRING:
        lo, hi = literal_to_device(value, STRING, col.dictionary)
        codes = col.data
        if op == "EqualTo":
            if lo == hi:  # literal not present.
                return jnp.zeros(codes.shape[0], jnp.bool_)
            return codes == lo
        if op == "LessThan":
            return codes < lo
        if op == "LessThanOrEqual":
            return codes < hi
        if op == "GreaterThan":
            return codes >= hi
        if op == "GreaterThanOrEqual":
            return codes >= lo
        raise HyperspaceException(f"Unknown op {op}")
    lit = literal_to_device(value, col.dtype, None)
    data = col.data
    # 32-bit lanes: one-pass fused Pallas compare on TPU. A fractional
    # literal against an int column must NOT enter the fused kernel (it
    # casts the literal to the column dtype, truncating 5.5 → 5); the jnp
    # path below promotes the column instead.
    from ..ops import pallas_kernels
    if (pallas_kernels.enabled() and data.shape[0] > 0
            and data.dtype in (jnp.int32, jnp.float32, jnp.uint32)
            and not (jnp.issubdtype(data.dtype, jnp.integer)
                     and not isinstance(lit, (int, bool)))):
        sym = {"EqualTo": "==", "LessThan": "<", "LessThanOrEqual": "<=",
               "GreaterThan": ">", "GreaterThanOrEqual": ">="}[op]
        return pallas_kernels.fused_compare_mask(data, sym, lit)
    return {
        "EqualTo": lambda: data == lit,
        "LessThan": lambda: data < lit,
        "LessThanOrEqual": lambda: data <= lit,
        "GreaterThan": lambda: data > lit,
        "GreaterThanOrEqual": lambda: data >= lit,
    }[op]()


def _align_for_compare(lc: Column, rc: Column, op_name: str):
    if lc.dtype == STRING or rc.dtype == STRING:
        if lc.dtype != STRING or rc.dtype != STRING:
            raise HyperspaceException("Cannot compare string with non-string")
        if dictionaries_equal(lc.dictionary, rc.dictionary):
            return lc.data, rc.data
        if op_name != "EqualTo":
            raise HyperspaceException(
                "Ordering comparison across different string dictionaries "
                "is not supported yet")
        return lc.data, translate_codes(lc.dictionary, rc)
    return lc.data, rc.data


def _eval_in(table: Table, e: E.In) -> Column:
    col = eval_expr(table, e.value)
    values = [opt.value for opt in e.options]
    if not values:
        return Column(BOOL, jnp.zeros(len(col), jnp.bool_), col.validity)
    mask = compare_literal(col, "EqualTo", values[0])
    for v in values[1:]:
        mask = mask | compare_literal(col, "EqualTo", v)
    return Column(BOOL, mask, col.validity)


def _eval_arith(table: Table, e) -> Column:
    def operand(x) -> Tuple:
        if isinstance(x, E.Lit):
            return None, x.value
        c = eval_expr(table, x)
        if c.dtype == STRING:
            raise HyperspaceException("Arithmetic on string column")
        return c, None

    lcol, lval = operand(e.left)
    rcol, rval = operand(e.right)
    if lcol is None and rcol is None:
        raise HyperspaceException("Arithmetic between two literals")
    ld = lcol.data if lcol is not None else lval
    rd = rcol.data if rcol is not None else rval
    if isinstance(e, E.Add):
        data = ld + rd
    elif isinstance(e, E.Subtract):
        data = ld - rd
    elif isinstance(e, E.Multiply):
        data = ld * rd
    else:
        data = jnp.asarray(ld, jnp.float64) / rd
    validity = _merge_validity(
        lcol.validity if lcol is not None else None,
        rcol.validity if rcol is not None else None)
    dtype = FLOAT64 if jnp.issubdtype(data.dtype, jnp.floating) else INT64
    data = data.astype(jnp.float64 if dtype == FLOAT64 else jnp.int64)
    return Column(dtype, data, validity)
