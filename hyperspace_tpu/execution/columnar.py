"""Device-resident columnar tables.

This is the TPU-native data representation the whole engine computes over:
every column is a fixed-width JAX array in HBM. Variable-length strings are
dictionary-encoded **order-preserving** at the host→device boundary (codes
compare like the strings they stand for, so range predicates and sorts work
directly on codes — SURVEY §7 hard-part #2). Dates are int32 days; decimals
become float64.

Host↔device crossings happen only at parquet read/write and at collect().
"""

from __future__ import annotations

import datetime
import functools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from ..exceptions import HyperspaceException
from ..schema import BOOL, DATE, FLOAT32, FLOAT64, INT32, INT64, STRING, Field, Schema

_DEVICE_DTYPE = {
    INT32: jnp.int32,
    INT64: jnp.int64,
    FLOAT32: jnp.float32,
    FLOAT64: jnp.float64,
    BOOL: jnp.bool_,
    DATE: jnp.int32,
    STRING: jnp.int32,  # dictionary codes.
}


@dataclass
class Column:
    """One device column: values (or dictionary codes) + optional validity."""

    dtype: str  # logical type name from schema.py
    data: jax.Array
    validity: Optional[jax.Array] = None  # bool, True = valid; None = all valid
    dictionary: Optional[np.ndarray] = None  # sorted unique strings (host)

    def __post_init__(self):
        if self.dtype == STRING and self.dictionary is None:
            raise HyperspaceException("STRING columns require a dictionary")

    def __len__(self):
        return int(self.data.shape[0])

    @property
    def has_nulls(self) -> bool:
        return self.validity is not None

    def take(self, indices) -> "Column":
        # clip mode: padded gather indices (shape-class execution) may
        # carry out-of-range filler in the pad tail; clipping keeps the
        # gather defined (the clipped rows land in the pad region of the
        # result and are never read as data).
        return Column(self.dtype, jnp.take(self.data, indices, axis=0,
                                           mode="clip"),
                      None if self.validity is None
                      else jnp.take(self.validity, indices, axis=0,
                                    mode="clip"),
                      self.dictionary)

    def slice(self, start: int, stop: int) -> "Column":
        return Column(self.dtype, self.data[start:stop],
                      None if self.validity is None else self.validity[start:stop],
                      self.dictionary)


@dataclass
class Table:
    """An ordered set of equal-length device columns.

    ``bucket_order`` is a physical-layout hint: ``(num_buckets, key_cols)``
    means rows are grouped by ascending bucket id (hash of key_cols) and
    sorted by key_cols within each bucket — the covering-index invariant.
    The join path uses it to skip re-sorting (shuffle-free SMJ analogue).
    Operations that permute or merge rows must drop it.

    ``valid_rows`` is the shape-class execution contract
    (execution/shapes.py): when set, the column arrays are padded to a
    length class and only rows ``[0, valid_rows)`` are data — the pad tail
    holds arbitrary values that must never be read. ``num_rows`` is the
    LOGICAL count; ``data_rows`` the physical array length. Everything
    leaving the engine (to_arrow/to_host/compact) drops the padding, so
    results are byte-identical to exact-shape execution.
    """

    columns: Dict[str, Column]
    bucket_order: Optional[Tuple[int, Tuple[str, ...]]] = None
    valid_rows: Optional[int] = None

    def __post_init__(self):
        lengths = {len(c) for c in self.columns.values()}
        if len(lengths) > 1:
            raise HyperspaceException(f"Ragged table: column lengths {lengths}")
        if self.valid_rows is not None:
            phys = next(iter(lengths), 0)
            if not 0 <= self.valid_rows <= phys:
                raise HyperspaceException(
                    f"valid_rows {self.valid_rows} outside [0, {phys}]")
            if self.valid_rows == phys:
                self.valid_rows = None  # exact: no padding in play

    @property
    def num_rows(self) -> int:
        if self.valid_rows is not None:
            return self.valid_rows
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    @property
    def data_rows(self) -> int:
        """Physical column length (== num_rows unless class-padded)."""
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    @property
    def is_padded(self) -> bool:
        return self.valid_rows is not None

    def compact(self) -> "Table":
        """Drop class padding: slice every column to the valid prefix
        (one fused program per (table signature, valid count) — a
        data-dependent count compiles per value, so terminal results
        prefer the free host-boundary trim in executor.execute). No-op
        (and no copy) for exact tables."""
        n = self.valid_rows
        if n is None:
            return self
        return self.slice(0, n)

    @property
    def names(self) -> List[str]:
        return list(self.columns.keys())

    def column(self, name: str) -> Column:
        if name not in self.columns:
            raise HyperspaceException(
                f"Unknown column '{name}'; available: {self.names}")
        return self.columns[name]

    def schema(self) -> Schema:
        return Schema([Field(n, c.dtype, c.has_nulls)
                       for n, c in self.columns.items()])

    def _keep_order(self, names: Sequence[str]) -> Optional[Tuple]:
        if self.bucket_order and all(k in names for k in self.bucket_order[1]):
            return self.bucket_order
        return None

    def select(self, names: Sequence[str]) -> "Table":
        return Table({n: self.column(n) for n in names},
                     bucket_order=self._keep_order(names),
                     valid_rows=self.valid_rows)

    def take(self, indices, valid_rows: Optional[int] = None) -> "Table":
        """Row gather. ``valid_rows`` declares the valid prefix of a
        class-padded ``indices`` array (shape-class execution). All
        column buffers gather through ONE fused program
        (kernels.gather_arrays) — one compile per table signature."""
        from ..ops import kernels
        arrays, spec = [], []
        for n, c in self.columns.items():
            arrays.append(c.data)
            spec.append((n, "d"))
            if c.validity is not None:
                arrays.append(c.validity)
                spec.append((n, "v"))
        taken = dict(zip(spec, kernels.gather_arrays(indices, arrays)))
        return Table({n: Column(c.dtype, taken[(n, "d")],
                                taken.get((n, "v")), c.dictionary)
                      for n, c in self.columns.items()},
                     valid_rows=valid_rows)

    def filter(self, mask, padded: bool = False) -> "Table":
        # A subsequence of bucket-ordered rows is still bucket-ordered.
        # One flatnonzero for the whole table: per-column boolean indexing
        # would re-run the mask→indices conversion for every column (and
        # jax's bool-index path is markedly slower than an int gather).
        # Shape classes: the survivor count is data-dependent — the classic
        # recompile driver — so with ``padded=True`` (the executor's hot
        # path) the gather indices are padded to their length class and the
        # result rides with valid_rows. Default stays exact: callers
        # outside the padded pipeline (SPMD routing, build, chunk streams)
        # read .data directly and must keep exact shapes.
        if mask.shape[0] != self.data_rows:
            # jnp.take clips out-of-range indices silently; fail loud here.
            raise HyperspaceException(
                f"filter mask length {mask.shape[0]} != rows {self.data_rows}")
        idx, m = filter_indices(mask, self.valid_rows, padded=padded)
        out = self.take(idx, valid_rows=m if int(idx.shape[0]) != m else None)
        return Table(out.columns, bucket_order=self.bucket_order,
                     valid_rows=out.valid_rows)

    def slice(self, start: int, stop: int) -> "Table":
        # start/stop address the valid prefix, so the result is exact.
        # Device-resident buffers slice through ONE fused program per
        # table signature; host (numpy) buffers slice for free.
        from ..ops import kernels
        dev, spec = [], []
        for n, c in self.columns.items():
            if not isinstance(c.data, np.ndarray):
                dev.append(c.data)
                spec.append((n, "d"))
            if c.validity is not None and not isinstance(c.validity,
                                                         np.ndarray):
                dev.append(c.validity)
                spec.append((n, "v"))
        sliced = dict(zip(spec, kernels.slice_arrays(dev, start, stop))) \
            if dev else {}

        def part(c, name, kind, host):
            if (name, kind) in sliced:
                return sliced[(name, kind)]
            return host[start:stop]

        return Table({n: Column(c.dtype, part(c, n, "d", c.data),
                                part(c, n, "v", c.validity)
                                if c.validity is not None else None,
                                c.dictionary)
                      for n, c in self.columns.items()},
                     bucket_order=self.bucket_order)

    def with_column(self, name: str, col: Column) -> "Table":
        out = dict(self.columns)
        out[name] = col
        return Table(out, bucket_order=self.bucket_order,
                     valid_rows=self.valid_rows)

    def to_host(self) -> "Table":
        """Materialize every column as host numpy with ONE device_get over
        the whole pytree. On a remote-attached TPU the per-transfer round
        trip (not bandwidth) dominates, so anything that will be sliced
        many times on the host (e.g. one parquet file per bucket) must be
        fetched wholesale first, never slice-by-slice. Class padding is
        dropped on the host (free — a numpy slice, no device program)."""
        import jax
        arrays = {}
        for n, c in self.columns.items():
            arrays[(n, "d")] = c.data
            if c.validity is not None:
                arrays[(n, "v")] = c.validity
        host = jax.device_get(arrays)
        rows = self.num_rows

        def trim(a):
            a = np.asarray(a)
            return a[:rows] if self.valid_rows is not None else a

        return Table({n: Column(c.dtype, trim(host[(n, "d")]),
                                trim(host[(n, "v")])
                                if c.validity is not None else None,
                                c.dictionary)
                      for n, c in self.columns.items()},
                     bucket_order=self.bucket_order)

    def rename(self, mapping: Dict[str, str]) -> "Table":
        order = self.bucket_order
        if order:
            order = (order[0], tuple(mapping.get(k, k) for k in order[1]))
        return Table({mapping.get(n, n): c for n, c in self.columns.items()},
                     bucket_order=order, valid_rows=self.valid_rows)

    @staticmethod
    def concat(tables: Sequence["Table"]) -> "Table":
        """Union of schema-aligned tables; string dictionaries are re-unified.
        Class-padded inputs are compacted first (an interleaved pad tail
        cannot ride through a concatenation)."""
        tables = [t.compact() for t in tables]
        tables = [t for t in tables if t.num_rows > 0] or list(tables[:1])
        if len(tables) == 1:
            return tables[0]
        first = tables[0]
        out: Dict[str, Column] = {}
        for name in first.names:
            cols = [t.column(name) for t in tables]
            dtype = cols[0].dtype
            if any(c.dtype != dtype for c in cols):
                raise HyperspaceException(f"concat dtype mismatch on '{name}'")
            if dtype == STRING:
                out[name] = _concat_string_columns(cols)
            else:
                data = jnp.concatenate([c.data for c in cols])
                validity = None
                if any(c.validity is not None for c in cols):
                    validity = jnp.concatenate([
                        c.validity if c.validity is not None
                        else jnp.ones(len(c), dtype=jnp.bool_) for c in cols])
                out[name] = Column(dtype, data, validity)
        return Table(out)

    # ------------------------------------------------------------------
    # Host boundary.
    # ------------------------------------------------------------------

    def to_arrow(self) -> pa.Table:
        # ONE batched device_get for every device-resident buffer (data +
        # validity across all columns): on the TPU tunnel each device_get
        # is a full round trip, so per-column fetches made a 4-column
        # result cost 8 round trips. Host-resident columns (e.g. after
        # to_host()) skip the transfer entirely.
        device_buffers = {}
        for name, col in self.columns.items():
            if not isinstance(col.data, np.ndarray):
                device_buffers[(name, "d")] = col.data
            if col.validity is not None and \
                    not isinstance(col.validity, np.ndarray):
                device_buffers[(name, "v")] = col.validity
        fetched = jax.device_get(device_buffers) if device_buffers else {}

        def fetch(a, key):
            if key in fetched:
                return np.asarray(fetched[key])
            return a

        arrays = []
        rows = self.num_rows
        for name, col in self.columns.items():
            np_data = fetch(col.data, (name, "d"))
            np_valid = (fetch(col.validity, (name, "v"))
                        if col.validity is not None else None)
            if self.valid_rows is not None:
                # Drop class padding at the host boundary (a numpy slice —
                # no device program, byte-identical to exact execution).
                np_data = np.asarray(np_data)[:rows]
                if np_valid is not None:
                    np_valid = np.asarray(np_valid)[:rows]
            mask = None if np_valid is None else ~np_valid
            if col.dtype == STRING:
                codes = np_data
                safe = np.where(codes >= 0, codes, 0)
                values = col.dictionary[safe] if len(col.dictionary) else \
                    np.array([""] * len(codes), dtype=object)
                arr = pa.array(values, type=pa.string(),
                               mask=mask if mask is not None else (codes < 0))
            elif col.dtype == DATE:
                arr = pa.array(np_data.astype("int32"), type=pa.int32(), mask=mask)
                arr = arr.cast(pa.date32())
            elif col.dtype == BOOL:
                arr = pa.array(np_data.astype(bool), mask=mask)
            else:
                arr = pa.array(np_data, mask=mask)
            arrays.append((name, arr))
        return pa.table(dict(arrays))

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    @staticmethod
    def from_arrow(table: pa.Table, pad_to_class: bool = False) -> "Table":
        # Struct columns are flattened into dotted leaf names ("a.b.c") so
        # only fixed-width flat arrays reach the device (see
        # Schema.from_arrow).
        while any(pa.types.is_struct(f.type) for f in table.schema):
            table = table.flatten()
        # Shape classes at the host->device boundary: padding in numpy is
        # FREE (no device program), so executor-bound reads land on their
        # length class before any XLA op ever sees the exact row count.
        target = None
        if pad_to_class and table.num_rows > 0:
            from . import shapes
            cls = shapes.padded_length(table.num_rows)
            if cls != table.num_rows:
                target = cls
        cols: Dict[str, Column] = {}
        for name in table.column_names:
            cols[name] = _encode_arrow_column(table.column(name), target)
        return Table(cols, valid_rows=table.num_rows
                     if target is not None else None)


def filter_indices(mask, valid_rows: Optional[int] = None,
                   padded: bool = True):
    """(gather indices, survivor count) for a keep mask over a possibly
    class-padded table. Pad rows are masked out; with ``padded`` the
    indices come out at the survivor count's length class directly
    (jnp.nonzero with a static class size, filler 0 — always in-bounds
    for a non-empty source): no exact-length array ever materializes, so
    downstream gathers compile once per class instead of once per
    survivor count."""
    from ..ops import kernels
    return kernels.mask_count_nonzero(mask, valid_rows, padded)


def pad_table_to_class(table: Table) -> Table:
    """Class-pad an exact table (one lax.pad per column buffer — a few
    tiny programs per distinct table length, vs one per downstream op).
    The executor applies this at scan boundaries so every chain over the
    table runs at its length class."""
    from . import shapes
    n = table.num_rows
    if table.is_padded or n == 0:
        return table
    cls = shapes.padded_length(n)
    if cls == n:
        return table
    cols = {}
    for name, c in table.columns.items():
        if isinstance(c.data, np.ndarray):
            return table  # host-resident tables stay exact
        cols[name] = Column(c.dtype, shapes.pad_to(c.data, cls),
                            shapes.pad_to(c.validity, cls, False)
                            if c.validity is not None else None,
                            c.dictionary)
    return Table(cols, bucket_order=table.bucket_order, valid_rows=n)


# ---------------------------------------------------------------------------
# Encoding.
# ---------------------------------------------------------------------------

def _pad_host(np_data: np.ndarray, target: Optional[int], fill=0) -> np.ndarray:
    """Host-side class pad (no device program; see Table.from_arrow)."""
    if target is None or np_data.shape[0] >= target:
        return np_data
    out = np.empty(target, dtype=np_data.dtype)
    out[:np_data.shape[0]] = np_data
    out[np_data.shape[0]:] = fill
    return out


def _encode_arrow_column(chunked: pa.ChunkedArray,
                         target: Optional[int] = None) -> Column:
    t = chunked.type
    if pa.types.is_dictionary(t):
        chunked = chunked.cast(t.value_type)
        t = t.value_type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return _encode_string(chunked, target)
    combined = chunked.combine_chunks() if chunked.num_chunks != 1 else chunked.chunk(0)
    null_count = combined.null_count
    if pa.types.is_date32(t):
        np_data = combined.cast(pa.int32()).to_numpy(zero_copy_only=False)
        dtype = DATE
    elif pa.types.is_decimal(t):
        np_data = combined.cast(pa.float64()).to_numpy(zero_copy_only=False)
        dtype = FLOAT64
    elif pa.types.is_timestamp(t):
        np_data = combined.cast(pa.int64()).to_numpy(zero_copy_only=False)
        dtype = INT64
    elif pa.types.is_boolean(t):
        np_data = combined.to_numpy(zero_copy_only=False)
        dtype = BOOL
    elif pa.types.is_integer(t):
        # fill_null BEFORE to_numpy: the null path otherwise round-trips
        # through float64 (NaN-null), silently corrupting int64 values
        # beyond ±2^53. Validity masks the filled zeros below.
        filled = combined.fill_null(0) if null_count else combined
        wide = filled.cast(pa.int64()).to_numpy(zero_copy_only=False)
        if t.bit_width <= 32:
            np_data, dtype = wide.astype(np.int32), INT32
        else:
            np_data, dtype = wide, INT64
    elif pa.types.is_floating(t):
        np_data = combined.to_numpy(zero_copy_only=False)
        dtype = FLOAT32 if t.bit_width == 32 else FLOAT64
    else:
        raise HyperspaceException(f"Unsupported arrow type: {t}")

    validity = None
    if null_count:
        valid_np = ~np.asarray(combined.is_null())
        fill = 0
        np_data = np.where(valid_np, np.nan_to_num(np_data, nan=fill)
                           if np_data.dtype.kind == "f" else np_data, fill)
        validity = jnp.asarray(_pad_host(valid_np, target, False))
    dev_dtype = _DEVICE_DTYPE[dtype]
    np_data = _pad_host(np.ascontiguousarray(np_data), target)
    return Column(dtype, jnp.asarray(np_data, dtype=dev_dtype), validity)


def _encode_string(chunked: pa.ChunkedArray,
                   target: Optional[int] = None) -> Column:
    """Order-preserving dictionary encoding: codes sort like the strings."""
    combined = chunked.combine_chunks() if chunked.num_chunks != 1 else chunked.chunk(0)
    uniques = pc.unique(combined.drop_null())
    dictionary = np.sort(np.asarray(uniques).astype(str)) if len(uniques) else \
        np.array([], dtype=str)
    values = np.asarray(combined.fill_null("")).astype(str)
    codes = np.searchsorted(dictionary, values).astype(np.int32) \
        if len(dictionary) else np.zeros(len(values), np.int32)
    validity = None
    if combined.null_count:
        valid_np = ~np.asarray(combined.is_null())
        codes = np.where(valid_np, codes, -1).astype(np.int32)
        validity = jnp.asarray(_pad_host(valid_np, target, False))
    return Column(STRING, jnp.asarray(_pad_host(codes, target)), validity,
                  dictionary)


def _concat_string_columns(cols: List[Column]) -> Column:
    """Re-unify dictionaries so codes stay order-preserving across parts."""
    merged = np.unique(np.concatenate([c.dictionary for c in cols])) \
        if any(len(c.dictionary) for c in cols) else np.array([], dtype=str)
    datas, validities, any_valid = [], [], False
    for c in cols:
        remap = np.searchsorted(merged, c.dictionary).astype(np.int32) \
            if len(c.dictionary) else np.zeros(0, np.int32)
        remap_dev = jnp.asarray(remap)
        codes = jnp.where(c.data >= 0,
                          jnp.take(remap_dev, jnp.maximum(c.data, 0)), -1) \
            if len(remap) else c.data
        datas.append(codes)
        v = c.validity if c.validity is not None else jnp.ones(len(c), jnp.bool_)
        validities.append(v)
        any_valid = any_valid or c.validity is not None
    data = jnp.concatenate(datas)
    validity = jnp.concatenate(validities) if any_valid else None
    return Column(STRING, data, validity, merged)


# ---------------------------------------------------------------------------
# Parquet IO.
# ---------------------------------------------------------------------------

def _resolve_files(files: Sequence[str]):
    """(filesystem-or-None, normalized paths) — the multi-path form of
    data_store.fs_and_path, delegating to the same store resolution."""
    if not files:
        return None, list(files)
    from ..index import data_store
    store = data_store.store_for_path(files[0])
    if store is None:
        return None, list(files)
    return store.filesystem(), [store.normalize(f) for f in files]


def _file_size_weight(fs):
    """Per-file byte-weight estimator for the reader pool's in-flight
    budget (decoded size ≈ file size to first order; 0 = unweighted)."""
    import os

    def weight(path) -> int:
        try:
            if fs is None:
                return int(os.path.getsize(path))
            return int(fs.get_file_info(path).size or 0)
        except Exception:
            return 0
    return weight


def _read_parquet_pooled(files, read_cols, filters, fs) -> pa.Table:
    """Multi-file parquet read fanned out over the shared reader pool
    (parallel/io.py): the file list splits into one CONTIGUOUS sublist
    per pool thread, each task runs the fast dataset path single-threaded
    (our pool IS the parallelism — nesting pyarrow's own pool under it
    only oversubscribes), and the ordered concat keeps file order, so the
    result is byte-identical to the sequential bulk read. Sublists, not
    per-file tasks: they amortize the per-call dataset setup a per-file
    fan-out pays N times (measured 3x the bulk read's cost that way).

    ``io.enabled=false`` restores the exact legacy bulk read (pyarrow's
    native threading); ``io.threads=1`` is the strict sequential
    baseline (single-threaded bulk read) the bench A/B and determinism
    tests compare against."""
    from ..parallel import io as pio
    p = pio.active_params()
    n = p.resolved_threads()
    if not p.enabled:
        return pq.read_table(list(files), columns=read_cols,
                             filters=filters, filesystem=fs)
    if len(files) > 1 and n > 1 and not pio.in_worker():
        step = (len(files) + n - 1) // n
        groups = [files[i:i + step] for i in range(0, len(files), step)]
        fweight = _file_size_weight(fs)
        parts = pio.map_ordered(
            lambda g: pq.read_table(list(g), columns=read_cols,
                                    filters=filters, filesystem=fs,
                                    use_threads=False),
            groups, weight=lambda g: sum(fweight(f) for f in g),
            params=p, label="read_parquet")
        try:
            return pa.concat_tables(parts)
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            # Heterogeneous per-file schemas: unification is the bulk
            # dataset reader's job.
            pass
    return pq.read_table(list(files), columns=read_cols, filters=filters,
                         filesystem=fs,
                         use_threads=n > 1 and not pio.in_worker())


def read_parquet(files: Sequence[str], columns: Optional[Sequence[str]] = None,
                 fmt: str = "parquet", filters=None,
                 pad_to_class: bool = False, pool: bool = True) -> Table:
    """``pad_to_class`` class-pads the result host-side (free) for the
    executor's shape-class pipeline; leave False for callers that read
    ``.data`` directly (builds, sketches, spmd leaves). Multi-file reads
    of every format fan out per file over the shared reader pool
    (parallel/io.py) with order-preserving gather; device encoding stays
    on the calling thread.

    Class-padded parquet reads route through the tiered buffer pool
    (execution/buffer_pool.py) keyed by file signature + column set +
    pruning filter: a warm probe serves the decoded padded table with
    ZERO file reads and ZERO host→device transfers; a miss decodes here
    (the pooled fan-out readers are the pool's producers) and admits the
    result. ``pool=False`` opts a caller out (the index-scan path has
    its own pool view and must not double-store)."""
    from ..parallel import io as pio
    from ..robustness import fault_names as _fn
    from ..robustness import faults as _faults
    from . import buffer_pool as _bp
    if not files:
        raise HyperspaceException("read_parquet: no files")
    # Robustness fault point: the scan-decode boundary every format
    # funnels through (hard no-op disarmed; see robustness/faults.py).
    # Fires BEFORE the pool probe so fault semantics are identical
    # pool-on vs pool-off.
    _faults.fault_point(_fn.SCAN_PARQUET_DECODE)
    pool_key = None
    if fmt == "parquet" and pad_to_class and pool and _bp.enabled():
        pool_key = _bp.scan_key(files, columns, filters)
        if pool_key is not None:
            cached = _bp.get_pool().get(pool_key)
            if cached is not None:
                return cached
    if fmt == "parquet":
        fs, files = _resolve_files(files)
        read_cols = list(columns) if columns else None
        flatten_select = None
        if columns:
            top_level = set(pq.read_schema(files[0], filesystem=fs).names)
            if any(c not in top_level for c in columns):
                # Dotted struct leaves: read each leaf's root struct column,
                # flatten after read, then select the exact leaves (pyarrow's
                # columns= would select nested leaves but rename them to the
                # leaf's own name, losing the dotted path).
                roots = []
                for c in columns:
                    root = c if c in top_level else c.split(".", 1)[0]
                    if root not in roots:
                        roots.append(root)
                read_cols, flatten_select = roots, list(columns)
        at = _read_parquet_pooled(files, read_cols, filters, fs)
        if flatten_select is not None:
            while any(pa.types.is_struct(f.type) for f in at.schema):
                at = at.flatten()
            at = at.select(flatten_select)
    elif fmt == "csv":
        import pyarrow.csv as pa_csv

        def _read_csv(f):
            # Workers parse single-threaded: the pool is the parallelism
            # (nesting pyarrow's own pool oversubscribes); the sequential
            # path keeps pyarrow's default threading like the legacy loop.
            if pio.in_worker():
                return pa_csv.read_csv(f, read_options=pa_csv.ReadOptions(
                    use_threads=False))
            return pa_csv.read_csv(f)

        tables = pio.map_ordered(_read_csv, files,
                                 weight=_file_size_weight(None),
                                 label="read_csv")
        at = pa.concat_tables(tables)
        if columns:
            at = at.select(list(columns))
    elif fmt == "avro":
        from ..util.avro import read_avro
        tables = pio.map_ordered(
            lambda f: read_avro(f, list(columns) if columns else None),
            files, weight=_file_size_weight(None), label="read_avro")
        at = pa.concat_tables(tables)
    elif fmt == "json":
        # Newline-delimited JSON (the reference's spark json source shape,
        # DefaultFileBasedSource.scala:37-44).
        import pyarrow.json as pa_json

        def _read_json(f):
            if pio.in_worker():
                return pa_json.read_json(
                    f, read_options=pa_json.ReadOptions(use_threads=False))
            return pa_json.read_json(f)

        tables = pio.map_ordered(_read_json, files,
                                 weight=_file_size_weight(None),
                                 label="read_json")
        at = pa.concat_tables(tables)
        if columns:
            at = at.select(list(columns))
    elif fmt == "orc":
        import pyarrow.orc as pa_orc
        tables = pio.map_ordered(
            lambda f: pa_orc.ORCFile(f).read(
                columns=list(columns) if columns else None),
            files, weight=_file_size_weight(None), label="read_orc")
        at = pa.concat_tables(tables)
    elif fmt == "text":
        # Spark text-source semantics: one string column "value" per line.
        # Hadoop's LineReader treats \n, \r, and \r\n all as line
        # terminators (but NOT \x0b/\x0c etc., so str.splitlines would
        # silently diverge from the reference).
        import re

        def _read_text(f):
            with open(f, encoding="utf-8", newline="") as fh:
                body = fh.read()
            lines_ = re.split("\r\n|\r|\n", body)
            if lines_ and lines_[-1] == "":
                lines_.pop()  # trailing terminator, not an empty last line
            return pa.array(lines_, type=pa.string())

        arrays = pio.map_ordered(_read_text, files,
                                 weight=_file_size_weight(None),
                                 label="read_text")
        at = pa.table({"value": pa.concat_arrays(arrays)})
        if columns:
            at = at.select(list(columns))
    else:
        raise HyperspaceException(f"Unsupported format: {fmt}")
    table = Table.from_arrow(at, pad_to_class=pad_to_class)
    if pool_key is not None:
        _bp.get_pool().put(pool_key, table)
    return table


@functools.lru_cache(maxsize=65536)
def _file_row_count(path: str, size: int, mtime_ns: int) -> int:
    fs, paths = _resolve_files([path])
    return pq.ParquetFile(paths[0], filesystem=fs).metadata.num_rows


def parquet_row_counts(files: Sequence[str]) -> List[int]:
    """Row count per file from parquet footers (no data read). Memoized
    per (path, size, mtime): budget checks run on every filtered scan,
    and re-opening every footer per query would tax the hot cached path
    (index files are immutable, so staleness means a new path/version)."""
    import os

    from ..index import data_store
    out = []
    for f in files:
        store = data_store.store_for_path(f)
        if store is None:
            st = os.stat(f)
            out.append(_file_row_count(f, st.st_size, st.st_mtime_ns))
        else:
            _, size, mtime_ms = store.file_info(f)
            out.append(_file_row_count(f, size, mtime_ms))
    return out


def _table_nbytes_estimate(obj) -> int:
    """In-flight byte estimate for a chunk (Table or (Table, provenance))
    crossing the prefetch queue — device buffer sizes, host-visible."""
    t = obj[0] if isinstance(obj, tuple) else obj
    total = 0
    for c in t.columns.values():
        total += int(getattr(c.data, "nbytes", 0) or 0)
        if c.validity is not None:
            total += int(getattr(c.validity, "nbytes", 0) or 0)
    return total


def iter_parquet_chunks(files: Sequence[str], columns: Optional[Sequence[str]],
                        chunk_rows: int):
    """Stream files as device Tables of ≤ ``chunk_rows`` rows each, yielding
    ``(table, [(file_index, rows_from_that_file), ...])`` so callers can
    attribute rows to source files (lineage). Row groups are the streaming
    unit, which is what bounds the HBM footprint for data larger than
    device memory (SURVEY §7 hard-part #1): at most ``prefetchDepth``
    buffered chunks (further capped by ``maxInflightBytes`` of decoded
    bytes) + one in production + one at the consumer are resident, the
    parallel-io prefetcher decoding chunk k+1 while chunk k computes.
    Order and provenance are exactly the sequential stream's."""
    from ..parallel import io as pio
    return pio.prefetch_iter(
        _iter_parquet_chunks(files, columns, chunk_rows),
        nbytes=_table_nbytes_estimate, label="parquet_chunks")


def _iter_parquet_chunks(files: Sequence[str],
                         columns: Optional[Sequence[str]], chunk_rows: int):
    batch: List[pa.Table] = []
    batch_rows = 0
    provenance: List[Tuple[int, int]] = []

    def flush():
        nonlocal batch, batch_rows, provenance
        if not batch:
            return None
        at = pa.concat_tables(batch) if len(batch) > 1 else batch[0]
        out = (Table.from_arrow(at), provenance)
        batch, batch_rows, provenance = [], 0, []
        return out

    fs, files = _resolve_files(list(files))
    read_cols = list(columns) if columns else None
    for fi, path in enumerate(files):
        pf = pq.ParquetFile(path, filesystem=fs)
        for rg in range(pf.num_row_groups):
            t = pf.read_row_group(rg, columns=read_cols)
            start = 0
            while start < t.num_rows:
                take = min(t.num_rows - start, chunk_rows - batch_rows)
                batch.append(t.slice(start, take))
                if provenance and provenance[-1][0] == fi:
                    provenance[-1] = (fi, provenance[-1][1] + take)
                else:
                    provenance.append((fi, take))
                batch_rows += take
                start += take
                if batch_rows >= chunk_rows:
                    yield flush()
    tail = flush()
    if tail is not None:
        yield tail


def iter_dataset_chunks(files: Sequence[str],
                        columns: Optional[Sequence[str]], chunk_rows: int,
                        filters=None):
    """Stream files as device Tables of ≤ ``chunk_rows`` rows with parquet
    predicate pushdown: row groups whose statistics exclude the filter are
    never decoded (the scan-side counterpart of iter_parquet_chunks, which
    the build uses for its lineage provenance). Depth-N prefetching
    (parallel/io.py): chunk k+1 decodes to device while the consumer
    executes chunk k.

    Streams up to ``bufferPool.streamAdmitBytes`` route through the
    tiered buffer pool: a warm probe replays the exact chunk sequence
    (byte-identical, chunk-for-chunk) with zero file reads; a miss
    streams normally while collecting chunks, admitting the sequence
    only after NORMAL exhaustion (abandoned iterations never admit a
    truncated stream). Chunk payloads are device-resident — the entries
    are device-only: evicted by dropping, never demoted."""
    from ..parallel import io as pio
    from . import buffer_pool as _bp
    pool_key = None
    if _bp.enabled():
        pool_key = _bp.stream_key(files, columns, filters, chunk_rows)
        if pool_key is not None:
            cached = _bp.get_pool().get(pool_key)
            if cached is not None:
                return iter(list(cached))
    source = _iter_dataset_chunks(files, columns, chunk_rows, filters)
    if pool_key is not None:
        source = _collect_stream(pool_key, source,
                                 _bp.stream_admit_bytes())
    return pio.prefetch_iter(source, nbytes=_table_nbytes_estimate,
                             label="dataset_chunks")


def _collect_stream(pool_key, source, admit_bytes: int):
    """Pass chunks through while accumulating them for pool admission;
    over-budget streams stop collecting (too big to replay), and only a
    NORMALLY exhausted stream admits — a consumer that abandons the
    iterator early (GeneratorExit) must never poison the pool with a
    truncated sequence."""
    from . import buffer_pool as _bp
    chunks: List[Table] = []
    total = 0
    for chunk in source:
        if chunks is not None:
            total += _table_nbytes_estimate(chunk)
            if total > admit_bytes:
                chunks = None
            else:
                chunks.append(chunk)
        yield chunk
    if chunks is not None:
        _bp.get_pool().put(pool_key, chunks, nbytes=total,
                           device_only=True)


def _iter_dataset_chunks(files: Sequence[str],
                         columns: Optional[Sequence[str]], chunk_rows: int,
                         filters=None):
    import pyarrow.dataset as pa_ds

    expr = pq.filters_to_expression(filters) if filters is not None else None
    fs, files = _resolve_files(list(files))
    ds = pa_ds.dataset(list(files), format="parquet", filesystem=fs)
    batch: List[pa.Table] = []
    batch_rows = 0
    for rb in ds.scanner(columns=list(columns) if columns else None,
                         filter=expr,
                         batch_size=max(chunk_rows, 1)).to_batches():
        if rb.num_rows == 0:
            continue
        t = pa.Table.from_batches([rb])
        start = 0
        while start < t.num_rows:
            take = min(t.num_rows - start, chunk_rows - batch_rows)
            batch.append(t.slice(start, take))
            batch_rows += take
            start += take
            if batch_rows >= chunk_rows:
                yield Table.from_arrow(pa.concat_tables(batch))
                batch, batch_rows = [], 0
    if batch:
        yield Table.from_arrow(pa.concat_tables(batch))


def write_parquet(table: Table, path: str, row_group_size: Optional[int] = None) -> None:
    fs, paths = _resolve_files([path])
    pq.write_table(table.to_arrow(), paths[0],
                   row_group_size=row_group_size, filesystem=fs)


def empty_table(schema: "Schema") -> Table:
    cols = {}
    for f in schema.fields:
        dictionary = np.array([], dtype=str) if f.dtype == STRING else None
        cols[f.name] = Column(f.dtype,
                              jnp.zeros(0, _DEVICE_DTYPE[f.dtype]),
                              None, dictionary)
    return Table(cols)


def dictionaries_equal(a: Optional[np.ndarray], b: Optional[np.ndarray]) -> bool:
    return a is b or (a is not None and b is not None
                      and len(a) == len(b) and bool(np.array_equal(a, b)))


def translate_codes(target_dictionary: np.ndarray, col: Column):
    """Re-map a STRING column's codes into ``target_dictionary``'s code space.

    Strings absent from the target dictionary map to -2, which equals no
    valid code (and no null code, -1) — equality against translated codes is
    therefore exact. Shared by cross-dictionary comparisons and string-key
    joins.
    """
    src = col.dictionary
    if len(src) == 0:
        return jnp.full(col.data.shape, -2, jnp.int32)
    if len(target_dictionary) == 0:
        return jnp.full(col.data.shape, -2, jnp.int32)
    pos = np.searchsorted(target_dictionary, src)
    pos_c = np.clip(pos, 0, len(target_dictionary) - 1)
    present = (pos < len(target_dictionary)) & (target_dictionary[pos_c] == src)
    mapping = np.where(present, pos_c, -2).astype(np.int32)
    mapping_dev = jnp.asarray(mapping)
    return jnp.where(col.data >= 0,
                     jnp.take(mapping_dev, jnp.maximum(col.data, 0)), -2)


def literal_to_device(value, dtype: str, dictionary: Optional[np.ndarray]):
    """Encode a python literal for comparison against a device column.

    For STRING columns returns ``(lo, hi)`` searchsorted bounds into the
    dictionary: lo == searchsorted(dict, v, 'left'), hi == 'right' — every
    comparison op can be phrased over codes with these two ints (see
    ops/kernels.py:compare_literal).
    """
    if dtype == STRING:
        if dictionary is None:
            raise HyperspaceException("string literal against non-string column")
        v = str(value)
        lo = int(np.searchsorted(dictionary, v, side="left"))
        hi = int(np.searchsorted(dictionary, v, side="right"))
        return lo, hi
    if dtype == DATE:
        if isinstance(value, str):
            value = datetime.date.fromisoformat(value)
        if isinstance(value, datetime.date):
            return int((value - datetime.date(1970, 1, 1)).days)
        return int(value)
    if dtype == BOOL:
        return bool(value)
    if dtype in (FLOAT32, FLOAT64):
        return float(value)
    if isinstance(value, float) and not value.is_integer():
        # Fractional literal against an int column: int() truncation would
        # change comparison semantics (5 < 5.5 but not 5 < int(5.5));
        # jnp promotes the int column for the comparison instead.
        return value
    return int(value)
