"""Predicate pushdown to the parquet reader.

Row-group pruning at the IO boundary: conjuncts of the form Col <op>
Literal, Col IN (literals...), and Col IS [NOT] NULL are translated to
pyarrow compute expressions and handed to the parquet reader, which skips
row groups whose min/max/null-count stats can't match (IN-heavy TPC-DS
filters and NOT NULL guards prune row groups like any comparison). The
device Filter stays in the plan (pushdown is an IO optimization, not a
semantic transfer).

This is where the covering index's within-bucket sort order pays off for
filter queries: index files are sorted by the indexed columns, so row-group
stats are tight and a range predicate prunes most of the file.
"""

from __future__ import annotations

import datetime
from typing import Optional

import pyarrow.compute as pc

from ..plan import expr as E
from ..schema import DATE, Schema

_OPS = {
    "EqualTo": lambda f, v: f == v,
    "LessThan": lambda f, v: f < v,
    "LessThanOrEqual": lambda f, v: f <= v,
    "GreaterThan": lambda f, v: f > v,
    "GreaterThanOrEqual": lambda f, v: f >= v,
}

_FLIP = {
    "EqualTo": "EqualTo",
    "LessThan": "GreaterThan",
    "LessThanOrEqual": "GreaterThanOrEqual",
    "GreaterThan": "LessThan",
    "GreaterThanOrEqual": "LessThanOrEqual",
}

_CMP_TYPES = tuple(getattr(E, n) for n in _OPS)


def _literal(value, column: str, schema: Schema):
    # Date columns accept ISO strings in our expression language; parquet
    # stats need a real date value. Other strings pass through untouched.
    if column in schema and schema.field(column).dtype == DATE \
            and isinstance(value, str):
        return datetime.date.fromisoformat(value)
    return value


def _translate(e: E.Expr, schema: Schema, allow_nested: bool):
    def field(column: str):
        # A dotted name is a flattened struct leaf; in source files the
        # physical column is the root struct, and pc.field("a.b") raises
        # "No match for FieldRef" against it. Index files store leaves as
        # flat dotted-named columns, so there the reference is valid.
        if "." in column and not allow_nested:
            return None
        return pc.field(column)

    if isinstance(e, _CMP_TYPES):
        op = type(e).__name__
        left, right = e.left, e.right
        if isinstance(left, E.Lit) and isinstance(right, E.Col):
            left, right = right, left
            op = _FLIP[op]
        if isinstance(left, E.Col) and isinstance(right, E.Lit):
            f = field(left.column)
            if f is None:
                return None
            return _OPS[op](f, _literal(right.value, left.column, schema))
        return None
    if isinstance(e, E.In) and isinstance(e.value, E.Col):
        values = [_literal(o.value, e.value.column, schema)
                  for o in e.options if isinstance(o, E.Lit)]
        if len(values) == len(e.options):
            f = field(e.value.column)
            if f is None:
                return None
            return f.isin(values)
        return None
    if isinstance(e, E.IsNull) and isinstance(e.child, E.Col):
        # Row groups carry null counts: IS NULL prunes all-valid groups,
        # IS NOT NULL prunes all-null ones (the TPC-DS outer-join-guard
        # shape). Never yields null itself, so pushing is sound.
        f = field(e.child.column)
        if f is None:
            return None
        return ~f.is_null() if e.negated else f.is_null()
    if isinstance(e, E.Or):
        l = _translate(e.left, schema, allow_nested)
        r = _translate(e.right, schema, allow_nested)
        if l is not None and r is not None:
            return l | r
        return None
    return None


def filter_constrains(condition: E.Expr, schema: Schema,
                      column: str) -> bool:
    """True when at least one *pushable* conjunct references only
    ``column``. Used to decide whether an index read should go through the
    parquet reader (row-group pruning on the leading sorted column beats a
    cached full-table device mask) instead of the HBM-resident cache."""
    for conjunct in E.split_conjunctive_predicates(condition):
        if conjunct.references == [column] \
                and _translate(conjunct, schema, True) is not None:
            return True
    return False


def prefers_pruned_read(entry, condition: E.Expr, schema: Schema) -> bool:
    """Policy (shared by the single-device executor and the SPMD leaf
    load): when a pushable conjunct constrains the LEADING indexed
    column, the within-bucket sort makes row-group stats tight — a
    pruned parquet read costs ~selectivity of the file, far cheaper than
    masking a cached full table. No expression translation happens here;
    callers that already built a pa filter just reuse it."""
    return (entry.derivedDataset.kind == "CoveringIndex"
            and bool(entry.indexed_columns)
            and filter_constrains(condition, schema,
                                  entry.indexed_columns[0]))


def pruned_index_read_filter(entry, condition: E.Expr,
                             schema: Schema) -> Optional[pc.Expression]:
    """The pa filter to read a covering index with INSTEAD of the HBM
    cache, or None to use the cache (see prefers_pruned_read)."""
    if not prefers_pruned_read(entry, condition, schema):
        return None
    return pushable_filter(condition, schema)


def pushable_filter(condition: E.Expr, schema: Schema,
                    allow_nested: bool = True) -> Optional[pc.Expression]:
    """AND of the translatable conjuncts, or None.

    Pushing a subset of conjuncts is sound: each is a necessary condition,
    and the full device filter still runs afterward. ``allow_nested=False``
    excludes dotted (struct-leaf) columns — required for source scans, where
    the physical parquet column is the root struct.
    """
    out = None
    for conjunct in E.split_conjunctive_predicates(condition):
        t = _translate(conjunct, schema, allow_nested)
        if t is not None:
            out = t if out is None else (out & t)
    return out
