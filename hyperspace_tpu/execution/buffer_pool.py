"""Tiered columnar buffer pool: decoded scans shared across queries.

The r06 result cache short-circuits *identical* plans; everything else —
a literal variant, a different projection, a standing-query fire — used
to re-read parquet, re-decode Arrow→numpy, re-pad to shape classes, and
re-ship host→device even when the underlying (file, columns) bytes were
unchanged. This module is the missing cache tier underneath all of that:
a process-wide, byte-budgeted, two-tier (device HBM → host) pool of
decoded, shape-class-padded column buffers, keyed by source file
signature (path, size, mtime) + column set + row-group pruning selection
+ padding/dtype profile, so any two queries touching the same columns of
the same files share ONE decode and ONE host→device transfer.

All three scan paths route through it:

- ``columnar.read_parquet(pad_to_class=True)`` — the executor's bulk
  scan (the r09 pooled fan-out readers are the *producers* into the
  pool: a miss decodes through them, the admit makes every later probe
  skip them entirely);
- ``columnar.iter_dataset_chunks`` — the chunked filtered scan admits
  its full chunk sequence (bounded by ``streamAdmitBytes``) and replays
  it byte-identically;
- the SPMD file-aligned scan (execution/spmd.py) — per-device sharded
  blocks cached keyed by mesh signature (device-only entries: they drop
  on eviction, never demote).

``execution/index_cache.py``'s IndexTableCache is a thin view over this
pool (namespace "index"), so index and source scans obey ONE budget.

Correctness is by construction: keys embed the (size, mtime, path) file
signature, so append/refresh/optimize/compact produce new signatures and
stale entries simply age out of the LRU — the same invalidation story as
the result cache. Eviction ladders device → host → drop. The
``buffer.load`` fault point fires at every probe: under the r14 degrade
contract an injected (or real) load failure is a SILENT MISS — the entry
is dropped and the caller re-reads — never a wrong answer; with
``robustness.degrade.enabled=false`` it fails loud.

The pool is purely process-local (no recovery surface, nothing on disk);
in a cluster each worker warms its own pool and the per-worker
OpenMetrics scrape carries the ``buffer_pool`` collector.

Thread safety: one lock around both tiers and every counter, the
result-cache pattern — device→host demotions and host→device promotions
(the batched ``jax.device_put``) run OUTSIDE the lock.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..robustness import fault_names as _fn
from ..robustness import faults as _faults
from ..telemetry import metric_names as _mn
from ..telemetry import metrics as _metrics

TIER_DEVICE = "device"
TIER_HOST = "host"

# Fallback budgets when no session conf is active (the executor is
# session-free by design; within an execution the parallel-io session
# scope provides the conf and get_pool() refreshes the budgets live).
_DEVICE_BYTES_DEFAULT = 4 << 30
_HOST_BYTES_DEFAULT = 4 << 30
_STREAM_ADMIT_BYTES_DEFAULT = 256 << 20


class PoolKey(NamedTuple):
    """One pool entry's identity: namespace ("scan" | "stream" | "index"
    | "blocks"), the hashable key tuple (file signature + column set +
    pruning selection + profile), and the summed source bytes the key's
    files hold (credited to ``decode_bytes_saved`` on every hit)."""

    ns: str
    key: tuple
    source_bytes: int


class _Entry:
    __slots__ = ("payload", "nbytes", "source_bytes", "device_only")

    def __init__(self, payload, nbytes: int, source_bytes: int,
                 device_only: bool):
        self.payload = payload
        self.nbytes = nbytes
        self.source_bytes = source_bytes
        self.device_only = device_only


def table_nbytes(table) -> int:
    """Approximate residency cost of a Table (device or host): column
    data + validity bitmaps + dictionary slots. The single byte
    accounting shared by this pool, the index-cache view, and the
    serving result cache (serving/result_cache.py)."""
    total = 0
    for col in table.columns.values():
        total += col.data.size * col.data.dtype.itemsize
        if col.validity is not None:
            total += col.validity.size
        if col.dictionary is not None:
            total += col.dictionary.size * 8
    return total


def _table_to_host(table):
    """Demote a Table to host numpy with ONE batched device_get, KEEPING
    class padding and ``valid_rows`` (unlike Table.to_host, which trims)
    — a later promotion must restore the exact device layout so the
    shape-class pipeline sees the same compiled programs."""
    import jax

    from .columnar import Column, Table
    arrays = {}
    for n, c in table.columns.items():
        if not isinstance(c.data, np.ndarray):
            arrays[(n, "d")] = c.data
        if c.validity is not None and not isinstance(c.validity,
                                                     np.ndarray):
            arrays[(n, "v")] = c.validity
    host = jax.device_get(arrays) if arrays else {}

    def pick(a, key):
        return np.asarray(host[key]) if key in host else a

    return Table({n: Column(c.dtype, pick(c.data, (n, "d")),
                            pick(c.validity, (n, "v"))
                            if c.validity is not None else None,
                            c.dictionary)
                  for n, c in table.columns.items()},
                 bucket_order=table.bucket_order,
                 valid_rows=table.valid_rows)


def _table_to_device(table):
    """Promote a host-tier Table back into HBM with ONE batched
    jax.device_put, preserving ``valid_rows`` (the demotion kept the
    padded physical length)."""
    import jax

    from .columnar import Column, Table
    if not any(isinstance(c.data, np.ndarray)
               for c in table.columns.values()):
        return table
    arrays = {}
    for n, c in table.columns.items():
        arrays[(n, "d")] = c.data
        if c.validity is not None:
            arrays[(n, "v")] = c.validity
    dev = jax.device_put(arrays)
    return Table({n: Column(c.dtype, dev[(n, "d")],
                            dev[(n, "v")] if c.validity is not None
                            else None, c.dictionary)
                  for n, c in table.columns.items()},
                 bucket_order=table.bucket_order,
                 valid_rows=table.valid_rows)


class BufferPool:
    """Two-tier (device → host) byte-budgeted LRU of decoded buffers.

    Entries are Tables (demotable) or opaque device objects (SPMD block
    dicts, chunk-stream lists — ``device_only``: evicted by dropping).
    Counters: ``device_hits``/``host_hits``/``misses`` per probe,
    ``admissions``/``rejections`` per put, ``loads`` (pool-filling
    decode+transfer), ``promotions`` (host→device re-uploads — together
    with loads these are the pool's host→device TRANSFER count),
    ``demotions``/``evictions`` down the ladder, ``invalidations``
    (fault-dropped entries) and ``degraded_loads`` (probes the
    ``buffer.load`` fault degraded to silent misses).
    """

    def __init__(self, device_bytes: int, host_bytes: int):
        self.device_bytes = int(device_bytes)
        self.host_bytes = int(host_bytes)
        self._lock = threading.Lock()
        self._device: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._host: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._device_nbytes = 0
        self._host_nbytes = 0
        # Per-namespace probe counters (the index-cache view's legacy
        # hits/misses aliases read the "index" slice).
        self._ns: Dict[str, Dict[str, int]] = {}
        self.device_hits = 0
        self.host_hits = 0
        self.misses = 0
        self.admissions = 0
        self.rejections = 0
        self.loads = 0
        self.promotions = 0
        self.demotions = 0
        self.evictions = 0
        self.invalidations = 0
        self.degraded_loads = 0
        self.decode_bytes_saved = 0

    # ------------------------------------------------------------------
    # Lock-held helpers (delegates in the HS301 registry).
    # ------------------------------------------------------------------

    def _bump_ns(self, ns: str, field: str) -> None:
        """Under the lock: bump one per-namespace probe counter."""
        slot = self._ns.get(ns)
        if slot is None:
            slot = {"hits": 0, "misses": 0}
            self._ns[ns] = slot
        slot[field] += 1

    def _drop(self, full: tuple) -> int:
        """Under the lock: remove ``full`` from both tiers; returns the
        dropped byte count (0 if absent)."""
        e = self._device.pop(full, None)
        if e is not None:
            self._device_nbytes -= e.nbytes
            return e.nbytes
        e = self._host.pop(full, None)
        if e is not None:
            self._host_nbytes -= e.nbytes
            return e.nbytes
        return 0

    def _pop_device_victims(self) -> list:
        """Under the lock: pop LRU device entries until the device tier
        fits its budget; returns the (key, entry) victims for the caller
        to demote or drop OUTSIDE the lock."""
        victims = []
        while self._device_nbytes > self.device_bytes \
                and len(self._device) > 1:
            full, e = self._device.popitem(last=False)
            self._device_nbytes -= e.nbytes
            victims.append((full, e))
        return victims

    def _pop_host_victims(self) -> list:
        victims = []
        while self._host_nbytes > self.host_bytes and len(self._host) > 1:
            full, e = self._host.popitem(last=False)
            self._host_nbytes -= e.nbytes
            victims.append((full, e))
        return victims

    # ------------------------------------------------------------------
    # Probe / admit.
    # ------------------------------------------------------------------

    def get(self, pk: PoolKey):
        """The cached payload, or None (a miss — caller re-reads). The
        ``buffer.load`` fault point fires here: an injected (or real)
        load failure drops the entry and reports a silent miss under the
        degrade contract, never a wrong answer."""
        full = (pk.ns,) + tuple(pk.key)
        try:
            _faults.fault_point(_fn.BUFFER_LOAD)
        except Exception:
            if not _faults.degrade_enabled():
                raise
            _faults.note(degraded_buffer_loads=1)
            with self._lock:
                if self._drop(full):
                    self.invalidations += 1
                self.degraded_loads += 1
                self.misses += 1
                self._bump_ns(pk.ns, "misses")
            _note_query(pool_misses=1)
            _emit_event(_miss_event, pk.ns, "fault")
            return None
        promote = None
        with self._lock:
            e = self._device.get(full)
            if e is not None:
                self._device.move_to_end(full)
                self.device_hits += 1
                self.decode_bytes_saved += e.source_bytes
                self._bump_ns(pk.ns, "hits")
                payload, saved, tier = e.payload, e.source_bytes, \
                    TIER_DEVICE
            else:
                e = self._host.get(full)
                if e is None:
                    self.misses += 1
                    self._bump_ns(pk.ns, "misses")
                else:
                    self._host.move_to_end(full)
                    self.host_hits += 1
                    self.decode_bytes_saved += e.source_bytes
                    self._bump_ns(pk.ns, "hits")
                    payload, saved, tier = e.payload, e.source_bytes, \
                        TIER_HOST
                    promote = (full, e)
        if e is None:
            _note_query(pool_misses=1)
            _emit_event(_miss_event, pk.ns, "")
            return None
        if promote is not None:
            payload = self._promote(promote[0], promote[1])
        _note_query(pool_hits=1, pool_bytes_saved=saved)
        _emit_event(_hit_event, pk.ns, tier, e.nbytes)
        return payload

    def _promote(self, full: tuple, e: _Entry):
        """Host-tier hit: re-upload into HBM (ONE batched device_put,
        outside the lock) and move the entry back to the device tier. A
        real upload failure serves the host copy instead — residency is
        an optimization and must never fail the query."""
        try:
            dev_payload = _table_to_device(e.payload)
        except Exception:
            if not _faults.degrade_enabled():
                raise
            _faults.note(degraded_buffer_loads=1)
            with self._lock:
                self.degraded_loads += 1
            return e.payload
        with self._lock:
            cur = self._host.pop(full, None)
            if cur is None:
                # A concurrent clear/evict raced us: serve the promoted
                # table, don't re-admit.
                return dev_payload
            self._host_nbytes -= cur.nbytes
            cur.payload = dev_payload
            self._device[full] = cur
            self._device_nbytes += cur.nbytes
            self.promotions += 1
            victims = self._pop_device_victims()
        self._settle_victims(victims)
        return dev_payload

    def put(self, pk: PoolKey, payload, nbytes: Optional[int] = None,
            device_only: bool = False) -> None:
        """Admit a freshly decoded payload to the device tier (one
        ``load`` = the decode + host→device transfer the admit paid;
        every later hit skips both). Oversized payloads (> device
        budget) are rejected rather than thrashing the LRU."""
        if nbytes is None:
            nbytes = table_nbytes(payload)
        full = (pk.ns,) + tuple(pk.key)
        with self._lock:
            if nbytes > self.device_bytes:
                self.rejections += 1
                return
            self._drop(full)
            self._device[full] = _Entry(payload, nbytes, pk.source_bytes,
                                        device_only)
            self._device_nbytes += nbytes
            self.admissions += 1
            self.loads += 1
            victims = self._pop_device_victims()
        self._settle_victims(victims)

    def _settle_victims(self, victims: list) -> None:
        """Demote device victims to the host tier (drop device-only
        payloads and everything once the host tier is full) — the
        device→host→drop eviction ladder, conversions outside the lock."""
        if not victims:
            return
        dropped = []
        for full, e in victims:
            if e.device_only or self.host_bytes <= 0:
                dropped.append((TIER_DEVICE, e.nbytes, False))
                continue
            try:
                host_payload = _table_to_host(e.payload)
            except Exception:
                if not _faults.degrade_enabled():
                    raise
                dropped.append((TIER_DEVICE, e.nbytes, False))
                continue
            e.payload = host_payload
            with self._lock:
                self._host[full] = e
                self._host_nbytes += e.nbytes
                self.demotions += 1
                host_victims = self._pop_host_victims()
            dropped.append((TIER_DEVICE, e.nbytes, True))
            for _, he in host_victims:
                dropped.append((TIER_HOST, he.nbytes, False))
        with self._lock:
            self.evictions += sum(1 for _, _, dem in dropped if not dem)
        for tier, nb, demoted in dropped:
            _emit_event(_evict_event, tier, nb, demoted)

    # ------------------------------------------------------------------
    # Maintenance / observability.
    # ------------------------------------------------------------------

    def set_budgets(self, device_bytes: int, host_bytes: int) -> None:
        with self._lock:
            self.device_bytes = int(device_bytes)
            self.host_bytes = int(host_bytes)

    def clear(self, ns: Optional[str] = None) -> None:
        """Drop every entry (or one namespace's). Counters survive — a
        clear is maintenance, not history rewriting."""
        with self._lock:
            if ns is None:
                self._device.clear()
                self._host.clear()
                self._device_nbytes = 0
                self._host_nbytes = 0
                return
            for tier, attr in ((self._device, "_device_nbytes"),
                               (self._host, "_host_nbytes")):
                for full in [k for k in tier if k[0] == ns]:
                    e = tier.pop(full)
                    setattr(self, attr, getattr(self, attr) - e.nbytes)

    def ns_counts(self, ns: str) -> Tuple[int, int]:
        """(hits, misses) of one namespace — the index-cache view's
        legacy counter aliases."""
        with self._lock:
            slot = self._ns.get(ns, None)
            if slot is None:
                return 0, 0
            return slot["hits"], slot["misses"]

    def ns_nbytes(self, ns: str) -> int:
        with self._lock:
            return sum(e.nbytes for k, e in self._device.items()
                       if k[0] == ns) + \
                sum(e.nbytes for k, e in self._host.items()
                    if k[0] == ns)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "device_hits": self.device_hits,
                "host_hits": self.host_hits,
                "hits": self.device_hits + self.host_hits,
                "misses": self.misses,
                "admissions": self.admissions,
                "rejections": self.rejections,
                "loads": self.loads,
                "promotions": self.promotions,
                "transfers": self.loads + self.promotions,
                "demotions": self.demotions,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "degraded_loads": self.degraded_loads,
                "decode_bytes_saved": self.decode_bytes_saved,
                "device_entries": len(self._device),
                "host_entries": len(self._host),
                "device_nbytes": self._device_nbytes,
                "host_nbytes": self._host_nbytes,
                "device_bytes": self.device_bytes,
                "host_bytes": self.host_bytes,
                "namespaces": {ns: dict(slot)
                               for ns, slot in self._ns.items()},
            }
        return out

    def reset_stats(self) -> None:
        """Zero the counters (bench A/B phases; entries stay resident)."""
        with self._lock:
            self.device_hits = self.host_hits = self.misses = 0
            self.admissions = self.rejections = 0
            self.loads = self.promotions = self.demotions = 0
            self.evictions = self.invalidations = 0
            self.degraded_loads = self.decode_bytes_saved = 0
            self._ns.clear()


# ---------------------------------------------------------------------------
# Process-wide singleton + conf resolution (config.py only; the executor
# is session-free, so the conf rides the parallel-io session scope).
# ---------------------------------------------------------------------------

_POOL: Optional[BufferPool] = None
_POOL_LOCK = threading.Lock()


def _conf():
    from ..parallel import io as pio
    session = pio.active_session()
    return session.hs_conf if session is not None else None


def enabled() -> bool:
    c = _conf()
    if c is None:
        return True
    return c.buffer_pool_enabled()


def stream_admit_bytes() -> int:
    c = _conf()
    if c is None:
        return _STREAM_ADMIT_BYTES_DEFAULT
    return c.buffer_pool_stream_admit_bytes()


def get_pool() -> BufferPool:
    """THE process pool. Budgets refresh live from the active session's
    conf on every resolution (config.py's live-tuning contract)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = BufferPool(_DEVICE_BYTES_DEFAULT, _HOST_BYTES_DEFAULT)
        pool = _POOL
    c = _conf()
    if c is not None:
        pool.set_budgets(c.buffer_pool_device_bytes(),
                         c.buffer_pool_host_bytes())
    return pool


def pool_stats() -> dict:
    """Snapshot for the ``buffer_pool`` metrics collector and
    ``Hyperspace.buffer_pool_stats()``."""
    return get_pool().stats()


# The pool counters are a named collector in the process metrics
# registry (telemetry/metrics.py): every worker's OpenMetrics scrape
# (and Hyperspace.metrics()) carries them — the fleet-visibility story,
# no cross-process byte shipping.
_metrics.get_registry().register_collector(_mn.COLLECTOR_BUFFER_POOL,
                                           pool_stats)


# ---------------------------------------------------------------------------
# Keys.
# ---------------------------------------------------------------------------

def file_signature(files: Sequence[str]) -> Optional[tuple]:
    """((path, size, mtime), ...) — THE invalidation carrier: any
    append/refresh/optimize/compact changes size/mtime/path, so stale
    entries become unreachable by construction (the result-cache
    source-signature story applied per file). None when any file cannot
    be stat'd — the caller simply skips the pool."""
    from ..index import data_store
    sig = []
    for f in files:
        try:
            store = data_store.store_for_path(f)
            if store is None:
                st = os.stat(f)
                sig.append((str(f), int(st.st_size), int(st.st_mtime_ns)))
            else:
                path, size, mtime = store.file_info(f)
                sig.append((str(path), int(size), int(mtime)))
        except Exception:
            return None
    return tuple(sig)


def _sig_bytes(sig: tuple) -> int:
    return sum(size for _, size, _ in sig)


def scan_key(files: Sequence[str], columns, filters) -> Optional[PoolKey]:
    """Key for one bulk scan read: file signature + column set +
    row-group pruning selection (the pyarrow filter expression IS the
    pruning choice) + the padded-read profile."""
    sig = file_signature(files)
    if sig is None:
        return None
    cols = tuple(columns) if columns is not None else None
    return PoolKey("scan", (sig, cols, repr(filters), "padded"),
                   _sig_bytes(sig))


def stream_key(files: Sequence[str], columns, filters,
               chunk_rows: int) -> Optional[PoolKey]:
    """Key for one chunked filtered scan (iter_dataset_chunks): the
    chunk size participates because the REPLAY must be byte-identical
    chunk-for-chunk, not just row-for-row."""
    sig = file_signature(files)
    if sig is None:
        return None
    cols = tuple(columns) if columns is not None else None
    return PoolKey("stream", (sig, cols, repr(filters), int(chunk_rows)),
                   _sig_bytes(sig))


def index_key(legacy_key: tuple) -> PoolKey:
    """The IndexTableCache view's namespace: index data versions are
    immutable on disk, so the legacy (entry id, name, files, columns)
    tuple stays sufficient — rebuilds produce new file paths."""
    return PoolKey("index", tuple(legacy_key), 0)


def blocks_key(files: Sequence[str], names: Sequence[str], bounds,
               shard_rows: int, mesh_sig) -> Optional[PoolKey]:
    """Key for the SPMD file-aligned scan's per-device sharded blocks:
    file signature + stream array names + file-aligned bounds + padded
    shard rows + mesh signature (a different mesh lays buffers out on
    different devices — never share across meshes)."""
    sig = file_signature(files)
    if sig is None:
        return None
    return PoolKey("blocks", (sig, tuple(names), tuple(bounds),
                              int(shard_rows), tuple(mesh_sig)),
                   _sig_bytes(sig))


# ---------------------------------------------------------------------------
# Attribution + telemetry.
# ---------------------------------------------------------------------------

def _note_query(**deltas) -> None:
    """Per-query attribution: the active QueryContext gets pool probe
    counters (pool_hits / pool_misses / pool_bytes_saved), mirroring the
    parallel-io read attribution — explain's I/O section credits them."""
    from ..serving.context import active_context
    ctx = active_context()
    if ctx is not None:
        ctx.note_io(**deltas)


def _hit_event(ns: str, tier: str, nbytes: int):
    from ..telemetry.events import BufferPoolHitEvent
    return BufferPoolHitEvent(
        message=f"buffer pool hit ({ns}, {tier} tier)",
        namespace=ns, tier=tier, nbytes=nbytes)


def _miss_event(ns: str, reason: str):
    from ..telemetry.events import BufferPoolMissEvent
    return BufferPoolMissEvent(
        message=f"buffer pool miss ({ns})", namespace=ns, reason=reason)


def _evict_event(tier: str, nbytes: int, demoted: bool):
    from ..telemetry.events import BufferPoolEvictEvent
    return BufferPoolEvictEvent(
        message=f"buffer pool {'demotion' if demoted else 'eviction'} "
                f"({tier} tier)",
        tier=tier, nbytes=nbytes, demoted=demoted)


def _emit_event(make, *args) -> None:
    from ..parallel import io as pio
    session = pio.active_session()
    if session is None:
        return
    try:
        from ..telemetry.logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            make(*args))
    except Exception:
        return  # observability must never fail a read
