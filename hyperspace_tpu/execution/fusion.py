"""Whole-plan fusion: ONE XLA program per (region fingerprint, shape class).

The staged executor runs operator-at-a-time: every plan node materializes
a host ``Table``, so a filter→project→join-probe→aggregate chain pays a
separate dispatch (and its host round trip) per stage — the overhead
Flare (PAPERS.md, arxiv 1703.08219) eliminates in Spark by compiling the
whole query instead of stitching per-operator programs. This module is
the single-device counterpart of the SPMD tier's fused mesh programs
(execution/spmd.py): a fusion planner walks the optimized plan, carves
it into maximal fusible regions, and compiles each region into ONE
jitted program registered in the process-wide ProgramBank keyed
``(region fingerprint, shape-class vector)`` — so intermediates never
cross the host ``Table`` boundary and a warm region re-dispatches with
zero compiles.

Region shape (mirroring the SPMD chain grammar)::

    [Aggregate (grouped or global, no COUNT DISTINCT)]
      └─ {Filter, Project, inner/semi/anti single-key equi-Join}*
           └─ Scan | IndexScan | <any barrier subtree, executed staged>

Execution model — mask-based streaming with static shapes (the r07
padding contract): the stream loads once at its length class; filters
AND into a keep mask instead of compacting; joins probe a prepared
(sorted, key-unique for inner) side with a searchsorted and gather its
columns in place; the aggregate sorts kept rows by the group keys inside
the program and segments into capacity-bounded slots. Exactly ONE scalar
leaves the program per execution (the survivor/group count), where the
staged pipeline paid one per stage. Literal values of slot-fusable
predicates ride as runtime scalar arguments (the r07 contract), so a
literal sweep reuses one compiled region.

Byte-identity: the fused program replays the staged operator semantics
step for step — the same stable sorts over the same null-aware keys, the
same segment ops over rows in the same order — so answers are
byte-identical to staged execution (asserted over verbatim TPC-H/TPC-DS
in tests/test_fusion.py). Anything the program does not absorb falls
back per-stage at a named boundary (execution/fusion_boundaries.py,
frozen registry): sorts, windows, outer/cross joins, COUNT DISTINCT,
chunked (over-budget) sources, bucket-ordered streams (the staged
executor owns the covering-index fast paths), and literal-sweep batches.
``hyperspace.tpu.execution.fusion.enabled=false`` restores pure staged
execution.

The fusion attempt runs only where the distributed tier declined — the
mesh keeps right of way — and compiles ONLY through the ProgramBank
(ops/kernels.run_fused_region; scripts/lint.py pins jax.jit sites).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException, QueryDeadlineError
from ..plan import expr as E
from ..plan.nodes import (Aggregate, BucketUnion, Filter, IndexScan, Join,
                          Limit, LogicalPlan, Project, Scan, Sort, Union,
                          Window, infer_dtype)
from ..schema import FLOAT64, INT64, STRING
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from . import fusion_boundaries as FB
from . import shapes
from .columnar import (_DEVICE_DTYPE, Column, Table, dictionaries_equal,
                       translate_codes)
from .evaluator import _pred_eval, eval_expr, predicate_slots

# Fused region executions in this process (tests/bench assert the path is
# actually taken, the spmd.DISPATCH_COUNT convention).
DISPATCH_COUNT = 0

_FUSABLE_AGGS = (E.Count, E.Sum, E.Avg, E.Min, E.Max)


class _FuseFallback(Exception):
    """Runtime bailout on an otherwise fusible region; ``kind`` names the
    boundary (fusion_boundaries registry) and the staged executor re-runs
    the region byte-identically. ``node`` (when the bailout is pinned to
    one plan node — a duplicate-keyed join side, a chunked/bucket-ordered
    leaf) gets marked so the staged descent's sub-region attempts skip
    it instead of repeating its IO/prep per chain node."""

    def __init__(self, kind: str, node: Optional[LogicalPlan] = None):
        super().__init__(kind)
        self.kind = kind
        self.node = node


class _FusionState:
    """Process-wide counters + the poisoned-region memo (a region whose
    fused program failed once stays staged instead of re-failing per
    query). Lives in one object so the module-level mutable-state lint
    gate stays clean."""

    def __init__(self):
        self.lock = threading.Lock()
        self.boundaries: Dict[str, int] = {}
        self.poisoned: Set[tuple] = set()
        self.fused_nodes_total = 0

    def stats(self) -> dict:
        with self.lock:
            return {
                "fused_executions": DISPATCH_COUNT,
                "fused_nodes_total": self.fused_nodes_total,
                "fallbacks": dict(self.boundaries),
                "poisoned_regions": len(self.poisoned),
            }


_STATE = _FusionState()


def _bump(kind: str) -> None:
    with _STATE.lock:
        _STATE.boundaries[kind] = _STATE.boundaries.get(kind, 0) + 1


def note_boundary(kind: str) -> None:
    """Count a region boundary / fallback by kind (frozen registry —
    scripts/lint.py rejects free-form kinds at these call sites)."""
    _bump(kind)


def stats() -> dict:
    return _STATE.stats()


def reset_stats() -> None:
    """Tests only: zero the counters (the poisoned memo survives — a
    broken region stays broken across tests in one process)."""
    global DISPATCH_COUNT
    with _STATE.lock:
        _STATE.boundaries.clear()
        _STATE.fused_nodes_total = 0
        DISPATCH_COUNT = 0


# ---------------------------------------------------------------------------
# Region planning (pure plan-shape analysis; no IO).
# ---------------------------------------------------------------------------

_BARRIER_KINDS = {
    Sort: FB.SORT, Window: FB.WINDOW, Limit: FB.LIMIT, Union: FB.UNION,
    BucketUnion: FB.UNION, Aggregate: FB.AGGREGATE,
}


class _Region:
    """A planned fusible region: ``stages`` bottom-up over ``bottom``
    (a leaf or a staged barrier subtree), optional ``agg`` root."""

    def __init__(self, stages: List[tuple], bottom: LogicalPlan,
                 agg: Optional[Aggregate], root: LogicalPlan):
        self.stages = stages  # bottom-up [("filter"|"project"|"join", ...)]
        self.bottom = bottom
        self.agg = agg
        self.root = root

    @property
    def node_count(self) -> int:
        return len(self.stages) + (1 if self.agg is not None else 0)


def _strip_alias(e: E.Expr) -> E.Expr:
    while isinstance(e, E.Alias):
        e = e.child
    return e


def _normalized_pair(node: Join) -> Optional[Tuple[str, str]]:
    """The single (left, right) equi-join key pair, or None (barrier)."""
    pairs = E.extract_equi_join_keys(node.condition)
    if pairs is None:
        note_boundary(FB.NON_EQUI_JOIN)
        return None
    if len(pairs) != 1:
        note_boundary(FB.MULTI_KEY_JOIN)
        return None
    a, b = pairs[0]
    left_names = set(node.left.schema.names)
    right_names = set(node.right.schema.names)
    if a in left_names and b in right_names:
        return a, b
    if b in left_names and a in right_names:
        return b, a
    note_boundary(FB.NON_EQUI_JOIN)
    return None


def _plan_region(root: LogicalPlan, session) -> Optional[_Region]:
    agg = None
    node = root
    if isinstance(node, Aggregate):
        child_schema = node.child.schema
        for a in node.aggs:
            inner = _strip_alias(a)
            if isinstance(inner, E.CountDistinct):
                note_boundary(FB.COUNT_DISTINCT)
                return None
            if not isinstance(inner, _FUSABLE_AGGS):
                note_boundary(FB.UNSUPPORTED_AGG)
                return None
            # Statically decidable dtype constraints — checked HERE so a
            # doomed region never pays leaf IO / side prep first: string
            # sum/avg is an error either way (staged raises it too), and
            # a STRING min/max output needs a plain-Col child whose
            # dictionary the host can re-attach.
            try:
                if isinstance(inner, (E.Sum, E.Avg)) \
                        and infer_dtype(inner.child, child_schema) \
                        == STRING:
                    note_boundary(FB.UNSUPPORTED_AGG)
                    return None
                if isinstance(inner, (E.Min, E.Max)) \
                        and infer_dtype(inner, child_schema) == STRING \
                        and not isinstance(_strip_alias(inner.child),
                                           E.Col):
                    note_boundary(FB.UNSUPPORTED_AGG)
                    return None
            except HyperspaceException:
                note_boundary(FB.UNSUPPORTED_AGG)
                return None
        agg = node
        node = node.child
    stages_td: List[tuple] = []
    while isinstance(node, (Filter, Project, Join)):
        if isinstance(node, Filter):
            stages_td.append(("filter", node))
            node = node.child
        elif isinstance(node, Project):
            stages_td.append(("project", node))
            node = node.child
        else:
            if getattr(node, "_fusion_skip", None) is not None:
                # This join bailed at runtime before (duplicate probe
                # keys, empty/odd side): stop the chain here — stages
                # ABOVE still fuse over the staged join's output.
                break
            jt = node.join_type
            if jt == "cross":
                note_boundary(FB.CROSS_JOIN)
                break
            if jt in ("left", "right", "full"):
                note_boundary(FB.OUTER_JOIN)
                break
            pair = _normalized_pair(node)
            if pair is None:
                break
            stages_td.append(("join", node, pair))
            node = node.left
    skip = getattr(node, "_fusion_skip", None)
    if skip is not None:
        _bump(skip)  # kinds recorded at the original runtime bailout
        if isinstance(node, (Scan, IndexScan)):
            # A marked LEAF is the stream itself (chunked / bucket
            # order): no region over it can fuse.
            return None
    elif isinstance(node, (Scan, IndexScan)):
        if isinstance(node, IndexScan) and node.use_bucket_spec:
            # Bucket-spec index scans feed the staged shuffle-free merge
            # join / sort-skipping group-by — fast paths the fused program
            # does not replay. Decide statically, before any IO.
            note_boundary(FB.BUCKET_ORDER)
            return None
        note_boundary(FB.LEAF)
    else:
        barrier = _BARRIER_KINDS.get(type(node))
        if barrier is None:
            note_boundary(FB.UNSUPPORTED_EXPR)
        else:
            _bump(barrier)  # kinds from the _BARRIER_KINDS FB.* table
    min_stages = max(2, session.hs_conf.fusion_min_stages())
    region = _Region(list(reversed(stages_td)), node, agg, root)
    if region.node_count < min_stages:
        note_boundary(FB.REGION_TOO_SMALL)
        return None
    return region


def _region_needs(region: _Region, out_names: List[str]):
    """Top-down column-need analysis: the bottom subtree's needed set and
    each join stage's right-side needed set (keys included — the side must
    materialize them to build probe codes)."""
    if region.agg is not None:
        needed: Set[str] = set(region.agg.group_cols)
        for a in region.agg.aggs:
            needed |= set(a.references)
    else:
        needed = set(out_names)
    right_needed: Dict[int, Set[str]] = {}
    for i in range(len(region.stages) - 1, -1, -1):
        st = region.stages[i]
        kind, node = st[0], st[1]
        if kind == "filter":
            needed |= set(node.condition.references)
        elif kind == "project":
            # Mirror the staged executor: EVERY project expr evaluates
            # (XLA dead-code-eliminates unconsumed outputs for free).
            below: Set[str] = set()
            for e in node.exprs:
                below |= set(e.references)
            needed = below
        else:
            lname, rname = st[2]
            if node.join_type in ("semi", "anti"):
                right_needed[i] = {rname}
                needed = needed | {lname}
            else:
                rnames = set(node.right.schema.names)
                right_needed[i] = {n for n in needed if n in rnames} | {rname}
                needed = {n for n in needed if n not in rnames} | {lname}
    return needed, right_needed


# ---------------------------------------------------------------------------
# Runtime prep: leaf load, join-side preparation, fingerprint + args.
# ---------------------------------------------------------------------------

def _leaf_within_budget(leaf, session) -> bool:
    """Mirror of spmd._leaf_within_budget: a leaf past the chunk budget
    belongs to the streaming (chunked) staged path, never to a program
    that materializes it whole."""
    from .columnar import parquet_row_counts
    try:
        if isinstance(leaf, IndexScan):
            total = sum(parquet_row_counts(
                list(leaf.index_entry.content.files)
                + list(leaf.appended_files)))
        else:
            relation = leaf.relation
            fmt = getattr(relation, "data_file_format", relation.file_format)
            if fmt != "parquet":
                return True
            total = sum(parquet_row_counts(relation.all_files()))
    except Exception:
        return True
    return total <= session.hs_conf.max_chunk_rows()


def _load_leaf(leaf, lead_filters, needed, ex) -> Table:
    """Materialize the stream leaf with the same IO pruning the staged
    Filter-over-leaf branch applies: filter stages sitting directly above
    the leaf push their row-group-prunable conjuncts into the read (the
    full mask re-applies on device, so the pruned read is byte-identical).
    The spmd._load_leaf contract, single-device."""
    conds = [n.condition for n in lead_filters]
    if conds:
        from .pushdown import pruned_index_read_filter, pushable_filter
        combined = conds[0]
        for c in conds[1:]:
            combined = E.And(combined, c)
        if isinstance(leaf, IndexScan):
            pa_filter = pruned_index_read_filter(
                leaf.index_entry, combined, leaf.schema)
            if pa_filter is not None:
                table = ex._execute_index_scan(
                    leaf, needed, pa_filter, prefer_pruned_read=True)
                if table.num_rows > 0:
                    return table
        else:
            pa_filter = pushable_filter(combined, leaf.schema,
                                        allow_nested=False)
            if pa_filter is not None:
                table = ex._execute_scan(leaf, needed, pa_filter)
                if table.num_rows > 0:
                    return table
    return ex._execute(leaf, needed)


def _dict_fp(dic: Optional[np.ndarray]):
    """Dictionary content fingerprint (spmd._dict_fingerprint precedent:
    dictionaries become trace-time constants — literal bounds, translate
    tables — so they key programs by VALUE)."""
    if dic is None:
        return None
    return tuple(dic.tolist())


def _empty_device(dtype) -> jax.Array:
    """Zero-length device array WITHOUT a compile: jnp.zeros lowers a
    one-off broadcast_in_dim/convert program per dtype (the first thing a
    cold boot would pay for), device_put of a host array is a transfer."""
    return jax.device_put(np.zeros(0, dtype))


def _tiny(meta: Dict[str, Tuple[str, Optional[np.ndarray], bool]]
          ) -> Dict[str, Column]:
    """Zero-length columns carrying (dtype, dictionary, nullability) —
    the metadata-propagation trick the SPMD prep walk uses."""
    return {n: Column(dt, _empty_device(_DEVICE_DTYPE[dt]),
                      _empty_device(np.bool_) if nul else None, dic)
            for n, (dt, dic, nul) in meta.items()}


def _meta_of(table_or_cols) -> Dict[str, Tuple]:
    cols = table_or_cols.columns if isinstance(table_or_cols, Table) \
        else table_or_cols
    return {n: (c.dtype, c.dictionary, c.validity is not None)
            for n, c in cols.items()}


def _dtype_max_np(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return np.inf
    if dtype == jnp.bool_:
        return True
    return jnp.iinfo(dtype).max


class _SidePrep:
    """A prepared join side: ``keys`` ascending (class-padded with the
    dtype max so the searchsorted precondition holds over the pad tail),
    ``cols`` row-aligned data columns (inner joins only), ``n`` the valid
    key count. Inner sides are key-unique (checked, one host sync)."""

    def __init__(self, keys, n: int, col_order: List[str],
                 cols: Dict[str, Column]):
        self.keys = keys
        self.n = n
        self.col_order = col_order
        self.cols = cols


def _prepare_side(node: Join, pair, tiny: Dict[str, Column],
                  right_needed: Set[str], ex) -> Tuple[_SidePrep, tuple]:
    """Execute + key-sort one join side; returns (prep, descriptor)."""
    from ..ops import kernels
    lname, rname = pair
    jt = node.join_type
    keys_only = jt in ("semi", "anti")
    right = ex._execute(node.right, set(right_needed)).compact()
    if right.num_rows == 0:
        raise _FuseFallback(FB.EMPTY_INPUT, node)
    rk = right.column(rname)
    lcol = tiny[lname]
    if (lcol.dtype == STRING) != (rk.dtype == STRING):
        raise _FuseFallback(FB.KEY_DTYPE, node)
    if rk.validity is not None:
        # Inner/semi/anti: null side keys never match — drop them up
        # front, exactly like the staged join paths.
        right = right.filter(rk.validity)
        if right.num_rows == 0:
            raise _FuseFallback(FB.EMPTY_INPUT, node)
        rk = right.column(rname)
    if rk.dtype == STRING:
        codes = rk.data if dictionaries_equal(lcol.dictionary, rk.dictionary) \
            else translate_codes(lcol.dictionary, rk)
        promo = jnp.int32
    else:
        try:
            promo = jnp.promote_types(_DEVICE_DTYPE[lcol.dtype],
                                      rk.data.dtype)
        except TypeError:
            raise _FuseFallback(FB.KEY_DTYPE, node)
        if not (jnp.issubdtype(promo, jnp.integer)
                or jnp.issubdtype(promo, jnp.floating)):
            raise _FuseFallback(FB.KEY_DTYPE, node)
        codes = rk.data if rk.data.dtype == promo \
            else kernels.cast_array(rk.data, promo)
    order = kernels.lex_sort_indices([codes], pad=False)
    codes = kernels.gather_arrays(order, (codes,))[0]
    n_side = int(codes.shape[0])
    if jt == "inner" and n_side > 1 \
            and bool(kernels.has_adjacent_duplicates(codes)):  # HOST SYNC
        # m:n join: the mask-streaming program cannot expand matches —
        # the staged merge join owns it.
        raise _FuseFallback(FB.DUPLICATE_PROBE_KEYS, node)
    cls = shapes.padded_length(n_side)
    keys = shapes.pad_to(codes, cls, fill=_dtype_max_np(codes.dtype))
    cols: Dict[str, Column] = {}
    col_order: List[str] = []
    if not keys_only:
        right = right.take(order)
        for n in right.names:
            c = right.column(n)
            data = shapes.pad_to(c.data, cls)
            validity = None if c.validity is None \
                else shapes.pad_to(c.validity, cls, fill=False)
            cols[n] = Column(c.dtype, data, validity, c.dictionary)
            col_order.append(n)
    descr = ("J", jt, lname, rname, str(keys.dtype),
             tuple((n, c.dtype, _dict_fp(c.dictionary),
                    c.validity is not None)
                   for n, c in cols.items()))
    return _SidePrep(keys, n_side, col_order, cols), descr


class _RegionSpec:
    """Everything the traced builder needs, fully determined by ``key``:
    bottom-up stage program, stream column metadata/order, side layouts,
    aggregate description, output names."""

    def __init__(self, stages, col_order, col_meta, out_names, agg,
                 group_cols, key):
        self.stages = stages        # bottom-up builder stage tuples
        self.col_order = col_order  # stream column name order
        self.col_meta = col_meta    # name -> (dtype, dict, nullable)
        self.out_names = out_names
        self.agg = agg              # Aggregate node or None
        self.group_cols = group_cols
        self.key = key


# ---------------------------------------------------------------------------
# The traced program body (runs under ONE jax.jit via the ProgramBank).
# ---------------------------------------------------------------------------

def _null_aware(c: Column) -> List:
    """executor._null_aware_keys, inlined (nulls sort first)."""
    if c.validity is None:
        return [c.data]
    return [c.validity.astype(jnp.int32),
            jnp.where(c.validity, c.data, jnp.zeros((), c.data.dtype))]


def _sum_out_dtype(sums) -> str:
    return FLOAT64 if jnp.issubdtype(sums.dtype, jnp.floating) else INT64


def _sentinel(dtype, maxval: bool):
    if jnp.issubdtype(dtype, jnp.floating):
        info = jnp.finfo(dtype)
    else:
        info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if maxval else info.min, dtype)


def _traced_agg(agg_expr: E.Expr, stable: Table, gids, num_segments: int
                ) -> Column:
    """Mirror of executor._eval_agg over traced inputs: identical
    widening, null-sentinel substitution, valid counting, and mean
    division — and identical per-segment accumulation ORDER (rows arrive
    group-sorted, non-routed rows park at an out-of-range id), so sums
    are bitwise equal to the staged path's."""
    import jax

    agg = _strip_alias(agg_expr)
    if isinstance(agg, E.Count):
        if agg.child is None:
            ones = jnp.ones(gids.shape[0], jnp.int64)
        else:
            c = eval_expr(stable, agg.child)
            ones = jnp.ones(gids.shape[0], jnp.int64) if c.validity is None \
                else c.validity.astype(jnp.int64)
        return Column(INT64, jax.ops.segment_sum(
            ones, gids, num_segments=num_segments))
    child = eval_expr(stable, agg.child)
    validity = child.validity
    counts = None
    if validity is not None or isinstance(agg, E.Avg):
        ones = jnp.ones(gids.shape[0], jnp.int64) if validity is None \
            else validity.astype(jnp.int64)
        counts = jax.ops.segment_sum(ones, gids, num_segments=num_segments)
    out_validity = (counts > 0) if validity is not None else None
    if isinstance(agg, (E.Sum, E.Avg)):
        acc = child.data.astype(jnp.float64) \
            if jnp.issubdtype(child.data.dtype, jnp.floating) \
            else child.data.astype(jnp.int64)
        if validity is not None:
            acc = jnp.where(validity, acc, jnp.zeros((), acc.dtype))
        sums = jax.ops.segment_sum(acc, gids, num_segments=num_segments)
        if isinstance(agg, E.Sum):
            return Column(_sum_out_dtype(sums), sums, out_validity)
        return Column(FLOAT64,
                      sums.astype(jnp.float64)
                      / jnp.maximum(counts, 1).astype(jnp.float64),
                      out_validity)
    is_min = isinstance(agg, E.Min)
    data = child.data
    if validity is not None:
        data = jnp.where(validity, data, _sentinel(data.dtype, is_min))
    fn = jax.ops.segment_min if is_min else jax.ops.segment_max
    return Column(child.dtype,
                  fn(data, gids, num_segments=num_segments),
                  out_validity, child.dictionary)


def _make_builder(spec: _RegionSpec):
    """The fused program body. Pure function of ``spec`` (== the bank
    key), as the ProgramBank contract requires."""

    def run(args):
        import jax

        n, col_arrays, lit_stages, sides = args
        cols: Dict[str, Column] = {}
        for name, (data, validity) in zip(spec.col_order, col_arrays):
            dt, dic, _nul = spec.col_meta[name]
            cols[name] = Column(dt, data, validity, dic)
        phys = int(col_arrays[0][0].shape[0])
        iota = jnp.arange(phys, dtype=jnp.int32)
        keep = iota < n
        out: Dict[str, jnp.ndarray] = {}
        lit_i = 0
        side_i = 0
        for st in spec.stages:
            kind = st[0]
            if kind == "fslot":
                _, refs, pspec = st
                pcols = tuple((cols[nm].data, cols[nm].validity)
                              for nm in refs)
                data, validity = _pred_eval(pspec, pcols,
                                            lit_stages[lit_i])
                lit_i += 1
                mask = data if validity is None else (data & validity)
                keep = keep & mask
            elif kind == "frepr":
                _, cond = st
                c = eval_expr(Table(dict(cols)), cond)
                mask = c.data if c.validity is None \
                    else (c.data & c.validity)
                keep = keep & mask
            elif kind == "project":
                _, node = st
                t = Table(dict(cols))
                cols = {e.name: eval_expr(t, e) for e in node.exprs}
            else:  # join
                _, node, pair, jid, side_meta = st
                lname, _rname = pair
                keys, n_side, side_arrays = sides[side_i]
                side_i += 1
                lc = cols[lname]
                lk = lc.data if lc.dtype == STRING \
                    else lc.data.astype(keys.dtype)
                lvalid = lc.validity
                lo = jnp.minimum(jnp.searchsorted(keys, lk, side="left"),
                                 n_side)
                hi = jnp.minimum(jnp.searchsorted(keys, lk, side="right"),
                                 n_side)
                matched = lo < hi
                if lvalid is not None:
                    matched = matched & lvalid
                if node.join_type == "inner":
                    keep = keep & matched
                    pos = jnp.clip(lo, 0, keys.shape[0] - 1).astype(jnp.int32)
                    for (sname, sdt, sdic, snul), (sdata, svalid) in zip(
                            side_meta, side_arrays):
                        data = jnp.take(sdata, pos, axis=0, mode="clip")
                        validity = None if svalid is None else \
                            jnp.take(svalid, pos, axis=0, mode="clip")
                        cols[sname] = Column(sdt, data, validity, sdic)
                    # Observed join output rows (the staged path's
                    # _record_join_actual feed): kept-so-far ∧ matched.
                    out[f"jrows:{jid}"] = jnp.sum(keep.astype(jnp.int64))
                elif node.join_type == "semi":
                    keep = keep & matched
                else:  # anti: null left keys never match -> kept
                    keep = keep & ~matched

        if spec.agg is None:
            out["mask"] = keep
            out["count"] = jnp.sum(keep)
            for nm in spec.out_names:
                c = cols[nm]
                out[f"o:{nm}"] = c.data
                if c.validity is not None:
                    out[f"ov:{nm}"] = c.validity
            return out

        if not spec.group_cols:
            # Global aggregate: one segment, non-kept rows parked at the
            # dropped out-of-range id (executor._execute_global_aggregate
            # over a class-padded table, with the filter mask folded in).
            gids = jnp.where(keep, jnp.int32(0), jnp.int32(phys))
            stable = Table(dict(cols))
            for a in spec.agg.aggs:
                col = _traced_agg(a, stable, gids, 1)
                out[f"a:{a.name}"] = col.data
                if col.validity is not None:
                    out[f"av:{a.name}"] = col.validity
            out["ng"] = jnp.int32(1)
            return out

        # Grouped aggregate: stable-sort kept rows by the null-aware group
        # keys (non-kept rows last via the leading ~keep key — the valid
        # prefix is byte-identical to the staged sort of the compacted
        # survivors), then segment into capacity-`phys` slots.
        from ..ops import kernels
        key_cols = [cols[g] for g in spec.group_cols]
        sort_keys = [(~keep).astype(jnp.int32)]
        for c in key_cols:
            sort_keys.extend(_null_aware(c))
        order = kernels.lex_sort_indices(sort_keys)
        keep_s = jnp.take(keep, order)
        scols = {nm: Column(c.dtype,
                            jnp.take(c.data, order, axis=0, mode="clip"),
                            None if c.validity is None
                            else jnp.take(c.validity, order, axis=0,
                                          mode="clip"),
                            c.dictionary)
                 for nm, c in cols.items()}
        skeys = []
        for g in spec.group_cols:
            skeys.extend(_null_aware(scols[g]))
        change = jnp.zeros(phys, jnp.bool_)
        for k in skeys:
            change = change | jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), k[1:] != k[:-1]])
        change = change & keep_s
        gids = jnp.cumsum(change.astype(jnp.int32))
        last = jnp.max(jnp.where(keep_s, gids, 0))
        ng = jnp.where(jnp.any(keep_s), last + 1, 0).astype(jnp.int32)
        gids = jnp.where(keep_s, gids, jnp.int32(phys))
        out["ng"] = ng
        import jax
        firsts = jax.ops.segment_min(iota, gids, num_segments=phys)
        for g in spec.group_cols:
            c = scols[g]
            out[f"g:{g}"] = jnp.take(c.data, firsts, axis=0, mode="clip")
            if c.validity is not None:
                out[f"gv:{g}"] = jnp.take(c.validity, firsts, axis=0,
                                          mode="clip")
        stable = Table(dict(scols))
        for a in spec.agg.aggs:
            col = _traced_agg(a, stable, gids, phys)
            out[f"a:{a.name}"] = col.data
            if col.validity is not None:
                out[f"av:{a.name}"] = col.validity
        return out

    return run


# ---------------------------------------------------------------------------
# Entry point.
# ---------------------------------------------------------------------------

def try_execute(plan: LogicalPlan, needed: Optional[Set[str]]
                ) -> Optional[Table]:
    """Fuse-and-execute the maximal region rooted at ``plan``, or return
    None for the staged executor. Called from executor._execute for chain
    roots and from the Aggregate branch AFTER the SPMD attempt (the
    distributed tier keeps right of way)."""
    from . import executor as ex
    session = ex._SESSION.get()
    if session is None:
        return None
    if not session.hs_conf.fusion_enabled():
        note_boundary(FB.DISABLED)
        return None
    from ..serving import batcher
    if batcher.active_sweep() is not None:
        # Literal-sweep batches collapse members into ONE vmapped staged
        # invocation over shared scans — their win, their path.
        note_boundary(FB.SWEEP)
        return None
    region = _plan_region(plan, session)
    if region is None:
        return None
    try:
        return _execute_region(region, needed, session, ex)
    except _FuseFallback as f:
        _bump(f.kind)
        if f.node is not None:
            # Data-dependent bailout (duplicate probe keys, bucket
            # order, chunked source, ...): mark the responsible plan
            # node so the staged descent's sub-region attempts skip it
            # instead of repeating the leaf IO / side prep per chain
            # node. A pure perf hint — at worst (plan object memoized
            # across a data change) a now-fusible region stays staged.
            f.node._fusion_skip = f.kind
        return None
    except QueryDeadlineError:
        raise
    except Exception as e:
        from ..adaptive.feedback import ReplanRequested
        if isinstance(e, ReplanRequested):
            # Adaptive re-plan (staged joins can execute UNDER a fused
            # region via _execute_region's staged-bottom descent): a
            # control transfer to Session._execute_uncaptured, never a
            # fused-program failure to absorb.
            raise
        # A fused trace/compile failure must never fail the query: the
        # staged path re-runs the region byte-identically, and the region
        # key is poisoned so the failure is paid once, not per query —
        # UNLESS degradation is off (robustness.degrade.enabled=false,
        # the r14 fail-loud debugging contract): then the error surfaces.
        if not session.hs_conf.robustness_degrade_enabled():
            raise
        note_boundary(FB.FUSED_PROGRAM_ERROR)
        return None


def _execute_region(region: _Region, needed: Optional[Set[str]],
                    session, ex) -> Optional[Table]:
    root = region.root
    if region.agg is not None:
        out_names = list(region.agg.schema.names)
    else:
        out_names = [n for n in root.schema.names
                     if needed is None or n in needed] \
            or [root.schema.names[0]]
    bottom_needed, right_needed = _region_needs(region, out_names)

    # ---- stream ----------------------------------------------------------
    bottom = region.bottom
    if isinstance(bottom, (Scan, IndexScan)):
        if not _leaf_within_budget(bottom, session):
            raise _FuseFallback(FB.CHUNKED_SOURCE, bottom)
        lead_filters = []
        for st in region.stages:
            if st[0] != "filter":
                break
            lead_filters.append(st[1])
        stream = _load_leaf(bottom, lead_filters, bottom_needed, ex)
    else:
        stream = ex._execute(bottom, bottom_needed)
    if stream.bucket_order is not None:
        # The staged executor owns the covering-index fast paths (merge
        # join without sort, sort-skipping group-by) — and their output
        # row order.
        raise _FuseFallback(FB.BUCKET_ORDER, bottom)
    if stream.num_rows == 0 or stream.data_rows == 0 or not stream.columns:
        raise _FuseFallback(FB.EMPTY_INPUT)

    # ---- per-stage prep: metadata walk, slots, sides, fingerprint --------
    col_order = list(stream.names)
    tiny = _tiny(_meta_of(stream))
    builder_stages: List[tuple] = []
    descr: List[tuple] = []
    lit_values: List[tuple] = []
    side_preps: List[_SidePrep] = []
    from ..exceptions import HyperspaceException
    jid = 0
    try:
        for stage_i, st in enumerate(region.stages):
            kind, node = st[0], st[1]
            if kind == "filter":
                slots = predicate_slots(Table(tiny), node.condition)
                if slots is not None:
                    pspec, lits = slots
                    refs = tuple(sorted(set(node.condition.references)))
                    builder_stages.append(("fslot", refs, pspec))
                    descr.append(("F", refs, pspec))
                    lit_values.append(tuple(lits))
                else:
                    builder_stages.append(("frepr", node.condition))
                    descr.append(("F!", repr(node.condition)))
            elif kind == "project":
                t = Table(tiny)
                tiny = {e.name: eval_expr(t, e) for e in node.exprs}
                builder_stages.append(("project", node))
                descr.append(("P", tuple(repr(e) for e in node.exprs)))
            else:
                pair = st[2]
                prep, side_descr = _prepare_side(
                    node, pair, tiny, right_needed[stage_i], ex)
                side_meta = tuple(
                    (n, prep.cols[n].dtype, prep.cols[n].dictionary,
                     prep.cols[n].validity is not None)
                    for n in prep.col_order)
                builder_stages.append(("join", node, pair, jid, side_meta))
                descr.append(side_descr)
                side_preps.append(prep)
                jid += 1
                for n in prep.col_order:
                    c = prep.cols[n]
                    tiny[n] = Column(
                        c.dtype, _empty_device(_DEVICE_DTYPE[c.dtype]),
                        _empty_device(np.bool_)
                        if c.validity is not None else None,
                        c.dictionary)
        if region.agg is not None:
            # (Aggregate dtype constraints were checked statically in
            # _plan_region, before any IO.)
            descr.append(("A", tuple(region.agg.group_cols),
                          tuple((a.name, repr(a))
                                for a in region.agg.aggs)))
            for g in region.agg.group_cols:
                if g not in tiny:
                    raise _FuseFallback(FB.UNSUPPORTED_EXPR)
        else:
            for nm in out_names:
                if nm not in tiny:
                    raise _FuseFallback(FB.UNSUPPORTED_EXPR)
    except QueryDeadlineError:
        raise  # a cancellation is never a fallback (the r14 contract)
    except (HyperspaceException, KeyError):
        # Metadata walk hit an expression shape the evaluator rejects
        # (or a column the prep cannot see) — staged handles it.
        raise _FuseFallback(FB.UNSUPPORTED_EXPR)

    stream_meta = _meta_of(stream)
    key = ("region",
           tuple(descr),
           tuple((n,) + (stream_meta[n][0], _dict_fp(stream_meta[n][1]),
                         stream_meta[n][2])
                 for n in col_order),
           tuple(out_names))
    with _STATE.lock:
        poisoned = key in _STATE.poisoned
    if poisoned:
        raise _FuseFallback(FB.FUSED_PROGRAM_ERROR)

    spec = _RegionSpec(builder_stages, col_order, stream_meta, out_names,
                       region.agg, tuple(region.agg.group_cols)
                       if region.agg is not None else (), key)
    col_arrays = tuple((stream.columns[n].data, stream.columns[n].validity)
                       for n in col_order)
    sides = tuple((p.keys, p.n,
                   tuple((p.cols[n].data, p.cols[n].validity)
                         for n in p.col_order))
                  for p in side_preps)
    args = (stream.num_rows, col_arrays, tuple(lit_values), sides)
    shape_vec = ((int(stream.data_rows),)
                 + tuple(int(p.keys.shape[0]) for p in side_preps))

    final_meta = _meta_of(tiny)
    if _trace.idle():
        return _run_program(region, spec, key, shape_vec, args, final_meta,
                            session)
    with _trace.span(SN.EXEC_FUSED, fused_nodes=region.node_count,
                     root=root.node_name) as sp:
        table = _run_program(region, spec, key, shape_vec, args,
                             final_meta, session)
        if sp is not None:
            sp.attrs["rows"] = int(table.num_rows)
        return table


def _run_program(region: _Region, spec: _RegionSpec, key, shape_vec, args,
                 final_meta, session) -> Table:
    from ..ops import kernels
    global DISPATCH_COUNT
    try:
        out = kernels.run_fused_region(key, shape_vec,
                                       lambda: _make_builder(spec), args)
    except Exception as e:
        # Poison only genuine program defects (trace/compile errors that
        # would re-fail every query). Transient errors — OSError/timeout
        # and the robustness layer's injected faults (which surface here
        # through the bank's compile fault point) — must NOT permanently
        # demote the region to staged.
        from ..robustness.faults import InjectedFaultError
        if not isinstance(e, (OSError, TimeoutError, InjectedFaultError,
                              QueryDeadlineError)):
            with _STATE.lock:
                _STATE.poisoned.add(key)
        raise
    with _STATE.lock:
        # Under the state lock with the other fusion counters: fused
        # regions dispatch from concurrent serving workers, and an
        # unguarded += loses updates (HS302).
        DISPATCH_COUNT += 1
        _STATE.fused_nodes_total += region.node_count
    _record_actuals(region, out, session)
    if region.agg is None:
        return _finish_chain(spec, out, final_meta)
    if not spec.group_cols:
        return _finish_global(region.agg, out, final_meta)
    return _finish_grouped(region.agg, spec, out, final_meta)


def _record_actuals(region: _Region, out, session) -> None:
    """Per-join observed output rows into the r10/r13 actuals store, so
    the join-reorder q-error loop keeps learning from fused executions."""
    from ..serving import context as qctx
    jid = 0
    for st in region.stages:
        if st[0] != "join":
            continue
        node = st[1]
        rows_key = f"jrows:{jid}"
        jid += 1
        if node.join_type != "inner" or node.condition is None \
                or rows_key not in out:
            continue
        rows = int(out[rows_key])  # HOST SYNC (single scalar)
        key = qctx.join_actual_key(node.condition, node.left, node.right)
        ctx = qctx.active_context()
        if ctx is not None:
            ctx.record_join_actual(key, rows)
        elif session is not None:
            qctx.record_join_actual(session, key, rows)


def _finish_chain(spec: _RegionSpec, out, final_meta) -> Table:
    """Compact the masked stream exactly like the staged filter output:
    survivor count (the ONE scalar sync), class-padded gather indices,
    one fused gather."""
    from ..ops import kernels
    m = int(out["count"])  # HOST SYNC (single scalar)
    cls = shapes.padded_length(m)
    idx = kernels.nonzero_pad_indices(out["mask"], cls)
    cols = {}
    for nm in spec.out_names:
        dt, dic, _nul = final_meta[nm]
        cols[nm] = Column(dt, out[f"o:{nm}"], out.get(f"ov:{nm}"), dic)
    return Table(cols).take(idx, valid_rows=m if cls != m else None)


def _agg_out_dict(agg_expr, final_meta):
    """The dictionary a STRING min/max output carries: its plain-Col
    child's (prep guaranteed the child IS a plain column)."""
    inner = _strip_alias(agg_expr)
    ref = _strip_alias(inner.child)
    if isinstance(ref, E.Col) and ref.column in final_meta:
        return final_meta[ref.column][1]
    return None


def _finish_global(agg: Aggregate, out, final_meta) -> Table:
    cols = {}
    for a in agg.aggs:
        f = agg.schema.field(a.name)
        dic = _agg_out_dict(a, final_meta) if f.dtype == STRING else None
        cols[a.name] = Column(f.dtype, out[f"a:{a.name}"],
                              out.get(f"av:{a.name}"), dic)
    return Table(cols)


def _finish_grouped(agg: Aggregate, spec: _RegionSpec, out,
                    final_meta) -> Table:
    ng = int(out["ng"])  # HOST SYNC (single scalar)
    if ng == 0:
        # Mirror executor._execute_aggregate's empty-result construction.
        cols = {}
        for f in agg.schema.fields:
            dt = f.dtype
            dic = None
            if f.name in final_meta and final_meta[f.name][0] == STRING:
                dic = final_meta[f.name][1]
            cols[f.name] = Column(
                dt, _empty_device(_DEVICE_DTYPE[dt]), None, dic)
        return Table(cols)
    cls = shapes.padded_length(ng)
    out_valid = ng if cls != ng else None
    from ..ops import kernels

    def fit(arr):
        if int(arr.shape[0]) == cls:
            return arr
        if int(arr.shape[0]) > cls:
            return kernels.slice_arrays((arr,), 0, cls)[0]
        return shapes.pad_to(arr, cls)

    cols = {}
    for g in spec.group_cols:
        dt, dic, _nul = final_meta[g]
        validity = out.get(f"gv:{g}")
        cols[g] = Column(dt, fit(out[f"g:{g}"]),
                         None if validity is None else fit(validity), dic)
    for a in agg.aggs:
        f = agg.schema.field(a.name)
        dic = _agg_out_dict(a, final_meta) if f.dtype == STRING else None
        validity = out.get(f"av:{a.name}")
        cols[a.name] = Column(f.dtype, fit(out[f"a:{a.name}"]),
                              None if validity is None else fit(validity),
                              dic)
    return Table(cols, valid_rows=out_valid)


# The fusion layer's counters are a named collector in the process metrics
# registry (telemetry/metrics.py), the program-bank precedent.
from ..telemetry import metrics as _metrics  # noqa: E402

_metrics.get_registry().register_collector("fusion", stats)
