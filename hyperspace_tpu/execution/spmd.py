"""SPMD distributed query execution over the device mesh.

The query-side product path for multi-chip execution (the build side is
parallel/distributed_build.py). The reference runs *every* plan distributed
because Spark is its engine; here eligible aggregation plans run SPMD over a
1-D mesh with XLA collectives (psum/pmin/pmax over ICI), and everything else
falls back to the single-device executor.

Supported plan shape (checked structurally; any mismatch → fallback):

    Aggregate[global or grouped]
      └─ chain of {Filter, Project, Join(broadcast m:1)}*
           └─ Scan | IndexScan                      ← the sharded stream

Execution model — mask-based streaming, never row compaction:

- The leaf table is loaded once and row-sharded over the mesh
  (``pad_and_shard``); a boolean *keep mask* rides along instead of
  physically filtering, so every shape stays static under ``shard_map``.
- Filters AND into the mask; Projects re-evaluate columns (the expression
  evaluator is shape-preserving and traces cleanly per device).
- Joins execute broadcast-style — the analogue of the reference's broadcast
  join (SURVEY §2 distributed primitive 4): the non-stream side is
  materialized by the normal executor, required to be unique on the join
  key (m:1, the star-schema/foreign-key case), key-sorted, replicated to
  every device, and probed with a per-device searchsorted; unmatched rows
  just clear the mask. Many-to-many joins fall back.
- Global aggregates psum/pmin/pmax partial contributions (one collective
  per partial).
- Grouped aggregates compute capacity-bounded per-device partials (local
  sort → segment ops into ``G`` slots) and merge them on host — the
  two-phase partial-aggregation pattern Spark applies to group-by, with
  the host merge standing in for the final shuffle (valid whenever group
  cardinality ≪ row count; capacity overflow falls back).

Null semantics match the single-device executor: filters keep
true-and-valid rows, inner-join null keys never match, aggregates skip
invalid values, and nullable group keys treat null as its own group
(null-first in the output order, the same encoding the single-device
path uses — executor._null_aware_keys).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import kernels
from ..parallel.mesh import DATA_AXIS, make_mesh, pad_and_shard
from ..plan import expr as E
from ..plan.nodes import (Aggregate, Filter, IndexScan, Join, LogicalPlan,
                          Project, Scan)
from ..schema import BOOL, DATE, FLOAT64, INT32, INT64, STRING
from .columnar import Column, Table, dictionaries_equal, translate_codes
from .evaluator import eval_expr, eval_predicate_mask

# Max distinct groups per device shard for grouped aggregation. Beyond this
# the SPMD path falls back (correctness first; a group count comparable to
# the row count has no partial-aggregation win anyway).
MAX_LOCAL_GROUPS = 1 << 16

# Successful SPMD executions in this process (tests / dryrun assert the
# path is actually taken).
DISPATCH_COUNT = 0


class _Unsupported(Exception):
    """Plan/dtype/shape not handled by the SPMD path — fall back."""


_DEVICE_DTYPE = {INT32: jnp.int32, INT64: jnp.int64, "float32": jnp.float32,
                 FLOAT64: jnp.float64, BOOL: jnp.bool_, DATE: jnp.int32,
                 STRING: jnp.int32}


# ---------------------------------------------------------------------------
# Plan linearization + column-need analysis.
# ---------------------------------------------------------------------------

def _linearize(plan: LogicalPlan):
    """Split the subtree under Aggregate into (leaf, bottom-up stage list).
    The sharded stream side of a Join is its *left* child (fact table
    left, dimension right — the DataFrame API convention)."""
    stages: List[Tuple[str, LogicalPlan]] = []
    node = plan
    while True:
        if isinstance(node, (Scan, IndexScan)):
            return node, list(reversed(stages))
        if isinstance(node, Filter):
            stages.append(("filter", node))
            node = node.child
        elif isinstance(node, Project):
            stages.append(("project", node))
            node = node.child
        elif isinstance(node, Join):
            stages.append(("join", node))
            node = node.left
        else:
            raise _Unsupported(node.node_name)


def _normalized_join_pairs(join: Join) -> List[Tuple[str, str]]:
    pairs = E.extract_equi_join_keys(join.condition)
    if pairs is None:
        raise _Unsupported("non-equi join")
    left_names = set(join.left.schema.names)
    right_names = set(join.right.schema.names)
    norm = []
    for a, b in pairs:
        if a in left_names and b in right_names:
            norm.append((a, b))
        elif b in left_names and a in right_names:
            norm.append((b, a))
        else:
            raise _Unsupported("join keys do not split across sides")
    return norm


def _needed_per_stage(agg: Aggregate, stages):
    """Top-down walk computing the leaf's needed column set and, per join
    stage index, the broadcast side's needed set."""
    needed: Set[str] = set(agg.group_cols)
    for a in agg.aggs:
        needed |= set(a.references)
    right_needed: Dict[int, Set[str]] = {}
    for i in range(len(stages) - 1, -1, -1):
        kind, node = stages[i]
        if kind == "filter":
            needed = needed | set(node.condition.references)
        elif kind == "project":
            below: Set[str] = set()
            for e in node.exprs:
                if e.name in needed:
                    below |= set(e.references)
            needed = below
        else:  # join
            pairs = _normalized_join_pairs(node)
            rnames = set(node.right.schema.names)
            right_needed[i] = {n for n in needed if n in rnames} | \
                {r for _, r in pairs}
            needed = {n for n in needed if n not in rnames} | \
                {l for l, _ in pairs}
    return needed, right_needed


# ---------------------------------------------------------------------------
# Broadcast join side (prepared outside shard_map, replicated).
# ---------------------------------------------------------------------------

class _BroadcastSide:
    """A materialized, key-sorted, key-unique join side: ``keys`` ascending
    in the stream key's code space (null keys dropped — inner join),
    ``table`` row-aligned with ``keys``."""

    def __init__(self, keys: jax.Array, table: Table):
        self.keys = keys
        self.table = table


def _prepare_broadcast(right: Table, rkey: str, lcol: Column
                       ) -> _BroadcastSide:
    rc = right.column(rkey)
    if rc.dtype != lcol.dtype:
        raise _Unsupported("join key dtype mismatch")
    if rc.dtype == STRING and not dictionaries_equal(lcol.dictionary,
                                                     rc.dictionary):
        keys = translate_codes(lcol.dictionary, rc)
    else:
        keys = rc.data
    if rc.validity is not None:  # inner join: null keys never match.
        keep = rc.validity
        right = right.filter(keep)
        keys = keys[keep]
    order = kernels.lex_sort_indices([keys])
    keys = jnp.take(keys, order)
    right = right.take(order)
    # m:1 requirement — broadcast side unique on the key (one host sync).
    if keys.shape[0] > 1 and bool(jnp.any(keys[1:] == keys[:-1])):
        raise _Unsupported("broadcast join side has duplicate keys")
    return _BroadcastSide(keys, right)


# ---------------------------------------------------------------------------
# Aggregate specs: per-device partials + host finalization.
# ---------------------------------------------------------------------------

def _strip_alias(e: E.Expr):
    while isinstance(e, E.Alias):
        e = e.child
    return e


def _min_sentinel(dtype):
    return jnp.asarray(
        jnp.finfo(dtype).min if jnp.issubdtype(dtype, jnp.floating)
        else jnp.iinfo(dtype).min, dtype)


def _max_sentinel(dtype):
    return jnp.asarray(
        jnp.finfo(dtype).max if jnp.issubdtype(dtype, jnp.floating)
        else jnp.iinfo(dtype).max, dtype)


class _AggSpec:
    """One aggregate: how to fold per-device partials and finalize merged
    partials on host. Output dtypes mirror executor._eval_agg exactly."""

    def __init__(self, name: str, kind: str, child: Optional[E.Expr],
                 out_dtype: str, dictionary=None):
        self.name = name
        self.kind = kind  # count | sum | avg | min | max
        self.child = child
        self.out_dtype = out_dtype
        self.dictionary = dictionary

    @staticmethod
    def build(agg: E.Expr, probe: Callable[[E.Expr], Column]) -> "_AggSpec":
        inner = _strip_alias(agg)
        name = agg.name
        if isinstance(inner, E.Count):
            return _AggSpec(name, "count", inner.child, INT64)
        if not isinstance(inner, (E.Sum, E.Avg, E.Min, E.Max)):
            raise _Unsupported(f"agg {inner!r}")
        c = probe(inner.child)
        if isinstance(inner, (E.Min, E.Max)):
            kind = "min" if isinstance(inner, E.Min) else "max"
            return _AggSpec(name, kind, inner.child, c.dtype, c.dictionary)
        if c.dtype == STRING:
            raise _Unsupported("sum/avg over string column")
        if isinstance(inner, E.Sum):
            is_f = c.dtype in (FLOAT64, "float32")
            return _AggSpec(name, "sum", inner.child,
                            FLOAT64 if is_f else INT64)
        return _AggSpec(name, "avg", inner.child, FLOAT64)

    def partial_keys(self) -> List[str]:
        if self.kind == "count":
            return ["count"]
        if self.kind in ("sum", "avg"):
            return ["sum", "count"]
        return [self.kind, "count"]

    # ---- per-device (traced); fold maps per-row arrays → partials ----

    def partials(self, table: Table, mask, fold) -> Dict[str, jax.Array]:
        if self.kind == "count":
            if self.child is None:
                v = mask
            else:
                c = eval_expr(table, self.child)
                v = mask if c.validity is None else (mask & c.validity)
            return {"count": fold["sum"](v.astype(jnp.int64))}
        c = eval_expr(table, self.child)
        valid = mask if c.validity is None else (mask & c.validity)
        cnt = fold["sum"](valid.astype(jnp.int64))
        if self.kind in ("sum", "avg"):
            acc = c.data.astype(jnp.float64) \
                if jnp.issubdtype(c.data.dtype, jnp.floating) \
                else c.data.astype(jnp.int64)
            return {"sum": fold["sum"](jnp.where(valid, acc, 0)),
                    "count": cnt}
        if self.kind == "min":
            vals = jnp.where(valid, c.data, _max_sentinel(c.data.dtype))
            return {"min": fold["min"](vals), "count": cnt}
        vals = jnp.where(valid, c.data, _min_sentinel(c.data.dtype))
        return {"max": fold["max"](vals), "count": cnt}

    # ---- host finalization over merged numpy partials ----

    def finalize(self, merged: Dict[str, np.ndarray],
                 nullable_inputs: bool) -> Column:
        cnt = merged["count"]
        if self.kind == "count":
            return Column(INT64, jnp.asarray(cnt.astype(np.int64)))
        # Parity with _eval_agg: output validity only when the input column
        # was nullable (SQL: empty-of-valid group aggregates to NULL).
        validity = jnp.asarray(cnt > 0) if nullable_inputs else None
        if self.kind == "sum":
            dt = np.float64 if self.out_dtype == FLOAT64 else np.int64
            return Column(self.out_dtype,
                          jnp.asarray(merged["sum"].astype(dt)), validity)
        if self.kind == "avg":
            s = merged["sum"].astype(np.float64)
            return Column(FLOAT64, jnp.asarray(s / np.maximum(cnt, 1)),
                          validity)
        return Column(self.out_dtype, jnp.asarray(merged[self.kind]),
                      validity, self.dictionary)


# ---------------------------------------------------------------------------
# Entry point.
# ---------------------------------------------------------------------------

def try_execute_aggregate(plan: Aggregate, session,
                          executor: Callable) -> Optional[Table]:
    """Execute an Aggregate subtree SPMD over the mesh, or return None to
    fall back. ``executor(plan, needed)`` is the single-device recursive
    executor, used to materialize the scan leaf and broadcast join sides."""
    if session is None:
        return None
    try:
        if not session.hs_conf.distributed_enabled():
            return None
        if len(jax.devices()) < 2:
            return None
        return _run(plan, executor)
    except _Unsupported as e:
        from ..telemetry.logging import emit_distributed_fallback
        emit_distributed_fallback(session, "spmd_query", str(e))
        return None


def _dict_fingerprint(dic: Optional[np.ndarray]):
    if dic is None:
        return None
    # Dictionaries are trace-time constants (translate tables, literal
    # bounds); they must participate in the compile-cache key by *content*
    # (not a hash of the content) so __eq__ compares real values and a
    # hash collision can never alias two distinct compiled programs.
    return tuple(dic.tolist())


def _run(plan: Aggregate, executor) -> Table:
    global DISPATCH_COUNT
    leaf, stages = _linearize(plan.child)
    leaf_needed, right_needed = _needed_per_stage(plan, stages)

    leaf_table = executor(leaf, set(leaf_needed) if leaf_needed else None)
    if leaf_table.num_rows == 0:
        raise _Unsupported("empty stream")

    mesh = make_mesh()
    n_dev = mesh.devices.size

    # Shard the stream columns (+ per-column validity).
    stream_arrays: Dict[str, jax.Array] = {}
    col_meta: Dict[str, Tuple[str, Optional[np.ndarray], bool]] = {}
    for name in leaf_table.names:
        c = leaf_table.column(name)
        stream_arrays[f"d:{name}"] = c.data
        if c.validity is not None:
            stream_arrays[f"v:{name}"] = c.validity
        col_meta[name] = (c.dtype, c.dictionary, c.validity is not None)
    sharded, valid = pad_and_shard(mesh, stream_arrays, leaf_table.num_rows)

    # Prepare broadcast join sides while walking the stage chain in order
    # over zero-length columns (the evaluator propagates dtype/dictionary/
    # nullability exactly as the traced per-device program will). The join
    # prep therefore sees the stream key's *post-stage* metadata — a
    # Project below the Join that redefines the key name (cast, computed
    # expression, dictionary change) feeds the broadcast side the same
    # dtype/dictionary the traced probe will use, never stale leaf meta.
    joins: Dict[int, Tuple[Tuple[str, str], _BroadcastSide]] = {}
    bcast_arrays: Dict[str, jax.Array] = {}
    tiny = {n: Column(dt, jnp.zeros(0, _DEVICE_DTYPE[dt]),
                      jnp.zeros(0, jnp.bool_) if nul else None, dic)
            for n, (dt, dic, nul) in col_meta.items()}
    for i, (kind, node) in enumerate(stages):
        if kind == "filter":
            continue
        if kind == "project":
            t = Table(tiny)
            tiny = {e.name: eval_expr(t, e) for e in node.exprs}
            continue
        pairs = _normalized_join_pairs(node)
        if len(pairs) != 1:
            raise _Unsupported("multi-key broadcast join")
        lname, rname = pairs[0]
        if lname not in tiny:
            raise _Unsupported(f"unknown stream join key {lname}")
        lc = tiny[lname]
        right_table = executor(node.right, right_needed[i])
        side = _prepare_broadcast(right_table, rname, lc)
        joins[i] = (pairs[0], side)
        bcast_arrays[f"k:{i}"] = side.keys
        for n in side.table.names:
            rc = side.table.column(n)
            if n != rname:
                bcast_arrays[f"b:{i}:{n}"] = rc.data
                if rc.validity is not None:
                    bcast_arrays[f"bv:{i}:{n}"] = rc.validity
                tiny[n] = Column(rc.dtype,
                                 jnp.zeros(0, _DEVICE_DTYPE[rc.dtype]),
                                 jnp.zeros(0, jnp.bool_)
                                 if rc.validity is not None else None,
                                 rc.dictionary)
            col_meta[n] = (rc.dtype, rc.dictionary, rc.validity is not None)
        if rname in node.schema.names and rname not in tiny:
            # Matched rows: right key == left key by definition.
            tiny[rname] = Column(lc.dtype, lc.data, lc.validity,
                                 lc.dictionary)
    final_meta = {n: (c.dtype, c.dictionary, c.validity is not None)
                  for n, c in tiny.items()}

    def probe(e: E.Expr) -> Column:
        tiny = {n: Column(dt, jnp.zeros(0, _DEVICE_DTYPE[dt]),
                          jnp.zeros(0, jnp.bool_) if nul else None, dic)
                for n, (dt, dic, nul) in final_meta.items()}
        return eval_expr(Table(tiny), e)

    agg_specs = tuple(_AggSpec.build(a, probe) for a in plan.aggs)
    group_cols = tuple(plan.group_cols)
    for g in group_cols:
        if g not in final_meta:
            raise _Unsupported(f"unknown group column {g}")

    grouped = bool(group_cols)
    shard_rows = next(iter(sharded.values())).shape[0] // n_dev
    G = min(shard_rows, MAX_LOCAL_GROUPS)

    descr = _StageDescr(stages, joins, col_meta, agg_specs, group_cols)
    out = _spmd_program(sharded, valid, bcast_arrays, mesh=mesh,
                        descr=descr, grouped=grouped, G=G)

    if grouped:
        if bool(np.asarray(jax.device_get(out["overflow"]))):
            raise _Unsupported("local group capacity overflow")
        table = _merge_grouped(out, agg_specs, list(group_cols), final_meta)
    else:
        table = _merge_global(out, agg_specs, final_meta)
    DISPATCH_COUNT += 1
    return table


class _StageDescr:
    """Static (hashable) description of the SPMD program. The hash is a
    *structural* signature so repeated executions of the same query shape
    hit the jit cache instead of recompiling; string dictionaries are part
    of the key because they become trace-time constants."""

    def __init__(self, stages, joins, col_meta, agg_specs, group_cols):
        self.stages = stages
        self.joins = joins
        self.col_meta = col_meta
        self.agg_specs = agg_specs
        self.group_cols = group_cols
        parts: List = [group_cols]
        for kind, node in stages:
            if kind == "filter":
                parts.append(("F", repr(node.condition)))
            elif kind == "project":
                parts.append(("P", tuple(repr(e) for e in node.exprs)))
            else:
                parts.append(("J", repr(node.condition),
                              tuple(node.schema.names)))
        for n, (dt, dic, nul) in sorted(col_meta.items()):
            parts.append((n, dt, _dict_fingerprint(dic), nul))
        for s in agg_specs:
            parts.append((s.name, s.kind, repr(s.child), s.out_dtype,
                          _dict_fingerprint(s.dictionary)))
        self._sig = tuple(parts)

    def __hash__(self):
        return hash(self._sig)

    def __eq__(self, other):
        return isinstance(other, _StageDescr) and self._sig == other._sig


@partial(jax.jit, static_argnames=("mesh", "descr", "grouped", "G"))
def _spmd_program(sharded, valid, bcast, *, mesh: Mesh, descr: _StageDescr,
                  grouped: bool, G: int):
    stages, joins, col_meta = descr.stages, descr.joins, descr.col_meta
    agg_specs, group_cols = descr.agg_specs, descr.group_cols

    def per_device(sharded, valid, bcast):
        cols = {}
        for key, arr in sharded.items():
            tag, name = key.split(":", 1)
            if tag != "d":
                continue
            dt, dic, _ = col_meta[name]
            cols[name] = Column(dt, arr, sharded.get(f"v:{name}"), dic)
        table = Table(cols)
        mask = valid

        for i, (kind, node) in enumerate(stages):
            if kind == "filter":
                mask = mask & eval_predicate_mask(table, node.condition)
            elif kind == "project":
                table = Table({e.name: eval_expr(table, e)
                               for e in node.exprs})
            else:  # broadcast join probe
                (lname, rname), side = joins[i]
                lc = table.column(lname)
                lk = lc.data
                rkeys = bcast[f"k:{i}"]
                n_r = rkeys.shape[0]
                if n_r == 0:
                    found = jnp.zeros(lk.shape[0], jnp.bool_)
                    idx_c = jnp.zeros(lk.shape[0], jnp.int32)
                else:
                    idx = jnp.searchsorted(rkeys, lk)
                    idx_c = jnp.minimum(idx, n_r - 1)
                    found = jnp.take(rkeys, idx_c) == lk
                if lc.validity is not None:
                    found = found & lc.validity
                mask = mask & found
                new_cols = dict(table.columns)
                for n in side.table.names:
                    if n == rname:
                        continue
                    rc = side.table.column(n)
                    if n_r == 0:
                        data = jnp.zeros(lk.shape[0],
                                         _DEVICE_DTYPE[rc.dtype])
                        vv = None
                    else:
                        data = jnp.take(bcast[f"b:{i}:{n}"], idx_c, axis=0)
                        vkey = f"bv:{i}:{n}"
                        vv = (jnp.take(bcast[vkey], idx_c)
                              if vkey in bcast else None)
                    new_cols[n] = Column(rc.dtype, data, vv, rc.dictionary)
                if rname in node.schema.names and rname not in new_cols:
                    # Matched rows: right key == left key by definition.
                    new_cols[rname] = Column(lc.dtype, lk, lc.validity,
                                             lc.dictionary)
                table = Table(new_cols)

        if not grouped:
            fold = {
                "sum": lambda v: jax.lax.psum(jnp.sum(v), DATA_AXIS),
                "min": lambda v: jax.lax.pmin(jnp.min(v), DATA_AXIS),
                "max": lambda v: jax.lax.pmax(jnp.max(v), DATA_AXIS),
            }
            out = {}
            for spec in agg_specs:
                for k, v in spec.partials(table, mask, fold).items():
                    out[f"{spec.name}:{k}"] = v
            return out

        # ---- grouped: capacity-bounded local partials ----
        # Sort the shard by (masked-out last, [null-first, value] per key).
        key_flags, key_datas = [], []
        sort_ops = [(~mask).astype(jnp.int32)]
        for g in group_cols:
            c = table.column(g)
            if c.validity is not None:
                flag = c.validity.astype(jnp.int32)  # null(0) sorts first
                data = jnp.where(c.validity, c.data,
                                 jnp.zeros((), c.data.dtype))
            else:
                flag = jnp.ones(c.data.shape[0], jnp.int32)
                data = c.data
            key_flags.append(flag)
            key_datas.append(data)
            sort_ops.extend([flag, data])
        order = kernels.lex_sort_indices(sort_ops)
        s_mask = jnp.take(mask, order)
        s_flags = [jnp.take(f, order) for f in key_flags]
        s_datas = [jnp.take(d, order) for d in key_datas]
        n_rows = s_mask.shape[0]
        change = jnp.zeros(n_rows, jnp.bool_)
        for arr in s_flags + s_datas:
            change = change | jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), arr[1:] != arr[:-1]])
        first = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), jnp.zeros(n_rows - 1, jnp.bool_)])
        newg = s_mask & (change | first)
        gids_raw = jnp.cumsum(newg.astype(jnp.int32)) - 1
        gids = jnp.where(s_mask, gids_raw, G)  # out-of-range → dropped
        local_groups = jnp.max(jnp.where(s_mask, gids_raw + 1, 0))
        overflow = jax.lax.pmax((local_groups > G).astype(jnp.int32),
                                DATA_AXIS)

        s_table = table.take(order)
        fold = {
            "sum": lambda v: kernels.segment_sum(v, gids, G),
            "min": lambda v: kernels.segment_min(v, gids, G),
            "max": lambda v: kernels.segment_max(v, gids, G),
        }
        out = {"overflow": overflow}
        for spec in agg_specs:
            for k, v in spec.partials(s_table, s_mask, fold).items():
                out[f"{spec.name}:{k}"] = v
        firsts = jnp.minimum(kernels.segment_first_index(gids, G),
                             n_rows - 1)
        for g, flag, data in zip(group_cols, s_flags, s_datas):
            out[f"g:{g}"] = jnp.take(data, firsts)
            out[f"gf:{g}"] = jnp.take(flag, firsts)
        out["gvalid"] = (jnp.arange(G, dtype=jnp.int32)
                         < jnp.minimum(local_groups, G))
        return out

    if grouped:
        out_specs: Dict[str, P] = {"overflow": P()}
        for spec in agg_specs:
            for k in spec.partial_keys():
                out_specs[f"{spec.name}:{k}"] = P(DATA_AXIS)
        for g in group_cols:
            out_specs[f"g:{g}"] = P(DATA_AXIS)
            out_specs[f"gf:{g}"] = P(DATA_AXIS)
        out_specs["gvalid"] = P(DATA_AXIS)
    else:
        out_specs = {f"{spec.name}:{k}": P()
                     for spec in agg_specs for k in spec.partial_keys()}

    return jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=out_specs, check_vma=False)(sharded, valid, bcast)


# ---------------------------------------------------------------------------
# Host-side merges.
# ---------------------------------------------------------------------------

def _nullable_inputs(spec: _AggSpec, col_meta) -> bool:
    if spec.child is None:
        return False
    return any(col_meta.get(r, (None, None, False))[2]
               for r in spec.child.references)


def _merge_global(out, agg_specs, final_meta) -> Table:
    cols = {}
    for spec in agg_specs:
        merged = {k: np.atleast_1d(np.asarray(
            jax.device_get(out[f"{spec.name}:{k}"])))
            for k in spec.partial_keys()}
        cols[spec.name] = spec.finalize(
            merged, nullable_inputs=_nullable_inputs(spec, final_meta))
    return Table(cols)


def _merge_grouped(out, agg_specs, group_cols: List[str], col_meta) -> Table:
    gvalid = np.asarray(jax.device_get(out["gvalid"]))
    sel = np.nonzero(gvalid)[0]
    keys = [np.asarray(jax.device_get(out[f"g:{g}"]))[sel]
            for g in group_cols]
    flags = [np.asarray(jax.device_get(out[f"gf:{g}"]))[sel]
             for g in group_cols]
    partials = {f"{s.name}:{k}": np.asarray(
        jax.device_get(out[f"{s.name}:{k}"]))[sel]
        for s in agg_specs for k in s.partial_keys()}

    # Merge-sort all per-device partial groups by (null-first, value) —
    # the same order the per-device sort used, and the output row order
    # (the single-device path also emits groups key-sorted).
    sort_cols: List[np.ndarray] = []
    for f, k in zip(flags, keys):
        # Flag before key: np.lexsort makes the *last* key primary, and
        # sort_cols is reversed below, so per group column the null-flag
        # must precede the value to be the more significant key — matching
        # the per-device (flag, data) sort order (null-first, since null
        # rows carry flag 0 and value 0, and negative values sort after
        # the null group only when the flag dominates).
        sort_cols.append(f)
        sort_cols.append(k)
    order = np.lexsort(tuple(reversed(sort_cols))) if sort_cols else \
        np.arange(len(sel))
    keys = [k[order] for k in keys]
    flags = [f[order] for f in flags]
    partials = {k: v[order] for k, v in partials.items()}

    n = len(order)
    if n == 0:
        boundaries = np.zeros(0, np.intp)
    else:
        change = np.zeros(n, bool)
        change[0] = True
        for arr in keys + flags:
            change[1:] |= arr[1:] != arr[:-1]
        boundaries = np.nonzero(change)[0]

    def reduceat(op, arr):
        return op.reduceat(arr, boundaries) if n else arr[:0]

    cols: Dict[str, Column] = {}
    for g, k, f in zip(group_cols, keys, flags):
        dt, dic, has_nulls = col_meta[g]
        validity = jnp.asarray(f[boundaries].astype(bool)) if has_nulls \
            else None
        cols[g] = Column(dt, jnp.asarray(k[boundaries]), validity, dic)
    for spec in agg_specs:
        merged = {}
        for k in spec.partial_keys():
            arr = partials[f"{spec.name}:{k}"]
            op = {"count": np.add, "sum": np.add,
                  "min": np.minimum, "max": np.maximum}[k]
            merged[k] = reduceat(op, arr)
        cols[spec.name] = spec.finalize(
            merged, nullable_inputs=_nullable_inputs(spec, col_meta))
    ordered = {g: cols[g] for g in group_cols}
    for spec in agg_specs:
        ordered[spec.name] = cols[spec.name]
    return Table(ordered)
