"""SPMD distributed query execution over the device mesh.

The query-side product path for multi-chip execution (the build side is
parallel/distributed_build.py). The reference runs *every* plan distributed
because Spark is its engine; here eligible aggregation plans run SPMD over a
1-D mesh with XLA collectives (psum/pmin/pmax over ICI), and everything else
falls back to the single-device executor.

Supported plan shapes (checked structurally; any mismatch → fallback):

    Aggregate[global or grouped]                     (try_execute_aggregate)
      └─ chain of {Filter, Project, Join}*
           └─ Scan | IndexScan                       ← the sharded stream

    [Limit] [Sort] chain of {Filter, Project, Join}* (try_execute_plan —
      └─ Scan | IndexScan                     row-returning stream queries)

Execution model — mask-based streaming with static shapes throughout.
The per-device program launches as ONE mesh-partitioned ``jax.jit``
through ``parallel/sharding.device_view`` (NamedSharding + sharding
constraints — see that module) and registers in the serving ProgramBank
keyed on (stage fingerprint, shape-class vector, mesh signature):

- The leaf table is loaded once and row-sharded over the mesh
  (``pad_and_shard``; multi-file parquet scans shard file-aligned —
  each device's rows come from its own files, read through the parallel
  reader pool); a boolean *keep mask* rides along instead of physically
  filtering, so every shape stays static in the partitioned program.
- Filters AND into the mask; Projects re-evaluate live columns (the
  expression evaluator is shape-preserving and traces cleanly per device).
- Joins pick one of two strategies per stage, and cover every join type
  (inner, left/right/full outer, semi, anti — Spark distributes all of
  them, so falling back would concede the reference's coverage):
  * broadcast (m:1): the non-stream side is materialized, required unique
    on the key, key-sorted, replicated, and probed with a per-device
    searchsorted. Multi-key joins probe a bit-packed composite built from
    the broadcast side's per-column value ranges (out-of-range stream
    values hit a sentinel that never matches). Left outer keeps unmatched
    stream rows with the right columns invalid; semi/anti broadcast the
    KEYS only (duplicates fine) and just mask the stream.
  * exchange (m:n): both sides are hash-routed over ICI with ONE
    lax.all_to_all each (value-stable key hash → owner device, the
    reference's shuffle join), then merge-joined locally into
    capacity-bounded output slots; on capacity overflow the program
    reports its exact needs and ONE right-sized recompile retries
    (2 in the rare skewed-send case) — never an open-ended
    escalation ladder on a backend where compiles are the risk.
    Multi-key joins route on the bit-packed composite. Because equal
    keys all meet on one device, local match status is global: left
    outer pads unmatched stream rows in place, right/full outer
    append each owner's unmatched right rows — no extra collective.
- Global aggregates psum/pmin/pmax partial contributions (one collective
  per partial).
- Grouped aggregates compute capacity-bounded per-device partials (local
  sort → segment ops into ``G`` slots). On real multi-chip meshes the
  partial groups then hash-route to owner devices with one all_to_all
  and combine there — the full two-phase shuffle-aggregate on device;
  the host receives disjoint final groups and only concatenates + orders
  them (owner capacity retries once with the exact reported need,
  hard-bounded by ``n_dev*G``). On single-host CPU meshes the exchange
  would run on the same silicon as the host merge, so the partials go
  straight to the host merge instead (_use_routed_merge;
  HST_SPMD_ROUTED_MERGE=on|off overrides). Local-partial overflow still
  falls back (with a telemetry event).
- Row-returning (non-aggregate) chains return each device's columns +
  mask; the host gathers valid rows and concatenates (Sort/Limit wrappers
  then run on the reduced result).

Null semantics match the single-device executor: filters keep
true-and-valid rows, inner-join null keys never match, aggregates skip
invalid values, and nullable group keys treat null as its own group
(null-first in the output order, the same encoding the single-device
path uses — executor._null_aware_keys).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import kernels
from ..parallel.mesh import (DATA_AXIS, make_mesh, pad_and_shard,
                             pad_and_shard_blocks)
from ..parallel.sharding import bank_program, device_view, mesh_signature
from ..plan import expr as E
from ..plan.nodes import (Aggregate, Filter, IndexScan, Join, LogicalPlan,
                          Project, Scan)
from ..schema import BOOL, DATE, FLOAT64, INT32, INT64, STRING
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from .columnar import Column, Table, dictionaries_equal, translate_codes
from .evaluator import eval_expr, eval_predicate_mask

# Max distinct groups per device shard for grouped aggregation. Beyond this
# the SPMD path falls back (correctness first; a group count comparable to
# the row count has no partial-aggregation win anyway).
MAX_LOCAL_GROUPS = 1 << 16

# Successful SPMD executions in this process (tests / dryrun assert the
# path is actually taken). These tallies (and LAST_CAP_ATTEMPTS below)
# are bumped by concurrent serving workers and asserted exact by tests,
# so every write happens under the lock — an unguarded += loses updates
# (HS301/HS302, scripts/analysis lock-discipline registry).
DISPATCH_COUNT = 0

# Distributed ORDER BY executions (range-partitioned sample sort).
SORT_DISPATCH_COUNT = 0

_COUNT_LOCK = threading.Lock()

# Per-device sample count for the distributed sort's splitter estimation.
_SORT_SAMPLES = 64


class _Unsupported(Exception):
    """Plan/dtype/shape not handled by the SPMD path — fall back."""


_DEVICE_DTYPE = {INT32: jnp.int32, INT64: jnp.int64, "float32": jnp.float32,
                 FLOAT64: jnp.float64, BOOL: jnp.bool_, DATE: jnp.int32,
                 STRING: jnp.int32}


# ---------------------------------------------------------------------------
# Plan linearization + column-need analysis.
# ---------------------------------------------------------------------------

def _linearize(plan: LogicalPlan):
    """Split the subtree under Aggregate into (leaf, bottom-up stage list).
    The sharded stream side of a Join is its *left* child (fact table
    left, dimension right — the DataFrame API convention)."""
    stages: List[Tuple[str, LogicalPlan]] = []
    node = plan
    while True:
        if isinstance(node, (Scan, IndexScan)):
            return node, list(reversed(stages))
        if isinstance(node, Filter):
            stages.append(("filter", node))
            node = node.child
        elif isinstance(node, Project):
            stages.append(("project", node))
            node = node.child
        elif isinstance(node, Join):
            stages.append(("join", node))
            node = node.left
        else:
            raise _Unsupported(node.node_name)


def _load_leaf(leaf, stages, needed) -> "Table":
    """Materialize the stream leaf, pruning the read when possible.

    Filter stages sitting DIRECTLY above the leaf (before any project or
    join stage) are necessary conditions on the raw leaf rows, so their
    pushable conjuncts can narrow the parquet read — the same IO
    optimization the single-device Filter-over-leaf branch applies; the
    later mask evaluation over the pruned rows is unchanged. For an
    IndexScan leaf, a leading-indexed-column constraint additionally
    bypasses the HBM cache (within-bucket sort makes row-group pruning
    sharp — executor._execute's policy).

    The returned table may be CLASS-PADDED (``valid_rows`` set):
    compacting here would compile one gather per distinct valid count,
    while the SPMD stream's keep mask absorbs the pad tail for free and
    class-stable shapes are exactly what lets the sharded programs bank
    (the r07 padding contract carried through r12's launcher)."""
    from . import executor as ex

    conds = []
    for kind, node in stages:
        if kind != "filter":
            break
        conds.append(node.condition)
    if conds:
        from .pushdown import pruned_index_read_filter, pushable_filter

        combined = conds[0]
        for c in conds[1:]:
            combined = E.And(combined, c)
        if isinstance(leaf, IndexScan):
            pa_filter = pruned_index_read_filter(
                leaf.index_entry, combined, leaf.schema)
            if pa_filter is not None:
                table = ex._execute_index_scan(
                    leaf, needed, pa_filter, prefer_pruned_read=True)
                if table.num_rows > 0:
                    return table
                # Filter matched nothing: fall through to the cached full
                # read so the SPMD stream still runs (an all-false mask)
                # instead of a spurious single-device fallback.
        else:  # Scan: dotted struct leaves aren't physical columns there.
            pa_filter = pushable_filter(combined, leaf.schema,
                                        allow_nested=False)
            if pa_filter is not None:
                table = ex._execute_scan(leaf, needed, pa_filter)
                if table.num_rows > 0:
                    return table
    # Padded-pipeline read (NOT the compacting callback): the stream
    # shards the physical class-padded arrays and masks the tail.
    return ex._execute(leaf, needed)


def _normalized_join_pairs(join: Join) -> List[Tuple[str, str]]:
    pairs = E.extract_equi_join_keys(join.condition)
    if pairs is None:
        raise _Unsupported("non-equi join")
    left_names = set(join.left.schema.names)
    right_names = set(join.right.schema.names)
    norm = []
    for a, b in pairs:
        if a in left_names and b in right_names:
            norm.append((a, b))
        elif b in left_names and a in right_names:
            norm.append((b, a))
        else:
            raise _Unsupported("join keys do not split across sides")
    return norm


def _needed_per_stage(needed: Set[str], stages):
    """Top-down walk computing the leaf's needed column set, per join stage
    the non-stream side's needed set, and per project stage the *live*
    output names (the traced program evaluates only those — a dead project
    expr may reference columns that were pruned below it).

    ``right_used[i]`` is the subset of the right side's columns a stage
    above actually consumes — join KEYS appear in ``right_needed[i]``
    (the side must be materialized with them to compute routing codes)
    but ride the exchange as data only when used."""
    needed = set(needed)
    right_needed: Dict[int, Set[str]] = {}
    right_used: Dict[int, Set[str]] = {}
    project_live: Dict[int, frozenset] = {}
    for i in range(len(stages) - 1, -1, -1):
        kind, node = stages[i]
        if kind == "filter":
            needed = needed | set(node.condition.references)
        elif kind == "project":
            live = {e.name for e in node.exprs if e.name in needed}
            project_live[i] = frozenset(live)
            below: Set[str] = set()
            for e in node.exprs:
                if e.name in live:
                    below |= set(e.references)
            needed = below
        else:  # join
            pairs = _normalized_join_pairs(node)
            if node.join_type in ("semi", "anti"):
                # Existence probe: the right side contributes keys only
                # and no columns survive into the output (schema = left).
                right_needed[i] = {r for _, r in pairs}
                right_used[i] = set()
                needed = needed | {l for l, _ in pairs}
            else:
                rnames = set(node.right.schema.names)
                right_used[i] = {n for n in needed if n in rnames}
                right_needed[i] = right_used[i] | {r for _, r in pairs}
                needed = {n for n in needed if n not in rnames} | \
                    {l for l, _ in pairs}
    return needed, right_needed, right_used, project_live


# ---------------------------------------------------------------------------
# Join sides. Two strategies, chosen per join stage:
#   broadcast — small m:1 side replicated to every device, probed with a
#     searchsorted (the reference's broadcast join, primitive 4);
#   exchange — both sides hash-routed over ICI with one all_to_all so equal
#     keys meet on one device, then merge-joined locally (the reference's
#     shuffle join, primitives 1+5). Handles m:n and big-big joins.
# ---------------------------------------------------------------------------

class _BroadcastSide:
    """A materialized, key-sorted, key-unique join side: ``keys`` ascending
    in the stream key's code space (null keys dropped — inner join),
    ``table`` row-aligned with ``keys``. ``pack`` is the multi-key
    composite spec: a tuple of (rmin, shift, sentinel) per key column —
    None for single-key joins."""

    def __init__(self, keys: jax.Array, table: Table, pack=None):
        self.keys = keys
        self.table = table
        self.pack = pack


class _ExchangeSide:
    """An m:n join side sharded over the mesh for the bucket exchange.
    ``arrays``/``valid`` are row-sharded (pad_and_shard); ``key_dtype`` is
    the stream-code-space dtype used for value-stable routing hashes.
    ``stream_meta`` snapshots the STREAM side's per-column metadata at this
    stage (projects below the join may have created or redefined columns
    that the leaf col_meta doesn't know). ``pack`` is the multi-key
    composite spec (None for single-key): the routed "k" arrays hold the
    packed int64 composite, and every right column — keys included —
    additionally rides as data so outer-join appendix rows can surface
    their own key values."""

    def __init__(self, arrays: Dict[str, jax.Array], valid: jax.Array,
                 table_meta: Dict[str, Tuple[str, Optional[np.ndarray], bool]],
                 key_dtype: str,
                 stream_meta: Dict[str, Tuple[str, Optional[np.ndarray], bool]],
                 pack=None):
        self.arrays = arrays
        self.valid = valid
        self.table_meta = table_meta
        self.key_dtype = key_dtype
        self.stream_meta = stream_meta
        self.pack = pack


def _right_key_codes(right: Table, rkey: str, lcol: Column) -> jax.Array:
    """The right key column in the STREAM side's code space (strings are
    translated into the stream dictionary so codes compare equal iff the
    strings do)."""
    rc = right.column(rkey)
    if rc.dtype != lcol.dtype:
        raise _Unsupported("join key dtype mismatch")
    if rc.dtype == STRING and not dictionaries_equal(lcol.dictionary,
                                                     rc.dictionary):
        return translate_codes(lcol.dictionary, rc)
    return rc.data


def _drop_null_keys(right: Table, rkeys: List[str]):
    keep = None
    for rk in rkeys:
        v = right.column(rk).validity
        if v is not None:
            keep = v if keep is None else (keep & v)
    if keep is not None:  # inner join: null keys never match.
        return right.filter(keep), keep
    return right, None


def _pack_codes(codes):
    """Bit-pack multiple key-code arrays into one int64 composite (None
    pack for single-key). Each key column is offset into [0, range) from
    the RIGHT side's own min/max and packed into disjoint bit fields. A +1
    sentinel per field encodes "stream value outside the right side's
    range" — it can never equal a packed right key, so composite equality
    ⇔ per-column equality, exactly."""
    if len(codes) == 1:
        return codes[0], None
    pack = []
    shift = 0
    packed = None
    for c in codes:
        c64 = c.astype(jnp.int64)
        if c64.shape[0] == 0:
            rmin, rmax = 0, 0
        else:
            rmin = int(jnp.min(c64))
            rmax = int(jnp.max(c64))
        span = rmax - rmin + 2  # +1 for the out-of-range sentinel
        bits = max(int(span - 1).bit_length(), 1)
        pack.append((rmin, shift, span - 1))
        packed = (c64 - rmin) << shift if packed is None else \
            packed | ((c64 - rmin) << shift)
        shift += bits
        if shift > 62:
            raise _Unsupported("multi-key composite exceeds 62 bits")
    return packed, tuple(pack)


def _prepare_broadcast(right: Table, pairs, tiny: Dict[str, Column],
                       keys_only: bool = False) -> _BroadcastSide:
    """``keys_only`` (semi/anti probes) skips the m:1 uniqueness demand —
    duplicate keys are harmless to an existence searchsorted — and ships
    no data columns at all."""
    right, _ = _drop_null_keys(right, [r for _, r in pairs])
    codes = [_right_key_codes(right, rname, tiny[lname])
             for lname, rname in pairs]
    keys, pack = _pack_codes(codes)
    order = kernels.lex_sort_indices([keys])
    keys = jnp.take(keys, order)
    if keys_only:
        return _BroadcastSide(keys, Table({}), pack)
    right = right.take(order)
    # m:1 requirement — broadcast side unique on the key (one host sync).
    if keys.shape[0] > 1 and bool(jnp.any(keys[1:] == keys[:-1])):
        raise _Unsupported("broadcast join side has duplicate keys")
    return _BroadcastSide(keys, right, pack)


def _prepare_exchange(right: Table, pairs, tiny: Dict[str, Column],
                      mesh: Mesh, used: Set[str],
                      keep_null_keys: bool) -> _ExchangeSide:
    """Shard an m:n join side over the mesh for the all-to-all route.
    Multi-key joins route on the bit-packed composite (the same trick the
    broadcast side uses, VERDICT r3 #7) — both sides hash the composite,
    so equal key TUPLES meet on one device.

    ``used`` gates the data payload: join keys ride only the routing "k"
    array unless a stage above consumes the column. ``keep_null_keys``
    (right/full outer) keeps null-key rows in the route — they match
    nothing, but the preserving side must still emit them (the single-
    device executor's _execute_outer_join does); a "kv" flag rides along
    so the merge can exclude them from matching."""
    key_validity = None
    if keep_null_keys:
        for _, rk in pairs:
            v = right.column(rk).validity
            if v is not None:
                key_validity = v if key_validity is None \
                    else (key_validity & v)
    else:
        right, _ = _drop_null_keys(right, [r for _, r in pairs])
    codes = [_right_key_codes(right, rname, tiny[lname])
             for lname, rname in pairs]
    if right.num_rows == 0:
        raise _Unsupported("empty exchange side")
    if key_validity is not None and len(pairs) > 1:
        # Null slots hold arbitrary fill — pin them to each column's valid
        # min so the composite's bit budget reflects real values only.
        pinned = []
        for c in codes:
            vmin = jnp.min(jnp.where(key_validity, c,
                                     _max_sentinel(c.dtype)))
            vmin = jnp.where(jnp.any(key_validity), vmin,
                             jnp.zeros((), c.dtype))
            pinned.append(jnp.where(key_validity, c, vmin))
        codes = pinned
    keys, pack = _pack_codes(codes)
    arrays: Dict[str, jax.Array] = {"k": keys}
    if key_validity is not None:
        arrays["kv"] = key_validity
    rkeys = {r for _, r in pairs}
    # Key columns ride as data only when some stage consumes them AND the
    # program cannot rebuild them for free from the stream side: single-
    # key non-preserve-right joins reconstruct the right key from the
    # stream key (equal by definition on matches, null on padding), so
    # only composites (unpackable) and right/full (appendix rows carry
    # their OWN key values) pay the duplicate payload.
    carry_keys = pack is not None or keep_null_keys
    meta: Dict[str, Tuple[str, Optional[np.ndarray], bool]] = {}
    for n in right.names:
        if n in rkeys and not (n in used and carry_keys):
            continue
        rc = right.column(n)
        arrays[f"d:{n}"] = rc.data
        if rc.validity is not None:
            arrays[f"v:{n}"] = rc.validity
        meta[n] = (rc.dtype, rc.dictionary, rc.validity is not None)
    from .shapes import padded_length
    arrays, valid = pad_and_shard(mesh, arrays, right.num_rows,
                                  pad_rows=padded_length(right.num_rows))
    stream_meta = {n: (c.dtype, c.dictionary, c.validity is not None)
                   for n, c in tiny.items()}
    key_dtype = INT64 if pack is not None else tiny[pairs[0][0]].dtype
    return _ExchangeSide(arrays, valid, meta, key_dtype, stream_meta, pack)


# ---------------------------------------------------------------------------
# Aggregate specs: per-device partials + host finalization.
# ---------------------------------------------------------------------------

def _strip_alias(e: E.Expr):
    while isinstance(e, E.Alias):
        e = e.child
    return e


def _min_sentinel(dtype):
    return jnp.asarray(
        jnp.finfo(dtype).min if jnp.issubdtype(dtype, jnp.floating)
        else jnp.iinfo(dtype).min, dtype)


def _max_sentinel(dtype):
    return jnp.asarray(
        jnp.finfo(dtype).max if jnp.issubdtype(dtype, jnp.floating)
        else jnp.iinfo(dtype).max, dtype)


class _AggSpec:
    """One aggregate: how to fold per-device partials and finalize merged
    partials on host. Output dtypes mirror executor._eval_agg exactly."""

    def __init__(self, name: str, kind: str, child: Optional[E.Expr],
                 out_dtype: str, dictionary=None):
        self.name = name
        self.kind = kind  # count | sum | avg | min | max
        self.child = child
        self.out_dtype = out_dtype
        self.dictionary = dictionary

    @staticmethod
    def build(agg: E.Expr, probe: Callable[[E.Expr], Column]) -> "_AggSpec":
        inner = _strip_alias(agg)
        name = agg.name
        if isinstance(inner, E.Count):
            return _AggSpec(name, "count", inner.child, INT64)
        if not isinstance(inner, (E.Sum, E.Avg, E.Min, E.Max)):
            raise _Unsupported(f"agg {inner!r}")
        c = probe(inner.child)
        if isinstance(inner, (E.Min, E.Max)):
            kind = "min" if isinstance(inner, E.Min) else "max"
            return _AggSpec(name, kind, inner.child, c.dtype, c.dictionary)
        if c.dtype == STRING:
            raise _Unsupported("sum/avg over string column")
        if isinstance(inner, E.Sum):
            is_f = c.dtype in (FLOAT64, "float32")
            return _AggSpec(name, "sum", inner.child,
                            FLOAT64 if is_f else INT64)
        return _AggSpec(name, "avg", inner.child, FLOAT64)

    def partial_keys(self) -> List[str]:
        if self.kind == "count":
            return ["count"]
        if self.kind in ("sum", "avg"):
            return ["sum", "count"]
        return [self.kind, "count"]

    # ---- per-device (traced); fold maps per-row arrays → partials ----

    def partials(self, table: Table, mask, fold) -> Dict[str, jax.Array]:
        if self.kind == "count":
            if self.child is None:
                v = mask
            else:
                c = eval_expr(table, self.child)
                v = mask if c.validity is None else (mask & c.validity)
            return {"count": fold["sum"](v.astype(jnp.int64))}
        c = eval_expr(table, self.child)
        valid = mask if c.validity is None else (mask & c.validity)
        cnt = fold["sum"](valid.astype(jnp.int64))
        if self.kind in ("sum", "avg"):
            acc = c.data.astype(jnp.float64) \
                if jnp.issubdtype(c.data.dtype, jnp.floating) \
                else c.data.astype(jnp.int64)
            return {"sum": fold["sum"](jnp.where(valid, acc, 0)),
                    "count": cnt}
        if self.kind == "min":
            vals = jnp.where(valid, c.data, _max_sentinel(c.data.dtype))
            return {"min": fold["min"](vals), "count": cnt}
        vals = jnp.where(valid, c.data, _min_sentinel(c.data.dtype))
        return {"max": fold["max"](vals), "count": cnt}

    # ---- host finalization over merged numpy partials ----

    def finalize(self, merged: Dict[str, np.ndarray],
                 nullable_inputs: bool) -> Column:
        cnt = merged["count"]
        if self.kind == "count":
            return Column(INT64, jnp.asarray(cnt.astype(np.int64)))
        # Parity with _eval_agg: output validity only when the input column
        # was nullable (SQL: empty-of-valid group aggregates to NULL).
        validity = jnp.asarray(cnt > 0) if nullable_inputs else None
        if self.kind == "sum":
            dt = np.float64 if self.out_dtype == FLOAT64 else np.int64
            return Column(self.out_dtype,
                          jnp.asarray(merged["sum"].astype(dt)), validity)
        if self.kind == "avg":
            s = merged["sum"].astype(np.float64)
            return Column(FLOAT64, jnp.asarray(s / np.maximum(cnt, 1)),
                          validity)
        return Column(self.out_dtype, jnp.asarray(merged[self.kind]),
                      validity, self.dictionary)


# ---------------------------------------------------------------------------
# Entry point.
# ---------------------------------------------------------------------------

def _device_count(session=None) -> int:
    """Devices the dispatch mesh will span (tests shrink this to exercise
    the 1-device fused path on a multi-device host; the
    ``distributed.mesh.maxDevices`` knob caps it, 0 = all local)."""
    n = len(jax.devices())
    if session is not None:
        cap = session.hs_conf.distributed_mesh_max_devices()
        if cap > 0:
            n = min(n, cap)
    return n


def _spmd_eligible(session) -> bool:
    if session is None:
        return False
    if not session.hs_conf.distributed_enabled():
        return False
    from ..serving import batcher
    if batcher.active_sweep() is not None:
        # A literal-sweep batch already collapses its members into ONE
        # vmapped invocation over shared scans (serving/batcher.py) —
        # the sweep kernel lives in the single-device padded pipeline,
        # and distributing each member individually would both defeat
        # the batching win and skip the shared-scan accounting.
        return False
    if _device_count(session) >= 2:
        return True
    # ONE device: the "SPMD" program degenerates to a single fused jit
    # program (collectives over a 1-device mesh are identity, and XLA
    # removes them). That still matters on an accelerator, where the
    # interpreted executor pays a host↔device round trip per operator —
    # the measured round-3 on-chip filter bottleneck — while the fused
    # program pays ~one. On CPU the "device" shares the silicon with the
    # host, so fusing buys nothing and costs compiles; "auto" therefore
    # keys on the backend (VERDICT r3 #8).
    mode = session.hs_conf.distributed_single_device()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return jax.default_backend() not in ("cpu",)


def _stream_leaf_rows(root) -> Optional[int]:
    """Row count of the stream leaf from parquet METADATA only (no read),
    or None when unknowable (non-parquet, structural mismatch — let the
    caller proceed/fail for its own reason)."""
    from .columnar import parquet_row_counts

    try:
        leaf, _ = _linearize(root)
    except _Unsupported:
        return None
    if isinstance(leaf, IndexScan):
        # Index leaves materialize fully (index content PLUS any hybrid
        # appended files).
        try:
            return sum(parquet_row_counts(
                list(leaf.index_entry.content.files)
                + list(leaf.appended_files)))
        except Exception:
            return None
    if not isinstance(leaf, Scan):
        return None
    relation = leaf.relation
    fmt = getattr(relation, "data_file_format", relation.file_format)
    if fmt != "parquet":
        return None
    try:
        return sum(parquet_row_counts(relation.all_files()))
    except Exception:
        return None


def _leaf_within_budget(root, session) -> bool:
    """False when the stream leaf exceeds the device-footprint budget —
    the SPMD path materializes the leaf before sharding, so oversized
    sources must go to the chunked single-device path instead (the two
    compose once the chunked reader learns to feed shards directly)."""
    total = _stream_leaf_rows(root)
    return total is None or total <= session.hs_conf.max_chunk_rows()


def _passes_min_rows(root, session) -> bool:
    """The distributed COST GATE: streams whose leaf holds fewer rows
    than ``distributed.minStreamRows`` stay single-device — an N-device
    program over a few hundred rows pays compile + collective overhead
    for zero scaling win (and on the virtual test mesh it would tax the
    whole suite with mesh compiles). Unknown row counts pass (the
    structural checks decide). Observable like every other fallback."""
    min_rows = session.hs_conf.distributed_min_stream_rows()
    if min_rows <= 0:
        return True
    rows = _stream_leaf_rows(root)
    if rows is None or rows >= min_rows:
        return True
    from ..telemetry.logging import emit_distributed_fallback
    emit_distributed_fallback(
        session, "spmd_query",
        f"stream leaf {rows} rows below distributed.minStreamRows "
        f"{min_rows}")
    return False


def try_execute_aggregate(plan: Aggregate, session,
                          executor: Callable) -> Optional[Table]:
    """Execute an Aggregate subtree SPMD over the mesh, or return None to
    fall back. ``executor(plan, needed)`` is the single-device recursive
    executor, used to materialize the scan leaf and join sides."""
    if not _spmd_eligible(session):
        return None
    if not _passes_min_rows(plan.child, session):
        return None
    if not _leaf_within_budget(plan.child, session):
        from ..telemetry.logging import emit_distributed_fallback
        emit_distributed_fallback(session, "spmd_query",
                                  "leaf exceeds device chunk budget")
        return None
    try:
        return _run(plan, executor, session)
    except _Unsupported as e:
        from ..telemetry.logging import emit_distributed_fallback
        emit_distributed_fallback(session, "spmd_query", str(e))
        return None


def try_execute_plan(plan, session, executor: Callable) -> Optional[Table]:
    """Row-returning distributed execution for non-aggregate roots: a
    {Filter, Project, Join}* chain over a scan (optionally under Sort /
    Limit wrappers) runs SPMD; valid rows are gathered per device and
    concatenated on host, then the wrappers run single-device (their input
    is already reduced). Returns None to fall back."""
    from ..plan.nodes import Limit, Sort

    if not _spmd_eligible(session):
        return None
    wrappers = []
    node = plan
    while isinstance(node, (Sort, Limit)):
        wrappers.append(node)
        node = node.child
    if isinstance(node, Aggregate):
        return None  # aggregates dispatch inside the executor
    if isinstance(node, (Scan, IndexScan)) and not (
            wrappers and isinstance(wrappers[-1], Sort)
            and _use_spmd_sort()):
        return None  # a bare scan has no distributed work — unless a
        # Sort sits above it (the distributed sample sort IS the work)
    try:
        _linearize(node)  # raises _Unsupported on non-chain shapes
    except _Unsupported:
        return None
    if not _passes_min_rows(node, session):
        return None
    if not _leaf_within_budget(node, session):
        from ..telemetry.logging import emit_distributed_fallback
        emit_distributed_fallback(session, "spmd_query",
                                  "leaf exceeds device chunk budget")
        return None
    # Distributed ORDER BY: the innermost Sort runs ON the mesh as a
    # range-partitioned sample sort, so the host gather receives sorted
    # device ranges instead of unsorted rows (VERDICT r5 #4).
    sort_orders: Tuple = ()
    if wrappers and isinstance(wrappers[-1], Sort) and _use_spmd_sort():
        sort_orders = tuple(wrappers[-1].orders)
        wrappers = wrappers[:-1]
    try:
        table = _run_stream(node, executor, sort_orders, session)
    except _Unsupported as e:
        from ..telemetry.logging import emit_distributed_fallback
        emit_distributed_fallback(session, "spmd_query", str(e))
        return None
    # Wrappers (outermost first in `wrappers`): apply innermost-out.
    from . import executor as ex
    for w in reversed(wrappers):
        if isinstance(w, Sort):
            table = ex._execute_sort(w, table)
        else:
            table = table.slice(0, min(w.n, table.num_rows))
    return table


def _use_spmd_sort() -> bool:
    """Backend cost decision for the distributed ORDER BY, mirroring
    _use_routed_merge: on a single-host CPU mesh the sample-sort
    collectives run on the silicon the host sort would use, so the host
    sort wins; on real multi-chip the sort scales with devices and the
    exchange rides ICI. HST_SPMD_SORT=on|off overrides (tests and the
    multi-chip dryrun force it on)."""
    mode = os.environ.get("HST_SPMD_SORT", "auto")
    if mode in ("on", "off"):
        return mode == "on"
    return jax.devices()[0].platform != "cpu"


def _dict_fingerprint(dic: Optional[np.ndarray]):
    if dic is None:
        return None
    # Dictionaries are trace-time constants (translate tables, literal
    # bounds); they must participate in the compile-cache key by *content*
    # (not a hash of the content) so __eq__ compares real values and a
    # hash collision can never alias two distinct compiled programs.
    return tuple(dic.tolist())


class _Prepared:
    """Everything _spmd_program needs, prepared once per execution: the
    sharded stream, replicated broadcast arrays, sharded exchange arrays,
    join descriptors, per-stage metadata, and the final (post-stage) column
    metadata for probing aggregate dtypes / rebuilding host tables."""

    def __init__(self, mesh, n_dev, sharded, valid, bcast, xch, stages,
                 joins, col_meta, final_meta, shard_rows, out_rows,
                 project_live, file_aligned=False):
        self.mesh = mesh
        self.n_dev = n_dev
        self.sharded = sharded
        self.valid = valid
        self.bcast = bcast
        self.xch = xch
        self.stages = stages
        self.joins = joins
        self.col_meta = col_meta
        self.final_meta = final_meta
        self.shard_rows = shard_rows
        self.out_rows = out_rows  # per-device rows after the last stage
        self.project_live = project_live  # stage idx -> live output names
        self.file_aligned = file_aligned  # leaf sharded on file boundaries


def _file_aligned_bounds(leaf, leaf_table, n_dev: int):
    """Row offsets assigning whole files to devices, or None. Only for
    plain multi-file parquet Scan leaves whose materialized row count
    matches the file metadata exactly (no pruned read, no class padding)
    — then splitting the already-read arrays at file boundaries gives
    every device rows from its own files at zero extra IO (the host read
    itself fanned per-file through the parallel reader pool). Any
    monotonic bounds are CORRECT (order preserved, padding masked);
    alignment buys locality, not semantics."""
    from .columnar import parquet_row_counts

    if not isinstance(leaf, Scan):
        return None
    relation = leaf.relation
    fmt = getattr(relation, "data_file_format", relation.file_format)
    if fmt != "parquet":
        return None
    try:
        files = list(relation.all_files())
        counts = parquet_row_counts(files)
    except Exception:
        return None
    if len(counts) < 2 or sum(counts) != leaf_table.num_rows:
        return None
    total = sum(counts)
    bounds = [0]
    acc = 0
    i = 0
    for d in range(1, n_dev):
        target = (d * total) // n_dev
        while i < len(counts) and acc + counts[i] <= target:
            acc += counts[i]
            i += 1
        bounds.append(acc)
    bounds.append(total)
    # Skew guard: every shard pads to the LARGEST block, so a lopsided
    # file layout (one giant file among small ones) would inflate device
    # memory toward n_dev x the data and serialize the real work onto
    # few devices. At 2x the even shard and beyond, locality stops
    # paying for the padding — fall back to the even row split. (Below
    # that the ratio is ordinary file-granularity quantization: e.g. 5
    # equal files over 8 devices necessarily hands some device a whole
    # file, 1.6x the even shard.)
    largest = max(bounds[d + 1] - bounds[d] for d in range(n_dev))
    if largest >= -(-total // n_dev) * 2:
        return None
    return bounds


def _sharded_blocks(mesh, leaf, stream_arrays, bounds, shard_rows):
    """File-aligned device sharding through the tiered buffer pool: the
    per-device sharded blocks are cached keyed by (leaf file signature,
    array names, block bounds, padded shard rows, mesh signature) so a
    repeat scan of unchanged files re-serves the SAME device buffers
    with zero host→device transfers. Entries are device-only (opaque
    sharded layouts never demote — evicted by dropping), and a
    different mesh never shares (its buffers live on other devices)."""
    from ..parallel.sharding import mesh_signature
    from . import buffer_pool as _bp

    key = None
    if _bp.enabled():
        try:
            files = list(leaf.relation.all_files())
        except Exception:
            files = None
        if files:
            key = _bp.blocks_key(files, sorted(stream_arrays), bounds,
                                 shard_rows, mesh_signature(mesh))
        if key is not None:
            cached = _bp.get_pool().get(key)
            if cached is not None:
                return cached
    sharded, valid = pad_and_shard_blocks(mesh, stream_arrays, bounds,
                                          shard_rows=shard_rows)
    if key is not None:
        nbytes = sum(int(a.nbytes) for a in sharded.values()) \
            + int(valid.nbytes)
        _bp.get_pool().put(key, (sharded, valid), nbytes=nbytes,
                           device_only=True)
    return sharded, valid


def _prepare(root, executor, caps: Dict[int, Tuple[int, int]],
             session=None) -> _Prepared:
    """Walk the stage chain preparing each join side. The walk runs over
    zero-length columns (the evaluator propagates dtype/dictionary/
    nullability exactly as the traced per-device program will), so join
    prep sees the stream key's *post-stage* metadata — a Project below a
    Join that redefines the key name feeds the join side the same
    dtype/dictionary the traced probe will use, never stale leaf meta.

    ``caps`` carries per-exchange-join capacities (send cap, output slots)
    from the retry loop; empty on the first attempt (defaults computed
    here)."""
    leaf, stages = _linearize(root)
    out_needed = set(root.schema.names)
    leaf_needed, right_needed, right_used, project_live = _needed_per_stage(
        out_needed, stages)

    leaf_table = _load_leaf(leaf, stages,
                            set(leaf_needed) if leaf_needed else None)
    if leaf_table.num_rows == 0:
        raise _Unsupported("empty stream")

    mesh = make_mesh(jax.devices()[:_device_count(session)])
    n_dev = mesh.devices.size

    stream_arrays: Dict[str, jax.Array] = {}
    col_meta: Dict[str, Tuple[str, Optional[np.ndarray], bool]] = {}
    for name in leaf_table.names:
        c = leaf_table.column(name)
        stream_arrays[f"d:{name}"] = c.data
        if c.validity is not None:
            stream_arrays[f"v:{name}"] = c.validity
        col_meta[name] = (c.dtype, c.dictionary, c.validity is not None)
    # Stream sharding keeps the r07 static-shape contract: the leaf pads
    # to its geometric LENGTH CLASS (shapes.padded_length under the
    # executor's active params) before the device split, so repeated
    # executions over different-length sources within one class hit ONE
    # compiled mesh program in the bank — the valid mask keeps results
    # byte-identical.
    from .shapes import padded_length
    bounds = None
    if n_dev > 1 and session is not None \
            and session.hs_conf.distributed_mesh_file_aligned_scan():
        bounds = _file_aligned_bounds(leaf, leaf_table, n_dev)
    if bounds is not None:
        max_block = max(bounds[i + 1] - bounds[i]
                        for i in range(len(bounds) - 1))
        sharded, valid = _sharded_blocks(
            mesh, leaf, stream_arrays, bounds,
            padded_length(max_block))
    else:
        sharded, valid = pad_and_shard(
            mesh, stream_arrays, leaf_table.num_rows,
            pad_rows=padded_length(leaf_table.num_rows))
    shard_rows = next(iter(sharded.values())).shape[0] // n_dev
    out_rows = shard_rows

    joins: Dict[int, Tuple] = {}
    bcast_arrays: Dict[str, jax.Array] = {}
    xch_arrays: Dict[str, jax.Array] = {}
    tiny = {n: Column(dt, jnp.zeros(0, _DEVICE_DTYPE[dt]),
                      jnp.zeros(0, jnp.bool_) if nul else None, dic)
            for n, (dt, dic, nul) in col_meta.items()}
    for i, (kind, node) in enumerate(stages):
        if kind == "filter":
            continue
        if kind == "project":
            t = Table(tiny)
            live = project_live.get(i, frozenset())
            tiny = {e.name: eval_expr(t, e) for e in node.exprs
                    if e.name in live}
            continue
        pairs = _normalized_join_pairs(node)
        jt = node.join_type
        for lname, _ in pairs:
            if lname not in tiny:
                raise _Unsupported(f"unknown stream join key {lname}")
        right_table = executor(node.right, right_needed[i])
        side = None
        if jt in ("semi", "anti"):
            # Existence probe: keys-only broadcast (duplicates fine, no
            # data columns, no schema change) — the classic broadcast
            # semi join, and the SPMD home of SQL [NOT] IN / EXISTS.
            # An _Unsupported here (e.g. composite bit overflow) falls
            # back to single-device — never to the exchange, which has
            # no existence-probe mode.
            side = _prepare_broadcast(right_table, pairs, tiny,
                                      keys_only=True)
        elif jt in ("inner", "left"):
            # m:1 probe; left outer keeps unmatched stream rows with the
            # right columns invalid instead of masking them out.
            try:
                side = _prepare_broadcast(right_table, pairs, tiny)
            except _Unsupported:
                side = None
        if side is not None:
            joins[i] = ("b", pairs, side, jt)
            bcast_arrays[f"k:{i}"] = side.keys
            for n in side.table.names:  # empty for keys_only sides
                rc = side.table.column(n)
                if n not in {r for _, r in pairs}:
                    bcast_arrays[f"b:{i}:{n}"] = rc.data
                    if rc.validity is not None:
                        bcast_arrays[f"bv:{i}:{n}"] = rc.validity
            if jt in ("semi", "anti"):
                continue
        if side is None:
            # m:n (duplicate keys) and right/full outer → hash-route both
            # sides over ICI and merge-join locally: the reference's
            # shuffle join. Right/full need the exchange because only
            # there is a right row owned by exactly ONE device (a
            # replicated broadcast side would emit its unmatched rows
            # once per device).
            side = _prepare_exchange(right_table, pairs, tiny, mesh,
                                     right_used[i],
                                     keep_null_keys=jt in ("right", "full"))
            if i not in caps:
                r_shard = next(iter(side.arrays.values())).shape[0] // n_dev
                cap = min(2 * max(out_rows, r_shard) // n_dev + 1,
                          max(out_rows, r_shard))
                k_out = 2 * max(out_rows, r_shard)
                if jt in ("left", "full"):
                    k_out += out_rows  # every stream row may emit alone
                if jt in ("right", "full"):
                    k_out += 2 * r_shard  # plus the unmatched-right tail
                caps[i] = (cap, k_out)
            joins[i] = ("x", pairs, side, jt)
            for name, arr in side.arrays.items():
                xch_arrays[f"x:{i}:{name}"] = arr
            xch_arrays[f"x:{i}:__valid"] = side.valid
            out_rows = caps[i][1]
        # Post-join stream metadata: non-key right columns appear; matched
        # rows' right key values equal the left key's. Outer joins make
        # the null-padded side's columns nullable (nodes.Join.schema).
        if jt in ("right", "full"):
            # Meta comes from the tiny column itself, NOT col_meta: a
            # Project below this join may have created/renamed columns
            # col_meta never saw (KeyError here would escape the
            # _Unsupported fallback net as a crash).
            for n, c in list(tiny.items()):
                col_meta[n] = (c.dtype, c.dictionary, True)
                tiny[n] = Column(c.dtype,
                                 jnp.zeros(0, _DEVICE_DTYPE[c.dtype]),
                                 jnp.zeros(0, jnp.bool_), c.dictionary)
        rnames = {r for _, r in pairs}
        side_meta = side.table_meta if isinstance(side, _ExchangeSide) else \
            {n: (side.table.column(n).dtype, side.table.column(n).dictionary,
                 side.table.column(n).validity is not None)
             for n in side.table.names}
        for n, (dt, dic, nul) in side_meta.items():
            if jt in ("left", "full"):
                nul = True
            if n not in rnames:
                tiny[n] = Column(dt, jnp.zeros(0, _DEVICE_DTYPE[dt]),
                                 jnp.zeros(0, jnp.bool_) if nul else None,
                                 dic)
            col_meta[n] = (dt, dic, nul)
        for lname, rname in pairs:
            if rname in tiny:
                continue
            # Left/full outer: the right key column is null on the
            # unmatched-left padding rows, so it turns nullable even
            # when the source key is not. The exchange path carries
            # the right key column as data (its OWN dictionary) exactly
            # when a stage above consumes it (right_used); the broadcast
            # path rebuilds it from the stream key whenever the join
            # schema exposes it.
            if isinstance(side, _ExchangeSide) and rname in side.table_meta:
                dt, dic, nul0 = side.table_meta[rname]
                nul = nul0 or jt in ("left", "full")
            elif isinstance(side, _ExchangeSide):
                # Key rides no data: the program rebuilds it from the
                # stream key (single-key, non-preserve-right only).
                if side.pack is not None or jt in ("right", "full") \
                        or rname not in node.schema.names:
                    continue
                lc = tiny[pairs[0][0]]
                dt, dic = lc.dtype, lc.dictionary
                nul = lc.validity is not None or jt in ("left", "full")
            else:
                if rname not in node.schema.names:
                    continue
                lc = tiny[lname]
                dt, dic = lc.dtype, lc.dictionary
                nul = lc.validity is not None or jt in ("left", "full")
            tiny[rname] = Column(
                dt, jnp.zeros(0, _DEVICE_DTYPE[dt]),
                jnp.zeros(0, jnp.bool_) if nul else None, dic)
            col_meta[rname] = (dt, dic, nul)
    final_meta = {n: (c.dtype, c.dictionary, c.validity is not None)
                  for n, c in tiny.items()}
    return _Prepared(mesh, n_dev, sharded, valid, bcast_arrays, xch_arrays,
                     stages, joins, col_meta, final_meta, shard_rows,
                     out_rows, project_live,
                     file_aligned=bounds is not None)


def _emit_spmd_events(session, mode: str, prep: "_Prepared", caps,
                      attempts: int) -> None:
    """Observability per successful dispatch: one ShardedExecutionEvent with
    the mesh identity, the chosen PartitionSpecs, and the compiled
    program's HLO collective counts, plus one SpmdExchangeEvent per join
    stage (strategy, capacities) and one for the sort's range exchange.
    Event emission must never fail an execution."""
    if session is None:
        return
    try:
        from ..telemetry.events import SpmdExchangeEvent, ShardedExecutionEvent
        from ..telemetry.logging import NoOpEventLogger, get_logger
        logger = get_logger(session.hs_conf.event_logger_class())
        if isinstance(logger, NoOpEventLogger):
            return  # skip event (and lazy HLO-count) work entirely
        sig = mesh_signature(prep.mesh)
        out_specs = {"stream": f"rows:P({DATA_AXIS}) flags:P()",
                     "sort": f"rows:P({DATA_AXIS}) flags:P()",
                     "grouped-agg": f"partials:P({DATA_AXIS}) flags:P()",
                     "global-agg": "partials:P()"}[mode]
        logger.log_event(ShardedExecutionEvent(
            message=f"spmd {mode} over {prep.n_dev}-device mesh",
            mode=mode, mesh_axes=list(sig[0]), mesh_shape=list(sig[1]),
            mesh_platform=sig[2], shard_rows=prep.shard_rows,
            file_aligned_scan=prep.file_aligned,
            in_specs=f"stream:P({DATA_AXIS}) bcast:P() xch:P({DATA_AXIS})",
            out_specs=out_specs,
            collectives=last_collectives(), cap_attempts=attempts))
        for i in sorted(prep.joins):
            jkind, _pairs, _side, jt = prep.joins[i]
            cap, k_out = caps.get(i, (0, 0))
            logger.log_event(SpmdExchangeEvent(
                message=f"stage {i} {jt} join via "
                        + ("bucket exchange" if jkind == "x"
                           else "broadcast"),
                stage=i, join_type=jt,
                strategy="exchange" if jkind == "x" else "broadcast",
                capacity=cap, output_slots=k_out,
                all_to_all=2 if jkind == "x" else 0))
        if mode == "sort":
            cap, _ = caps.get(-1, (0, 0))
            logger.log_event(SpmdExchangeEvent(
                message="distributed sort range exchange", stage=-1,
                join_type="", strategy="sort-route", capacity=cap,
                output_slots=0, all_to_all=1))
    except Exception:
        pass  # observability must never fail an execution


# Exchange-capacity retries PER EXCHANGE JOIN: each retry recompiles with
# the EXACT needs the failed program reported (see _escalate_on_overflow),
# so one overflowing join needs 1 retry (2 with a skewed send). Chained
# exchange joins can discover needs one at a time — an upstream join's
# clamped output hides the downstream join's true input — so the budget
# scales with the join count instead of being a flat constant.
_MAX_CAP_RETRIES = 2

# Capacity attempts of the most recent _run/_run_stream (1 = first program
# fit). Tests pin the one-recompile contract with this. LAST-DISPATCH
# semantics only: concurrent queries overwrite each other here, so the
# per-query spans/events carry their own local attempt counts instead.
LAST_CAP_ATTEMPTS = 0


def _out_rows(prep: _Prepared, caps: Dict[int, Tuple[int, int]]) -> int:
    """Per-device rows after the last stage under the CURRENT caps (the
    last exchange join's output slots, or the stream shard size)."""
    rows = prep.shard_rows
    for i in sorted(i for i, j in prep.joins.items() if j[0] == "x"):
        rows = caps[i][1]
    return rows


def _record_join_actuals(session, prep: "_Prepared", out) -> None:
    """Write the SPMD program's observed inner-join output rows (the
    psum'd ``jrows:`` outputs) to the same session store the
    single-device executor uses (serving/context.record_join_actual) —
    the join-reorder q-error pairing works on the distributed path too,
    so its instrumentation no longer pins ``distributed.enabled=false``."""
    from ..serving import context as qctx
    ctx = qctx.active_context()
    for i, (kind, node) in enumerate(prep.stages):
        key = f"jrows:{i}"
        if kind != "join" or key not in out:
            continue
        rows = int(np.asarray(jax.device_get(out[key])))
        akey = qctx.join_actual_key(node.condition, node.left, node.right)
        if ctx is not None:
            ctx.record_join_actual(akey, rows)
        elif session is not None:
            qctx.record_join_actual(session, akey, rows)


def _run(plan: Aggregate, executor, session=None) -> Table:
    """Dispatch wrapper: one ``spmd.dispatch`` span per mesh execution
    (capacity-escalation retries stay inside the one span — they are one
    dispatch from the query's point of view). The deadline check and the
    fault point sit here, BEFORE any mesh work: an expired query never
    pays a dispatch, and an injected dispatch fault propagates to the
    executor's SPMD->single-device degradation ladder."""
    from ..robustness import fault_names as _fltn
    from ..robustness import faults as _faults
    from ..serving.context import check_deadline
    check_deadline("spmd.dispatch")
    _faults.fault_point(_fltn.SPMD_DISPATCH)
    with _trace.span(SN.SPMD_DISPATCH, mode="agg") as sp:
        table, attempts = _run_impl(plan, executor, session)
        if sp is not None:
            sp.attrs["rows"] = int(table.num_rows)
            # The QUERY-LOCAL attempt count: the LAST_CAP_ATTEMPTS
            # module global is last-dispatch observability for
            # single-threaded tests/bench — a concurrent query may
            # overwrite it before this span closes.
            sp.attrs["cap_attempts"] = attempts
        return table


def _run_impl(plan: Aggregate, executor, session=None
              ) -> Tuple[Table, int]:
    global DISPATCH_COUNT, LAST_CAP_ATTEMPTS
    with _COUNT_LOCK:
        LAST_CAP_ATTEMPTS = 1
    caps: Dict[int, Tuple[int, int]] = {}
    # Prepared ONCE: leaf IO, join-side materialization, and sharding don't
    # depend on caps — only the jitted program (static shapes) does, so
    # escalation retries recompile but never redo IO.
    prep = _prepare(plan.child, executor, caps, session)

    def probe(e: E.Expr) -> Column:
        t = {n: Column(dt, jnp.zeros(0, _DEVICE_DTYPE[dt]),
                       jnp.zeros(0, jnp.bool_) if nul else None, dic)
             for n, (dt, dic, nul) in prep.final_meta.items()}
        return eval_expr(Table(t), e)

    agg_specs = tuple(_AggSpec.build(a, probe) for a in plan.aggs)
    group_cols = tuple(plan.group_cols)
    for g in group_cols:
        if g not in prep.final_meta:
            raise _Unsupported(f"unknown group column {g}")
    grouped = bool(group_cols)
    n_dev = prep.mesh.devices.size
    G2 = 0  # sized from G on first iteration
    cap_attempts = 0
    gmof_retried = False
    gof_retried = False
    G_floor = 0  # raised by the one-shot local-capacity retry
    routed = _use_routed_merge(prep.mesh)
    while True:
        # MAX_LOCAL_GROUPS is the INITIAL local-partial capacity, not a
        # ceiling (VERDICT r5 #6: TPC-DS groups by customer/item keys blow
        # 65k immediately): on overflow the program reports the exact
        # worldwide need and one retry re-runs with that many slots
        # (bounded by per-device rows — distinct groups can't exceed them).
        G = min(_out_rows(prep, caps), MAX_LOCAL_GROUPS)
        G = min(max(G, G_floor), _out_rows(prep, caps))
        G2 = min(max(G2, G), n_dev * G)
        descr = _StageDescr(prep.stages, prep.joins, prep.col_meta,
                            agg_specs, group_cols, dict(caps),
                            prep.project_live)
        out = _spmd_program(prep.sharded, prep.valid, prep.bcast, prep.xch,
                            mesh=prep.mesh, descr=descr, grouped=grouped,
                            G=G, G2=G2, mode="agg", routed_merge=routed)
        if _escalate_on_overflow(out, caps):
            cap_attempts += 1
            n_xch = sum(1 for j in prep.joins.values() if j[0] == "x")
            if cap_attempts > _MAX_CAP_RETRIES * max(n_xch, 1):
                raise _Unsupported(
                    "exchange join capacity escalation exhausted")
            with _COUNT_LOCK:
                LAST_CAP_ATTEMPTS = cap_attempts + 1
            # New caps → new partial-group distribution; the one-shot
            # owner-capacity retry becomes available again.
            gmof_retried = False
            continue
        if grouped:
            if bool(np.asarray(jax.device_get(out["overflow"]))):
                if gof_retried:
                    raise _Unsupported("local group capacity overflow "
                                       "after exact-need retry")
                gof_retried = True
                need = int(np.asarray(jax.device_get(out["gneed"])))
                G_floor = min(_round_up_pow2(need),
                              _out_rows(prep, caps))
                gmof_retried = False  # new G → new owner distribution
                continue
            if routed and bool(np.asarray(jax.device_get(out["gmof"]))):
                # One owner device holds more than G2 distinct groups
                # (hash skew). The program reports the exact capacity
                # needed, so ONE retry — with its own budget, not the
                # exchange-cap one — always suffices (rounded up to a
                # multiple of G to keep the jit cache coarse; hard bound:
                # total groups ≤ n_dev*G).
                if gmof_retried:
                    raise _Unsupported("merge capacity retry failed")
                gmof_retried = True
                need = int(np.asarray(jax.device_get(out["gmneed"])))
                G2 = min(max(G2 + 1, -(-need // G) * G), n_dev * G)
                continue
            table = _merge_grouped(out, agg_specs, list(group_cols),
                                   prep.final_meta)
        else:
            table = _merge_global(out, agg_specs, prep.final_meta)
        with _COUNT_LOCK:
            DISPATCH_COUNT += 1
        _record_join_actuals(session, prep, out)
        # Emit the query-local attempt count, not the module global: a
        # concurrent dispatch may have reset LAST_CAP_ATTEMPTS already.
        _emit_spmd_events(session,
                          "grouped-agg" if grouped else "global-agg",
                          prep, caps, cap_attempts + 1)
        return table, cap_attempts + 1


def _run_stream(root, executor, sort_orders=(), session=None) -> Table:
    """Dispatch wrapper for the row-returning path — see :func:`_run`."""
    from ..robustness import fault_names as _fltn
    from ..robustness import faults as _faults
    from ..serving.context import check_deadline
    check_deadline("spmd.dispatch")
    _faults.fault_point(_fltn.SPMD_DISPATCH)
    mode = "sort" if sort_orders else "stream"
    with _trace.span(SN.SPMD_DISPATCH, mode=mode) as sp:
        table, attempts = _run_stream_impl(root, executor, sort_orders,
                                           session)
        if sp is not None:
            sp.attrs["rows"] = int(table.num_rows)
            sp.attrs["cap_attempts"] = attempts  # query-local; see _run
        return table


def _run_stream_impl(root, executor, sort_orders=(), session=None
                     ) -> Tuple[Table, int]:
    """Row-returning SPMD execution of a {Filter, Project, Join}* chain:
    every device runs the stages on its shard, the host gathers each
    device's valid rows and concatenates (VERDICT r3 #3a). With
    ``sort_orders``, the program additionally range-partitions and sorts
    on device (sample sort), so the gathered rows arrive globally sorted
    and the host does NO sort work."""
    global DISPATCH_COUNT, SORT_DISPATCH_COUNT, LAST_CAP_ATTEMPTS
    with _COUNT_LOCK:
        LAST_CAP_ATTEMPTS = 1
    caps: Dict[int, Tuple[int, int]] = {}
    prep = _prepare(root, executor, caps, session)  # once; see _run
    out_names = [n for n in root.schema.names if n in prep.final_meta]
    if not out_names:
        raise _Unsupported("no output columns")
    mode = "stream"
    if sort_orders:
        mode = "sort"
        for n, _asc in sort_orders:
            if n not in prep.final_meta:
                raise _Unsupported(f"sort key {n!r} not in stream output")
        # Initial per-(src, dst) send block: 2x the balanced share;
        # sorted/skewed inputs overflow once and retry with the exact
        # reported need (same mechanism as the exchange joins, keyed -1).
        caps[-1] = (_round_up_pow2(
            max(2 * prep.shard_rows // prep.n_dev, 128)), 0)
    out_pairs = tuple((n, prep.final_meta[n][2]) for n in out_names)
    n_xch = sum(1 for j in prep.joins.values() if j[0] == "x")
    for attempt in range(_MAX_CAP_RETRIES * (n_xch + 1) + 1):
        with _COUNT_LOCK:
            LAST_CAP_ATTEMPTS = attempt + 1
        descr = _StageDescr(prep.stages, prep.joins, prep.col_meta,
                            (), out_pairs, dict(caps), prep.project_live,
                            sort_orders=tuple(sort_orders))
        out = _spmd_program(prep.sharded, prep.valid, prep.bcast, prep.xch,
                            mesh=prep.mesh, descr=descr, grouped=False,
                            G=1, mode=mode)
        if _escalate_on_overflow(out, caps):
            continue
        mask = np.asarray(jax.device_get(out["omask"]))
        cols: Dict[str, Column] = {}
        for n in out_names:
            dt, dic, nul = prep.final_meta[n]
            data = np.asarray(jax.device_get(out[f"o:{n}"]))[mask]
            validity = None
            if f"ov:{n}" in out:
                validity = jnp.asarray(
                    np.asarray(jax.device_get(out[f"ov:{n}"]))[mask])
            cols[n] = Column(dt, jnp.asarray(data), validity, dic)
        with _COUNT_LOCK:
            DISPATCH_COUNT += 1
            if mode == "sort":
                SORT_DISPATCH_COUNT += 1
        _record_join_actuals(session, prep, out)
        # Query-local attempt count (see _run): the module global is
        # last-dispatch observability only.
        _emit_spmd_events(session, mode, prep, caps, attempt + 1)
        return Table(cols), attempt + 1
    raise _Unsupported("exchange join capacity escalation exhausted")


def _round_up_pow2(n: int) -> int:
    """Retry capacities round up to a power of two: ≤2× memory waste and a
    coarse jit-cache key (many different exact needs share one program)."""
    return max(128, 1 << max(int(n) - 1, 1).bit_length())


def _escalate_on_overflow(out, caps: Dict[int, Tuple[int, int]]) -> bool:
    """True if any exchange join overflowed its capacity; caps are set in
    place from the EXACT needs the program reported, so one recompile
    suffices in the common case (VERDICT r3 #6 — a blind ×4 ladder would
    recompile up to 4 programs per query on a backend where each compile
    can kill the remote-compile service).

    The send-block need (``xneedc``) is measured before slot clamping and
    is always exact. The output-slot need (``xneedo``) is exact only when
    the send side fit — a clamped receive undercounts matches — so after a
    send overflow (``xneedc`` above cap) the retry doubles the reported output need as
    a safety margin; the attempt after that sees exact numbers. Worst case
    is therefore 2 retries (skewed send), 1 in the common case."""
    bumped = False
    for key in out:
        if not key.startswith("xof:"):
            continue
        i = int(key.split(":")[1])
        if bool(np.asarray(jax.device_get(out[key]))):
            cap, k_out = caps[i]
            need_c = int(np.asarray(jax.device_get(out[f"xneedc:{i}"])))
            need_o = int(np.asarray(jax.device_get(out[f"xneedo:{i}"])))
            send_of = need_c > cap  # definitionally the send overflow
            new_cap = max(cap, _round_up_pow2(need_c))
            new_out = max(k_out, _round_up_pow2(
                need_o * 2 if send_of else need_o))
            caps[i] = (new_cap, new_out)
            bumped = True
    return bumped


class _StageDescr:
    """Static (hashable) description of the SPMD program. The hash is a
    *structural* signature so repeated executions of the same query shape
    hit the jit cache instead of recompiling; string dictionaries are part
    of the key because they become trace-time constants.

    ``group_cols`` doubles as the output-column list in stream mode (the
    program has no grouping there). ``caps`` maps exchange-join stage index
    → (send capacity per destination, output slots per device)."""

    def __init__(self, stages, joins, col_meta, agg_specs, group_cols,
                 caps, project_live, sort_orders=()):
        self.stages = stages
        self.joins = joins
        self.col_meta = col_meta
        self.agg_specs = agg_specs
        self.group_cols = group_cols
        self.caps = caps
        self.project_live = project_live
        self.sort_orders = tuple(sort_orders)
        parts: List = [group_cols, tuple(sorted(caps.items())),
                       self.sort_orders,
                       tuple(sorted((i, tuple(sorted(v)))
                             for i, v in project_live.items()))]
        for i, (kind, node) in enumerate(stages):
            if kind == "filter":
                parts.append(("F", repr(node.condition)))
            elif kind == "project":
                parts.append(("P", tuple(repr(e) for e in node.exprs)))
            else:
                jkind, pairs, side, jt = joins[i]
                parts.append(("J", jkind, jt, repr(node.condition),
                              tuple(node.schema.names), side.pack))
        for n, (dt, dic, nul) in sorted(col_meta.items()):
            parts.append((n, dt, _dict_fingerprint(dic), nul))
        for s in agg_specs:
            parts.append((s.name, s.kind, repr(s.child), s.out_dtype,
                          _dict_fingerprint(s.dictionary)))
        self._sig = tuple(parts)

    def __hash__(self):
        return hash(self._sig)

    def __eq__(self, other):
        return isinstance(other, _StageDescr) and self._sig == other._sig


def _stream_probe_key(table: Table, pairs, pack) -> Tuple[jax.Array, jax.Array]:
    """(probe key array, all-keys-valid mask) for a join stage. Single-key
    joins probe the raw column; multi-key joins build the bit-packed
    composite using the broadcast side's (rmin, shift, sentinel) spec —
    out-of-range stream values map to the sentinel, which never matches."""
    if pack is None:
        lc = table.column(pairs[0][0])
        valid = lc.validity if lc.validity is not None \
            else jnp.ones(lc.data.shape[0], jnp.bool_)
        return lc.data, valid
    comp = None
    valid = None
    for (lname, _), (rmin, shift, sentinel) in zip(pairs, pack):
        lc = table.column(lname)
        c = lc.data.astype(jnp.int64)
        code = jnp.where((c >= rmin) & (c <= rmin + sentinel - 1),
                         c - rmin, sentinel)
        comp = (code << shift) if comp is None else comp | (code << shift)
        v = lc.validity
        if v is not None:
            valid = v if valid is None else (valid & v)
    if valid is None:
        valid = jnp.ones(comp.shape[0], jnp.bool_)
    return comp, valid


def _use_routed_merge(mesh: Mesh) -> bool:
    """Backend cost decision for the grouped final merge: route partial
    groups to owner devices over the mesh collective (real multi-chip —
    the merge then scales with devices and the host only concatenates), or
    hand the partials straight to the host merge (single-host CPU mesh:
    the 'devices' share the silicon the host merge runs on, so the
    exchange is pure added work). HST_SPMD_ROUTED_MERGE=on|off overrides."""
    mode = os.environ.get("HST_SPMD_ROUTED_MERGE", "auto")
    if mode in ("on", "off"):
        return mode == "on"
    return mesh.devices.flat[0].platform != "cpu"


def _group_segments(mask, flags, datas, cap: int):
    """Shared grouping step for the local-partial AND owner-merge phases:
    sort rows by (masked-out last, [null-flag, value] per key column),
    detect group boundaries, and assign capacity-bounded segment ids.

    Returns (order, sorted mask, sorted flags, sorted datas, gids,
    n_groups): ``gids`` carries ``cap`` for masked-out rows (segment ops
    drop them); ``n_groups`` is the distinct count before clamping —
    overflow iff > cap."""
    sort_ops = [(~mask).astype(jnp.int32)]
    for f, d in zip(flags, datas):
        sort_ops.extend([f, d])
    order = kernels.lex_sort_indices(sort_ops)
    s_mask = jnp.take(mask, order)
    s_flags = [jnp.take(f, order) for f in flags]
    s_datas = [jnp.take(d, order) for d in datas]
    n = s_mask.shape[0]
    change = jnp.zeros(n, jnp.bool_)
    for arr in s_flags + s_datas:
        change = change | jnp.concatenate(
            [jnp.zeros(1, jnp.bool_), arr[1:] != arr[:-1]])
    first = jnp.concatenate(
        [jnp.ones(1, jnp.bool_), jnp.zeros(n - 1, jnp.bool_)])
    newg = s_mask & (change | first)
    gids_raw = jnp.cumsum(newg.astype(jnp.int32)) - 1
    gids = jnp.where(s_mask, gids_raw, cap)
    n_groups = jnp.max(jnp.where(s_mask, gids_raw + 1, 0))
    return order, s_mask, s_flags, s_datas, gids, n_groups


def _a2a_exchange(arrays: Dict[str, jax.Array], send_ok: jax.Array,
                  dst: jax.Array, n_dev: int, cap: int):
    """Route rows to their destination device with ONE lax.all_to_all.
    ``dst`` in [0, n_dev); rows with ``send_ok`` False are dropped. Returns
    (received arrays, received-valid mask, overflow flag, exact need) —
    overflow is raised (pmax) when any (device, destination) block exceeds
    ``cap``; ``need`` is the worldwide max block count, i.e. the exact
    capacity a retry must allocate (counts are measured BEFORE clamping,
    so the need is reliable even on overflow)."""
    rows = send_ok.shape[0]
    dst = jnp.where(send_ok, dst, n_dev)  # drop → virtual device n_dev
    perm = kernels.lex_sort_indices([dst])
    sorted_dst = jnp.take(dst, perm)
    starts = jnp.searchsorted(sorted_dst,
                              jnp.arange(n_dev + 1, dtype=sorted_dst.dtype))
    counts = starts[1:] - starts[:-1]
    overflow = jax.lax.pmax(jnp.any(counts > cap).astype(jnp.int32),
                            DATA_AXIS)
    need = jax.lax.pmax(jnp.max(counts).astype(jnp.int32), DATA_AXIS)
    pos = jnp.arange(rows, dtype=jnp.int32) - jnp.take(
        starts, jnp.minimum(sorted_dst, n_dev)).astype(jnp.int32)
    slot_ok = (pos < cap) & (sorted_dst < n_dev)
    send_idx = jnp.where(slot_ok, sorted_dst * cap + pos, n_dev * cap)

    def scatter(arr):
        taken = jnp.take(arr, perm, axis=0)
        buf = jnp.zeros((n_dev * cap + 1,) + arr.shape[1:], arr.dtype)
        return buf.at[send_idx].set(taken, mode="drop")[:-1]

    def a2a(x):
        return jax.lax.all_to_all(
            x.reshape((n_dev, cap) + x.shape[1:]), DATA_AXIS,
            split_axis=0, concat_axis=0).reshape((n_dev * cap,) + x.shape[1:])

    recv = {name: a2a(scatter(a)) for name, a in arrays.items()}
    recv_valid = a2a(jnp.zeros(n_dev * cap + 1, jnp.bool_)
                     .at[send_idx].set(slot_ok, mode="drop")[:-1])
    return recv, recv_valid, overflow, need


# (program, shape signature) of the most recent SPMD dispatch. Rebound
# (never mutated) per _spmd_program call; last_collectives() reads it
# lazily. The SIGNATURE is retained, not the arguments — live device
# arrays here would pin the last query's whole sharded input in device
# memory for as long as the process idles.
_LAST_PROGRAM: Optional[Tuple] = None


def last_collectives() -> Dict[str, int]:
    """HLO collective counts of the most recent SPMD program — computed
    lazily from the retained compiled executable (rendering HLO text is
    too expensive for the dispatch path) and cached per program."""
    if _LAST_PROGRAM is None:
        return {}
    prog, sig = _LAST_PROGRAM
    return prog.collectives_for(sig)


def _spmd_program(sharded, valid, bcast, xch, *, mesh: Mesh,
                  descr: _StageDescr, grouped: bool, G: int, mode: str,
                  G2: int = 1, routed_merge: bool = True):
    stages, joins, col_meta = descr.stages, descr.joins, descr.col_meta
    agg_specs, group_cols = descr.agg_specs, descr.group_cols
    n_dev = mesh.devices.size

    def per_device(sharded, valid, bcast, xch):
        cols = {}
        for key, arr in sharded.items():
            tag, name = key.split(":", 1)
            if tag != "d":
                continue
            dt, dic, _ = col_meta[name]
            cols[name] = Column(dt, arr, sharded.get(f"v:{name}"), dic)
        table = Table(cols)
        mask = valid
        overflow_flags = {}

        for i, (kind, node) in enumerate(stages):
            if kind == "filter":
                mask = mask & eval_predicate_mask(table, node.condition)
            elif kind == "project":
                live = descr.project_live.get(i, frozenset())
                table = Table({e.name: eval_expr(table, e)
                               for e in node.exprs if e.name in live})
            elif joins[i][0] == "b":  # broadcast join probe
                _, pairs, side, jt = joins[i]
                lk, keys_valid = _stream_probe_key(table, pairs, side.pack)
                rkeys = bcast[f"k:{i}"]
                n_r = rkeys.shape[0]
                if n_r == 0:
                    found = jnp.zeros(lk.shape[0], jnp.bool_)
                    idx_c = jnp.zeros(lk.shape[0], jnp.int32)
                else:
                    idx = jnp.searchsorted(rkeys, lk)
                    idx_c = jnp.minimum(idx, n_r - 1)
                    found = jnp.take(rkeys, idx_c) == lk
                found = found & keys_valid
                if jt == "semi":
                    mask = mask & found
                    continue
                if jt == "anti":
                    # Null / unmatched keys match nothing → kept (the
                    # NOT IN non-null convention the executor documents).
                    mask = mask & ~found
                    continue
                if jt == "inner":
                    mask = mask & found
                    # Observed join output rows (m:1 probe: one emit per
                    # surviving stream row) — psum'd so the host can
                    # write the actual back to the session's q-error
                    # store (optimizer/join_order pairing).
                    overflow_flags[f"jrows:{i}"] = jax.lax.psum(
                        jnp.sum(mask.astype(jnp.int32)), DATA_AXIS)
                # left outer: mask unchanged — unmatched stream rows stay,
                # with the right columns invalid below.
                rnames = {r for _, r in pairs}
                new_cols = dict(table.columns)
                for n in side.table.names:
                    if n in rnames:
                        continue
                    rc = side.table.column(n)
                    if n_r == 0:
                        data = jnp.zeros(lk.shape[0],
                                         _DEVICE_DTYPE[rc.dtype])
                        vv = None
                    else:
                        data = jnp.take(bcast[f"b:{i}:{n}"], idx_c, axis=0)
                        vkey = f"bv:{i}:{n}"
                        vv = (jnp.take(bcast[vkey], idx_c)
                              if vkey in bcast else None)
                    if jt == "left":
                        vv = found if vv is None else (vv & found)
                    new_cols[n] = Column(rc.dtype, data, vv, rc.dictionary)
                for lname, rname in pairs:
                    if rname in node.schema.names and rname not in new_cols:
                        lc = table.column(lname)
                        # Matched rows: right key == left key by definition;
                        # left-outer padding rows carry a null right key.
                        vv = lc.validity
                        if jt == "left":
                            vv = found if vv is None else (vv & found)
                        new_cols[rname] = Column(lc.dtype, lc.data, vv,
                                                 lc.dictionary)
                table = Table(new_cols)
            else:  # exchange (m:n shuffle) join
                _, pairs, side, jt = joins[i]
                cap, k_out = descr.caps[i]
                lk, keys_valid = _stream_probe_key(table, pairs, side.pack)
                preserve_left = jt in ("left", "full")
                preserve_right = jt in ("right", "full")
                # Preserved-left rows route even with a null key (they
                # must surface as unmatched); a "kv" flag rides along so
                # the merge still refuses to match them. Otherwise
                # null-key rows are dropped at the send.
                l_ok = mask if preserve_left else (mask & keys_valid)
                # Routing hashes the key in the SAME code space on both
                # sides, so equal keys land on one device. String keys are
                # already translated into one dictionary — their codes
                # hash as plain int32 (no dictionary needed for routing;
                # equal codes ⇔ equal strings); multi-key composites are
                # packed int64 on both sides.
                dtype = INT32 if side.key_dtype == STRING else side.key_dtype
                dst_l = (kernels.hash32_values(lk, dtype)
                         % np.uint32(n_dev)).astype(jnp.int32)
                l_arrays = {"k": lk}
                if preserve_left:
                    l_arrays["kv"] = keys_valid
                for n in table.names:
                    c = table.column(n)
                    l_arrays[f"d:{n}"] = c.data
                    if c.validity is not None:
                        l_arrays[f"v:{n}"] = c.validity
                recv_l, lvalid, of_l, need_l = _a2a_exchange(
                    l_arrays, l_ok, dst_l, n_dev, cap)

                rk = xch[f"x:{i}:k"]
                r_ok = xch[f"x:{i}:__valid"]
                dst_r = (kernels.hash32_values(rk, dtype)
                         % np.uint32(n_dev)).astype(jnp.int32)
                r_arrays = {n[len(f"x:{i}:"):]: a for n, a in xch.items()
                            if n.startswith(f"x:{i}:") and
                            not n.endswith("__valid")}
                recv_r, rvalid, of_r, need_r = _a2a_exchange(
                    r_arrays, r_ok, dst_r, n_dev, cap)
                overflow_flags[f"xof:{i}"] = jnp.maximum(of_l, of_r)
                # Exact retry sizing: worst (src, dst) block over both
                # sides. Send overflow is recoverable host-side as
                # need > cap, so no separate flag rides along.
                overflow_flags[f"xneedc:{i}"] = jnp.maximum(need_l, need_r)

                # Local merge join: right sorted (valid first, by key),
                # invalid tail pinned to the key dtype's max so the whole
                # array stays ascending for searchsorted; hi is clamped to
                # the valid prefix length. Because equal keys all meet on
                # one device, LOCAL match status is GLOBAL match status —
                # which is what lets outer joins emit their unmatched
                # rows here without any further coordination.
                rkr = recv_r["k"]
                # Key-valid ∧ receive-valid: null-key right rows (carried
                # only under right/full, flagged "kv") must never match
                # but still appendix as unmatched.
                rkeyok = rvalid
                if "kv" in recv_r:
                    rkeyok = rvalid & recv_r["kv"]
                sort_r = kernels.lex_sort_indices(
                    [(~rkeyok).astype(jnp.int32), rkr])
                rk_sorted = jnp.take(rkr, sort_r)
                rvalid_sorted = jnp.take(rvalid, sort_r)
                rkeyok_sorted = jnp.take(rkeyok, sort_r)
                n_valid_r = jnp.sum(rkeyok.astype(jnp.int32))
                rk_probe = jnp.where(rkeyok_sorted, rk_sorted,
                                     _max_sentinel(rk_sorted.dtype))
                lkr = recv_l["k"]
                lkvalid = lvalid
                if preserve_left:
                    lkvalid = lvalid & recv_l["kv"]
                lo = jnp.searchsorted(rk_probe, lkr, side="left")
                hi = jnp.minimum(
                    jnp.searchsorted(rk_probe, lkr, side="right"), n_valid_r)
                matched_counts = jnp.where(
                    lkvalid, jnp.maximum(hi - lo, 0), 0).astype(jnp.int32)
                if preserve_left:
                    # Every received stream row emits at least once.
                    emit_counts = jnp.where(
                        lvalid, jnp.maximum(matched_counts, 1), 0)
                else:
                    emit_counts = matched_counts
                total_l = jnp.sum(emit_counts)
                n_l = lkr.shape[0]
                li = jnp.repeat(jnp.arange(n_l, dtype=jnp.int32),
                                emit_counts, total_repeat_length=k_out)
                starts_ = jnp.cumsum(emit_counts) - emit_counts
                base = jnp.repeat(starts_.astype(jnp.int32), emit_counts,
                                  total_repeat_length=k_out)
                within = jnp.arange(k_out, dtype=jnp.int32) - base
                is_match = within < jnp.take(matched_counts, li)
                ri = jnp.repeat(lo.astype(jnp.int32), emit_counts,
                                total_repeat_length=k_out) + \
                    jnp.where(is_match, within, 0)
                ri = jnp.clip(ri, 0, max(rkr.shape[0] - 1, 0))

                if preserve_right:
                    # Right rows whose key no received left row carries
                    # emit once, appended after the matched block. The
                    # left keys need their own sort for the probe.
                    sort_l = kernels.lex_sort_indices(
                        [(~lkvalid).astype(jnp.int32), lkr])
                    lk_sorted = jnp.take(lkr, sort_l)
                    lkv_sorted = jnp.take(lkvalid, sort_l)
                    n_valid_l = jnp.sum(lkvalid.astype(jnp.int32))
                    lk_probe = jnp.where(lkv_sorted, lk_sorted,
                                         _max_sentinel(lk_sorted.dtype))
                    lo_r = jnp.searchsorted(lk_probe, rk_sorted, side="left")
                    hi_r = jnp.minimum(
                        jnp.searchsorted(lk_probe, rk_sorted, side="right"),
                        n_valid_l)
                    r_unmatched = rvalid_sorted & \
                        (~rkeyok_sorted | ((hi_r - lo_r) <= 0))
                    appendix = jnp.sum(r_unmatched.astype(jnp.int32))
                    appendix_pos = total_l + jnp.cumsum(
                        r_unmatched.astype(jnp.int32)) - 1
                    # mode="drop" discards slots at/above k_out.
                    appendix_slot = jnp.where(r_unmatched, appendix_pos,
                                              k_out).astype(jnp.int32)
                    total_eff = total_l + appendix
                else:
                    appendix_slot = None
                    total_eff = total_l
                overflow_flags[f"xof:{i}"] = jnp.maximum(
                    overflow_flags[f"xof:{i}"],
                    jax.lax.pmax((total_eff > k_out).astype(jnp.int32),
                                 DATA_AXIS))
                # Exact per-device output need (counts are computed before
                # any slot clamping, so this is exact whenever the send
                # side fit — xneedc above cap marks the exception).
                overflow_flags[f"xneedo:{i}"] = jax.lax.pmax(
                    total_eff.astype(jnp.int32), DATA_AXIS)
                out_mask = jnp.arange(k_out, dtype=jnp.int32) < total_eff

                live = jnp.arange(k_out, dtype=jnp.int32) < total_l
                new_cols = {}
                for n in table.names:
                    # Stream meta snapshot from prep time: projects below
                    # this join may have created/redefined columns the
                    # leaf col_meta doesn't describe.
                    dt, dic, _ = side.stream_meta[n]
                    data = jnp.take(recv_l[f"d:{n}"], li, axis=0)
                    vv = (jnp.take(recv_l[f"v:{n}"], li)
                          if f"v:{n}" in recv_l else None)
                    if preserve_right:
                        # Appendix rows have no left side: null-pad.
                        vv = live if vv is None else (vv & live)
                    new_cols[n] = Column(dt, data, vv, dic)
                for n, (dt, dic, nul) in side.table_meta.items():
                    col_sorted = jnp.take(recv_r[f"d:{n}"], sort_r, axis=0)
                    data = jnp.take(col_sorted, ri, axis=0)
                    vv = (jnp.take(jnp.take(recv_r[f"v:{n}"], sort_r), ri)
                          if f"v:{n}" in recv_r else None)
                    if preserve_left:
                        # Unmatched stream rows: right side is null.
                        vv = is_match if vv is None else (vv & is_match)
                    if preserve_right:
                        base_v = vv if vv is not None else \
                            jnp.ones(k_out, jnp.bool_)
                        scat_v = (jnp.take(recv_r[f"v:{n}"], sort_r)
                                  if f"v:{n}" in recv_r
                                  else jnp.ones(rkr.shape[0], jnp.bool_))
                        data = data.at[appendix_slot].set(
                            col_sorted, mode="drop")
                        vv = base_v.at[appendix_slot].set(
                            scat_v, mode="drop")
                    new_cols[n] = Column(dt, data, vv, dic)
                if side.pack is None and not preserve_right:
                    # Single-key, no appendix: the right key column is
                    # rebuilt for free from the stream key (equal on
                    # matches, null on left-outer padding) instead of
                    # riding the exchange as duplicate payload.
                    lname, rname = pairs[0]
                    if rname in node.schema.names \
                            and rname not in new_cols:
                        lcm = side.stream_meta[lname]
                        data = jnp.take(recv_l[f"d:{lname}"], li, axis=0)
                        vv = (jnp.take(recv_l[f"v:{lname}"], li)
                              if f"v:{lname}" in recv_l else None)
                        if preserve_left:
                            vv = is_match if vv is None else \
                                (vv & is_match)
                        new_cols[rname] = Column(lcm[0], data, vv, lcm[1])
                table = Table(new_cols)
                mask = out_mask
                if jt == "inner":
                    # Emitted match pairs across the mesh (inner: every
                    # emit is a match; preserved-outer shapes are not
                    # recorded, matching the executor's actuals policy).
                    overflow_flags[f"jrows:{i}"] = jax.lax.psum(
                        total_eff.astype(jnp.int32), DATA_AXIS)

        if mode == "sort":
            # Distributed ORDER BY: range-partitioned sample sort (the
            # TPU-native analogue of Spark's range-partitioned global
            # sort consumed via exchange planning). Each device samples
            # its primary sort key, splitters come back over one
            # all_gather, rows route with one all_to_all, and each
            # device's local lex sort finishes the job — the host then
            # concatenates ALREADY-SORTED device ranges in rank order.
            k0, asc0 = descr.sort_orders[0]
            c0 = table.column(k0)
            view = kernels._sort_key_view(c0.data, asc0)
            if c0.validity is not None:
                # Null placement (nulls first when ascending, last when
                # descending) holds in view space by routing nulls to the
                # extreme sentinel; the local sort places them exactly.
                sentinel = _min_sentinel(view.dtype) if asc0 \
                    else _max_sentinel(view.dtype)
                view = jnp.where(c0.validity, view, sentinel)

            order0 = kernels.lex_sort_indices(
                [(~mask).astype(jnp.int32), view])
            sorted_view = jnp.take(view, order0)
            v_count = jnp.sum(mask.astype(jnp.int32))
            k = _SORT_SAMPLES
            pos = jnp.minimum((jnp.arange(k, dtype=jnp.int32) * v_count)
                              // k, jnp.maximum(v_count - 1, 0))
            samples = jnp.where(
                v_count > 0, jnp.take(sorted_view, pos),
                jnp.full(k, _max_sentinel(view.dtype), view.dtype))
            all_samples = jax.lax.all_gather(
                samples, DATA_AXIS).reshape(-1)
            all_sorted = jnp.sort(all_samples)
            total = n_dev * k
            spl_pos = (jnp.arange(1, n_dev, dtype=jnp.int32) * total) \
                // n_dev
            splitters = jnp.take(all_sorted, spl_pos)
            dst = jnp.searchsorted(splitters, view,
                                   side="right").astype(jnp.int32)

            arrays = {}
            for n, nul in group_cols:
                c = table.column(n)
                arrays[f"d:{n}"] = c.data
                if nul:
                    arrays[f"v:{n}"] = c.validity \
                        if c.validity is not None \
                        else jnp.ones(c.data.shape[0], jnp.bool_)
            cap = descr.caps[-1][0]
            recv, rvalid, of, need = _a2a_exchange(
                arrays, mask, dst, n_dev, cap)
            out = dict(overflow_flags)
            out["xof:-1"] = of
            out["xneedc:-1"] = need
            out["xneedo:-1"] = need

            keys = [(~rvalid).astype(jnp.int32)]
            ascs = [True]
            for n, asc in descr.sort_orders:
                vkey = f"v:{n}"
                data = recv[f"d:{n}"]
                if vkey in recv:
                    keys.append(recv[vkey].astype(jnp.int32))
                    ascs.append(asc)
                    data = jnp.where(recv[vkey], data,
                                     jnp.zeros((), data.dtype))
                keys.append(data)
                ascs.append(asc)
            final = kernels.lex_sort_indices(keys, ascs)
            out["omask"] = jnp.take(rvalid, final)
            for n, nul in group_cols:
                out[f"o:{n}"] = jnp.take(recv[f"d:{n}"], final, axis=0)
                if nul:
                    out[f"ov:{n}"] = jnp.take(recv[f"v:{n}"], final)
            return out

        if mode == "stream":
            # group_cols doubles as ((name, nullable), ...) in stream mode.
            out = dict(overflow_flags)
            out["omask"] = mask
            for n, nul in group_cols:
                c = table.column(n)
                out[f"o:{n}"] = c.data
                if nul:
                    out[f"ov:{n}"] = c.validity if c.validity is not None \
                        else jnp.ones(c.data.shape[0], jnp.bool_)
            return out

        if not grouped:
            fold = {
                "sum": lambda v: jax.lax.psum(jnp.sum(v), DATA_AXIS),
                "min": lambda v: jax.lax.pmin(jnp.min(v), DATA_AXIS),
                "max": lambda v: jax.lax.pmax(jnp.max(v), DATA_AXIS),
            }
            out = dict(overflow_flags)
            for spec in agg_specs:
                for k, v in spec.partials(table, mask, fold).items():
                    out[f"{spec.name}:{k}"] = v
            return out

        # ---- grouped: capacity-bounded local partials ----
        # Null-aware (flag, data) encoding per key: null(0) sorts first.
        key_flags, key_datas = [], []
        for g in group_cols:
            c = table.column(g)
            if c.validity is not None:
                flag = c.validity.astype(jnp.int32)
                data = jnp.where(c.validity, c.data,
                                 jnp.zeros((), c.data.dtype))
            else:
                flag = jnp.ones(c.data.shape[0], jnp.int32)
                data = c.data
            key_flags.append(flag)
            key_datas.append(data)
        order, s_mask, s_flags, s_datas, gids, local_groups = \
            _group_segments(mask, key_flags, key_datas, G)
        n_rows = s_mask.shape[0]
        overflow = jax.lax.pmax((local_groups > G).astype(jnp.int32),
                                DATA_AXIS)
        # Exact worldwide need: a local-capacity overflow retries ONCE
        # with this (distinct groups ≤ rows, so the retry always fits).
        gneed = jax.lax.pmax(local_groups, DATA_AXIS)

        s_table = table.take(order)
        fold = {
            "sum": lambda v: kernels.segment_sum(v, gids, G),
            "min": lambda v: kernels.segment_min(v, gids, G),
            "max": lambda v: kernels.segment_max(v, gids, G),
        }
        out = {"overflow": overflow, "gneed": gneed}
        out.update(overflow_flags)
        for spec in agg_specs:
            for k, v in spec.partials(s_table, s_mask, fold).items():
                out[f"{spec.name}:{k}"] = v
        firsts = jnp.minimum(kernels.segment_first_index(gids, G),
                             n_rows - 1)
        for g, flag, data in zip(group_cols, s_flags, s_datas):
            out[f"g:{g}"] = jnp.take(data, firsts)
            out[f"gf:{g}"] = jnp.take(flag, firsts)
        out["gvalid"] = (jnp.arange(G, dtype=jnp.int32)
                         < jnp.minimum(local_groups, G))

        # ---- distributed final merge (the "final shuffle" on device) ----
        # Each partial group is hash-routed to its owner device with one
        # all_to_all and combined there, so the host receives DISJOINT
        # final groups and merely concatenates (its reduceat degenerates
        # to identity). cap=G can't overflow: a source device holds at
        # most G valid partial groups total. Owner-side capacity G2
        # escalates in _run (bounded by n_dev*G, the hard total).
        # ``routed_merge`` is a backend cost decision made by the caller:
        # on a VIRTUAL (single-host CPU) mesh the exchange adds work on
        # the same silicon the host merge would use, so the partials go
        # to the host merge instead; on real multi-chip the collective
        # rides ICI and the host stops being the merge bottleneck.
        if n_dev > 1 and routed_merge:
            send = {k: v for k, v in out.items()
                    if k not in ("overflow", "gvalid", "gneed")
                    and not k.startswith(("xof:", "xneedc:",
                                          "xneedo:", "jrows:"))}
            gv = out["gvalid"]
            h = None
            for g in group_cols:
                dt = table.column(g).dtype
                ch = kernels.hash32_values(
                    out[f"g:{g}"], INT32 if dt == STRING else dt)
                ch = kernels.hash_combine(
                    ch, out[f"gf:{g}"].astype(jnp.uint32))
                h = ch if h is None else kernels.hash_combine(h, ch)
            dst = (h % np.uint32(n_dev)).astype(jnp.int32)
            recv, rvalid, _, _ = _a2a_exchange(send, gv, dst, n_dev, cap=G)
            order2, m2, sflags2, sdatas2, gids2, owned = _group_segments(
                rvalid, [recv[f"gf:{g}"] for g in group_cols],
                [recv[f"g:{g}"] for g in group_cols], G2)
            nr = m2.shape[0]
            out["gmof"] = jax.lax.pmax((owned > G2).astype(jnp.int32),
                                       DATA_AXIS)
            # Exact capacity an owner needs — _run retries ONCE with this
            # (rounded up) instead of stepping blindly.
            out["gmneed"] = jax.lax.pmax(owned, DATA_AXIS)
            for spec in agg_specs:
                for k in spec.partial_keys():
                    v = jnp.take(recv[f"{spec.name}:{k}"], order2, axis=0)
                    if k == "min":
                        v = jnp.where(m2, v, _max_sentinel(v.dtype))
                        merged = kernels.segment_min(v, gids2, G2)
                    elif k == "max":
                        v = jnp.where(m2, v, _min_sentinel(v.dtype))
                        merged = kernels.segment_max(v, gids2, G2)
                    else:  # sum / count merge by summation
                        v = jnp.where(m2, v, jnp.zeros((), v.dtype))
                        merged = kernels.segment_sum(v, gids2, G2)
                    out[f"{spec.name}:{k}"] = merged
            firsts2 = jnp.minimum(kernels.segment_first_index(gids2, G2),
                                  nr - 1)
            for g, f2, d2 in zip(group_cols, sflags2, sdatas2):
                out[f"g:{g}"] = jnp.take(d2, firsts2)
                out[f"gf:{g}"] = jnp.take(f2, firsts2)
            out["gvalid"] = (jnp.arange(G2, dtype=jnp.int32)
                             < jnp.minimum(owned, G2))
        else:
            out["gmof"] = jnp.zeros((), jnp.int32)
        return out

    xof_keys = [f"{tag}:{i}" for i, j in descr.joins.items() if j[0] == "x"
                for tag in ("xof", "xneedc", "xneedo")]
    # Replicated (psum'd) per-inner-join output counts — the SPMD-path
    # join actuals the host records after a successful dispatch.
    xof_keys += [f"jrows:{i}" for i, j in descr.joins.items()
                 if j[3] == "inner"]
    if mode == "sort":
        xof_keys += ["xof:-1", "xneedc:-1", "xneedo:-1"]
    if mode in ("stream", "sort"):
        out_specs: Dict[str, P] = {"omask": P(DATA_AXIS)}
        for n, nul in group_cols:
            out_specs[f"o:{n}"] = P(DATA_AXIS)
            if nul:
                out_specs[f"ov:{n}"] = P(DATA_AXIS)
    elif grouped:
        out_specs = {"overflow": P(), "gmof": P(), "gneed": P()}
        if mesh.devices.size > 1 and routed_merge:
            out_specs["gmneed"] = P()
        for spec in agg_specs:
            for k in spec.partial_keys():
                out_specs[f"{spec.name}:{k}"] = P(DATA_AXIS)
        for g in group_cols:
            out_specs[f"g:{g}"] = P(DATA_AXIS)
            out_specs[f"gf:{g}"] = P(DATA_AXIS)
        out_specs["gvalid"] = P(DATA_AXIS)
    else:
        out_specs = {f"{spec.name}:{k}": P()
                     for spec in agg_specs for k in spec.partial_keys()}
    for k in xof_keys:
        out_specs[k] = P()

    def global_view(sharded, valid, bcast, xch):
        return device_view(
            per_device, mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(DATA_AXIS)),
            out_specs=out_specs)(sharded, valid, bcast, xch)

    # One bank entry per (stage fingerprint, mesh signature): the stage
    # fingerprint is the structural _StageDescr signature plus every
    # capacity/mode static — exactly what used to be the jit static-arg
    # key — so retries with escalated caps compile their own program while
    # repeated executions of the same query shape hit the bank (and two
    # sessions share it: the r11 cross-session contract now covers the
    # distributed tier).
    args = (sharded, valid, bcast, xch)
    prog = bank_program("exec", mesh,
                        (descr, grouped, G, G2, mode, routed_merge),
                        args, lambda: global_view)
    global _LAST_PROGRAM
    _LAST_PROGRAM = (prog, prog.signature(args))
    return prog(*args)


# ---------------------------------------------------------------------------
# Host-side merges.
# ---------------------------------------------------------------------------

def _nullable_inputs(spec: _AggSpec, col_meta) -> bool:
    if spec.child is None:
        return False
    return any(col_meta.get(r, (None, None, False))[2]
               for r in spec.child.references)


def _merge_global(out, agg_specs, final_meta) -> Table:
    cols = {}
    for spec in agg_specs:
        merged = {k: np.atleast_1d(np.asarray(
            jax.device_get(out[f"{spec.name}:{k}"])))
            for k in spec.partial_keys()}
        cols[spec.name] = spec.finalize(
            merged, nullable_inputs=_nullable_inputs(spec, final_meta))
    return Table(cols)


def _merge_grouped(out, agg_specs, group_cols: List[str], col_meta) -> Table:
    gvalid = np.asarray(jax.device_get(out["gvalid"]))
    sel = np.nonzero(gvalid)[0]
    keys = [np.asarray(jax.device_get(out[f"g:{g}"]))[sel]
            for g in group_cols]
    flags = [np.asarray(jax.device_get(out[f"gf:{g}"]))[sel]
             for g in group_cols]
    partials = {f"{s.name}:{k}": np.asarray(
        jax.device_get(out[f"{s.name}:{k}"]))[sel]
        for s in agg_specs for k in s.partial_keys()}

    # Merge-sort all per-device partial groups by (null-first, value) —
    # the same order the per-device sort used, and the output row order
    # (the single-device path also emits groups key-sorted).
    sort_cols: List[np.ndarray] = []
    for f, k in zip(flags, keys):
        # Flag before key: np.lexsort makes the *last* key primary, and
        # sort_cols is reversed below, so per group column the null-flag
        # must precede the value to be the more significant key — matching
        # the per-device (flag, data) sort order (null-first, since null
        # rows carry flag 0 and value 0, and negative values sort after
        # the null group only when the flag dominates).
        sort_cols.append(f)
        sort_cols.append(k)
    order = np.lexsort(tuple(reversed(sort_cols))) if sort_cols else \
        np.arange(len(sel))
    keys = [k[order] for k in keys]
    flags = [f[order] for f in flags]
    partials = {k: v[order] for k, v in partials.items()}

    n = len(order)
    if n == 0:
        boundaries = np.zeros(0, np.intp)
    else:
        change = np.zeros(n, bool)
        change[0] = True
        for arr in keys + flags:
            change[1:] |= arr[1:] != arr[:-1]
        boundaries = np.nonzero(change)[0]

    def reduceat(op, arr):
        return op.reduceat(arr, boundaries) if n else arr[:0]

    cols: Dict[str, Column] = {}
    for g, k, f in zip(group_cols, keys, flags):
        dt, dic, has_nulls = col_meta[g]
        validity = jnp.asarray(f[boundaries].astype(bool)) if has_nulls \
            else None
        cols[g] = Column(dt, jnp.asarray(k[boundaries]), validity, dic)
    for spec in agg_specs:
        merged = {}
        for k in spec.partial_keys():
            arr = partials[f"{spec.name}:{k}"]
            op = {"count": np.add, "sum": np.add,
                  "min": np.minimum, "max": np.maximum}[k]
            merged[k] = reduceat(op, arr)
        cols[spec.name] = spec.finalize(
            merged, nullable_inputs=_nullable_inputs(spec, col_meta))
    ordered = {g: cols[g] for g in group_cols}
    for spec in agg_specs:
        ordered[spec.name] = cols[spec.name]
    return Table(ordered)
