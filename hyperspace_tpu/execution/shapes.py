"""Shape-class execution layer: padded length classes + compile observability.

The engine's hot-path tax on TPU is the XLA recompilation storm: every
data-dependent array length (filter survivor count, join match total, group
count, per-file row count) is a distinct static shape, and every eager jnp
primitive touching it forces a fresh trace+compile. One TPC-H q17 run was
measured at ~350 compilations (BENCH_r05) — the classic shape-instability
failure mode that makes cold/first-query latency unpredictable.

The fix implemented here: canonicalize lengths entering jitted kernels to a
GEOMETRIC LENGTH CLASS (power-of-``growthFactor`` multiples of
``minPadElements``), with an explicit valid count riding along. All
per-file / per-bucket / per-predicate invocations then collapse onto a
handful of compiled programs — one per (op, class) instead of one per
(op, exact length). Kernels guarantee byte-identical results after
unpadding; the padding/masking contract is:

- Padded rows carry arbitrary values. Any kernel consuming a padded array
  must either (a) be elementwise (garbage in the pad region stays in the pad
  region), (b) mask pads explicitly (``valid_mask``/``mask_tail``), or
  (c) route pads to a sink: sorts get a leading is-pad key so pads sort
  last; segment scatters get an out-of-range segment id (XLA drops
  out-of-bounds scatter updates); gathers use in-bounds filler indices.
- ``padded_length(n) == n`` whenever bucketing is disabled, the array is
  huge (``exactFallbackRows`` + ``maxWasteRatio``), or the input is a
  tracer (inside an outer jit the shape is already static — the SPMD path
  compiles its own fused programs and must not be re-padded).

Compile observability: a process-level counter hooked on jax.monitoring's
``/jax/core/compile/backend_compile_duration`` event (one firing per real
XLA backend compile). The executor emits the per-execution delta as a
``KernelCompileEvent``; ``explain()`` surfaces totals in its
"Compilation:" section; bench.py records per-phase counts from it.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import threading
from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..index.constants import IndexConstants

# ---------------------------------------------------------------------------
# Parameters (conf-backed; see config.py shape_bucketing_* accessors).
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ShapeParams:
    enabled: bool = \
        IndexConstants.TPU_SHAPE_BUCKETING_ENABLED_DEFAULT == "true"
    growth_factor: float = float(
        IndexConstants.TPU_SHAPE_BUCKETING_GROWTH_FACTOR_DEFAULT)
    min_pad: int = int(IndexConstants.TPU_SHAPE_BUCKETING_MIN_PAD_DEFAULT)
    max_waste_ratio: float = float(
        IndexConstants.TPU_SHAPE_BUCKETING_MAX_WASTE_RATIO_DEFAULT)
    exact_fallback_rows: int = int(
        IndexConstants.TPU_SHAPE_BUCKETING_EXACT_FALLBACK_ROWS_DEFAULT)


_DEFAULT_PARAMS = ShapeParams()
_PARAMS: contextvars.ContextVar = contextvars.ContextVar(
    "hst_shape_params", default=None)


def params_from_conf(hs_conf) -> ShapeParams:
    """Build ShapeParams from a HyperspaceConf (validated, clamped sane)."""
    growth = max(float(hs_conf.shape_bucketing_growth_factor()), 1.125)
    return ShapeParams(
        enabled=bool(hs_conf.shape_bucketing_enabled()),
        growth_factor=growth,
        min_pad=max(int(hs_conf.shape_bucketing_min_pad()), 1),
        max_waste_ratio=max(
            float(hs_conf.shape_bucketing_max_waste_ratio()), 0.0),
        exact_fallback_rows=max(
            int(hs_conf.shape_bucketing_exact_fallback_rows()), 1))


def active_params() -> ShapeParams:
    p = _PARAMS.get()
    return p if p is not None else _DEFAULT_PARAMS


@contextlib.contextmanager
def use_params(p: Optional[ShapeParams]):
    """Scope the active shape parameters (executor/actions enter this with
    the session conf; tests use it to force-enable/disable)."""
    token = _PARAMS.set(p)
    try:
        yield
    finally:
        _PARAMS.reset(token)


@contextlib.contextmanager
def use_conf(hs_conf):
    with use_params(params_from_conf(hs_conf) if hs_conf is not None
                    else None):
        yield


# ---------------------------------------------------------------------------
# Length classes.
# ---------------------------------------------------------------------------

def padded_length(n: int, params: Optional[ShapeParams] = None) -> int:
    """The geometric length class for ``n`` — the canonical padded length.

    Returns ``n`` unchanged when bucketing is disabled, ``n <= 0``, or the
    array is huge and the padding would waste more than ``max_waste_ratio``
    of its size (huge arrays amortize their own compile; the waste would be
    real HBM).
    """
    p = params if params is not None else active_params()
    if not p.enabled or n <= 0:
        return n
    c = p.min_pad
    # Geometric ladder; ceil keeps growth > 1 making progress at every rung.
    while c < n:
        c = int(math.ceil(c * p.growth_factor))
    if n >= p.exact_fallback_rows and (c - n) > p.max_waste_ratio * n:
        return n
    return c


def is_padded(arr, n: int) -> bool:
    return int(arr.shape[0]) != int(n)


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# Pad / mask / unpad primitives.
# ---------------------------------------------------------------------------

def pad_to(arr, target: int, fill=0):
    """Pad a 1-D array to ``target`` with ``fill``. Host numpy pads on host
    (no compile); device arrays go through the banked pad kernel (one
    program per (length, class, dtype) — vs one per op in the downstream
    chain — that the artifact store can persist across boots); tracers
    (SPMD prep walks) stay on the in-trace lax.pad."""
    n = int(arr.shape[0])
    if target <= n:
        return arr
    if isinstance(arr, np.ndarray):
        out = np.empty(target, dtype=arr.dtype)
        out[:n] = arr
        out[n:] = fill
        return out
    if _is_tracer(arr):
        pad_scalar = jnp.asarray(fill, arr.dtype)
        return jax.lax.pad(arr, pad_scalar, [(0, target - n, 0)])
    from ..ops import kernels
    return kernels.pad_array(arr, fill, target)


def pad_class(arr, fill=0, params: Optional[ShapeParams] = None):
    """(padded array, valid count): pad to the array's length class."""
    n = int(arr.shape[0])
    if _is_tracer(arr):
        return arr, n
    return pad_to(arr, padded_length(n, params), fill), n


def unpad(arr, n: int):
    """First ``n`` entries (the valid prefix) of a padded array."""
    if int(arr.shape[0]) == int(n):
        return arr
    if isinstance(arr, np.ndarray) or _is_tracer(arr):
        return arr[:n]
    from ..ops import kernels
    return kernels.slice_arrays((arr,), 0, int(n))[0]


def valid_mask(target: int, n: int):
    """Boolean mask: True for the valid prefix [0, n) of a class-length
    array. The comparison scalar is a runtime argument, so one compiled
    program serves every ``n`` at a given class."""
    return jnp.arange(target, dtype=jnp.int32) < jnp.int32(n)


def mask_tail(arr, n: int, fill):
    """Overwrite the pad region with ``fill`` (e.g. a searchsorted sentinel
    or an out-of-range segment id). No-op when the array is exact."""
    target = int(arr.shape[0])
    if target == int(n):
        return arr
    return jnp.where(valid_mask(target, n), arr,
                     jnp.asarray(fill, arr.dtype))


# ---------------------------------------------------------------------------
# Process-level compile counter (jax.monitoring hook).
# ---------------------------------------------------------------------------

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_counter_lock = threading.Lock()
_compile_total = 0
_compile_seconds = 0.0
_scope_counts: Dict[str, int] = {}
_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "hst_compile_scope", default=None)
_listener_installed = False


def _on_compile_event(event: str, duration_secs: float, **_kw) -> None:
    if event != _COMPILE_EVENT:
        return
    global _compile_total, _compile_seconds
    holder = _SCOPE.get()
    with _counter_lock:
        _compile_total += 1
        _compile_seconds += float(duration_secs)
        if holder is not None:
            holder["count"] += 1
            holder["seconds"] += float(duration_secs)
            label = holder["label"]
            _scope_counts[label] = _scope_counts.get(label, 0) + 1


def install_compile_counter() -> None:
    """Register the monitoring listener once per process (idempotent).
    The claim-then-register dance runs under the counter lock: two
    threads racing the unguarded flag would BOTH register the listener
    and double-count every compile from then on (HS301)."""
    global _listener_installed
    with _counter_lock:
        if _listener_installed:
            return
        _listener_installed = True
    try:
        jax.monitoring.register_event_duration_secs_listener(
            _on_compile_event)
    except Exception:  # very old jax without monitoring: counter stays 0
        with _counter_lock:
            _listener_installed = False


def compile_count() -> int:
    install_compile_counter()
    return _compile_total


def compile_seconds() -> float:
    install_compile_counter()
    return _compile_seconds


def scope_compile_count(label: str) -> int:
    return _scope_counts.get(label, 0)


@contextlib.contextmanager
def compile_scope(label: str):
    """Attribute compiles fired inside the scope to ``label`` (the executor
    wraps plan execution; tests wrap individual kernels). Yields a holder
    dict whose ``count``/``seconds`` tally only THIS context's compiles —
    the contextvar keeps concurrent serving executions from reading each
    other's deltas off the process-global counter."""
    install_compile_counter()
    holder = {"label": label, "count": 0, "seconds": 0.0}
    token = _SCOPE.set(holder)
    try:
        yield holder
    finally:
        _SCOPE.reset(token)


install_compile_counter()
