"""Frozen registry of fusion-region boundary kinds.

Every place the fusion planner/executor (execution/fusion.py) draws a
region boundary or abandons a fused execution must name WHY with one of
these constants — free-form strings are rejected by the scripts/lint.py
boundary-discipline gate (the span_names/fault_names precedent), and
every kind registered here must be referenced under tests/ (an
unexercised boundary is an unverified fallback path).

Two families share the registry:

- *Barriers* — plan shapes the fused program does not absorb; the region
  stops there and the barrier subtree executes staged (its own subchains
  may fuse independently).
- *Fallbacks* — runtime discoveries (duplicate probe keys, bucket-ordered
  streams, chunked sources, trace failures) that abandon an otherwise
  fusible region; the staged executor re-runs it byte-identically.

Keep the vocabulary SMALL: the kinds key fusion.stats()["fallbacks"]
and the bench/tests assert on them.
"""

from __future__ import annotations

# ---- barriers: plan shapes that end a region ------------------------------

# The region bottomed out at a source leaf (Scan/IndexScan) — the normal,
# successful boundary, counted so stats distinguish it from bailouts.
LEAF = "leaf"

SORT = "sort"
WINDOW = "window"
LIMIT = "limit"
UNION = "union"
AGGREGATE = "aggregate"          # a nested (non-root) Aggregate subtree
OUTER_JOIN = "outer-join"
CROSS_JOIN = "cross-join"
NON_EQUI_JOIN = "non-equi-join"
MULTI_KEY_JOIN = "multi-key-join"
COUNT_DISTINCT = "count-distinct"
UNSUPPORTED_AGG = "unsupported-agg"
UNSUPPORTED_EXPR = "unsupported-expr"

# ---- fallbacks: runtime bailouts on an otherwise fusible region -----------

DISABLED = "disabled"            # hyperspace.tpu.execution.fusion.enabled=false
SWEEP = "sweep"                  # literal-sweep batches own the staged path
REGION_TOO_SMALL = "region-too-small"
CHUNKED_SOURCE = "chunked-source"
BUCKET_ORDER = "bucket-order"    # stream carries covering-index layout
DUPLICATE_PROBE_KEYS = "duplicate-probe-keys"
KEY_DTYPE = "key-dtype"
EMPTY_INPUT = "empty-input"
FUSED_PROGRAM_ERROR = "fused-program-error"

BOUNDARY_KINDS = frozenset({
    LEAF, SORT, WINDOW, LIMIT, UNION, AGGREGATE, OUTER_JOIN, CROSS_JOIN,
    NON_EQUI_JOIN, MULTI_KEY_JOIN, COUNT_DISTINCT, UNSUPPORTED_AGG,
    UNSUPPORTED_EXPR, DISABLED, SWEEP, REGION_TOO_SMALL, CHUNKED_SOURCE,
    BUCKET_ORDER, DUPLICATE_PROBE_KEYS, KEY_DTYPE, EMPTY_INPUT,
    FUSED_PROGRAM_ERROR,
})
