"""Eager columnar plan executor.

Executes a logical plan bottom-up over device-resident tables. Each operator
is a fused XLA computation (jit happens inside the kernels); host↔device
traffic is limited to parquet IO, and the two architecturally-required scalar
syncs (join output size, group count) noted in ops/kernels.py.

The reference delegates all of this to Spark's execution engine; this module
is its TPU-native replacement (SURVEY §2 "the JVM/Spark execution engine
itself ... is the part the new framework replaces with XLA/Pallas kernels").
"""

from __future__ import annotations

import contextvars
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..exceptions import HyperspaceException, QueryDeadlineError
from ..ops import kernels
from ..plan import expr as E
from ..plan.nodes import (Aggregate, BucketUnion, Filter, IndexScan, Join, Limit,
                          LogicalPlan, Project, Scan, Sort, Union, Window)
from ..schema import BOOL, DATE, FLOAT64, INT32, INT64, STRING
from ..serving.context import check_deadline
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from . import shapes
from .columnar import (Column, Table, dictionaries_equal, filter_indices,
                       read_parquet, translate_codes)
from .evaluator import (eval_expr, eval_expr_maybe_fused,
                        eval_predicate_mask)
from .pushdown import prefers_pruned_read, pushable_filter


# Session for the in-flight execution: the SPMD dispatch reads its conf
# (distributed on/off) without threading a parameter through the recursion.
_SESSION: contextvars.ContextVar = contextvars.ContextVar(
    "hst_executing_session", default=None)


def execute(plan: LogicalPlan, session=None) -> Table:
    token = _SESSION.set(session)
    try:
        # Shape-class execution scope: kernels and the padded pipeline
        # below read the session's shapeBucketing conf through it. The
        # parallel-io scope routes every read under this execution through
        # the session's hyperspace.tpu.io.* conf (and its event logger).
        from ..parallel import io as pio
        conf = session.hs_conf if session is not None else None
        with shapes.use_conf(conf), pio.use_session(session), \
                shapes.compile_scope("execute") as tally:
            # Row-returning distributed path: a {Filter, Project, Join}*
            # chain root (optionally under Sort/Limit) runs SPMD over the
            # mesh, rows gathered per device (execution/spmd.py). Aggregate
            # roots dispatch inside _execute; anything else falls through
            # to single-device. SPMD manages its own static shapes, so it
            # only ever sees compacted tables.
            from . import spmd
            result = _spmd_with_fault_fallback(
                lambda: spmd.try_execute_plan(plan, session,
                                              _execute_compact), session)
            if result is None:
                result = _execute(plan, needed=None)
                if result.is_padded:
                    # The result leaving the engine is always exact: class
                    # padding is an internal representation. Final results
                    # trim at the HOST boundary (one device_get, numpy
                    # slice): a device-side slice would compile one
                    # program per distinct row count — the literal-sweep
                    # serving pattern would recompile per query.
                    result = result.to_host()
        _emit_compile_event(session, tally["count"], tally["seconds"])
        return result
    finally:
        _SESSION.reset(token)


def _execute_compact(plan: LogicalPlan, needed: Optional[Set[str]]) -> Table:
    """_execute for callers outside the padded pipeline (SPMD leaf reads)."""
    return _execute(plan, needed).compact()


def _spmd_with_fault_fallback(run, session) -> Optional[Table]:
    """The SPMD -> single-device degradation ladder (robustness layer):
    a dispatch or compile FAULT — injected or real — degrades to
    single-device re-execution of the same stage instead of failing the
    query, observable as a DistributedFallbackEvent with reason
    "fault: ...". Structural mismatches already return None inside
    try_execute_* (the pre-existing fallback); a QueryDeadlineError is a
    CANCELLATION, never degraded; ``robustness.degrade.enabled=false``
    restores fail-loud behavior for debugging. The single-device rerun
    produces byte-identical answers (proven under fault injection in
    tests/test_robustness.py), because both paths execute the same
    logical stage."""
    try:
        return run()
    except QueryDeadlineError:
        raise
    except Exception as e:
        from ..adaptive.feedback import ReplanRequested
        if isinstance(e, ReplanRequested):
            # A re-plan request is a CONTROL transfer to
            # Session._execute_uncaptured, never a fault to degrade.
            raise
        if session is None or \
                not session.hs_conf.robustness_degrade_enabled():
            raise
        from ..robustness import faults as _faults
        from ..telemetry.logging import emit_distributed_fallback
        _faults.note(degraded_spmd=1)
        emit_distributed_fallback(
            session, "spmd_query", f"fault: {type(e).__name__}: {e}")
        return None


def _emit_compile_event(session, count: int, seconds: float) -> None:
    """Surface the per-execution XLA compile tally (shapes.py counter) as
    a KernelCompileEvent. No-op when nothing compiled or no session."""
    if session is None or count <= 0:
        return
    from ..telemetry.events import KernelCompileEvent
    from ..telemetry.logging import get_logger
    get_logger(session.hs_conf.event_logger_class()).log_event(
        KernelCompileEvent(
            message=f"{count} XLA compilation(s) during plan execution",
            count=count, seconds=round(seconds, 4),
            total=shapes.compile_count()))


def _shared_scan_key(plan: Scan, needed: Optional[Set[str]]):
    """Batch-sweep scan-sharing key: the full relation detail plus the
    column set about to be read (fingerprint._node_detail pins format,
    paths and options)."""
    from ..serving.fingerprint import _node_detail
    return (_node_detail(plan),
            tuple(sorted(needed)) if needed is not None else None)


def _execute(plan: LogicalPlan, needed: Optional[Set[str]]) -> Table:
    """Per-stage tracing wrapper: one ``exec.stage`` span per executed
    plan node, nesting with the recursion so the span tree mirrors the
    plan tree. ``idle()`` short-circuits the whole thing to a plain call
    while tracing is off (the no-op fast path contract).

    The per-node deadline check makes every stage boundary a
    cooperative cancellation point (robustness layer): deadline-less
    queries pay one contextvar read + one attribute test."""
    check_deadline("exec.stage")
    if isinstance(plan, (Filter, Project, Join)):
        # Whole-plan fusion (execution/fusion.py): a chain root opening a
        # fusible region executes as ONE banked program — no exec.stage
        # spans (and no host Tables) for its interior nodes. Aggregate
        # roots attempt fusion inside _execute_node, AFTER the SPMD
        # dispatch (the distributed tier keeps right of way; chains only
        # reach here once execute()'s spmd.try_execute_plan declined).
        from . import fusion
        fused = fusion.try_execute(plan, needed)
        if fused is not None:
            check_deadline("exec.stage")
            return fused
    if _trace.idle():
        table = _execute_node(plan, needed)
        # Checked on EXIT too: the recursion enters ancestors before
        # their slow leaves run, so entry checks alone would let an
        # expired query bubble all the way up uncancelled.
        check_deadline("exec.stage")
        return table
    with _trace.span(SN.EXEC_STAGE, node=plan.node_name) as sp:
        table = _execute_node(plan, needed)
        if sp is not None:
            sp.attrs["rows"] = int(table.num_rows)
        check_deadline("exec.stage")
        return table


def _execute_node(plan: LogicalPlan, needed: Optional[Set[str]]) -> Table:
    if isinstance(plan, Scan):
        from ..serving import batcher
        sweep = batcher.active_sweep()
        if sweep is not None:
            # Literal-sweep batch: every member reads the same sources —
            # the first member's table is reused by the rest.
            return sweep.shared_scan(
                _shared_scan_key(plan, needed),
                lambda: _execute_scan(plan, needed))
        return _execute_scan(plan, needed)
    if isinstance(plan, IndexScan):
        return _execute_index_scan(plan, needed)
    if isinstance(plan, Filter):
        child_needed = None if needed is None else \
            needed | set(plan.condition.references)
        if isinstance(plan.child, Scan):
            from ..serving import batcher
            sweep = batcher.active_sweep()
            if sweep is not None:
                # Under a sweep, row-group pushdown would prune
                # DIFFERENT row groups per member's literals; reading
                # the unpruned superset once is byte-identical (the full
                # predicate re-applies on device) and shares one table
                # across the batch. Sources past the chunk budget keep
                # the per-member streamed path (too big to pin).
                chunked = _chunked_filtered_scan(
                    plan.child, child_needed, plan.condition, None)
                if chunked is not None:
                    return chunked
                table = sweep.shared_scan(
                    _shared_scan_key(plan.child, child_needed),
                    lambda: _execute_scan(plan.child, child_needed))
                return _filter_table(table, plan.condition)
        if isinstance(plan.child, (Scan, IndexScan)):
            # Push row-group-prunable conjuncts into the parquet read. A
            # source scan's struct leaves aren't physical columns, so dotted
            # names can't be pushed there (index files store them flat).
            pa_filter = pushable_filter(
                plan.condition, plan.child.schema,
                allow_nested=isinstance(plan.child, IndexScan))
            if isinstance(plan.child, Scan):
                chunked = _chunked_filtered_scan(plan.child, child_needed,
                                                 plan.condition, pa_filter)
                if chunked is not None:
                    return chunked
                table = _execute_scan(plan.child, child_needed, pa_filter)
            else:
                buckets = _equality_bucket_subset(plan.child, plan.condition)
                chunked = _chunked_filtered_index_scan(
                    plan.child, child_needed, plan.condition, pa_filter,
                    bucket_subset=buckets)
                if chunked is not None:
                    return chunked
                pruned = pa_filter is not None and prefers_pruned_read(
                    plan.child.index_entry, plan.condition, plan.child.schema)
                table = _execute_index_scan(plan.child, child_needed, pa_filter,
                                            bucket_subset=buckets,
                                            prefer_pruned_read=pruned)
        else:
            table = _execute(plan.child, child_needed)
        return _filter_table(table, plan.condition)
    if isinstance(plan, Project):
        child_needed = set()
        for e in plan.exprs:
            child_needed.update(e.references)
        table = _execute(plan.child, child_needed)
        out = Table({e.name: eval_expr_maybe_fused(table, e)
                     for e in plan.exprs},
                    valid_rows=table.valid_rows)
        # Pass-through column projections keep the bucket-order invariant.
        bo = table.bucket_order
        if bo:
            name_map = {}
            for e in plan.exprs:
                inner = e.child if isinstance(e, E.Alias) else e
                if isinstance(inner, E.Col):
                    name_map.setdefault(inner.column, e.name)
            if all(k in name_map for k in bo[1]):
                out = Table(out.columns,
                            bucket_order=(bo[0], tuple(name_map[k] for k in bo[1])),
                            valid_rows=table.valid_rows)
        return out
    if isinstance(plan, Join):
        table = _execute_join(plan, needed)
        _record_join_actual(plan, table)
        return table
    if isinstance(plan, Aggregate):
        # Multi-device product path: run eligible aggregation subtrees SPMD
        # over the mesh (execution/spmd.py); fall back on any mismatch —
        # and, via the robustness ladder, on any dispatch/compile FAULT.
        from . import spmd
        spmd_result = _spmd_with_fault_fallback(
            lambda: spmd.try_execute_aggregate(plan, _SESSION.get(),
                                               _execute_compact),
            _SESSION.get())
        if spmd_result is not None:
            return spmd_result
        from . import fusion
        fused = fusion.try_execute(plan, needed)
        if fused is not None:
            return fused
        child_needed = set(plan.group_cols)
        for a in plan.aggs:
            child_needed.update(a.references)
        table = _execute(plan.child, child_needed)
        return _execute_aggregate(plan, table)
    if isinstance(plan, Window):
        out_names = {name for name, _ in plan.wexprs}
        child_needed = None if needed is None else \
            (needed - out_names) | {r for _, w in plan.wexprs
                                    for r in w.references}
        # Window internals (segmented scans, scatter-back through the sort
        # permutation) assume exact shapes; compact at the boundary.
        table = _execute(plan.child, child_needed).compact()
        return _execute_window(plan, table)
    if isinstance(plan, Sort):
        child_needed = None if needed is None else \
            needed | {c for c, _ in plan.orders}
        table = _execute(plan.child, child_needed)
        return _execute_sort(plan, table)
    if isinstance(plan, Limit):
        table = _execute(plan.child, needed)
        return table.slice(0, min(plan.n, table.num_rows))
    if isinstance(plan, (Union, BucketUnion)):
        # Align on the UNION's pruned output schema, not child 0's
        # materialized columns: a child whose own filter referenced extra
        # columns materializes a superset, and those extras differ per
        # child (found by the property oracle's generated union shapes).
        out_names = [n for n in plan.schema.names
                     if needed is None or n in needed]
        child_needed = needed
        if not out_names:
            # count(*) shape: no column is referenced — pick one and widen
            # the CHILD need-set so every child materializes it.
            out_names = plan.schema.names[:1]
            child_needed = None if needed is None else \
                needed | set(out_names)
        tables = [_execute(c, child_needed) for c in plan.children]
        aligned = [t.select(out_names) for t in tables]
        return Table.concat(aligned)
    raise HyperspaceException(f"Cannot execute plan node {plan.node_name}")


def _record_join_actual(plan: Join, table: Table) -> None:
    """Observed output cardinality of executed inner joins, kept on the
    session keyed by the composite join_actual_key (condition repr +
    both side signatures, LRU-bounded) so explain's "Join order:"
    section and bench's join_reorder phase can report estimated vs
    actual rows (q-error) for the cost-based reorderer's steps — and,
    with the adaptive loop on, so corrections never cross table pairs.

    This is also the mid-query re-plan trigger (adaptive/feedback.py):
    the staged executor owns stage boundaries, so after the write-back
    the adaptive layer may raise ReplanRequested here when the actual
    blew past the estimate — Session._execute_uncaptured catches it and
    re-optimizes with the fresh correction applied."""
    if plan.join_type != "inner" or plan.condition is None:
        return
    from ..serving import context as qctx
    key = qctx.join_actual_key(plan.condition, plan.left, plan.right)
    ctx = qctx.active_context()
    if ctx is not None:
        # Serving path: the QueryContext routes the write to its owning
        # session's locked store.
        session = ctx.session
        ctx.record_join_actual(key, int(table.num_rows))
    else:
        session = _SESSION.get()
        if session is None:
            return
        qctx.record_join_actual(session, key, int(table.num_rows))
    if session.hs_conf.adaptive_replan_enabled():
        from ..adaptive import feedback as _feedback
        _feedback.maybe_replan(session, key, int(table.num_rows))


def _filter_table(table: Table, condition) -> Table:
    """Filter operator body. The fused predicate program (one compile per
    predicate structure, literals as runtime args — evaluator.
    eval_predicate_mask_counted) covers the common shapes; everything
    else evaluates eagerly. Output rides the survivor count's length
    class either way (byte-identical after compaction)."""
    from .evaluator import eval_predicate_mask_counted
    fused = eval_predicate_mask_counted(table, condition)
    if fused is None:
        mask = eval_predicate_mask(table, condition)
        return table.filter(mask, padded=True)
    mask, m = fused
    cls = shapes.padded_length(m)
    idx = kernels.nonzero_pad_indices(mask, cls)
    out = table.take(idx, valid_rows=m if cls != m else None)
    # A subsequence of bucket-ordered rows is still bucket-ordered.
    return Table(out.columns, bucket_order=table.bucket_order,
                 valid_rows=out.valid_rows)


# Chunked-scan observability (mirrors ops.index_build.CHUNK_STATS): tests
# pin the scan-side device footprint with max_device_rows. Serving
# workers stream chunks concurrently, so every write goes through
# _note_chunk_scan under the lock — an unguarded max()+assign or += here
# loses updates under contention (HS301/HS302, scripts/analysis).
CHUNK_SCAN_STATS = {"max_device_rows": 0, "chunks": 0}
_CHUNK_STATS_LOCK = threading.Lock()


def _note_chunk_scan(rows: int) -> None:
    with _CHUNK_STATS_LOCK:
        CHUNK_SCAN_STATS["max_device_rows"] = max(
            CHUNK_SCAN_STATS["max_device_rows"], rows)
        CHUNK_SCAN_STATS["chunks"] += 1


def _chunked_filtered_scan(plan: Scan, needed: Optional[Set[str]],
                           condition, pa_filter=None) -> Optional[Table]:
    """Filter-over-scan for data larger than HBM: stream parquet chunks
    with row-group predicate pushdown, evaluate the full mask per chunk on
    device, and keep only survivors — the full dataset is never resident
    at once. Returns None when the source fits the chunk budget (the
    in-memory path is cheaper) or isn't chunkable (non-parquet, nested
    projection)."""
    import pyarrow.parquet as pq

    from ..index.constants import IndexConstants
    from .columnar import iter_dataset_chunks, parquet_row_counts

    session = _SESSION.get()
    chunk_rows = session.hs_conf.max_chunk_rows() if session is not None \
        else int(IndexConstants.TPU_MAX_CHUNK_ROWS_DEFAULT)
    relation = plan.relation
    fmt = getattr(relation, "data_file_format", relation.file_format)
    if fmt != "parquet":
        return None
    files = relation.all_files()
    if not files:
        return None
    cols = None
    if needed is not None:
        cols = [n for n in relation.schema.names if n in needed]
        if not cols:
            cols = [relation.schema.names[0]]
    # Hive partition columns live in directory names, not in the files —
    # the streaming reader can't attach them; read_relation_files can.
    part_names = {f.name for f in
                  getattr(relation, "partition_fields", lambda: [])()}
    if part_names and (cols is None or any(c in part_names for c in cols)):
        return None
    try:
        # Nested struct leaves carry dotted names that are NOT physical
        # top-level parquet columns — those go to the in-memory reader,
        # whose root-read+flatten path understands them.
        physical = set(pq.read_schema(files[0]).names)
        if cols is not None and any(c not in physical for c in cols):
            return None
        if sum(parquet_row_counts(files)) <= chunk_rows:
            return None
    except Exception:
        return None
    parts: List[Table] = []
    for chunk in iter_dataset_chunks(files, cols, chunk_rows, pa_filter):
        _note_chunk_scan(chunk.num_rows)
        mask = eval_predicate_mask(chunk, condition)
        parts.append(chunk.filter(mask))
    if not parts:
        from .columnar import empty_table
        return empty_table(relation.schema.select(cols)
                           if cols is not None else relation.schema)
    return Table.concat(parts)


def _execute_scan(plan: Scan, needed: Optional[Set[str]],
                  pa_filter=None) -> Table:
    relation = plan.relation
    cols = None
    if needed is not None:
        cols = [n for n in relation.schema.names if n in needed]
        if not cols:  # e.g. count(*) over no particular column.
            cols = [relation.schema.names[0]]
    files = relation.all_files()
    if not files:
        # A data-skipping rewrite can prune every file; the scan is empty.
        from .columnar import empty_table
        return empty_table(relation.schema.select(cols)
                           if cols is not None else relation.schema)
    fmt = getattr(relation, "data_file_format", relation.file_format)
    if fmt != "parquet":
        pa_filter = None
    from ..sources.partitions import read_relation_files
    from .columnar import pad_table_to_class
    # Class-pad at the scan boundary: every downstream chain (mask eval,
    # gathers, key hashing) then runs at the table's length class, and an
    # append/refresh that changes the row count lands on the same class
    # instead of recompiling the whole chain. Simple reads pad host-side
    # (free); partition-attach assemblies pad on device here.
    return pad_table_to_class(read_relation_files(
        relation, files, cols, fmt, filters=pa_filter, pad_to_class=True))


def _equality_bucket_subset(plan: IndexScan, condition) -> Optional[Set[int]]:
    """Bucket pruning: equality/IN predicates on the first indexed column pin
    the buckets a matching row can live in (the reference's
    INDEX_FILTER_RULE_USE_BUCKET_SPEC behavior — Spark prunes bucket files;
    we prune before IO)."""
    if not plan.use_bucket_spec:
        return None
    entry = plan.index_entry
    # The bucket id combines the hashes of ALL indexed columns (index_build.
    # bucket_ids_for), so pruning needs an equality constraint on every one.
    from .columnar import literal_to_device
    per_column_hashes = []
    for name in entry.indexed_columns:
        if name not in entry.schema:
            return None
        dtype = entry.schema.field(name).dtype
        values = None
        for conjunct in E.split_conjunctive_predicates(condition):
            vals = _equality_values(conjunct, name)
            if vals is not None:
                values = vals if values is None else (values & vals)
        if values is None or len(values) > 16:
            return None
        hashes = []
        for v in values:
            if dtype == STRING:
                hashes.append(kernels.hash32_value_host(str(v), dtype))
            else:
                hashes.append(kernels.hash32_value_host(
                    literal_to_device(v, dtype, None), dtype))
        per_column_hashes.append(hashes)

    combos = [None]
    for hashes in per_column_hashes:
        combos = [kernels.hash_combine_host(c, h) if c is not None else h
                  for c in combos for h in hashes]
        if len(combos) > 256:
            return None
    return {c % entry.num_buckets for c in combos}


def _equality_values(conjunct, column: str):
    if isinstance(conjunct, E.EqualTo):
        left, right = conjunct.left, conjunct.right
        if isinstance(left, E.Lit) and isinstance(right, E.Col):
            left, right = right, left
        if isinstance(left, E.Col) and left.column == column \
                and isinstance(right, E.Lit):
            return {right.value}
    if isinstance(conjunct, E.In) and isinstance(conjunct.value, E.Col) \
            and conjunct.value.column == column:
        if all(isinstance(o, E.Lit) for o in conjunct.options):
            return {o.value for o in conjunct.options}
    return None


def _index_scan_layout(plan: IndexScan, needed: Optional[Set[str]],
                       bucket_subset: Optional[Set[int]]):
    """File list (bucket-grouped order) + explicit read columns for an
    index scan. Returns (index_files, cols, buckets_have_single_file)."""
    from ..index.constants import IndexConstants
    from ..ops.index_build import bucket_id_from_file

    entry = plan.index_entry
    # Read index files grouped by bucket id: after an incremental refresh a
    # bucket's rows can span several version dirs, and bucket-grouped order
    # is what downstream bucket-aware operators expect.
    keyed = sorted(((bucket_id_from_file(f), f)
                    for f in entry.content.files),
                   key=lambda t: (t[0] is None, t[0] or 0, t[1]))
    index_files = [f for _, f in keyed]
    buckets_have_single_file = len({b for b, _ in keyed}) == len(keyed) \
        and all(b is not None for b, _ in keyed)
    if bucket_subset is not None:
        index_files = [f for b, f in keyed if b in bucket_subset]
    schema_names = entry.schema.names
    # Columns are ALWAYS explicit: index files live under "v__=<n>"
    # directories, and pyarrow's reader hive-infers a phantom "v__"
    # column from the path when asked for all columns (columns=None).
    if needed is not None:
        cols = [n for n in schema_names if n in needed]
        if not cols:
            cols = [schema_names[0]]
    else:
        cols = [n for n in plan.schema.names]
    if plan.deleted_file_ids and IndexConstants.DATA_FILE_NAME_ID not in cols:
        cols = cols + [IndexConstants.DATA_FILE_NAME_ID]
    return index_files, cols, buckets_have_single_file


def _chunked_filtered_index_scan(plan: IndexScan, needed: Optional[Set[str]],
                                 condition, pa_filter=None,
                                 bucket_subset: Optional[Set[int]] = None
                                 ) -> Optional[Table]:
    """Filter-over-index-scan for indexes larger than HBM: stream the
    bucket-ordered index files in chunks, evaluate the mask (and the
    hybrid deleted-row mask) per chunk, keep survivors. Survivors stay in
    bucket-grouped order, so the bucket_order invariant is preserved when
    there are no appended files. Returns None when the index fits the
    chunk budget (the in-memory/cached path is cheaper)."""
    from ..index.constants import IndexConstants
    from .columnar import (Table as T, empty_table, iter_dataset_chunks,
                           parquet_row_counts, read_parquet)

    session = _SESSION.get()
    chunk_rows = session.hs_conf.max_chunk_rows() if session is not None \
        else int(IndexConstants.TPU_MAX_CHUNK_ROWS_DEFAULT)
    entry = plan.index_entry
    index_files, cols, buckets_have_single_file = _index_scan_layout(
        plan, needed, bucket_subset)
    if not index_files:
        return None
    try:
        # Appended files count toward the footprint too (mirrors
        # spmd._leaf_within_budget, so a query the SPMD gate bounced here
        # is guaranteed to take THIS path, not full materialization).
        total = sum(parquet_row_counts(
            index_files + list(plan.appended_files)))
        if total <= chunk_rows:
            return None
    except Exception:
        return None
    lineage = IndexConstants.DATA_FILE_NAME_ID
    wanted = needed if needed is not None else set(plan.schema.names)
    out_cols = [c for c in cols if c != lineage or c in wanted]
    deleted = None
    if plan.deleted_file_ids:
        deleted = jnp.asarray(
            np.sort(np.asarray(plan.deleted_file_ids, dtype=np.int64)))
    parts: List[Table] = []
    app_parts: List[Table] = []
    for chunk in iter_dataset_chunks(index_files, cols, chunk_rows,
                                     pa_filter):
        _note_chunk_scan(chunk.num_rows)
        mask = eval_predicate_mask(chunk, condition)
        if deleted is not None:
            lc = chunk.column(lineage)
            mask = mask & ~kernels.isin_sorted(
                lc.data.astype(jnp.int64), deleted)
        parts.append(chunk.filter(mask))
    if plan.appended_files:
        # Appended files stream under the same budget — they can be a
        # sizable fraction of an over-HBM index (hybrid append ratio).
        # Dotted struct leaves aren't physical top-level columns in the
        # SOURCE files (the index stores them flat); those must go through
        # read_parquet's root-read+flatten path — per file, sliced to the
        # budget. EVERY file's schema is probed (appends can carry evolved
        # schemas), unreadable probes take the safe fallback.
        app_cols = [c for c in cols if c != lineage]
        import pyarrow.parquet as _pq

        from ..parallel import io as pio
        try:
            # Footer probes fan out over the reader pool (one metadata
            # round trip per appended file — on remote stores the latency
            # sum, not bandwidth, is what the pool hides). Lazy gather:
            # all() short-circuits at the first evolved schema, closing
            # the stream and cancelling not-yet-started probes.
            flat = all(
                not any(c not in names for c in app_cols)
                for names in pio.imap_ordered(
                    lambda f: set(_pq.read_schema(f).names),
                    list(plan.appended_files), label="schema_probe"))
        except Exception:
            flat = False
        if flat:
            app_iter = iter_dataset_chunks(list(plan.appended_files),
                                           app_cols, chunk_rows, None)
        else:
            def _app_chunks():
                # Host-side arrow read + flatten, sliced BEFORE device
                # conversion so HBM holds at most chunk_rows (the host
                # holds one source file's arrow — host RAM ≫ HBM). Only
                # the ROOT columns of the dotted leaves are read.
                import pyarrow as _pa
                for f in plan.appended_files:
                    top = set(_pq.read_schema(f).names)
                    roots = []
                    for c in app_cols:
                        root = c if c in top else c.split(".", 1)[0]
                        if root not in roots:
                            roots.append(root)
                    at = _pq.read_table(f, columns=roots)
                    while any(_pa.types.is_struct(fld.type)
                              for fld in at.schema):
                        at = at.flatten()
                    at = at.select(app_cols)
                    for lo in range(0, at.num_rows, chunk_rows):
                        yield Table.from_arrow(at.slice(lo, chunk_rows))
            from .columnar import _table_nbytes_estimate
            app_iter = pio.prefetch_iter(
                _app_chunks(), nbytes=_table_nbytes_estimate,
                label="hybrid_appended_chunks")
        for chunk in app_iter:
            _note_chunk_scan(chunk.num_rows)
            mask = eval_predicate_mask(chunk, condition)
            appended = chunk.filter(mask)
            if lineage in cols:
                fill = Column(INT64, jnp.full(
                    appended.num_rows, IndexConstants.UNKNOWN_FILE_ID,
                    jnp.int64))
                appended = appended.with_column(lineage, fill)
            app_parts.append(appended.select(cols))
    parts = [p for p in parts if p.num_rows > 0]
    app_parts = [p for p in app_parts if p.num_rows > 0]
    if not parts and not app_parts:
        return empty_table(entry.schema.select(out_cols))
    table = Table.concat(parts) if parts else \
        empty_table(entry.schema.select(cols))
    if entry.derivedDataset.kind == "CoveringIndex" \
            and buckets_have_single_file \
            and all(c in table.names for c in entry.indexed_columns):
        # Filtered subsequence of bucket-ordered rows is still bucket-
        # ordered (chunks stream files in bucket order; concat preserves).
        table = T(table.columns, bucket_order=(
            entry.num_buckets, tuple(entry.indexed_columns)))
    if app_parts:
        # Appended survivors merge into the bucket-ordered stream the
        # same way the in-memory path does (VERDICT r5 #9: beyond the
        # chunk budget the merge used to degrade to concat, costing the
        # downstream consumer the sort-free path exactly at the scales
        # that matter). Fallback stays the order-dropping concat.
        app_table = Table.concat(app_parts) if len(app_parts) > 1 \
            else app_parts[0]
        merged = _merge_appended_preserving_order(entry, table, app_table)
        if merged is not None:
            table = merged
        else:
            table = Table.concat([table, app_table]) if table.num_rows \
                else app_table
    if lineage in table.names and lineage not in wanted:
        table = table.select([n for n in table.names if n != lineage])
    return table


def _emit_index_cache_probe(index_name: str, hit: bool) -> None:
    """Surface IndexTableCache probes through telemetry (the hit/miss
    counters in execution/index_cache.py were previously counted but
    never reported anywhere). No-op outside a session context."""
    session = _SESSION.get()
    if session is None:
        return
    from ..telemetry.events import IndexCacheHitEvent, IndexCacheMissEvent
    from ..telemetry.logging import get_logger
    cls = IndexCacheHitEvent if hit else IndexCacheMissEvent
    get_logger(session.hs_conf.event_logger_class()).log_event(
        cls(message=f"index table cache {'hit' if hit else 'miss'}",
            index_name=index_name))


def _execute_index_scan(plan: IndexScan, needed: Optional[Set[str]],
                        pa_filter=None,
                        bucket_subset: Optional[Set[int]] = None,
                        prefer_pruned_read: bool = False) -> Table:
    from ..index.constants import IndexConstants

    entry = plan.index_entry
    index_files, cols, buckets_have_single_file = _index_scan_layout(
        plan, needed, bucket_subset)
    schema_names = entry.schema.names
    if not index_files and bucket_subset is not None \
            and not plan.appended_files:
        from .columnar import empty_table
        out_schema = plan.schema if needed is None else \
            plan.schema.select([n for n in plan.schema.names if n in needed]
                               or [plan.schema.names[0]])
        return empty_table(out_schema)
    if not index_files:
        from .columnar import empty_table
        table = empty_table(entry.schema.select(cols or entry.schema.names))
    else:
        from . import index_cache
        if index_cache.enabled() \
                and not (prefer_pruned_read and pa_filter is not None):
            # HBM-resident path: cache the *unfiltered* read (the Filter
            # node above always re-evaluates its mask on device, so skipping
            # the parquet-level pushdown is purely an IO trade). Leading-
            # indexed-column filters bypass the cache: the sorted layout
            # makes row-group pruning read ~selectivity of the file, far
            # cheaper than masking the whole cached table.
            key = (entry.id, entry.name, tuple(index_files),
                   tuple(cols) if cols is not None else None)
            cache = index_cache.get_cache()
            table = cache.get(key)
            _emit_index_cache_probe(entry.name, hit=table is not None)
            if table is None:
                # Padded host-side at read: the cache's only consumer is
                # this (padded-aware) scan path. pool=False: the cache
                # view admits under its own "index" namespace below —
                # routing the inner read through the scan namespace too
                # would double-store every index table.
                table = read_parquet(index_files, cols, pad_to_class=True,
                                     pool=False)
                cache.put(key, table)
        else:
            table = read_parquet(index_files, cols, filters=pa_filter,
                                 pad_to_class=True)
    if entry.derivedDataset.kind == "CoveringIndex" \
            and buckets_have_single_file \
            and all(c in table.names for c in entry.indexed_columns):
        # Physical layout invariant: files are read in bucket order and rows
        # are sorted by the indexed columns within each bucket. Downstream
        # joins exploit this to skip re-sorting. (Subsequent filters keep it.)
        table = Table(table.columns, bucket_order=(
            entry.num_buckets, tuple(entry.indexed_columns)),
            valid_rows=table.valid_rows)
    if plan.deleted_file_ids:
        lineage = table.column(IndexConstants.DATA_FILE_NAME_ID)
        deleted = jnp.asarray(
            np.sort(np.asarray(plan.deleted_file_ids, dtype=np.int64)))
        keep = ~kernels.isin_sorted(lineage.data.astype(jnp.int64), deleted)
        table = table.filter(keep, padded=True)
    if plan.appended_files:
        # The order-preserving merge scatters by absolute row position —
        # exact shapes (appends are the rare path; correctness first).
        table = table.compact()
        appended = read_parquet(
            plan.appended_files,
            [c for c in (cols or schema_names)
             if c != IndexConstants.DATA_FILE_NAME_ID])
        if IndexConstants.DATA_FILE_NAME_ID in (cols or schema_names) \
                and IndexConstants.DATA_FILE_NAME_ID not in appended.names:
            fill = Column(INT64, jnp.full(appended.num_rows,
                                          IndexConstants.UNKNOWN_FILE_ID, jnp.int64))
            appended = appended.with_column(IndexConstants.DATA_FILE_NAME_ID, fill)
        merged = _merge_appended_preserving_order(entry, table, appended)
        if merged is not None:
            table = merged
        else:
            table = Table.concat([table, appended.select(table.names)])
    wanted = needed if needed is not None else set(plan.schema.names)
    drop_lineage = (IndexConstants.DATA_FILE_NAME_ID in table.names
                    and IndexConstants.DATA_FILE_NAME_ID not in wanted)
    if drop_lineage:
        table = table.select([n for n in table.names
                              if n != IndexConstants.DATA_FILE_NAME_ID])
    from .columnar import pad_table_to_class
    return pad_table_to_class(table)


# Observability counters for the shuffle-free fast paths (tests assert the
# path is actually taken, mirroring the reference's plan-shape assertions in
# HybridScanSuite).
HYBRID_MERGE_COUNT = 0   # appended rows merged without dropping bucket order
FAST_JOIN_COUNT = 0      # joins that skipped the sort via bucket order


def _merge_appended_preserving_order(entry, table: Table,
                                     appended: Table) -> Optional[Table]:
    """Hybrid Scan without losing the merge join: re-bucket the appended
    rows on device, sort only them by (bucket, key), and two-way-merge them
    into the already-(bucket, key)-sorted index stream — so ``bucket_order``
    survives appends and the downstream join still skips its sort.

    The TPU analogue of the reference's query-time re-bucketing of appended
    data (RuleUtils.scala:509-567: RepartitionByExpression + BucketUnion
    keeps the zero-exchange SMJ); here the "shuffle" is one small sort of
    the appended rows and the union is a position-scatter merge.

    Returns None (caller falls back to order-dropping concat) unless the
    index stream carries bucket order on a single int-family key that fits
    int32 — the same constraints the fast-join consumer has.
    """
    global HYBRID_MERGE_COUNT
    from ..ops.index_build import bucket_ids_for

    if table.bucket_order is None or len(entry.indexed_columns) != 1:
        return None
    key = entry.indexed_columns[0]
    if key not in table.names or key not in appended.names:
        return None
    icol = table.column(key)
    if icol.dtype not in (INT32, INT64, DATE):
        return None
    if table.num_rows == 0 or appended.num_rows == 0:
        return None
    appended = appended.select(table.names)
    num_buckets = table.bucket_order[0]

    # int32-fit check for the (bucket << 32 | biased key) packing — one
    # fused reduction + host sync, mirroring _bucketed_merge_keys.
    acol = appended.column(key)
    to_check = [a for a in (icol.data, acol.data) if a.dtype == jnp.int64]
    if to_check:
        extreme = int(jnp.maximum(*[jnp.max(jnp.abs(a)) for a in to_check])
                      if len(to_check) == 2 else jnp.max(jnp.abs(to_check[0])))
        if extreme >= 2 ** 31 or extreme < 0:
            return None

    def composite(t: Table) -> jnp.ndarray:
        bids = bucket_ids_for(t, [key], num_buckets)
        return kernels.pack2_int32(bids, t.column(key).data.astype(jnp.int32))

    # Sort ONLY the appended rows; the index stream is already sorted.
    comp_a = composite(appended)
    perm_a = kernels.lex_sort_indices([comp_a])
    appended = appended.take(perm_a)
    comp_a = jnp.take(comp_a, perm_a)
    comp_i = composite(table)

    # Two-way merge positions (ties: index rows first).
    n_i, n_a = table.num_rows, appended.num_rows
    pos_i = jnp.arange(n_i, dtype=jnp.int32) + \
        jnp.searchsorted(comp_a, comp_i, side="left").astype(jnp.int32)
    pos_a = jnp.arange(n_a, dtype=jnp.int32) + \
        jnp.searchsorted(comp_i, comp_a, side="right").astype(jnp.int32)
    union = Table.concat([table, appended])  # unifies string dictionaries
    gather = jnp.zeros(n_i + n_a, jnp.int32) \
        .at[jnp.concatenate([pos_i, pos_a])] \
        .set(jnp.arange(n_i + n_a, dtype=jnp.int32))
    merged = union.take(gather)
    HYBRID_MERGE_COUNT += 1
    return Table(merged.columns, bucket_order=(num_buckets, (key,)))


# ---------------------------------------------------------------------------
# Join.
# ---------------------------------------------------------------------------

def _join_key_arrays(left: Table, right: Table,
                     pairs: List[Tuple[str, str]]):
    """Device key arrays for the join, in a shared comparable space."""
    if len(pairs) == 1:
        lname, rname = pairs[0]
        lc, rc = left.column(lname), right.column(rname)
        if lc.dtype == STRING or rc.dtype == STRING:
            if lc.dtype != rc.dtype:
                raise HyperspaceException("Join key type mismatch")
            return _string_join_keys(lc, rc)
        return lc.data, rc.data
    if len(pairs) == 2:
        lks, rks = [], []
        for lname, rname in pairs:
            lc, rc = left.column(lname), right.column(rname)
            if lc.dtype not in (INT32, DATE) or rc.dtype not in (INT32, DATE):
                break
            lks.append(lc.data)
            rks.append(rc.data)
        else:
            return (kernels.pack2_int32(lks[0], lks[1]),
                    kernels.pack2_int32(rks[0], rks[1]))
    # General N-key path, any key dtypes: dense-rank the union of key
    # tuples so both sides join on one int32 rank column (equal tuples ↔
    # equal ranks). One extra lex-sort over left+right keys, no host sync.
    n_left = left.num_rows
    union_keys = []
    for lname, rname in pairs:
        lc, rc = left.column(lname), right.column(rname)
        union_keys.append(_comparable_concat(lc, rc))
    ranks = kernels.dense_rank(union_keys)
    return ranks[:n_left], ranks[n_left:]


def _comparable_concat(lc: Column, rc: Column) -> jnp.ndarray:
    """Concatenated (left ++ right) key values in one comparable space."""
    if (lc.dtype == STRING) != (rc.dtype == STRING):
        raise HyperspaceException("Join key type mismatch")
    if lc.dtype == STRING:
        ldata, rdata = _string_join_keys(lc, rc)
        return jnp.concatenate([ldata, rdata])
    int_family = (INT32, INT64, DATE, BOOL)
    if lc.dtype in int_family and rc.dtype in int_family:
        return jnp.concatenate([lc.data.astype(jnp.int64),
                                rc.data.astype(jnp.int64)])
    if lc.dtype in (FLOAT64, "float32") and rc.dtype in (FLOAT64, "float32"):
        return jnp.concatenate([lc.data.astype(jnp.float64),
                                rc.data.astype(jnp.float64)])
    raise HyperspaceException(
        f"Join key type mismatch: {lc.dtype} vs {rc.dtype}")


def _string_join_keys(lc: Column, rc: Column):
    if dictionaries_equal(lc.dictionary, rc.dictionary):
        return lc.data, rc.data
    return lc.data, translate_codes(lc.dictionary, rc)


def _execute_join(plan: Join, needed: Optional[Set[str]]) -> Table:
    if plan.join_type == "cross":
        return _execute_cross_join(plan, needed)
    pairs = E.extract_equi_join_keys(plan.condition)
    if pairs is None:
        raise HyperspaceException(
            f"Only conjunctive equi-joins are supported; got {plan.condition!r}")
    left_names = set(plan.left.schema.names)
    right_names = set(plan.right.schema.names)
    # Normalize each pair to (left column, right column).
    norm: List[Tuple[str, str]] = []
    for a, b in pairs:
        if a in left_names and b in right_names:
            norm.append((a, b))
        elif b in left_names and a in right_names:
            norm.append((b, a))
        else:
            raise HyperspaceException(
                f"Join keys ({a}, {b}) do not split across the two sides")
    lneed = None if needed is None else \
        {n for n in needed if n in left_names} | {p[0] for p in norm}
    rneed = None if needed is None else \
        {n for n in needed if n in right_names} | {p[1] for p in norm}
    left = _execute(plan.left, lneed)
    right = _execute(plan.right, rneed)

    how = plan.join_type
    if how in ("semi", "anti"):
        # Membership probes sort/search raw key arrays — exact shapes.
        return _execute_semi_anti_join(left.compact(), right.compact(),
                                       norm, how)
    if how == "right":
        # right join = left join with the sides swapped: the output below
        # is assembled by column NAME against plan.schema, so the swap is
        # otherwise transparent.
        left, right = right, left
        norm = [(r, l) for l, r in norm]
        how = "left"
    if how in ("left", "full"):
        # Outer padding scatters by absolute row position — exact shapes.
        return _execute_outer_join(plan, left.compact(), right.compact(),
                                   norm, how)

    if not _padded_join_keys_ok(left, right, norm):
        # The general N-key path dense-ranks the concatenation of both
        # sides' keys — offsets are absolute row positions, so it needs
        # exact shapes.
        left, right = left.compact(), right.compact()
    lkeys, rkeys = _join_key_arrays(left, right, norm)
    # Inner join: drop null keys up front (pad rows ride along: the padded
    # filter keeps the key arrays and the table aligned).
    lvalid = _keys_validity(left, [p[0] for p in norm])
    if lvalid is not None:
        idx, m = filter_indices(lvalid, left.valid_rows)
        left = left.take(idx, valid_rows=m if int(idx.shape[0]) != m else None)
        lkeys = jnp.take(lkeys, idx, mode="clip")
    rvalid = _keys_validity(right, [p[1] for p in norm])
    if rvalid is not None:
        idx, m = filter_indices(rvalid, right.valid_rows)
        right = right.take(idx, valid_rows=m if int(idx.shape[0]) != m else None)
        rkeys = jnp.take(rkeys, idx, mode="clip")

    # Shuffle-free path: a side that carries the covering-index bucket order
    # on its join key is already sorted by (bucket, key) — probe it directly
    # instead of re-sorting (the TPU analogue of Spark consuming bucketSpec
    # for a zero-exchange sort-merge join, JoinIndexRule.scala:64-78).
    fast = _bucketed_merge_keys(left, right, norm, lkeys, rkeys)
    if fast is not None:
        global FAST_JOIN_COUNT
        FAST_JOIN_COUNT += 1
        lcomp, rcomp, swapped = fast
        if swapped:
            left, right = right, left
            lcomp, rcomp = rcomp, lcomp
        li, ri, total = kernels.merge_join_indices(
            lcomp, rcomp, left_valid=left.num_rows,
            right_valid=right.num_rows, padded_out=True)
        right_sorted = right
    else:
        r_padded = right.is_padded
        order = kernels.lex_sort_indices(
            [rkeys], valid_count=right.num_rows if r_padded else None,
            padded_out=r_padded)
        right_sorted = right.take(
            order, valid_rows=right.num_rows if r_padded else None)
        rkeys_sorted = jnp.take(rkeys, order, mode="clip")
        li, ri, total = kernels.merge_join_indices(
            lkeys, rkeys_sorted, left_valid=left.num_rows,
            right_valid=right.num_rows, padded_out=True)
    out_valid = total if int(li.shape[0]) != total else None
    out = {}
    taken_left = left.take(li, valid_rows=out_valid)
    taken_right = right_sorted.take(ri, valid_rows=out_valid)
    for n in plan.schema.names:
        # Children were column-pruned; emit only the materialized subset.
        if n in taken_left.columns:
            out[n] = taken_left.columns[n]
        elif n in taken_right.columns:
            out[n] = taken_right.columns[n]
    # The join output follows the probe (left) side's row order
    # (merge_join_indices emits ascending left indices), so the left
    # side's bucket order survives — downstream group-bys on those keys
    # can skip their sort.
    order_out = None
    lbo = left.bucket_order
    if lbo is not None and all(k in out for k in lbo[1]):
        order_out = lbo
    return Table(out, bucket_order=order_out, valid_rows=out_valid)


def _padded_join_keys_ok(left: Table, right: Table, norm) -> bool:
    """True when _join_key_arrays will take a per-row (elementwise or
    packed) key path that is safe over class-padded inputs. Mirrors its
    branching: 1 pair always; 2 pairs only when every key is INT32/DATE
    (otherwise it falls through to the absolute-offset dense-rank path)."""
    if len(norm) == 1:
        return True
    if len(norm) == 2:
        for lname, rname in norm:
            if left.column(lname).dtype not in (INT32, DATE) \
                    or right.column(rname).dtype not in (INT32, DATE):
                return False
        return True
    return False


def _execute_cross_join(plan: Join, needed: Optional[Set[str]]) -> Table:
    """Cartesian product via index expansion (left repeated, right tiled).
    The SQL front-end only emits this for single-row sides (comma-joined
    global aggregates — the TPC-DS q28/q61/q88/q90 shape), so the usual
    blow-up risk does not apply; a guard still bounds the general case."""
    left_names = set(plan.left.schema.names)
    lneed = None if needed is None else {n for n in needed
                                         if n in left_names}
    rneed = None if needed is None else {n for n in needed
                                         if n not in left_names}
    # Index expansion addresses absolute row positions — exact shapes.
    left = _execute(plan.left, lneed).compact()
    right = _execute(plan.right, rneed).compact()
    n, m = left.num_rows, right.num_rows
    if n * m > 50_000_000:
        raise HyperspaceException(
            f"Cross join too large: {n} x {m} rows")
    li = jnp.repeat(jnp.arange(n, dtype=jnp.int32), m)
    ri = jnp.tile(jnp.arange(m, dtype=jnp.int32), n)
    out = {}
    for name in plan.schema.names:
        if needed is not None and name not in needed:
            continue
        if name in left.columns:
            out[name] = left.column(name).take(li)
        elif name in right.columns:
            out[name] = right.column(name).take(ri)
    if not out:
        # count(*) over a cross join: materialize one column for the count.
        if left.columns:
            k = next(iter(left.columns))
            out[k] = left.columns[k].take(li)
        else:
            k = next(iter(right.columns))
            out[k] = right.columns[k].take(ri)
    return Table(out)


def _execute_semi_anti_join(left: Table, right: Table, norm,
                            how: str) -> Table:
    """Existence probe (SQL [NOT] IN / [NOT] EXISTS lowering): keep left
    rows with (semi) / without (anti) a key match on the right. No match
    expansion — membership is one sort + searchsorted, O(n log m). Null
    left keys never match (kept by anti, dropped by semi); null right keys
    are discarded up front. Left row order (and any bucket order) is
    preserved, the filter-like shape downstream rules rely on."""
    lkeys, rkeys = _join_key_arrays(left, right, norm)
    lvalid = _keys_validity(left, [p[0] for p in norm])
    rvalid = _keys_validity(right, [p[1] for p in norm])
    if rvalid is not None:
        rkeys = rkeys[rvalid]
    n_right = rkeys.shape[0]
    if n_right == 0:
        found = jnp.zeros(lkeys.shape[0], jnp.bool_)
    else:
        rsorted = jnp.sort(rkeys)
        pos = jnp.searchsorted(rsorted, lkeys)
        found = (pos < n_right) & (
            jnp.take(rsorted, jnp.minimum(pos, n_right - 1)) == lkeys)
    if lvalid is not None:
        found = found & lvalid
    mask = found if how == "semi" else ~found
    return left.filter(mask)


def _null_filled_like(table: Table, n: int) -> Dict[str, Column]:
    """n rows of every column of ``table``, all null (outer-join padding)."""
    out = {}
    for name, c in table.columns.items():
        data = jnp.zeros((n,) + c.data.shape[1:], c.data.dtype)
        out[name] = Column(c.dtype, data, jnp.zeros(n, jnp.bool_),
                          c.dictionary)
    return out


def _execute_outer_join(plan: Join, left: Table, right: Table, norm,
                        how: str) -> Table:
    """LEFT (or FULL) outer equi-join: inner matches plus unmatched
    preserved-side rows padded with nulls on the other side. Null join
    keys never match (SQL semantics) — those rows are emitted as
    unmatched. Row order: matched block first (probe order), then
    left-unmatched, then (full) right-unmatched; bucket order does not
    survive the concat."""
    lkeys_all, rkeys_all = _join_key_arrays(left, right, norm)
    lvalid = _keys_validity(left, [p[0] for p in norm])
    rvalid = _keys_validity(right, [p[1] for p in norm])
    l_idx = jnp.flatnonzero(lvalid) if lvalid is not None else None
    r_idx = jnp.flatnonzero(rvalid) if rvalid is not None else None
    lkeys = lkeys_all[l_idx] if l_idx is not None else lkeys_all
    rkeys = rkeys_all[r_idx] if r_idx is not None else rkeys_all

    order = kernels.lex_sort_indices([rkeys])
    rkeys_sorted = jnp.take(rkeys, order)
    li, ri, counts = kernels.merge_join_indices(lkeys, rkeys_sorted,
                                                return_counts=True)
    # Map subset indices back to original row positions.
    li_orig = jnp.take(l_idx, li) if l_idx is not None else li
    r_pos = jnp.take(r_idx, order) if r_idx is not None else order
    ri_orig = jnp.take(r_pos, ri)

    unmatched_l = jnp.flatnonzero(counts == 0)
    unmatched_l_orig = jnp.take(l_idx, unmatched_l) \
        if l_idx is not None else unmatched_l
    if lvalid is not None:
        unmatched_l_orig = jnp.concatenate(
            [unmatched_l_orig, jnp.flatnonzero(~lvalid)])

    blocks: List[Dict[str, Column]] = []
    matched_left = left.take(li_orig)
    matched_right = right.take(ri_orig)
    blocks.append({**matched_left.columns, **matched_right.columns})
    n_um_l = int(unmatched_l_orig.shape[0])  # HOST SYNC (scalar)
    if n_um_l:
        blocks.append({**left.take(unmatched_l_orig).columns,
                       **_null_filled_like(right, n_um_l)})
    if how == "full":
        # Right rows no left row matched: mark via a hit-scatter. ~hit
        # naturally includes null-key right rows (they never match).
        hit = jnp.zeros(right.num_rows, jnp.bool_).at[ri_orig].set(True)
        unmatched_r = jnp.flatnonzero(~hit)
        n_um_r = int(unmatched_r.shape[0])  # HOST SYNC (scalar)
        if n_um_r:
            blocks.append({**_null_filled_like(left, n_um_r),
                           **right.take(unmatched_r).columns})

    pieces = [Table({n: b[n] for n in b}) for b in blocks]
    ordered_names = [n for n in plan.schema.names
                     if n in pieces[0].names]
    out = Table.concat([p.select(ordered_names) for p in pieces])
    return out


def _bucketed_merge_keys(left: Table, right: Table, norm, lkeys, rkeys):
    """If one side is bucket-ordered on its single join key (covering-index
    layout), build composite (bucket, key) probe keys so the merge join can
    run without sorting that side. Returns (left_comp, right_comp, swapped)
    or None.

    Requires an integer-family key that fits in 32 bits (packed with the
    bucket id into one int64); the general path handles the rest.
    """
    if len(norm) != 1:
        return None
    lname, rname = norm[0]
    lcol, rcol = left.column(lname), right.column(rname)
    if lcol.dtype not in (INT32, INT64, DATE) or rcol.dtype != lcol.dtype:
        return None

    def ordered_on(table: Table, name: str):
        return table.bucket_order is not None and table.bucket_order[1] == (name,)

    if ordered_on(right, rname):
        swapped = False
        num_buckets = right.bucket_order[0]
    elif ordered_on(left, lname):
        swapped = True
        num_buckets = left.bucket_order[0]
    else:
        return None
    # Keys must fit int32 for the (bucket << 32 | biased key) packing; the
    # composite program also emits max(|key|) over the valid prefix, so
    # the check costs no extra program (pad tails are masked inside).
    lcomp, l_ext = kernels.bucket_composite_keys(
        lkeys, lcol.dtype, num_buckets, valid_count=left.num_rows)
    rcomp, r_ext = kernels.bucket_composite_keys(
        rkeys, rcol.dtype, num_buckets, valid_count=right.num_rows)
    for a, ext in ((lkeys, l_ext), (rkeys, r_ext)):
        if a.dtype == jnp.int64 and a.shape[0]:
            extreme = int(ext)  # HOST SYNC (single scalar)
            if extreme >= 2 ** 31 or extreme < 0:  # < 0: |int64 min| overflow
                return None
    return lcomp, rcomp, swapped


def _keys_validity(table: Table, names: Sequence[str]):
    v = None
    for n in names:
        c = table.column(n)
        cv = c.validity
        if c.dtype == STRING and cv is None:
            pass
        if cv is not None:
            v = cv if v is None else (v & cv)
    return v


# ---------------------------------------------------------------------------
# Aggregate / Sort.
# ---------------------------------------------------------------------------

def _null_aware_keys(c: Column) -> List[jnp.ndarray]:
    """Comparison keys for one column treating null as its own value that
    sorts before every real value: a (validity-flag, null-masked data) pair
    when nullable, just the data otherwise. The single encoding shared by
    sort, group-by, and the SPMD path's per-device order (spmd.py)."""
    if c.validity is None:
        return [c.data]
    return [c.validity.astype(jnp.int32),  # null(0) sorts first
            jnp.where(c.validity, c.data, jnp.zeros((), c.data.dtype))]


def _group_sort_keys(cols: Sequence[Column]) -> List[jnp.ndarray]:
    return [k for c in cols for k in _null_aware_keys(c)]


# Group-bys that avoided the full row sort (tests/bench assert the path is
# taken): SKIPPED = bucket order covers exactly the grouping keys (single
# pass, no sort at all); TWO_PHASE = bucket keys are a strict subset (runs
# aggregated then only the runs sorted).
GROUPBY_SORT_SKIPPED = 0
GROUPBY_TWO_PHASE = 0


def _execute_aggregate(plan: Aggregate, table: Table) -> Table:
    global GROUPBY_SORT_SKIPPED
    if not plan.group_cols:
        return _execute_global_aggregate(plan, table)
    key_cols = [table.column(g) for g in plan.group_cols]
    bo = table.bucket_order
    keys_non_null = all(c.validity is None for c in key_cols)
    padded_in = table.is_padded
    n_valid = table.num_rows
    if bo is not None and set(bo[1]) == set(plan.group_cols) \
            and keys_non_null:
        # Covering-index layout: rows sorted by (bucket, keys) ⇒ equal key
        # tuples are globally contiguous (a key tuple lives in exactly one
        # bucket), so segment detection works WITHOUT the O(n log n) sort —
        # the group-by analogue of the shuffle-free merge join. (Nullable
        # keys fall through: their fill values collide with real zeros.)
        sorted_table = table
        sorted_keys = [c.data for c in key_cols]
        GROUPBY_SORT_SKIPPED += 1
    elif bo is not None and set(bo[1]) < set(plan.group_cols) \
            and keys_non_null \
            and not any(isinstance(_unwrap_agg(a), E.CountDistinct)
                        for a in plan.aggs):
        # (CountDistinct is excluded: distinct counts of run partials
        # cannot be combined — the full-sort path below handles it.)
        # Bucket keys are a strict SUBSET of the grouping keys (e.g. Q3:
        # join output ordered by l_orderkey, grouped by (l_orderkey,
        # o_orderdate, o_shippriority)): equal group tuples need not be
        # globally contiguous, but RUNS of them are short-range — so run
        # the two-phase partial aggregation (segment per run, then sort
        # only the RUNS — usually ≈ the group count, vastly fewer than
        # rows — and combine). Sort cost drops from O(n log n) rows to
        # O(r log r) runs.
        global GROUPBY_TWO_PHASE
        GROUPBY_TWO_PHASE += 1
        return _execute_aggregate_two_phase(plan, table, key_cols)
    else:
        order = kernels.lex_sort_indices(
            _group_sort_keys(key_cols),
            valid_count=n_valid if padded_in else None,
            padded_out=padded_in)
        sorted_table = table.take(
            order, valid_rows=n_valid if padded_in else None)
        sorted_keys = _group_sort_keys(
            [sorted_table.column(g) for g in plan.group_cols])
    gids, num_groups = kernels.group_ids_from_sorted(
        sorted_keys, valid_count=n_valid if sorted_table.is_padded else None,
        padded_out=sorted_table.is_padded)
    if num_groups == 0:
        return Table({f.name: Column(f.dtype,
                                     jnp.zeros(0, _np_dtype_for(f.dtype)),
                                     None,
                                     _dict_for(table, f.name))
                      for f in plan.schema.fields})
    # The group count is data-dependent — outputs ride on its length class
    # (pad segments hold scatter identities, gathered with clip only).
    cap = shapes.padded_length(num_groups)
    out_valid = num_groups if cap != num_groups else None
    # One fused first-index + gather for every group column buffer.
    head_arrays, head_spec = [], []
    for g in plan.group_cols:
        c = sorted_table.column(g)
        head_arrays.append(c.data)
        head_spec.append((g, "d"))
        if c.validity is not None:
            head_arrays.append(c.validity)
            head_spec.append((g, "v"))
    heads = dict(zip(head_spec, kernels.segment_heads(
        gids, head_arrays, num_groups, padded_out=True)))
    out = {}
    for g in plan.group_cols:
        c = sorted_table.column(g)
        out[g] = Column(c.dtype, heads[(g, "d")], heads.get((g, "v")),
                        c.dictionary)
    for agg in plan.aggs:
        out[agg.name] = _eval_agg(agg, sorted_table, gids, num_groups,
                                  padded_out=True)
    return Table(out, valid_rows=out_valid)


def _execute_aggregate_two_phase(plan: Aggregate, table: Table,
                                 key_cols: List[Column]) -> Table:
    """Run-based partial aggregation: phase 1 segments CONSECUTIVE equal
    key tuples (no sort) and reduces each run to partials; phase 2 sorts
    only the runs and combines duplicate tuples. All on device; output is
    key-sorted like the main path.

    Shape classes: the run count and group count are both data-dependent,
    so phase-1 partials live on the run count's length class and the
    output on the group count's (kernels route pad rows to dropped
    segments; pad gathers clip)."""
    padded_in = table.is_padded
    n_valid = table.num_rows
    run_keys = [c.data for c in key_cols]
    rids, num_runs = kernels.group_ids_from_sorted(
        run_keys, valid_count=n_valid if padded_in else None,
        padded_out=padded_in)
    if num_runs == 0:
        return _execute_aggregate(
            plan, Table(dict(table.columns)))  # empty: reuse generic path
    cap_r = shapes.padded_length(num_runs)
    run_padded = cap_r != num_runs
    run_vals = list(kernels.segment_heads(rids, run_keys, num_runs,
                                          padded_out=True))

    order2 = kernels.lex_sort_indices(
        run_vals, valid_count=num_runs if run_padded else None,
        padded_out=run_padded)
    sorted_vals = list(kernels.gather_arrays(order2, run_vals))
    gids2, num_groups = kernels.group_ids_from_sorted(
        sorted_vals, valid_count=num_runs if run_padded else None,
        padded_out=run_padded)
    cap_g = shapes.padded_length(num_groups)
    out_valid = num_groups if cap_g != num_groups else None

    def combine(run_partial, op):
        # Fused gather-through-order2 + segment reduce.
        return kernels.gather_segment(run_partial, order2, gids2,
                                      num_groups, op, padded_out=True)

    out = {}
    group_vals = kernels.segment_heads(gids2, sorted_vals, num_groups,
                                       padded_out=True)
    for g, gv in zip(plan.group_cols, group_vals):
        src = table.column(g)
        out[g] = Column(src.dtype, gv, None, src.dictionary)
    for agg_expr in plan.aggs:
        agg = _unwrap_agg(agg_expr)
        name = agg_expr.name
        if isinstance(agg, E.Count):
            validity = None if agg.child is None \
                else eval_expr(table, agg.child).validity
            run_c = kernels.segment_count(rids, num_runs, validity,
                                          padded_out=True)
            out[name] = Column(INT64, combine(run_c, "sum"))
            continue
        child = _agg_child_column(agg, table)
        validity = child.validity
        out_validity = None
        total_valid = None
        if isinstance(agg, (E.Sum, E.Avg)):
            # Partial sums AND partial valid counts from one program.
            run_sums, run_valid = kernels.segment_agg(
                child.data, validity, rids, num_runs, "sum",
                padded_out=True)
            if run_valid is not None:
                total_valid = combine(run_valid, "sum")
                if validity is not None:
                    out_validity = total_valid > 0
            sums = combine(run_sums, "sum")
            if isinstance(agg, E.Sum):
                out[name] = Column(_sum_out_dtype(sums), sums, out_validity)
            else:
                if total_valid is None:
                    run_valid = kernels.segment_count(rids, num_runs,
                                                      padded_out=True)
                    total_valid = combine(run_valid, "sum")
                out[name] = Column(
                    FLOAT64,
                    sums.astype(jnp.float64) /
                    jnp.maximum(total_valid, 1).astype(jnp.float64),
                    out_validity)
        elif isinstance(agg, (E.Min, E.Max)):
            op = "min" if isinstance(agg, E.Min) else "max"
            run_m, run_valid = kernels.segment_agg(
                child.data, validity, rids, num_runs, op, widen=False,
                padded_out=True)
            if run_valid is not None:
                out_validity = combine(run_valid, "sum") > 0
            out[name] = Column(child.dtype, combine(run_m, op),
                               out_validity, child.dictionary)
        else:
            raise HyperspaceException(f"Unknown aggregate {agg!r}")
    return Table(out, valid_rows=out_valid)


def _np_dtype_for(dtype: str):
    return {INT32: jnp.int32, INT64: jnp.int64, "float32": jnp.float32,
            FLOAT64: jnp.float64, BOOL: jnp.bool_, DATE: jnp.int32,
            STRING: jnp.int32}[dtype]


def _dict_for(table: Table, name: str):
    if name in table.columns and table.columns[name].dtype == STRING:
        return table.columns[name].dictionary
    return None


def _unwrap_agg(agg: E.Expr) -> E.AggExpr:
    while isinstance(agg, E.Alias):
        agg = agg.child
    if not isinstance(agg, E.AggExpr):
        raise HyperspaceException(
            f"Aggregate list requires agg functions; got {agg!r}")
    return agg


def _agg_child_column(agg: E.AggExpr, table: Table) -> Column:
    child = eval_expr_maybe_fused(table, agg.child)
    if child.dtype == STRING and not isinstance(agg, (E.Min, E.Max)):
        raise HyperspaceException("sum/avg over string column")
    return child


def _acc_widen(values: jnp.ndarray, validity) -> jnp.ndarray:
    """Sum/avg accumulator: floats widen to f64, ints to i64; invalid
    rows contribute zero."""
    acc = values.astype(jnp.float64) \
        if jnp.issubdtype(values.dtype, jnp.floating) \
        else values.astype(jnp.int64)
    return acc if validity is None else jnp.where(validity, acc, 0)


def _sentinel_filled(child: Column, kind: str) -> jnp.ndarray:
    """Min/max input with invalid rows pushed past every real value."""
    if child.validity is None:
        return child.data
    sentinel = _max_sentinel(child.data.dtype) if kind == "min" \
        else _min_sentinel(child.data.dtype)
    return jnp.where(child.validity, child.data, sentinel)


def _sum_out_dtype(sums) -> str:
    return FLOAT64 if jnp.issubdtype(sums.dtype, jnp.floating) else INT64


def _count_distinct(child: Column, gids, num_groups: int) -> Column:
    """COUNT(DISTINCT value) per group: sort rows by (group, value), flag
    each (group, value) pair's first occurrence, segment-sum the flags.
    NULL values are excluded (SQL semantics) by parking their rows in a
    sentinel segment past the real groups."""
    n = child.data.shape[0]
    if n == 0:
        return Column(INT64, jnp.zeros(num_groups, jnp.int64))
    data = child.data.astype(jnp.int32) if child.dtype == BOOL else child.data
    gid_key = gids if child.validity is None else \
        jnp.where(child.validity, gids, num_groups)
    perm = kernels.lex_sort_indices([gid_key, data])
    sg = jnp.take(gid_key, perm)
    sv = jnp.take(data, perm)
    first = kernels.change_mask([sg, sv]).at[0].set(True)
    if jnp.issubdtype(sv.dtype, jnp.floating):
        # NaN != NaN would count every NaN separately; the sort places a
        # group's NaNs adjacent, so un-flag NaN-after-NaN pairs (Spark
        # semantics: NaN is ONE distinct value).
        nan_pair = jnp.concatenate([
            jnp.zeros(1, jnp.bool_),
            jnp.isnan(sv[1:]) & jnp.isnan(sv[:-1]) & (sg[1:] == sg[:-1])])
        first = first & ~nan_pair
    counts = kernels.segment_sum(first.astype(jnp.int64), sg,
                                 num_groups + 1)[:num_groups]
    return Column(INT64, counts)


def _eval_agg(agg: E.Expr, sorted_table: Table, gids, num_groups: int,
              padded_out: bool = False) -> Column:
    agg = _unwrap_agg(agg)
    if isinstance(agg, E.CountDistinct):
        col = _count_distinct(eval_expr(sorted_table, agg.child),
                              gids, num_groups)
        if padded_out:
            col = Column(col.dtype, shapes.pad_to(
                col.data, shapes.padded_length(num_groups)), col.validity,
                col.dictionary)
        return col
    if isinstance(agg, E.Count):
        if agg.child is None:
            data = kernels.segment_count(gids, num_groups,
                                         padded_out=padded_out)
        else:
            child = eval_expr(sorted_table, agg.child)
            data = kernels.segment_count(gids, num_groups, child.validity,
                                         padded_out=padded_out)
        return Column(INT64, data)
    child = _agg_child_column(agg, sorted_table)
    validity = child.validity
    if isinstance(agg, (E.Sum, E.Avg)):
        op = "mean" if isinstance(agg, E.Avg) else "sum"
        value, counts = kernels.segment_agg(child.data, validity, gids,
                                            num_groups, op,
                                            padded_out=padded_out)
        # SQL semantics: a group with no valid values aggregates to NULL.
        out_validity = (counts > 0) if validity is not None else None
        if isinstance(agg, E.Sum):
            return Column(_sum_out_dtype(value), value, out_validity)
        return Column(FLOAT64, value, out_validity)
    if isinstance(agg, (E.Min, E.Max)):
        op = "min" if isinstance(agg, E.Min) else "max"
        value, counts = kernels.segment_agg(child.data, validity, gids,
                                            num_groups, op, widen=False,
                                            padded_out=padded_out)
        out_validity = (counts > 0) if validity is not None else None
        return Column(child.dtype, value, out_validity, child.dictionary)
    raise HyperspaceException(f"Unknown aggregate {agg!r}")


def _max_sentinel(dtype):
    return jnp.array(jnp.finfo(dtype).max if jnp.issubdtype(dtype, jnp.floating)
                     else jnp.iinfo(dtype).max, dtype)


def _min_sentinel(dtype):
    return jnp.array(jnp.finfo(dtype).min if jnp.issubdtype(dtype, jnp.floating)
                     else jnp.iinfo(dtype).min, dtype)


def _execute_global_aggregate(plan: Aggregate, table: Table) -> Table:
    if table.is_padded:
        # One fused program: pad rows scatter to a dropped segment.
        gids = kernels.global_segment_ids(table.num_rows, table.data_rows)
    else:
        gids = jnp.zeros(table.data_rows, jnp.int32)
    out = {}
    for agg in plan.aggs:
        out[agg.name] = _eval_agg(agg, table, gids, 1)
    return Table(out)


def _segmented_scan(data: jnp.ndarray, seg_start: jnp.ndarray, op):
    """Inclusive running ``op`` within segments of pre-sorted rows:
    ``seg_start`` marks each segment's first row. One associative_scan —
    the XLA-native way to reset an accumulator at segment boundaries
    (no data-dependent Python control flow)."""
    def combine(a, b):
        af, av = a
        bf, bv = b
        return af | bf, jnp.where(bf, bv, op(av, bv))

    _, out = jax.lax.associative_scan(combine, (seg_start, data))
    return out


def _execute_window(plan: Window, table: Table) -> Table:
    """Analytic functions as sort + segmented scans, preserving the
    child's row order (outputs are computed in partition-sorted space and
    scattered back through the sort permutation). Window exprs sharing a
    (partition, order) spec share one sort."""
    n = table.num_rows
    out = dict(table.columns)
    if n == 0:
        for name, w in plan.wexprs:
            f = plan.schema.field(name)
            dic = _dict_for(table, w.arg.column) if (
                w.arg is not None and f.dtype == STRING) else None
            out[name] = Column(f.dtype, jnp.zeros(0, _np_dtype_for(f.dtype)),
                               None, dic)
        return Table(out, bucket_order=table.bucket_order)
    iota = jnp.arange(n, dtype=jnp.int32)

    specs = {}
    for name, w in plan.wexprs:
        key = (tuple(p.column for p in w.partition),
               tuple((o.column, asc) for o, asc in w.orders))
        specs.setdefault(key, []).append((name, w))

    for (pcols, oitems), group in specs.items():
        keys, asc_flags = [], []
        for p in pcols:
            for k in _null_aware_keys(table.column(p)):
                keys.append(k)
                asc_flags.append(True)
        for oc, a in oitems:
            for k in _null_aware_keys(table.column(oc)):
                keys.append(k)
                asc_flags.append(a)
        order = kernels.lex_sort_indices(keys, asc_flags) if keys else iota
        if pcols:
            pkeys_sorted = _group_sort_keys(
                [table.column(p).take(order) for p in pcols])
            pids, n_part = kernels.group_ids_from_sorted(pkeys_sorted)
        else:
            pkeys_sorted = []
            pids, n_part = jnp.zeros(n, jnp.int32), 1
        part_start = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), pids[1:] != pids[:-1]])
        part_first = kernels.segment_first_index(pids, n_part)
        pos = iota - jnp.take(part_first, pids)
        peer_gid = peer_first = peer_last = None
        if oitems:
            okeys_sorted = _group_sort_keys(
                [table.column(oc).take(order) for oc, _ in oitems])
            peer_gid, n_peer = kernels.group_ids_from_sorted(
                pkeys_sorted + okeys_sorted)
            peer_first = kernels.segment_first_index(peer_gid, n_peer)
            peer_last = kernels.segment_max(iota, peer_gid, n_peer)

        for name, w in group:
            dtype = plan.schema.field(name).dtype
            validity_s = None
            dic = None
            if w.fn == "row_number":
                vals = (pos + 1).astype(jnp.int64)
            elif w.fn == "rank":
                vals = (jnp.take(peer_first, peer_gid)
                        - jnp.take(part_first, pids) + 1).astype(jnp.int64)
            elif w.fn == "dense_rank":
                first_peer = jnp.take(peer_gid, jnp.take(part_first, pids))
                vals = (peer_gid - first_peer + 1).astype(jnp.int64)
            else:
                arg = None if w.arg is None \
                    else table.column(w.arg.column).take(order)
                vals, validity_s = _window_agg(
                    w, arg, pids, n_part, part_start, peer_gid, peer_last)
                if dtype == STRING:
                    dic = arg.dictionary
            data = jnp.zeros(n, vals.dtype).at[order].set(vals)
            validity = None if validity_s is None else \
                jnp.zeros(n, jnp.bool_).at[order].set(validity_s)
            out[name] = Column(dtype, data, validity, dic)
    return Table(out, bucket_order=table.bucket_order)


def _window_agg(w: E.WindowExpr, arg: Optional[Column], pids, n_part,
                part_start, peer_gid, peer_last):
    """One windowed aggregate in partition-sorted space. Returns (values,
    validity or None). Frames: 'partition' = whole partition;
    'rows' = running; 'range' = running where order-key peers share the
    value of their last row (the SQL default frame with ORDER BY)."""
    fn = w.fn
    frame = w.frame
    if frame == "range" and peer_gid is None:
        frame = "partition"  # no ORDER BY: every row is a peer

    if fn == "count":
        data = jnp.ones(pids.shape[0], jnp.int64) if arg is None or \
            arg.validity is None else arg.validity.astype(jnp.int64)
    elif fn in ("sum", "avg"):
        if arg.dtype == STRING:
            # Same guard as the aggregate path (_agg_child_column):
            # summing dictionary codes would be silently wrong.
            raise HyperspaceException(f"{fn} over string column")
        data = _acc_widen(arg.data, arg.validity)
        if fn == "avg":
            data = data.astype(jnp.float64)
    else:  # min / max
        data = _sentinel_filled(arg, fn)

    valid = None if arg is None or arg.validity is None \
        else arg.validity.astype(jnp.int64)

    def framed(values, op, identity_op_name):
        if frame == "partition":
            seg = {"sum": kernels.segment_sum,
                   "min": kernels.segment_min,
                   "max": kernels.segment_max}[identity_op_name](
                values, pids, n_part)
            return jnp.take(seg, pids)
        running = _segmented_scan(values, part_start, op)
        if frame == "range":
            running = jnp.take(running, jnp.take(peer_last, peer_gid))
        return running

    if fn == "count":
        return framed(data, jnp.add, "sum"), None
    if fn in ("min", "max"):
        op = jnp.minimum if fn == "min" else jnp.maximum
        vals = framed(data, op, fn)
        if valid is None:
            return vals, None
        cnt = framed(valid, jnp.add, "sum")
        return vals, cnt > 0
    # sum / avg
    total = framed(data, jnp.add, "sum")
    if fn == "avg":
        cnt = framed(valid if valid is not None
                     else jnp.ones(pids.shape[0], jnp.int64),
                     jnp.add, "sum")
        vals = total / jnp.maximum(cnt, 1)
        return vals, (cnt > 0) if valid is not None else None
    if valid is None:
        return total, None
    cnt = framed(valid, jnp.add, "sum")
    return total, cnt > 0


def _execute_sort(plan: Sort, table: Table) -> Table:
    keys, ascending = [], []
    for name, asc in plan.orders:
        # SQL order-by null placement (Spark default): NULLS FIRST when
        # ascending, NULLS LAST when descending — sorting the null-aware
        # (flag, data) keys in the requested direction realizes both.
        for k in _null_aware_keys(table.column(name)):
            keys.append(k)
            ascending.append(asc)
    padded = table.is_padded
    order = kernels.lex_sort_indices(
        keys, ascending, valid_count=table.num_rows if padded else None,
        padded_out=padded)
    return table.take(order,
                      valid_rows=table.num_rows if padded else None)
