"""HBM-resident index table cache — a view over the tiered buffer pool.

The covering index's value on TPU is being *resident*: once a query
touches an index version, its columns stay on device and every later
query probes HBM directly instead of re-reading bucket parquet files
from the lake. Since the buffer-pool PR this module no longer owns
storage: :class:`IndexTableCache` is a thin view over
``execution/buffer_pool.py``'s process pool (namespace ``"index"``), so
index and source scans obey ONE device/host byte budget and one
eviction ladder. The legacy surface is preserved exactly — same
constructor, same ``get``/``put``/``clear``, and the 4 legacy counters
(``hits``/``misses``/``nbytes``/``max_bytes``) keep reporting via
aliases over the pool's per-namespace counters, so IndexCacheHit/
MissEvent consumers and existing tests stay green.

Keys are (entry id, file tuple, column tuple): index data versions are
immutable on disk (index/IndexDataManager versioned dirs), so a key can
never go stale — rebuilds/refreshes produce new file paths and the old
entries age out of the LRU.

Knobs (env, not session conf — the executor is session-free by design):
  HST_INDEX_CACHE=off         disable
  HST_INDEX_CACHE_BYTES=N     standalone-view capacity (default 4 GiB)
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from .buffer_pool import BufferPool, index_key, table_nbytes
from .columnar import Table

# Re-export: table_nbytes moved to buffer_pool.py (the pool owns the
# shared byte accounting) but serving/result_cache.py and external
# callers import it from here.
__all__ = ["table_nbytes", "IndexTableCache", "enabled", "get_cache"]


class IndexTableCache:
    """The legacy index-cache API over a buffer pool.

    Standalone construction (``IndexTableCache(max_bytes)``) wraps a
    PRIVATE single-tier pool (host budget 0: evicted entries drop, the
    legacy semantics). The process singleton from :func:`get_cache`
    instead views the SHARED process pool, so index tables compete with
    source-scan buffers under one budget and may demote to the host
    tier before dropping.
    """

    def __init__(self, max_bytes: int, pool: Optional[BufferPool] = None):
        self.max_bytes = max_bytes
        self._pool = pool if pool is not None \
            else BufferPool(device_bytes=max_bytes, host_bytes=0)

    def get(self, key: Tuple) -> Optional[Table]:
        return self._pool.get(index_key(key))

    def put(self, key: Tuple, table: Table) -> None:
        self._pool.put(index_key(key), table)

    def clear(self) -> None:
        self._pool.clear("index")

    @property
    def hits(self) -> int:
        return self._pool.ns_counts("index")[0]

    @property
    def misses(self) -> int:
        return self._pool.ns_counts("index")[1]

    @property
    def nbytes(self) -> int:
        return self._pool.ns_nbytes("index")


_cache: Optional[IndexTableCache] = None


def enabled() -> bool:
    return os.environ.get("HST_INDEX_CACHE", "on") != "off"


def get_cache() -> IndexTableCache:
    global _cache
    if _cache is None:
        from .buffer_pool import get_pool
        _cache = IndexTableCache(int(os.environ.get(
            "HST_INDEX_CACHE_BYTES", str(4 << 30))), pool=get_pool())
    return _cache
