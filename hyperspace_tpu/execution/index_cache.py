"""HBM-resident index table cache.

The covering index's value on TPU is being *resident*: once a query touches
an index version, its columns stay on device and every later query probes
HBM directly instead of re-reading bucket parquet files from the lake (the
design target: filter pushdown and shuffle-free joins probe an HBM-resident
columnar index). Source scans are deliberately NOT cached — the index is
the derived, optimized structure; the lake is the cold path.

Keys are (entry id, file tuple, column tuple): index data versions are
immutable on disk (index/IndexDataManager versioned dirs), so a key can
never go stale — rebuilds/refreshes produce new file paths and the old
entries age out of the LRU.

Knobs (env, not session conf — the executor is session-free by design):
  HST_INDEX_CACHE=off         disable
  HST_INDEX_CACHE_BYTES=N     capacity (default 4 GiB; TPU v5e has 16 GiB)
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Optional, Tuple

from .columnar import Table


def table_nbytes(table: Table) -> int:
    """Approximate residency cost of a Table (device or host): column
    data + validity bitmaps + dictionary slots. The single byte
    accounting shared by this cache and the serving result cache
    (serving/result_cache.py)."""
    total = 0
    for col in table.columns.values():
        total += col.data.size * col.data.dtype.itemsize
        if col.validity is not None:
            total += col.validity.size
        if col.dictionary is not None:
            total += col.dictionary.size * 8
    return total


class IndexTableCache:
    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[Tuple, Tuple[Table, int]]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, key: Tuple) -> Optional[Table]:
        hit = self._entries.get(key)
        if hit is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return hit[0]

    def put(self, key: Tuple, table: Table) -> None:
        nbytes = table_nbytes(table)
        if nbytes > self.max_bytes:
            return  # larger than the whole cache: don't thrash.
        old = self._entries.pop(key, None)
        if old is not None:
            self._bytes -= old[1]
        self._entries[key] = (table, nbytes)
        self._bytes += nbytes
        while self._bytes > self.max_bytes and len(self._entries) > 1:
            _, (_, evicted) = self._entries.popitem(last=False)
            self._bytes -= evicted

    def clear(self) -> None:
        self._entries.clear()
        self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes


_cache: Optional[IndexTableCache] = None


def enabled() -> bool:
    return os.environ.get("HST_INDEX_CACHE", "on") != "off"


def get_cache() -> IndexTableCache:
    global _cache
    if _cache is None:
        _cache = IndexTableCache(int(os.environ.get(
            "HST_INDEX_CACHE_BYTES", str(4 << 30))))
    return _cache
