"""Execution engine package.

x64 is enabled globally: index keys are int64 in the lake formats we mirror
(TPC-H orderkeys overflow int32 at scale) and aggregate accumulation is
float64 for parity with CPU engines. XLA lowers 64-bit ops on TPU; narrow
dtypes are used wherever the data allows (see columnar.py int32 narrowing).
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache for ACCELERATOR backends (see
# ensure_compilation_cache below for the policy; CPU sessions skip it and
# the setup runs lazily at Session construction once the backend is known).
#
# The directory is keyed by a HOST CPU FINGERPRINT: XLA:CPU AOT executables
# bake in the compile machine's features (+amx/+avx512...), and jax's cache
# key does not include them — a container migrating to a host with fewer
# features loads the stale executable and aborts ("Fatal Python error:
# Aborted" in get_executable_and_time; observed in this sandbox). Separate
# per-fingerprint dirs make migration a cold cache instead of a crash.
def _host_fingerprint() -> str:
    import hashlib
    import platform
    bits = platform.machine() + ";" + platform.processor()
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    bits += ";" + " ".join(sorted(line.split(":", 1)[1]
                                                  .split()))
                    break
    except OSError:
        pass
    return hashlib.sha1(bits.encode()).hexdigest()[:12]


# CPU-backend sessions skip the persistent cache BY DEFAULT: XLA:CPU
# compiles are sub-second (the cache buys little) and this image's cache
# layer has crashed twice under it — an Abort loading a stale-feature AOT
# entry and a SIGSEGV serializing a fresh one. On accelerators the compile
# is tens of seconds and serialization is the hardened path, so the cache
# stays on. HST_XLA_CACHE=on OPTS IN on CPU too (the per-fingerprint dir
# above makes that safe against host migration) so tests and the bench can
# exercise the persistent-cache path without a chip.
# Detection uses jax's RESOLVED backend (not the env var), so in-process
# ``jax.config.update("jax_platforms", "cpu")`` switches — the bench's CPU
# fallback, test conftest — are honored; it therefore runs lazily at
# Session construction (the backend can't be queried before the caller has
# picked a platform). HST_XLA_CACHE: "auto" (default) | "on" | "off".
_cache_configured = False


def ensure_compilation_cache(force: bool = False) -> None:
    """Configure jax's persistent compilation cache per the policy above.
    ``force`` re-evaluates after the first call (tests flip HST_XLA_CACHE
    mid-process; production sessions never need it)."""
    global _cache_configured
    if _cache_configured and not force:
        return
    _cache_configured = True
    mode = os.environ.get("HST_XLA_CACHE", "auto")
    if mode == "off":
        return
    try:
        if mode == "auto" and jax.default_backend() == "cpu":
            return
        _cache_dir = os.environ.get(
            "HST_XLA_CACHE_DIR",
            os.path.join(os.path.expanduser("~"), ".cache", "hyperspace_tpu",
                         "xla", _host_fingerprint()))
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # older jax without these knobs: in-process cache only.

from .columnar import Column, Table, read_parquet, write_parquet  # noqa: F401,E402
from .executor import execute  # noqa: F401,E402
