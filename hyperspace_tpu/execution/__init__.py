"""Execution engine package.

x64 is enabled globally: index keys are int64 in the lake formats we mirror
(TPC-H orderkeys overflow int32 at scale) and aggregate accumulation is
float64 for parity with CPU engines. XLA lowers 64-bit ops on TPU; narrow
dtypes are used wherever the data allows (see columnar.py int32 narrowing).
"""

import jax

jax.config.update("jax_enable_x64", True)

from .columnar import Column, Table, read_parquet, write_parquet  # noqa: F401,E402
from .executor import execute  # noqa: F401,E402
